"""Benchmark: the quantized halo wire's A/B leg (ISSUE 10).

WEAK_SCALING.json shows exposed comm per step as the scaling ceiling and
PR 1's `wire_dtype` casts stop at 2x. This leg records what the int8/int4
per-slab-scale wire buys and costs:

- ``quant_wire_bytes_ratio`` — static, from `halo_comm_plan`: f32 bytes /
  int8 bytes at 4 coalesced fields (payload + appended scales; the
  contract tests pin >= 3.5x, the EQuARX-region number is ~3.76x).
- ``quant_step_speedup`` — measured exact-wire / int8-wire seconds per
  exchange-loop call on the live mesh. On the emulated CPU mesh there is
  no real wire to save, so this is an OVERHEAD gate in disguise: the
  quantize/dequantize arithmetic must not blow up the step
  (``quant_overhead_gate_ok`` = speedup >= 1/2.5); on ICI/DCN hardware
  the wire-byte reduction is the win the ratio row prices.
- ``quant_exposed_comm_model_delta_frac`` — the perf oracle's exposed-comm
  delta for diffusion3D on a 2-axis mesh under the per-axis policy
  ``z:int8,x:f32`` vs exact wire (`predict_step` on a deterministic
  ICI+DCN hierarchical profile: 45 GB/s x-links, 2 GB/s z-links), the
  HiCCL-style slow-axis-only configuration COMM_AVOID.json motivates.

Prints one JSON line per row. Usage: python bench_quant.py [--cpu]
"""

from __future__ import annotations

import sys

import bench_util


def quant_ab_rows(nx: int, c1: int, n_fields: int = 4):
    """A/B rows for the quantized wire on the CURRENT grid (caller owns
    init/finalize): static byte ratio + measured exact/int8 loop times."""
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models.common import make_state_runner

    fields = tuple(igg.ones_g((nx, nx, nx), np.float32) * (i + 1)
                   for i in range(n_fields))
    plan_f32 = igg.halo_comm_plan(*fields)
    plan_int8 = igg.halo_comm_plan(*fields, wire_dtype="int8")
    ratio = plan_f32["wire_bytes"] / plan_int8["wire_bytes"]

    secs = {}
    for mode, wire in (("exact", None), ("int8", "int8")):
        def step(s, wire=wire):
            out = igg.local_update_halo(*s, wire_dtype=wire or "off")
            return out if isinstance(out, tuple) else (out,)

        def chunk(c):
            run = make_state_runner(
                step, (3,) * n_fields, nt_chunk=c,
                key=("bench_quant_ab", mode, n_fields, nx))
            igg.sync(run(*fields))

        secs[mode] = bench_util.two_point(chunk, c1, 3 * c1)
    speedup = secs["exact"] / secs["int8"]
    return [
        {
            "metric": "quant_wire_bytes_ratio",
            "value": ratio,
            "unit": f"x (f32 bytes / int8 payload+scale bytes, "
                    f"{n_fields} coalesced fields)",
            "f32_wire_bytes": plan_f32["wire_bytes"],
            "int8_wire_bytes": plan_int8["wire_bytes"],
            "int4_wire_bytes": igg.halo_comm_plan(
                *fields, wire_dtype="int4")["wire_bytes"],
        },
        {
            "metric": "quant_step_speedup",
            "value": speedup,
            "unit": "x (exact_s / int8_s per exchange-loop call)",
            "exact_s_per_call": secs["exact"],
            "int8_s_per_call": secs["int8"],
            "note": "the emulated CPU mesh has no wire to save: this is "
                    "the quantize/dequantize overhead gate; the byte "
                    "ratio row prices the on-wire win",
        },
        {
            "metric": "quant_overhead_gate_ok",
            "value": 1.0 if speedup >= 1.0 / 2.5 else 0.0,
            "unit": "bool (1 = int8 wire costs < 2.5x the exact exchange "
                    "even with zero wire savings)",
        },
    ]


def exposed_comm_model_row(dims2):
    """The per-axis-policy exposed-comm delta, MODELED (`predict_step` —
    deterministic): diffusion3D on a 2-axis mesh with the z axis
    quantized (``z:int8,x:f32``) vs exact wire, priced on a HIERARCHICAL
    profile (x = ICI-class 45 GB/s, z = DCN-class 2 GB/s / 50 us — the
    COMM_AVOID.json regime where slow-axis tricks pay): the
    configuration the per-axis policy exists for."""
    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.telemetry.perfmodel import MachineProfile

    import jax
    import numpy as np

    profile = MachineProfile(
        membw_GBps=800.0, flops_G=45000.0,
        axes={"gx": {"GBps": 45.0, "latency_s": 5e-6},
              "gy": {"GBps": 45.0, "latency_s": 5e-6},
              "gz": {"GBps": 2.0, "latency_s": 5e-5}},
        source="default", device={"platform": "model:ici+dcn"})
    # production-scale blocks (256^3/shard): the z slab is ~100s of KB,
    # deep in the DCN link's bandwidth-bound regime — priced statically
    # via ShapeDtypeStruct, nothing is allocated
    nx = 256
    igg.init_global_grid(nx, nx, nx, dimx=dims2[0], dimy=dims2[1],
                         dimz=dims2[2], periodx=1, periodz=1, quiet=True)
    try:
        stacked = tuple(nx * d for d in dims2)
        T = jax.ShapeDtypeStruct(stacked, np.float32)
        Cp = jax.ShapeDtypeStruct(stacked, np.float32)
        exact = igg.predict_step("diffusion3d", (T, Cp), profile=profile)
        if "gz" not in exact["comm"]:  # z unpartitioned (e.g. 1 device)
            return {
                "metric": "quant_exposed_comm_model_delta_frac",
                "value": None,
                "note": f"mesh {dims2} has no partitioned z axis to "
                        "quantize; row skipped",
            }
        quant = igg.predict_step("diffusion3d", (T, Cp), profile=profile,
                                 wire_dtype="z:int8,x:f32")
        delta = exact["exposed_comm_s"] - quant["exposed_comm_s"]
        frac = (delta / exact["exposed_comm_s"]
                if exact["exposed_comm_s"] else 0.0)
        return {
            "metric": "quant_exposed_comm_model_delta_frac",
            "value": frac,
            "unit": "fraction of exposed comm removed by z:int8 on the "
                    "2-axis mesh (modeled, ICI+DCN hierarchical profile)",
            "exact_exposed_comm_s": exact["exposed_comm_s"],
            "quant_exposed_comm_s": quant["exposed_comm_s"],
            "z_wire_bytes_exact": exact["comm"]["gz"]["per_link_bytes"],
            "z_wire_bytes_quant": quant["comm"]["gz"]["per_link_bytes"],
        }
    finally:
        igg.finalize_global_grid()


def run_quant_ab(dims, cpu: bool):
    """The canonical leg: all-periodic grid over ``dims`` for the A/B,
    then a 2-axis mesh for the modeled per-axis-policy delta. Shared by
    this script's __main__ and `bench_all.py` (config in ONE place)."""
    import implicitglobalgrid_tpu as igg

    nx_ab, c_ab = (32, 4) if cpu else (256, 20)
    igg.init_global_grid(nx_ab, nx_ab, nx_ab, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        rows = quant_ab_rows(nx_ab, c_ab)
    finally:
        igg.finalize_global_grid()
    nd = dims[0] * dims[1] * dims[2]
    # always give the policy leg a partitioned z when possible; on one
    # device `exposed_comm_model_row` records the row as skipped
    dims2 = (2, 1, nd // 2) if nd >= 4 else (1, 1, nd)
    rows.append(exposed_comm_model_row(dims2))
    return rows


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_quant_ab(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("quant_wire_bytes_ratio", "x")
