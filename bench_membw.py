"""Memory-bandwidth microbenchmarks backing `docs/performance.md`'s
`update_halo` ceiling analysis (round-3 verdict: that analysis cited
in-session v5e numbers — ~294 GB/s Pallas copy bound, ~169 GB/s strided
edge-tile RMW — with no committed measurement behind them).

Rows (one JSON line each + a summary line):

- ``xla_triad_GBps``: fused XLA elementwise (2 reads + 1 write) — the
  practical HBM ceiling (same quantity as `bench.py`'s in-run
  calibration).
- ``pallas_copy_GBps``: a bare BlockSpec-pipelined Pallas read+write pass
  (1 read + 1 write) — the VMEM-mediated copy bound every delivery
  kernel is subject to.
- ``edge_rmw_GBps``: in-place RMW of the dim-2 (lane-edge) halo tiles via
  `pallas_halo.halo_write_inplace` — the strided-tile alternative the
  combined one-pass kernel beats (array-traffic convention: bytes moved
  = the touched lane tiles, 2 * 512-lane-tile columns).
- ``combined_unpack_GBps``: `halo_write_combined_pallas` delivering all
  six received slabs in ~2 full array passes (array-traffic convention:
  2 passes over the block).

Usage: python bench_membw.py          (real chip, 512^3 f32)
       python bench_membw.py --cpu    (small smoke run, virtual mesh)
"""

from __future__ import annotations

import sys

import bench_util


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from implicitglobalgrid_tpu.ops import pallas_halo as ph

    n = 64 if cpu else 512
    interpret = cpu
    A = jnp.asarray(np.random.default_rng(0)
                    .standard_normal((n, n, n)).astype(np.float32))
    nbytes = A.size * 4
    rows = []

    def wall_timer(fn):
        t0 = time.perf_counter()
        fn()
        return time.perf_counter() - t0

    def rate(name, make_chunk, bytes_per_iter, note=None):
        c1 = 2 if cpu else 8
        s = bench_util.two_point(make_chunk, c1, 3 * c1, timer=wall_timer)
        row = bench_util.emit({
            "metric": name, "value": bytes_per_iter / s / 1e9,
            "unit": "GB/s", "note": note,
            "method": bench_util.two_point.last["method"],
        })
        rows.append(row)

    # --- XLA fused triad: 2 reads + 1 write (shared calibration) ---------
    rows.append(bench_util.emit({
        "metric": "xla_triad_GBps",
        "value": bench_util.measure_triad_gbps(A.size),
        "unit": "GB/s",
        "note": "fused elementwise, 2R+1W — practical HBM ceiling (same "
                "helper as bench.py's hbm_triad_GBps)",
        "method": bench_util.two_point.last["method"],
    }))

    # --- bare Pallas copy pass: 1 read + 1 write -------------------------
    from jax.experimental import pallas as pl

    def copy_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    # 2-plane blocks: Pallas double-buffers in+out (4 buffers), so at
    # 512^3 f32 this keeps the VMEM working set at ~8 MiB (an (8,n,n)
    # block would need 32 MiB and fail Mosaic allocation)
    blk = (2, n, n)

    def copy_once(x):
        return pl.pallas_call(
            copy_kernel,
            grid=(n // blk[0],),
            in_specs=[pl.BlockSpec(blk, lambda i: (i, 0, 0))],
            out_specs=pl.BlockSpec(blk, lambda i: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret,
        )(x)

    @jax.jit
    def copy_loop(a, c):
        return jax.lax.fori_loop(0, c, lambda _, x: copy_once(x), a)

    rate("pallas_copy_GBps",
         lambda c: jax.block_until_ready(copy_loop(A, c)), 2 * nbytes,
         "BlockSpec-pipelined read+write pass — the VMEM copy bound")

    # --- dim-2 (lane-edge) strided tile RMW ------------------------------
    # The HYPOTHETICAL dim-2 delivery the framework deliberately does NOT
    # use (pallas_halo has no dim-2 kernel — this access pattern measured
    # slower than the combined one-pass unpack; docs/performance.md).
    # Measurement-only kernel: per x-plane, read+write the two edge
    # lane-tile columns in place, replacing the halo lane.
    from jax import lax

    lane_tile = min(128, n // 2)  # n//2 keeps the two edge tiles distinct
    n_lt = n // lane_tile

    def rmw_kernel(x_ref, o_ref):
        s = pl.program_id(1)
        row = x_ref[0]                          # (n, lane_tile)
        col = lax.broadcasted_iota(jnp.int32, row.shape, 1)
        halo_lane = jnp.where(s == 0, 0, lane_tile - 1)
        o_ref[0] = jnp.where(col == halo_lane, jnp.float32(0.123), row)

    def rmw_once(x):
        edge = pl.BlockSpec((1, n, lane_tile),
                            lambda i, s: (i, 0, s * (n_lt - 1)))
        return pl.pallas_call(
            rmw_kernel,
            grid=(n, 2),
            in_specs=[edge],
            out_specs=edge,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            input_output_aliases={0: 0},
            interpret=interpret,
        )(x)

    @jax.jit
    def rmw_loop(a, c):
        return jax.lax.fori_loop(0, c, lambda _, x: rmw_once(x), a)

    tile_bytes = 2 * (n * n * lane_tile * 4) * 2    # 2 sides, R+W
    rate("edge_rmw_GBps",
         lambda c: jax.block_until_ready(rmw_loop(A, c)), tile_bytes,
         f"strided in-place RMW of the 2 edge {lane_tile}-lane tile "
         "columns (the dim-2 delivery alternative the combined one-pass "
         "kernel replaces)")

    # --- combined one-pass unpack (all six slabs) ------------------------
    recvs = {
        0: (jnp.zeros((1, n, n), np.float32),) * 2,
        1: (jnp.zeros((n, 1, n), np.float32),) * 2,
        2: (jnp.zeros((n, n, 1), np.float32),) * 2,
    }

    @jax.jit
    def unpack_loop(a, c):
        def body(_, x):
            return ph.halo_write_combined_pallas(
                x, recvs, modes=(True, True, True), hws=(1, 1, 1),
                interpret=interpret)
        return jax.lax.fori_loop(0, c, body, a)

    rate("combined_unpack_GBps",
         lambda c: jax.block_until_ready(unpack_loop(A, c)), 2 * nbytes,
         "all six received slabs in one delivery pass; traffic = 2 "
         "array passes")

    bench_util.emit({
        "metric": "membw_suite", "value": float(len(rows)),
        "unit": "rows", "rows": [r["metric"] for r in rows],
        "block": [n, n, n],
    })


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("membw_suite", "rows")
