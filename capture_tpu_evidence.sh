#!/bin/bash
# Capture the committed TPU evidence artifacts in one pass (verdict r3
# item 2). Run when the axon tunnel is UP (check: the bench's backend
# probe, or tail /tmp/tpu_watch.out in-session). NO timeouts anywhere —
# a killed TPU-attached process wedges the chip claim for hours.
#
#   bash capture_tpu_evidence.sh && git add BENCH_TPU.json \
#       BENCH_HALO_TPU.json BENCH_PALLAS_TPU.json MEMBW_TPU.json && git commit
#
# Each artifact is the bench's JSON line(s), tagged with platform/
# device_kind by bench_util.emit; rows with "platform": "cpu" or a
# "fallback" tag mean the tunnel dropped mid-capture — do not commit those.
set -euo pipefail
cd "$(dirname "$0")"

# Disable the supervision harness's wall-clock kill: bench children run
# TPU-attached and must never be timeout-killed (bench_util._budget()
# treats 0 as "no deadline, no attempt timeout").
export IGG_BENCH_BUDGET=0

echo "== bench.py (full evidence: headline + configs + triad + kernel checks)"
python bench.py | tee BENCH_TPU.json

echo "== bench_halo.py (standalone exchange GB/s)"
python bench_halo.py | tee BENCH_HALO_TPU.json

echo "== bench_pallas_check.py (kernel-vs-XLA equality on hardware)"
python bench_pallas_check.py | tee BENCH_PALLAS_TPU.json

echo "== bench_membw.py (HBM microbenchmarks behind docs/performance.md)"
python bench_membw.py | tee MEMBW_TPU.json

echo "== done; every row's platform tag (null/cpu/fallback rows => do NOT commit):"
grep -h -o '"platform": [^,]*' BENCH_TPU.json BENCH_HALO_TPU.json \
    BENCH_PALLAS_TPU.json MEMBW_TPU.json | sort | uniq -c
if grep -l '"fallback"' BENCH_TPU.json BENCH_HALO_TPU.json \
        BENCH_PALLAS_TPU.json MEMBW_TPU.json; then
    echo "WARNING: a fallback tag is present — tunnel dropped mid-capture"
fi
