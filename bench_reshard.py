"""Measure on-device elastic resharding (ISSUE 14): resize downtime,
HBM-to-HBM vs the checkpoint (disk) path.

An elastic ``dims`` change used to round-trip through disk: live state ->
sharded checkpoint -> `restore_checkpoint_elastic` host reads. The
`reshard` subsystem re-blocks the state as a collective program (ppermute
slice rounds over the live device pool) with no disk in the loop. Two
properties ride the gates:

- ``reshard_vs_disk_speedup`` — the checkpoint path's wall time
  (sharded save + elastic restore, what EVERY disk resize pays) over the
  on-device path's steady-state wall time (the compiled transfer
  program re-dispatched; its one-time XLA compile is recorded
  separately, exactly like a chunk runner's cold compile). ABSOLUTE
  gate >= 1.0 under ``IGG_BENCH_STRICT`` — the autoscaling primitive
  must never lose to the disk it replaces.
- ``reshard_device_resize_s`` / ``reshard_disk_resize_s`` — the two
  downtimes themselves, plus ``reshard_compile_s`` (the one-time cost),
  all riding the perfdb trajectory.

Config owned by `run_reshard_ab` (shared with bench_all.py).

Usage: python bench_reshard.py --cpu   (8-device virtual mesh)
       python bench_reshard.py         (real devices)
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import bench_util


def _grid(nx, dims, igg):
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], quiet=True)


def run_reshard_ab(dims, cpu: bool):
    """The canonical leg (config in ONE place, shared with bench_all):
    a 4-field f32 state bounced between two decompositions of the same
    implicit global grid — on-device (steady-state: both directions'
    programs warm, the autoscaling regime) vs checkpoint save + elastic
    restore per resize. Sized so the moved-byte volume dominates the
    grid re-init both paths pay (a tiny state would gate on noise)."""
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.reshard import (
        build_reshard_plan, fields_of_state, live_topology, reshard_state,
    )
    from implicitglobalgrid_tpu.telemetry import predict_reshard
    from implicitglobalgrid_tpu.utils.checkpoint import (
        restore_checkpoint_elastic, save_checkpoint_sharded,
    )

    nx = 40 if cpu else 128
    src = tuple(int(d) for d in dims)
    if int(np.prod(src)) == 1:
        return [{
            "metric": "reshard_vs_disk_speedup", "value": None,
            "unit": "disk resize s / on-device resize s",
            "note": "single-device pool: nothing to re-block; row "
                    "skipped",
        }]
    # the destination: rotate the decomposition (same device count —
    # the re-balance move; grow/shrink correctness is tier-1-tested);
    # a cubic mesh rotates onto itself, so fold two axes instead
    dst = (src[2], src[0], src[1])
    if dst == src:
        dst = (src[0] * src[1], src[2], 1)
    reps = 3

    fields = ("T", "Cp", "Vx", "Vy")   # 4-field state: the byte volume
    _grid(nx, src, igg)                # must dominate the shared grid-
    rng = np.random.default_rng(14)    # re-init cost both paths pay
    stacked = tuple(src[d] * nx for d in range(3))
    state = {
        k: igg.device_put_g(rng.normal(size=stacked).astype(np.float32))
        for k in fields
    }
    plan = build_reshard_plan(live_topology(), dst, fields_of_state(state))
    predicted = predict_reshard(plan)

    # --- on-device path: first resize pays the XLA compile, then bounce
    # src <-> dst warm (the steady state an autoscaling service lives in)
    t0 = time.monotonic()
    state, _ = reshard_state(state, dst)
    compile_s = time.monotonic() - t0   # includes the one-time compile
    state, _ = reshard_state(state, src)  # warm the reverse program too
    times = []
    cur, other = src, dst
    for _ in range(2 * reps):
        t0 = time.monotonic()
        state, _ = reshard_state(state, other)
        times.append(time.monotonic() - t0)
        cur, other = other, cur
    device_s = min(times)
    igg.finalize_global_grid()

    # --- disk path: every resize pays save + elastic restore
    disk_times = []
    with tempfile.TemporaryDirectory() as tmp:
        _grid(nx, src, igg)
        state_d = {
            k: igg.device_put_g(
                rng.normal(size=stacked).astype(np.float32))
            for k in fields
        }
        cur, other = src, dst
        for i in range(2 * reps):
            ck = os.path.join(tmp, f"ck{i}")
            t0 = time.monotonic()
            save_checkpoint_sharded(ck, state_d)
            igg.finalize_global_grid()
            from implicitglobalgrid_tpu.utils.checkpoint import (
                elastic_local_size, saved_topology,
            )

            nloc = elastic_local_size(saved_topology(ck), other)
            igg.init_global_grid(nloc[0], nloc[1], nloc[2],
                                 dimx=other[0], dimy=other[1],
                                 dimz=other[2], quiet=True)
            state_d, _ = restore_checkpoint_elastic(ck)
            disk_times.append(time.monotonic() - t0)
            # each checkpoint is read exactly once: drop it so the leg
            # holds ONE checkpoint of temp disk, not 2*reps (at the
            # real-device config that difference is gigabytes)
            import shutil

            shutil.rmtree(ck, ignore_errors=True)
            cur, other = other, cur
        igg.finalize_global_grid()
    disk_s = min(disk_times)

    speedup = disk_s / device_s if device_s > 0 else None
    return [
        {
            "metric": "reshard_vs_disk_speedup",
            "value": speedup,
            "unit": "disk resize s / on-device resize s (>= 1.0: the "
                    "HBM path must never lose to the disk round-trip "
                    "it replaces)",
            "src_dims": list(src), "dst_dims": list(dst), "nx": nx,
            "rounds": plan.rounds, "wire_bytes": plan.wire_bytes,
        },
        {
            "metric": "reshard_device_resize_s",
            "value": device_s,
            "unit": "s wall, warm collective program (min of "
                    f"{2 * reps})",
            "predicted_s": predicted["seconds"],
        },
        {
            "metric": "reshard_disk_resize_s",
            "value": disk_s,
            "unit": "s wall, sharded save + elastic restore (min of "
                    f"{2 * reps})",
        },
        {
            "metric": "reshard_compile_s",
            "value": compile_s,
            "unit": "s wall of the FIRST resize (one-time XLA compile "
                    "of the transfer program, paid once per (plan, "
                    "devices))",
        },
    ]


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    dims = tuple(int(d) for d in igg.dims_create(len(jax.devices()),
                                                 (0, 0, 0)))
    rows = [bench_util.emit(r) for r in run_reshard_ab(dims, cpu)]
    with open("BENCH_RESHARD.json", "w") as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("bench_reshard", "suite")
