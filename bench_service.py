"""Benchmark: multi-run scheduler overhead vs back-to-back solo runs.

The scheduler (`service.MeshScheduler`) multiplexes jobs through one
device pool in chunk-granular slices; this leg measures what that costs
at steady state, where it matters — compiles excluded by construction
(both sides warmed first), so the numbers isolate the scheduler's own
bookkeeping:

- ``service_overhead_frac``: warm per-slice scheduler bookkeeping (grid
  swap, recorder swap, policy pick, per-job gauges, journal write) as a
  fraction of the chunk work the slice carried. Target < 2% (ISSUE 8
  acceptance).
- ``service_warm_switch_s``: the absolute warm context-switch cost per
  slice, in seconds (recorded alongside the gate).

Measurement is DETERMINISTIC per-slice accounting, not a wall-clock A/B:
each journal ``slice`` event brackets exactly one chunk-boundary
`advance()`, whose own ``chunk`` event stamps its ``build_s + exec_s`` —
the difference is the scheduler's added machinery, and because both
stamps come from the SAME slice, the shared box's ±15% per-call jitter
cancels instead of swamping the sub-1% signal (the bench_trace/
bench_perf lesson for bounding deterministic costs; a wall-clock A/B of
two warm loops was tried first and its window-to-window drift exceeded
the entire gate several-fold in both directions). What the subtraction
leaves also includes the driver's own per-boundary bookkeeping (report
build, heartbeat, watch) that a solo run pays too — so the gated number
OVERSTATES the scheduler's true marginal cost; it passing the 2% gate
is conservative.

Cold costs are excluded and visible elsewhere by design: admission is
journaled as ``admit_s`` and each job's first dispatch is its flight
stream's ``cold`` chunk — attributed to the job that pays them, which is
the scheduling contract, not an overhead of it.

Usage: python bench_service.py          (real chip)
       python bench_service.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import json
import sys
import time

import bench_util


def _diffusion_setup():
    import numpy as np

    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


def run_service_overhead(dims, cpu: bool):
    """The canonical leg: run a two-job round_robin queue to completion
    with a flight directory, then account each warm slice's journal
    duration against the chunk work it carried. Shared by this script's
    __main__ and `bench_all.py` so the config stays in ONE place."""
    import os
    import statistics
    import tempfile

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.runtime import RunSpec
    from implicitglobalgrid_tpu.service import JobSpec, MeshScheduler

    nx, chunk, chunks_per_job = (24, 20, 13) if cpu else (128, 50, 13)
    grid = dict(nx=nx, ny=nx, nz=nx, dimx=int(dims[0]), dimy=int(dims[1]),
                dimz=int(dims[2]), periodx=1, periody=1, periodz=1)
    nt = chunk * chunks_per_job

    d = tempfile.mkdtemp(prefix="bench_service_")
    with MeshScheduler(policy="round_robin", flight_dir=d) as sched:
        for name in ("a", "b"):
            sched.submit(JobSpec(
                name=name, setup=_diffusion_setup, nt=nt, grid=grid,
                run=RunSpec(nt_chunk=chunk, key=("bench_svc", name))))
        sched.run()
        states = sched.status()["states"]
    if states != {"done": 2}:
        raise RuntimeError(f"bench_service: jobs did not finish: {states}")

    # per-slice accounting: journal slice dur_s minus the matching chunk
    # event's build_s + exec_s (one chunk boundary per slice, in order);
    # the first slice per job carries admission + the XLA compile and is
    # excluded (cold — the attributed cost, not the overhead)
    from implicitglobalgrid_tpu.telemetry import read_flight_events

    slices: dict = {}
    for e in read_flight_events(os.path.join(d, "scheduler.jsonl")):
        if e.get("kind") == "slice":
            slices.setdefault(e["job"], []).append(float(e["dur_s"]))
    over, base = [], []
    for name, durs in sorted(slices.items()):
        chunks = [e for e in read_flight_events(
            os.path.join(d, f"job_{name}.jsonl"))
            if e.get("kind") == "chunk"]
        assert len(chunks) == len(durs), (len(chunks), len(durs))
        for dur, c in list(zip(durs, chunks))[1:]:
            work = float(c["build_s"]) + float(c["exec_s"])
            over.append(dur - work)
            base.append(float(c["exec_s"]))
    switch_s = statistics.median(over)
    frac = switch_s / statistics.median(base)
    return [{
        "metric": "service_overhead_frac",
        "value": frac,
        "unit": "fraction of warm chunk time (target < 0.02)",
        "target": 0.02,
        "nt_chunk": chunk,
        "warm_slices": len(over),
        "chunk_s_median": statistics.median(base),
        # worst case rides along: one bookkeeping outlier must be visible
        # even while the median gates
        "switch_s_max": max(over),
    }, {
        "metric": "service_warm_switch_s",
        "value": switch_s,
        "unit": "s per warm context switch (slice minus its chunk work)",
        "nt_chunk": chunk,
    }]


def run_serving_tier(dims, cpu: bool):
    """Serving-tier legs (ISSUE 17), shared with `bench_all.py`:

    - ``api_roundtrip_s``: median submit+status HTTP round trip against
      a live `serve.JobApiServer` (loopback, ephemeral port) — the
      front-door latency a tenant pays per job, queue-record write and
      journal-derived status read included.
    - ``query_read_s``: cold sub-box read of a committed snapshot over
      HTTP (`serve.SnapshotQueryServer`) — checksum verify + block
      decode + O(box) assembly + npy streaming.
    - ``query_cache_speedup``: cold / warm for the SAME box — the warm
      read answers from the block LRU (decoded once across clients), so
      this must never drop below 1.0 (absolute gate under
      IGG_BENCH_STRICT=1; cold medianed over fresh-cache servers so one
      slow first open cannot fake a speedup)."""
    import io
    import statistics
    import tempfile
    import urllib.request

    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.serve import (
        JobApiServer, SnapshotQueryServer,
    )

    rows = []

    # -- job API round trip (host-only: no scheduler attached) --------------
    d = tempfile.mkdtemp(prefix="bench_serve_api_")
    reps = 20
    with JobApiServer(d) as api:
        u = f"http://{api.host}:{api.port}"
        durs = []
        for i in range(reps):
            rec = json.dumps({"name": f"j{i:03d}", "model": "diffusion3d",
                              "nt": 8, "run": {"nt_chunk": 4}}).encode()
            t0 = time.perf_counter()
            req = urllib.request.Request(u + "/v1/jobs", data=rec,
                                         method="POST")
            with urllib.request.urlopen(req) as r:
                r.read()
            with urllib.request.urlopen(u + f"/v1/jobs/j{i:03d}") as r:
                r.read()
            durs.append(time.perf_counter() - t0)
    rows.append({
        "metric": "api_roundtrip_s",
        "value": statistics.median(durs),
        "unit": "s per submit+status HTTP round trip (loopback)",
        "requests": reps,
    })

    # -- read-side query: cold vs warm over one committed snapshot ----------
    nx = 32 if cpu else 128
    grid = dict(nx=nx, ny=nx, nz=nx, dimx=int(dims[0]), dimy=int(dims[1]),
                dimz=int(dims[2]))
    root = tempfile.mkdtemp(prefix="bench_serve_query_")
    igg.init_global_grid(quiet=True, **grid)
    T = igg.zeros_g() + 1.5
    igg.write_snapshot(root, step=1, state={"T": T})
    gx = int(igg.nx_g())
    igg.finalize_global_grid()
    box = f"1:{gx - 1},1:{gx - 1},0:{nx // 2}"  # spans every x/y block

    def read_once(q):
        u = f"http://{q.host}:{q.port}/v1/snapshots/1/T?box={box}"
        t0 = time.perf_counter()
        with urllib.request.urlopen(u) as r:
            body = r.read()
        dt = time.perf_counter() - t0
        return dt, np.load(io.BytesIO(body))

    cold = []
    for _ in range(5):  # fresh cache per server: honest cold medians
        with SnapshotQueryServer(root) as q:
            dt, arr = read_once(q)
            cold.append(dt)
    with SnapshotQueryServer(root) as q:
        read_once(q)  # fill the LRU
        warm = [read_once(q)[0] for _ in range(9)]
        assert q.cache.stats()["hits"] > 0
    cold_s = statistics.median(cold)
    warm_s = statistics.median(warm)
    rows.append({
        "metric": "query_read_s",
        "value": cold_s,
        "unit": "s per cold sub-box HTTP read (verify+decode+assemble)",
        "box": box,
        "box_bytes": int(arr.nbytes),
    })
    rows.append({
        "metric": "query_cache_speedup",
        "value": cold_s / warm_s,
        "unit": "x cold/warm for the same box (target >= 1.0)",
        "target": 1.0,
        "warm_s": warm_s,
    })
    return rows


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_service_overhead(dims, cpu):
        bench_util.emit(row)
    for row in run_serving_tier(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("service_overhead_frac", "fraction")
