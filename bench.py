"""Benchmark: full BASELINE evidence in ONE driver-parsed JSON line.

Headline metric (BASELINE.md): 3-D heat diffusion cell-updates/s per chip —
the reference achieves ≈0.95e9/GPU (P100, Float64 CuArray broadcasts,
`reference README.md:163-167`, 2x2x2 x 256³ local). Here: 256³/chip, the
whole time loop compiled as one program, Pallas fused step+exchange on TPU.

The single emitted line additionally carries every other BASELINE config and
the roofline accounting the round-2 verdict asked for:

- ``dtype``, ``effective_GBps``, ``pct_hbm_peak`` for the headline row
  (traffic model: the multi-plane kernel reads T (1+2/P)x + Cp 1x and
  writes T 1x);
- ``update_halo_GBps``: the standalone exchange benchmark, inline;
- ``configs``: bf16 diffusion, 2-D diffusion, acoustic (XLA and fused
  Pallas), pseudo-transient Stokes rates, and the f64 note (no native f64
  pipeline on this TPU generation — f64 semantics verified on the x64 CPU
  mesh by tests and `bench_all.py --cpu`);
- ``pallas_check``: non-interpreted kernel validation pass/fail counts
  (`bench_pallas_check.py`) run in a subprocess.

Measurement method: TWO-POINT windows — every rate is the slope
``(t(3c) - t(c)) / 2c`` over two warmed single-call chunk programs, so
fixed per-call costs (dispatch + drain round trips, substantial on
tunneled PJRT transports, absent on a normal TPU host) cancel exactly;
this is the same amortized steady-state quantity the reference's
100k-step wall-clock anchor reports (`reference README.md:163-167`).

Usage: python bench.py            (real TPU)
       python bench.py --cpu      (small smoke run on the 8-device CPU mesh)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import bench_util

# Approximate HBM peak by device kind (GB/s) for the %-of-roofline field.
_HBM_PEAK = {
    "TPU v5 lite": 819.0,   # v5e
    "TPU v5": 2765.0,       # v5p
    "TPU v4": 1228.0,
    "TPU v6 lite": 1640.0,  # Trillium
}


def _hbm_peak(device_kind: str):
    for k, v in _HBM_PEAK.items():
        if device_kind.startswith(k) and not (
                k == "TPU v5" and "lite" in device_kind):
            return v
    return None


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        init_acoustic3d, init_diffusion2d, init_diffusion3d, init_stokes3d,
        make_run, make_run_sr, run_acoustic, run_diffusion, run_stokes,
    )

    nd = len(jax.devices())
    dims3 = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    n_chips = int(np.prod(dims3))
    configs: dict = {}
    notes: dict = {}

    def _grid3(nx, **kw):
        igg.init_global_grid(nx, nx, nx, dimx=dims3[0], dimy=dims3[1],
                             dimz=dims3[2], periodx=1, periody=1, periodz=1,
                             quiet=True, **kw)

    two_point = bench_util.two_point

    def _rate3(nx, steps, dtype, impl=None):
        """cell-updates/s/chip for 3-D diffusion at nx³/chip: two-point
        windows of (steps, 3*steps)."""
        _grid3(nx)
        try:
            T, Cp, p = init_diffusion3d(dtype=dtype)

            def chunk(c):
                run = make_run(p, nt_chunk=c, impl=impl)
                igg.sync(run(T, Cp))

            s = two_point(chunk, steps, 3 * steps)
            cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
            return cells / s / n_chips
        finally:
            igg.finalize_global_grid()

    def _method_note(name):
        # ADVICE r3: distinguish slope-based rates from the inclusive
        # fallback (which re-includes fixed dispatch/drain costs).
        # ``two_point.last`` is reset by part() before each config, so a
        # record here is guaranteed to come from THIS config's final
        # two_point call (ADVICE r4: no stale cross-config inheritance).
        last = bench_util.two_point.last
        if last is not None and last["method"] != "two-point":
            notes[name + "_method"] = last["method"]

    from contextlib import contextmanager

    _VARIANT_VARS = ("IGG_MP_HANDOFF", "IGG_PLANE_RELAY")

    @contextmanager
    def _env0(var):
        """Force ONE variant env var to 0, restoring it afterwards."""
        old = os.environ.get(var)
        os.environ[var] = "0"
        try:
            yield
        finally:
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old

    @contextmanager
    def _variants_off():
        """Force the conservative kernel pipelines, RESTORING any
        user-set values afterwards (an A/B run like IGG_MP_HANDOFF=0
        must survive an unrelated config failure)."""
        from contextlib import ExitStack

        with ExitStack() as stack:
            for v in _VARIANT_VARS:
                stack.enter_context(_env0(v))
            yield

    def part(name, fn, variants=True):
        """Guarded config: a failure in a config that runs the kernel tier
        (``variants=True``) first retries with the round-4 kernel variants
        (window handoff / plane relay) disabled — they are
        Mosaic-unverified on hardware, and a variant rejection must degrade
        the row, not null it — then records the error.  Pure-XLA configs
        pass ``variants=False``: for them the variants-off retry would be
        measurement-identical, so it would only waste wall time and stamp a
        FALSE `_degraded` label on a transient flake."""
        bench_util.two_point.last = None  # per-config method attribution
        try:
            configs[name] = fn()
            _method_note(name)
            return
        except Exception as e:  # pragma: no cover - evidence robustness
            first_err = repr(e)[-250:]
            try:
                if igg.grid_is_initialized():
                    igg.finalize_global_grid()
            except Exception:
                pass
        if not variants:
            configs[name] = None
            notes[name] = first_err
            return
        try:
            with _variants_off():
                configs[name] = fn()
            _method_note(name)
            notes[name + "_degraded"] = (
                "kernel variants disabled after: " + first_err)
        except Exception as e2:  # pragma: no cover
            configs[name] = None
            notes[name] = first_err + " | degraded retry: " + repr(e2)[-250:]
            try:
                if igg.grid_is_initialized():
                    igg.finalize_global_grid()
            except Exception:
                pass

    # --- headline: diffusion3D f32 (BASELINE config 1) ---------------------
    nx, nt = (64, 10) if cpu else (256, 600)
    part("headline", lambda: _rate3(nx, nt, np.float32))
    headline = configs.pop("headline", None)

    # A/B pair for the round-4 window handoff (hardware only): the same
    # config with IGG_MP_HANDOFF=0 runs the pre-handoff pipeline that
    # re-DMAs the 2 overlap planes per window — the traffic model predicts
    # rate ratio (3 + 2/P)/3, and the measured pair either confirms the
    # model or falsifies it in the committed artifact.  The off-leg runs
    # ONLY when the headline actually exercised the handoff tier — with
    # IGG_USE_PALLAS=0 or an ineligible shape the two legs are the
    # identical program, and a ~1.0 ratio would falsely "falsify" the
    # model (and burn a full hardware measurement for nothing).
    def _handoff_active():
        import jax as _jax

        from implicitglobalgrid_tpu.ops.pallas_stencil import mp_handoff
        return (headline is not None
                and os.environ.get("IGG_USE_PALLAS", "1") != "0"
                and bool(mp_handoff(_jax.ShapeDtypeStruct(
                    (nx, nx, nx), np.float32))))

    if not cpu and "headline_degraded" not in notes and _handoff_active():
        def _rate3_handoff_off():
            with _env0("IGG_MP_HANDOFF"):
                return _rate3(nx, nt, np.float32)

        part("diffusion3D_f32_handoff_off", _rate3_handoff_off,
             variants=False)

    # roofline accounting for the headline row (multi-plane fused kernel:
    # T read 1.0x with the VMEM window handoff else (1+2/P)x, + Cp read
    # 1x + T write 1x; XLA path: ~2 passes+Cp)
    from implicitglobalgrid_tpu.ops.pallas_stencil import (
        mp_bytes_per_cell, mp_handoff, mp_planes,
    )

    sds = jax.ShapeDtypeStruct((nx, nx, nx), np.float32)
    P = mp_planes(sds)
    # the traffic model must match how the rate was MEASURED: a degraded
    # headline ran with the kernel variants off
    from contextlib import nullcontext

    with (_variants_off() if "headline_degraded" in notes
          else nullcontext()):
        bytes_per_cell = float(mp_bytes_per_cell(sds))
        notes["window_handoff"] = bool(mp_handoff(sds))
    effective_gbps = (headline * bytes_per_cell / 1e9
                      if headline is not None else None)
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = ""
    peak = _hbm_peak(kind)
    pct_peak = (100.0 * effective_gbps / peak
                if peak and effective_gbps is not None else None)

    # --- other configs (each guarded: a failed section records an error) ---
    import jax.numpy as jnp

    part("diffusion3D_bf16", lambda: _rate3(
        64 if cpu else 256, 10 if cpu else 600, jnp.bfloat16))

    # bf16 with stochastic-rounding storage (ops/precision.py): the
    # accuracy-preserving bf16 mode (bench_f64_accuracy.py's bf16_sr leg);
    # XLA tier with per-step PRNG, so it prices what correct bf16 costs
    # vs the round-to-nearest bandwidth row above.
    def _rate3_sr():
        nxs, c1 = (64, 10) if cpu else (256, 200)
        _grid3(nxs)
        try:
            T, Cp, p = init_diffusion3d(dtype=jnp.bfloat16, sr=True)

            def chunk(c):
                igg.sync(tuple(make_run_sr(p, c)(T, Cp, jnp.int32(0))))

            s = two_point(chunk, c1, 3 * c1)
            cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
            return cells / s / n_chips
        finally:
            igg.finalize_global_grid()

    part("diffusion3D_bf16_sr", _rate3_sr, variants=False)

    def _rate2():
        nx2, c1 = (64, 10) if cpu else (4096, 200)
        dims2 = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 1)))
        igg.init_global_grid(nx2, nx2, 1, dimx=dims2[0], dimy=dims2[1],
                             dimz=1, periodx=1, periody=1, quiet=True)
        try:
            T, Cp, p = init_diffusion2d(dtype=np.float32)

            def chunk(c):
                run_diffusion(T, Cp, p, c, nt_chunk=c)  # drains internally

            s = two_point(chunk, c1, 3 * c1)
            return float(igg.nx_g()) * float(igg.ny_g()) / s / n_chips
        finally:
            igg.finalize_global_grid()

    part("diffusion2D_f32", _rate2)

    def _rate_acoustic(impl, overlap):
        nxa, c1 = (32, 6) if cpu else (192, 100)
        _grid3(nxa)
        try:
            state, p = init_acoustic3d(dtype=np.float32, overlap=overlap)

            def chunk(c):
                run_acoustic(state, p, c, nt_chunk=c, impl=impl)

            s = two_point(chunk, c1, 3 * c1)
            cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
            return cells / s / n_chips
        finally:
            igg.finalize_global_grid()

    part("acoustic3D_xla_overlap_f32",
         lambda: _rate_acoustic("xla", True), variants=False)
    # On --cpu, the Pallas configs would run the interpret-mode EMULATOR:
    # its throughput is not a rate and a fallback row must not burn minutes
    # measuring it (round-4 verdict).  Correctness of the kernels on CPU is
    # covered by the pallas_check subprocess below; the rate rows run only
    # on real hardware.
    _INTERPRET_SKIP = ("skipped on --cpu: interpret-mode emulator "
                       "throughput is not a rate; kernel correctness is "
                       "covered by the pallas_check counts")
    if cpu:
        notes["acoustic3D_pallas_fused_f32"] = _INTERPRET_SKIP
    else:
        part("acoustic3D_pallas_fused_f32",
             lambda: _rate_acoustic("pallas", False))

    def _rate_stokes(impl):
        nxs, c1 = (24, 6) if cpu else (128, 800)
        igg.init_global_grid(nxs, nxs, nxs, dimx=dims3[0], dimy=dims3[1],
                             dimz=dims3[2], quiet=True)
        try:
            state, p = init_stokes3d(dtype=np.float32)

            def chunk(c):
                run_stokes(state, p, c, nt_chunk=c, impl=impl)

            s = two_point(chunk, c1, 3 * c1)
            cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
            return cells / s / n_chips
        finally:
            igg.finalize_global_grid()

    part("stokes3D_pt_xla_f32", lambda: _rate_stokes("xla"),
         variants=False)
    if cpu:
        notes["stokes3D_pt_f32"] = _INTERPRET_SKIP
    else:
        part("stokes3D_pt_f32", lambda: _rate_stokes("pallas"))

        # A/B pair for the round-4 plane relay: IGG_PLANE_RELAY=0 re-reads
        # each field's [i-1] plane from HBM (15 read streams + 7 writes =
        # 22 passes vs 18 with the relay — predicted ratio 22/18).
        # Skipped when the env already disables the relay: both legs
        # would run the identical program and fake a ~1.0 ratio.
        def _rate_stokes_relay_off():
            with _env0("IGG_PLANE_RELAY"):
                return _rate_stokes("pallas")

        if os.environ.get("IGG_PLANE_RELAY", "1") != "0":
            part("stokes3D_pt_relay_off_f32", _rate_stokes_relay_off,
                 variants=False)
    notes["kernel_tier"] = (
        "acoustic3D_pallas_fused_f32 / stokes3D_pt_f32 run the fused "
        "Pallas passes (pallas_wave/pallas_stokes; rate rows are "
        "hardware-only — skipped on --cpu); the *_xla_* rows are the "
        "pure-XLA formulations")

    # --- HBM calibration: measured achievable bandwidth ---------------------
    # A fused XLA triad (2 reads + 1 write over a large array) gives the
    # PRACTICAL bandwidth ceiling of this chip, so the roofline percentage
    # can be computed against measured reality instead of only the nominal
    # datasheet peak (round-3 verdict: the headline exceeded the nominal
    # roofline; nominal clocks and DMA efficiency are not ground truth).
    part("hbm_triad_GBps", lambda: bench_util.measure_triad_gbps(
        (1 << 20) if cpu else (1 << 27)),  # 512 MB f32 on TPU
         variants=False)

    # --- update_halo effective GB/s (BASELINE's first named metric) --------
    def _halo_gbps():
        nxh, c1 = (64, 5) if cpu else (512, 60)
        _grid3(nxh)
        try:
            from implicitglobalgrid_tpu.models.common import make_state_runner

            gg = igg.global_grid()
            hw = [int(h) for h in gg.halowidths]
            A = igg.ones_g((nxh, nxh, nxh), np.float32)

            def chunk(c):
                run = make_state_runner(
                    lambda s: (igg.local_update_halo(s[0]),), (3,),
                    nt_chunk=c, key="bench_halo")
                igg.sync(run(A))

            s = two_point(chunk, c1, 3 * c1)
            bytes_per_call = sum(4 * hw[d] * nxh * nxh * 4 for d in range(3))
            return bytes_per_call / s / 1e9
        finally:
            igg.finalize_global_grid()

    part("update_halo_GBps", _halo_gbps)

    # --- kernel validation counts (non-interpreted on TPU) -----------------
    pallas_check = None
    try:
        proc = subprocess.run(
            [sys.executable, "bench_pallas_check.py"]
            + (["--cpu"] if cpu else []),
            capture_output=True, text=True, timeout=600,
            env=bench_util.child_env(),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for ln in proc.stdout.splitlines():
            ln = ln.strip()
            if ln.startswith("{"):
                row = json.loads(ln)
                if row.get("metric") == "pallas_checks_passed":
                    pallas_check = {"passed": int(row["value"]),
                                    "total": int(row["unit"].split()[-1])}
        if pallas_check is None:  # crashed before the summary row
            notes["pallas_check"] = (
                f"no summary row; rc={proc.returncode}; "
                + (proc.stderr or proc.stdout or "")[-400:])
    except Exception as e:  # pragma: no cover
        notes["pallas_check"] = repr(e)[-300:]

    notes["method"] = (
        "two-point: rate = (c2-c1)/(t(c2)-t(c1)) over warmed single-call "
        "chunk windows (fixed dispatch/drain costs cancel); see module "
        "docstring")
    pct_meas = None
    if configs.get("hbm_triad_GBps") and effective_gbps is not None:
        pct_meas = 100.0 * effective_gbps / configs["hbm_triad_GBps"]

    # A/B variant deltas vs the traffic-model predictions (round-4
    # verdict: the measured ratio must confirm the 3+2/P -> 3.0 model)
    ab = {}
    off = configs.get("diffusion3D_f32_handoff_off")
    # a degraded on-row itself ran with the variants off — a ratio against
    # it would falsely "falsify" the model, so skip the pair instead
    if headline and off and "headline_degraded" not in notes:
        ab["window_handoff"] = {
            "measured_ratio": headline / off,
            "predicted_ratio": (3.0 + 2.0 / P) / 3.0,
            "note": "headline (handoff on) / IGG_MP_HANDOFF=0",
        }
    s_on = configs.get("stokes3D_pt_f32")
    s_off = configs.get("stokes3D_pt_relay_off_f32")
    if s_on and s_off and "stokes3D_pt_f32_degraded" not in notes:
        ab["plane_relay_stokes"] = {
            "measured_ratio": s_on / s_off,
            "predicted_ratio": 22.0 / 18.0,
            "note": "stokes fused (relay on) / IGG_PLANE_RELAY=0",
        }
    if pct_peak is not None and pct_peak > 100:
        notes["roofline"] = (
            "pct_hbm_peak>100 against the NOMINAL datasheet peak: compare "
            "pct_hbm_measured (vs the in-run triad calibration) — if that "
            "is also >100 the 3+2/P traffic model overcounts; see "
            "docs/performance.md roofline section")
    baseline = 0.95e9  # reference per-GPU rate (f64 P100 — BASELINE.md)
    bench_util.emit({
        "metric": "diffusion3D_cell_updates_per_s_per_chip",
        "value": headline,
        "unit": "cell-updates/s/chip",
        # LOUD degradation flag (round-4 verdict): True whenever ANY config
        # silently fell back to the conservative kernels — a reader must
        # not have to dig through notes.*_degraded to learn the headline
        # did not run the handoff tier.
        "degraded": any(k.endswith("_degraded") for k in notes),
        "vs_baseline": (headline / baseline
                        if headline is not None else None),
        "dtype": "f32",
        "baseline_note": "reference anchor is f64 on P100; this row is f32 "
                         "(no native f64 pipeline on this TPU generation; "
                         "measured substitution cost: 1.8e-7 max-rel after "
                         "400 steps — bench_f64_accuracy.py, docs/"
                         "performance.md)",
        "effective_GBps": effective_gbps,
        "bytes_per_cell_model": bytes_per_cell,
        "mp_planes_P": P,
        "hbm_peak_GBps": peak,
        "pct_hbm_peak": pct_peak,
        "pct_hbm_measured": pct_meas,
        "configs": configs,
        "variant_ab": ab or None,
        "pallas_check": pallas_check,
        "notes": notes or None,
    })


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries(
            "diffusion3D_cell_updates_per_s_per_chip", "cell-updates/s/chip"
        )
