"""Benchmark: 3-D heat diffusion cell-updates/s per chip.

Headline metric from BASELINE.md: the reference achieves ≈0.95e9
cell-updates/s per GPU (P100, Float64 CuArray broadcasts, incl. in-situ vis —
`reference README.md:163-167`, 510³ global / 2x2x2 x 256³ local, nt=1e5).

Here: 256³ per chip (BASELINE.json config "diffusion3D 256³/chip"), whole time
loop compiled as one XLA program (lax.fori_loop + inline halo exchange).
Prints ONE JSON line.

Usage: python bench.py            (real TPU, f32, 256³/chip)
       python bench.py --cpu      (small smoke run on CPU)
"""

from __future__ import annotations

import sys

import bench_util


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import init_diffusion3d, make_run

    if cpu:
        nx = 64
        nt = 30
        dims = (2, 2, 2)
    else:
        nx = 256
        nt = 2000
        nd = len(jax.devices())
        dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))

    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1], dimz=dims[2],
                         periodx=1, periody=1, periodz=1, quiet=True)
    n_chips = int(np.prod(dims))
    T, Cp, p = init_diffusion3d(dtype=np.float32)
    chunk = max(1, nt // 4)
    run = make_run(p, nt_chunk=chunk)

    # warmup/compile; igg.sync is a data-dependent drain (block_until_ready
    # can return early on the axon tunnel)
    igg.sync(run(T, Cp))

    igg.tic()
    Tc = T
    steps = 0
    while steps < nt:
        Tc, _ = run(Tc, Cp)
        steps += chunk
    t = igg.toc(sync_on=Tc)

    cells = float(igg.nx_g()) * float(igg.ny_g()) * float(igg.nz_g())
    rate = cells * steps / t
    rate_per_chip = rate / n_chips
    baseline = 0.95e9  # per-GPU reference throughput (BASELINE.md)
    bench_util.emit({
        "metric": "diffusion3D_cell_updates_per_s_per_chip",
        "value": rate_per_chip,
        "unit": "cell-updates/s/chip",
        "vs_baseline": rate_per_chip / baseline,
    })
    igg.finalize_global_grid()


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries(
            "diffusion3D_cell_updates_per_s_per_chip", "cell-updates/s/chip"
        )
