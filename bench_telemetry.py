"""Benchmark: telemetry overhead of the supervised driver.

The observability layer (ISSUE 3) instruments the resilient driver's
per-chunk host path — flight-recorder JSONL events (run/chunk/cache
records, flushed per line), metrics-registry counter bumps, and the
runner-cache notes — all strictly host-side (the HLO-level guarantee that
the chunk PROGRAM is unchanged lives in tests/test_hlo_audit.py). This leg
bounds what that instrumentation costs at the driver's operating point,
against the <2% gate (ISSUE 3 acceptance), with two measurements:

- ``value`` (gated): the DETERMINISTIC accounting — the microbenchmarked
  cost of one flushed recorder event (including its registry bumps and
  the open/close amortized) times the events a supervised run actually
  emits, over the run's median telemetry-off time. This measures the
  exact marginal work telemetry adds, reproducibly.
- ``ab_median_frac`` (corroboration): an end-to-end telemetry-on vs
  telemetry-off `run_resilient` A/B — alternating-order interleaved
  pairs, median of the per-pair fractional differences. On the shared
  CPU mesh the per-run jitter (±30-100% observed, `ab_noise_iqr`) is
  orders of magnitude above the ~0.1% signal, so this corroborates that
  the cost is lost in the noise rather than resolving it; on quiet
  hardware the two figures converge.

Like the guard-overhead leg (bench_resilience.py) this is INCLUSIVE
per-chunk cost, not a two-point slope: the overhead is per-chunk fixed,
which a slope over two window sizes would cancel by construction.

Usage: python bench_telemetry.py          (real chip)
       python bench_telemetry.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import itertools
import os
import sys
import tempfile

import bench_util


def telemetry_overhead_rows(nx: int, nt_chunk: int, n_chunks: int = 3,
                            reps: int = 10):
    """One row on the CURRENT grid (caller owns init/finalize): the
    telemetry overhead fraction of a supervised run (see module
    docstring for the two estimators)."""
    import statistics
    import time

    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, init_diffusion3d,
    )

    T, Cp, p = init_diffusion3d(dtype=np.float32)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    state = {"T": T, "Cp": Cp}
    nt = nt_chunk * n_chunks
    key = ("bench_telemetry", nx, nt_chunk)
    tmp = tempfile.mkdtemp(prefix="igg_bench_tel_")
    seq = itertools.count()

    def run_off():
        igg.run_resilient(step, state, nt, nt_chunk=nt_chunk, key=key)

    def run_on():
        igg.start_flight_recorder(
            os.path.join(tmp, f"run{next(seq)}.jsonl"))
        try:
            igg.run_resilient(step, state, nt, nt_chunk=nt_chunk, key=key)
        finally:
            igg.stop_flight_recorder()

    # warm: compile once (shared key), first JSONL file created
    run_off()
    run_on()

    # --- end-to-end A/B (corroboration) --------------------------------
    # alternating-order interleaved pairs cancel position bias; the
    # median of pair diffs is the only estimator that does not turn into
    # a coin flip at a sub-0.1% effect under multi-10% machine jitter
    times = {"off": [], "on": []}
    pair_fracs = []
    for r in range(reps):
        order = [(run_off, "off"), (run_on, "on")] if r % 2 == 0 \
            else [(run_on, "on"), (run_off, "off")]
        d = {}
        for fn, slot in order:
            igg.tic()
            fn()
            d[slot] = igg.toc()
            times[slot].append(d[slot])
        pair_fracs.append((d["on"] - d["off"]) / d["off"])
    pair_fracs.sort()
    iqr = (pair_fracs[(3 * len(pair_fracs)) // 4]
           - pair_fracs[len(pair_fracs) // 4])

    # --- deterministic accounting (the gated figure) -------------------
    # one flushed event write (registry bumps included via the same
    # hooks), open/close amortized over the probe; scaled by the events a
    # real run emits over the run's median telemetry-off time
    n_events = len(igg.read_flight_events(
        os.path.join(tmp, "run0.jsonl")))
    probe = os.path.join(tmp, "probe.jsonl")
    n_probe = 2000
    t0 = time.monotonic()
    igg.start_flight_recorder(probe)
    for i in range(n_probe):
        igg.record_event("chunk", chunk=i, step_begin=0, step_end=nt_chunk,
                         n=nt_chunk, ok=True, reasons=[], build_s=1e-3,
                         exec_s=0.1)
    igg.stop_flight_recorder()
    per_event_s = (time.monotonic() - t0) / n_probe
    t_off_med = statistics.median(times["off"])
    accounted = per_event_s * n_events / t_off_med

    return [{
        "metric": "telemetry_overhead_frac",
        "value": accounted,
        "unit": "fraction of run time, deterministic per-event accounting "
                "(target < 0.02)",
        "target": 0.02,
        "nt": nt,
        "nt_chunk": nt_chunk,
        "events_per_run": n_events,
        "per_event_write_s": per_event_s,
        "off_run_s_median": t_off_med,
        "on_run_s_median": statistics.median(times["on"]),
        "ab_median_frac": statistics.median(pair_fracs),
        "ab_noise_iqr": iqr,
        "note": "ab_median_frac is the end-to-end A/B (median of "
                "alternating interleaved pairs); on the shared-CPU mesh "
                "its noise floor (ab_noise_iqr) sits far above the "
                "accounted cost, corroborating the gate rather than "
                "resolving it",
    }]


def live_plane_rows(t_ref_s: float, n_boundaries: int = 3):
    """The LIVE observability plane's cost (ISSUE 18), host-only:

    - ``live_tail_overhead_frac`` (gated < 2%): the DETERMINISTIC
      per-boundary accounting — one full in-process alert cadence
      (append the driver's ~4 boundary events, drain the tail, evaluate
      the default rule pack over a fresh snapshot) microbenchmarked,
      times the boundaries a reference run crosses, over that run's
      telemetry-off wall time (``t_ref_s``, from the telemetry leg).
      This is exactly what `MeshScheduler(alerts=True)` adds per slice.
    - ``observe_roundtrip_s``: one ``GET /v1/observe`` against a live
      `ObserveServer` (poll + derive + serialize), median.
    - ``events_stream_lag_s``: append-to-NDJSON-line latency through an
      open ``GET /v1/events`` stream (the tail cadence bound), median.

    The latter two ride the perfdb trajectory (no absolute gate — they
    are loopback-HTTP latencies, machine-dependent by nature)."""
    import json
    import statistics
    import time
    import urllib.request

    from implicitglobalgrid_tpu.serve import ObserveServer
    from implicitglobalgrid_tpu.telemetry.live import (
        AlertEngine, LiveAggregate,
    )

    tmp = tempfile.mkdtemp(prefix="igg_bench_live_")
    path = os.path.join(tmp, "flight_j.jsonl")
    state = {"t": 100.0, "seq": 0}

    def append(kind, **kw):
        state["t"] += 0.05
        rec = {"t": state["t"], "kind": kind, "run": "j", "pid": 1,
               "proc": 0, "seq": state["seq"], **kw}
        state["seq"] += 1
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def boundary(i):
        # the supervised driver's per-boundary emissions
        append("chunk", chunk=i, step_begin=4 * i, step_end=4 * i + 4,
               n=4, ok=True, reasons=[], build_s=1e-3, exec_s=0.1)
        append("deadline_slack", step=4 * i + 4, slack_s=100.0)
        append("checkpoint", step=4 * i + 4, seconds=0.01)
        append("snapshot_submit", step=4 * i + 4, bytes=1 << 20)

    append("recorder_open", wall=5000.0)
    live = LiveAggregate(tmp)
    eng = AlertEngine()  # the default pack — the scheduler's cadence
    live.poll()

    # --- deterministic per-boundary accounting (the gated figure) ------
    n_probe = 300
    t0 = time.monotonic()
    for i in range(n_probe):
        boundary(i)
        live.poll()
        eng.evaluate(live.snapshot())
    per_boundary_s = (time.monotonic() - t0) / n_probe
    frac = per_boundary_s * n_boundaries / t_ref_s

    rows = [{
        "metric": "live_tail_overhead_frac",
        "value": frac,
        "unit": "fraction of run time, deterministic per-boundary "
                "accounting (target < 0.02)",
        "target": 0.02,
        "per_boundary_s": per_boundary_s,
        "events_per_boundary": 4,
        "boundaries_per_run": n_boundaries,
        "ref_run_s": t_ref_s,
        "note": "one in-process alert cadence (tail drain + default "
                "rule pack over a fresh snapshot) per chunk boundary — "
                "what MeshScheduler(alerts=True) adds per slice",
    }]

    # --- the HTTP surface ----------------------------------------------
    with ObserveServer(tmp) as obs:
        u = f"http://{obs.host}:{obs.port}"
        rts = []
        for _ in range(15):
            t0 = time.monotonic()
            with urllib.request.urlopen(u + "/v1/observe",
                                        timeout=10) as r:
                cursor = json.loads(r.read())["cursor"]
            rts.append(time.monotonic() - t0)
        lags = []
        stream = urllib.request.urlopen(
            u + f"/v1/events?since={cursor}&timeout_s=30&heartbeat_s=10",
            timeout=35)
        try:
            for i in range(5):
                t0 = time.monotonic()
                append("chunk", chunk=n_probe + i, n=4, ok=True,
                       reasons=[], build_s=1e-3, exec_s=0.1,
                       step_begin=0, step_end=4)
                while True:
                    e = json.loads(stream.readline())
                    if e.get("kind") != "heartbeat":
                        lags.append(time.monotonic() - t0)
                        break
        finally:
            stream.close()
    rows.append({
        "metric": "observe_roundtrip_s",
        "value": statistics.median(rts),
        "unit": "s (GET /v1/observe: poll + derive + serialize, median "
                "of 15 loopback round trips)",
        "reps": len(rts),
    })
    rows.append({
        "metric": "events_stream_lag_s",
        "value": statistics.median(lags),
        "unit": "s (flight append -> NDJSON line on an open /v1/events "
                "stream, median of 5; floor = the 50 ms tail cadence)",
        "reps": len(lags),
    })
    return rows


def tracing_rows(t_ref_s: float, n_events: int):
    """Distributed tracing's cost (ISSUE 20), host-only:

    - ``trace_ctx_overhead_frac`` (gated < 2%): the DETERMINISTIC
      accounting — the recorder's trace stamp is two dict inserts per
      event (`FlightRecorder.trace`), measured as the per-event delta
      between a traced and an untraced recorder over interleaved
      flushed-write probes, times the events a supervised run emits,
      over the telemetry leg's off-run time. The delta is clamped at
      zero: the stamp costs nanoseconds against a ~10 us flushed write,
      so the raw difference (recorded alongside) can go negative under
      machine jitter.
    - ``otlp_export_s``: `export_otlp` wall time on a 10k-event traced
      stream (journal-style minted span ids + flight-style synthesized
      ones) — the post-hoc export an operator runs per incident; perfdb
      trajectory, no absolute gate."""
    import json
    import statistics
    import time

    from implicitglobalgrid_tpu.telemetry import (
        FlightRecorder, TraceContext, export_otlp,
    )

    tmp = tempfile.mkdtemp(prefix="igg_bench_tracing_")
    tr = TraceContext.new().child()  # the job root, as the scheduler sets
    n_probe = 2000
    seq = itertools.count()

    def probe(trace):
        rec = FlightRecorder(os.path.join(tmp, f"p{next(seq)}.jsonl"),
                             run_id="probe")
        rec.trace = trace
        t0 = time.monotonic()
        for i in range(n_probe):
            rec.event("chunk", chunk=i, step_begin=0, step_end=4, n=4,
                      ok=True, reasons=[], build_s=1e-3, exec_s=0.1)
        dt = time.monotonic() - t0
        rec.close()
        return dt / n_probe

    offs, ons = [], []
    for r in range(5):  # alternating order cancels position bias
        for trace, acc in ([(None, offs), (tr, ons)] if r % 2 == 0
                           else [(tr, ons), (None, offs)]):
            acc.append(probe(trace))
    per_off = statistics.median(offs)
    per_on = statistics.median(ons)
    delta = per_on - per_off
    rows = [{
        "metric": "trace_ctx_overhead_frac",
        "value": max(0.0, delta) * n_events / t_ref_s,
        "unit": "fraction of run time, deterministic per-event "
                "accounting (target < 0.02)",
        "target": 0.02,
        "per_event_off_s": per_off,
        "per_event_traced_s": per_on,
        "per_event_delta_s": delta,
        "events_per_run": n_events,
        "ref_run_s": t_ref_s,
        "note": "the stamp is two dict inserts before a flushed JSONL "
                "write; span ids are synthesized at export, never on "
                "the hot path",
    }]

    # --- the post-hoc OTLP export on a 10k-event traced stream ---------
    path = os.path.join(tmp, "otlp_stream.jsonl")
    n_stream = 10_000
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "recorder_open", "wall": 5000.0,
                            "t": 100.0, "run": "j", "pid": 1, "proc": 0,
                            "seq": 0}) + "\n")
        for i in range(n_stream):
            e = {"t": 100.0 + 0.01 * i,
                 "kind": "slice" if i % 2 == 0 else "chunk",
                 "run": "j", "pid": 1, "proc": 0, "seq": i + 1,
                 "trace_id": tr.trace_id, "parent_span_id": tr.span_id,
                 "chunk": i, "exec_s": 0.005, "ok": True}
            if i % 2 == 0:  # journal-style events mint their span id
                e["span_id"] = f"{i + 1:016x}"
            f.write(json.dumps(e) + "\n")
    out = os.path.join(tmp, "spans.json")
    t0 = time.monotonic()
    export_otlp(path, out)
    otlp_s = time.monotonic() - t0
    with open(out) as f:
        n_spans = sum(len(ss["spans"])
                      for rs in json.load(f)["resourceSpans"]
                      for ss in rs["scopeSpans"])
    rows.append({
        "metric": "otlp_export_s",
        "value": otlp_s,
        "unit": "s (export_otlp on a 10k-event traced stream: read + "
                "encode + write)",
        "events": n_stream + 1,
        "spans": n_spans,
    })
    return rows


def run_telemetry_overhead(dims, cpu: bool):
    """The canonical leg: init its own grid over ``dims``, measure,
    finalize, return the rows. Shared by this script's __main__ and
    `bench_all.py` so the config stays in ONE place."""
    import implicitglobalgrid_tpu as igg

    # per-chunk fixed cost: chunks long enough that call jitter does not
    # swamp the sub-1% signal (same sizing rationale as bench_resilience)
    nx, nt_chunk = (32, 60) if cpu else (256, 200)
    igg.init_global_grid(nx, nx, nx, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        return telemetry_overhead_rows(nx, nt_chunk)
    finally:
        igg.finalize_global_grid()


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    rows = run_telemetry_overhead(dims, cpu)
    for row in rows:
        bench_util.emit(row)
    t_ref = next(r["off_run_s_median"] for r in rows
                 if r["metric"] == "telemetry_overhead_frac")
    n_chunks = next(r["nt"] // r["nt_chunk"] for r in rows
                    if r["metric"] == "telemetry_overhead_frac")
    for row in live_plane_rows(t_ref, n_boundaries=n_chunks):
        bench_util.emit(row)
    n_events = next(r["events_per_run"] for r in rows
                    if r["metric"] == "telemetry_overhead_frac")
    for row in tracing_rows(t_ref, n_events):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries("telemetry_overhead_frac", "fraction")
