"""Benchmark: ensemble axis — per-member step time vs solo (ISSUE 12).

The ensemble batches E scenario members through ONE compiled chunk (vmap
over the member axis, `models.common.make_state_runner(ensemble=E)`), and
jax's collective batching keeps the chunk's ppermute/psum COUNT flat in E
while every payload scales E x. The economics: per-member step time =
(E·compute + comm) / E = compute + comm/E — the exchange cost amortizes
over the batch, so per-member time approaches (from above or below,
depending on cache pressure) the solo step and the latency-bound share
vanishes as 1/E. This bench measures exactly that claim on the live mesh:

- ``ensemble_per_member_speedup_E{4,8,16}``: solo step time / per-member
  step time at E (>= 1 means a member inside the batch is no slower than
  a solo run — the amortization paid for the batching). Gated by the
  perfdb trailing-median check (higher-better by name).
- ``ensemble_permutes_flat_ok``: ABSOLUTE gate — the compiled guarded
  chunk at E=8 carries exactly the E=1 permute count and the same single
  guard psum (collective count independent of ensemble size, proven on
  the compiled program, not the plan).
- ``ensemble_amortization_ok``: ABSOLUTE gate — every measured per-member
  step sits within 10% of the solo step (speedup >= 0.9), the ISSUE-12
  acceptance bar.

Usage: python bench_ensemble.py          (real chip)
       python bench_ensemble.py --cpu    (8-device virtual CPU mesh)
"""

from __future__ import annotations

import sys

import bench_util

MEMBERS = (4, 8, 16)


def ensemble_rows(nx: int, c1: int, members=MEMBERS, dtype=None):
    """Measure per-member-vs-solo rows + the permute-flat gate on the
    CURRENT grid (caller owns init/finalize). Diffusion f32: the flagship
    workload, one exchanged field — the leanest program whose exchange
    the ensemble can amortize."""
    import numpy as np

    import implicitglobalgrid_tpu as igg
    from implicitglobalgrid_tpu.analysis import parse_program
    from implicitglobalgrid_tpu.models import (
        diffusion_step_local, ensemble_state, init_diffusion3d, make_run,
    )
    from implicitglobalgrid_tpu.runtime.health import make_guarded_runner

    dtype = dtype or np.float32
    T, Cp, p = init_diffusion3d(dtype=dtype)
    rows = []

    def timed(E):
        if E is None:
            args = (T, Cp)
        else:
            args = (ensemble_state(T, E, perturb=0.01),
                    ensemble_state(Cp, E))

        def chunk(c):
            run = make_run(p, c, 3, "xla", ensemble=E)
            igg.sync(run(*args))

        # reps=4 min-kept: same contention-robust estimator as the
        # coalescing A/B (bench_halo) — the shared-core mesh spikes
        # individual windows
        return bench_util.two_point(chunk, c1, 3 * c1, reps=4)

    t_solo = timed(None)
    rows.append({
        "metric": "ensemble_solo_step_s",
        "value": t_solo,
        "unit": "s/step (solo reference for the speedup rows)",
    })
    speedups = {}
    for E in members:
        t_e = timed(E)
        per_member = t_e / E
        speedups[E] = t_solo / per_member
        rows.append({
            "metric": f"ensemble_per_member_speedup_E{E}",
            "value": speedups[E],
            "unit": "x (solo_step_s / per_member_step_s; >=1 = batched "
                    "member no slower than solo)",
            "per_member_step_s": per_member,
            "ensemble_step_s": t_e,
            "solo_step_s": t_solo,
        })

    # absolute gate: compiled collective count flat in E — parse the
    # GUARDED chunk (the program the service actually dispatches: halo
    # permutes + the one stats psum) at E=1 and E=8
    def step(s):
        return (diffusion_step_local(s[0], s[1], p, "xla"), s[1])

    counts = {}
    for E in (1, 8):
        run = make_guarded_runner(step, (3, 3), nt_chunk=1,
                                  key=("bench_ens_gate", nx), ensemble=E)
        args = (ensemble_state(T, E), ensemble_state(Cp, E))
        ir = parse_program(run, *args)
        counts[E] = (len(ir.permutes), len(ir.all_reduces))
    flat_ok = counts[1] == counts[8] and counts[1][1] == 1
    rows.append({
        "metric": "ensemble_permutes_flat_ok",
        "value": 1.0 if flat_ok else 0.0,
        "unit": "bool (1 = compiled permute+psum count at E=8 equals E=1)",
        "permutes_E1": counts[1][0], "permutes_E8": counts[8][0],
        "psums_E1": counts[1][1], "psums_E8": counts[8][1],
    })
    amort_ok = all(s >= 0.9 for s in speedups.values())
    rows.append({
        "metric": "ensemble_amortization_ok",
        "value": 1.0 if amort_ok else 0.0,
        "unit": "bool (1 = per-member step within 10% of solo at every E)",
        "speedups": {str(k): v for k, v in speedups.items()},
    })
    return rows


def run_ensemble_ab(dims, cpu: bool):
    """The canonical ensemble leg: init its own all-periodic grid over
    ``dims``, measure, finalize, return the rows. Shared by this script's
    __main__ and `bench_all.py` so the config stays in ONE place.

    Block 16^3 on the CPU mesh: small enough that E=16 x 8 shards stays
    cache-resident, large enough that the exchange is a visible share —
    the regime the amortization claim is about."""
    import implicitglobalgrid_tpu as igg

    nx_e, c_e = (16, 8) if cpu else (128, 20)
    igg.init_global_grid(nx_e, nx_e, nx_e, dimx=dims[0], dimy=dims[1],
                         dimz=dims[2], periodx=1, periody=1, periodz=1,
                         quiet=True)
    try:
        return ensemble_rows(nx_e, c_e)
    finally:
        igg.finalize_global_grid()


def main() -> None:
    cpu = "--cpu" in sys.argv
    if cpu:
        import os

        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    import implicitglobalgrid_tpu as igg

    nd = len(jax.devices())
    dims = tuple(int(d) for d in igg.dims_create(nd, (0, 0, 0)))
    for row in run_ensemble_ab(dims, cpu):
        bench_util.emit(row)


if __name__ == "__main__":
    if bench_util.is_child():
        main()
    else:
        bench_util.run_with_retries(
            "ensemble_per_member_speedup_E8",
            "x (solo_step_s / per_member_step_s)")
