"""Device-mesh construction — the TPU analog of `MPI_Cart_create`.

Where the reference creates a Cartesian MPI communicator
(`/root/reference/src/init_global_grid.jl:100`), the TPU framework creates a
`jax.sharding.Mesh` with axes ``("gx", "gy", "gz")`` over the pod's devices.
The reference's ``reorder`` argument (let MPI renumber ranks for locality) maps
to letting `mesh_utils.create_device_mesh` pick an ICI-contiguous device
layout; ``reorder=0`` keeps plain device order.
"""

from __future__ import annotations

import numpy as np

from ..utils.exceptions import InvalidArgumentError, NotLoadedError
from .topology import AXIS_NAMES, NDIMS

__all__ = ["build_mesh", "resolve_devices"]


def resolve_devices(device_type: str, platform_override: str | None = None):
    """Return the JAX device list for ``device_type`` ("auto" picks the default
    backend; "none" forces CPU — the analog of the reference's CPU-only mode,
    `init_global_grid.jl:78`)."""
    import jax

    if platform_override:
        device_type = platform_override
    if device_type in ("auto", ""):
        return jax.devices(), jax.default_backend()
    if device_type == "none":
        device_type = "cpu"
    try:
        devs = jax.devices(device_type)
    except RuntimeError as e:
        raise NotLoadedError(
            f"device_type {device_type!r}: no functional JAX backend for this platform ({e})."
        ) from e
    return devs, device_type


def build_mesh(dims, devices, reorder: int = 1):
    """Create the 3-axis mesh from ``devices``.

    - If the grid uses ALL devices and ``reorder`` is set, delegate to
      `mesh_utils.create_device_mesh` so the mesh layout follows the physical
      ICI topology (nearest mesh neighbors = nearest ICI neighbors, which is
      what makes the per-axis `ppermute` halo exchange ride single ICI hops).
    - Otherwise (a subset of devices, or ``reorder=0``), reshape in plain
      enumeration order — the analog of `MPI.Cart_create(..., reorder=0)`.
    """
    import jax

    dims = tuple(int(d) for d in dims)
    if len(dims) != NDIMS:
        raise InvalidArgumentError(f"dims must have {NDIMS} entries.")
    n = int(np.prod(dims))
    if n > len(devices):
        raise InvalidArgumentError(
            f"Cannot create a {dims[0]}x{dims[1]}x{dims[2]} grid: requires {n} device(s), "
            f"but only {len(devices)} available."
        )
    use = devices[:n]
    dev_arr = None
    if reorder and n == len(devices) and n > 1:
        try:
            from jax.experimental import mesh_utils

            dev_arr = mesh_utils.create_device_mesh(dims, devices=use)
        except Exception:
            dev_arr = None  # fall back to plain order below
    if dev_arr is None:
        dev_arr = np.array(use, dtype=object).reshape(dims)
    return jax.sharding.Mesh(dev_arr, AXIS_NAMES)
