"""Device-mesh construction — the TPU analog of `MPI_Cart_create`.

Where the reference creates a Cartesian MPI communicator
(`/root/reference/src/init_global_grid.jl:100`), the TPU framework creates a
`jax.sharding.Mesh` with axes ``("gx", "gy", "gz")`` over the pod's devices.
The reference's ``reorder`` argument (let MPI renumber ranks for locality) maps
to letting `mesh_utils.create_device_mesh` pick an ICI-contiguous device
layout; ``reorder=0`` keeps plain device order.

Multi-slice deployments (the reference's multi-node story — it runs over any
MPI interconnect, `/root/reference/README.md:6-8`): when the devices span
several TPU slices, the grid axes named in ``IGG_TPU_DCN_AXES`` are laid out
so that slice boundaries fall ONLY between blocks along those axes — every
other axis' `ppermute` rides ICI; only the designated axes' boundary permutes
cross DCN. `arrange_devices` implements the layout (hybrid
`mesh_utils.create_hybrid_device_mesh` on real hardware, a deterministic
block arrangement otherwise/as fallback).
"""

from __future__ import annotations

import numpy as np

from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError, NotLoadedError
from .topology import AXIS_NAMES, NDIMS

__all__ = ["build_mesh", "resolve_devices", "arrange_devices",
           "controller_coords_of"]


def resolve_devices(device_type: str, platform_override: str | None = None):
    """Return the JAX device list for ``device_type`` ("auto" picks the default
    backend; "none" forces CPU — the analog of the reference's CPU-only mode,
    `init_global_grid.jl:78`)."""
    import jax

    if platform_override:
        device_type = platform_override
    if device_type in ("auto", ""):
        return jax.devices(), jax.default_backend()
    if device_type == "none":
        device_type = "cpu"
    try:
        devs = jax.devices(device_type)
    except RuntimeError as e:
        raise NotLoadedError(
            f"device_type {device_type!r}: no functional JAX backend for this platform ({e})."
        ) from e
    return devs, device_type


def _slice_groups(devices):
    """Group devices into DCN granules: by ``slice_index`` when the runtime
    exposes distinct slices, else by ``process_index`` (the DCN boundary in
    multi-host CPU/GPU deployments — and in multi-process runs where every
    device reports slice 0). Returns a list of lists, sorted by key."""
    for attr in ("slice_index", "process_index"):
        groups: dict = {}
        for d in devices:
            groups.setdefault(getattr(d, attr, 0), []).append(d)
        if len(groups) > 1:
            return [groups[k] for k in sorted(groups)]
    return [list(devices)]


def _dcn_factorization(dims, dcn_axes, n_slices):
    """Split ``dims`` into per-axis (dcn, ici) factors: the product of the
    dcn factors over ``dcn_axes`` must equal ``n_slices``, each dividing its
    axis' dims, factors as balanced as possible (fewest DCN boundary
    crossings per axis)."""
    axis_ids = {"x": 0, "y": 1, "z": 2}
    sel = [axis_ids[a] for a in dcn_axes]
    best = None

    def search(i, rem, acc):
        nonlocal best
        if i == len(sel):
            if rem == 1:
                cand = tuple(acc)
                score = (max(cand) - min(cand), max(cand))
                if best is None or score < best[0]:
                    best = (score, cand)
            return
        for f in range(1, min(int(dims[sel[i]]), rem) + 1):
            if rem % f == 0 and int(dims[sel[i]]) % f == 0:
                search(i + 1, rem // f, acc + [f])

    search(0, int(n_slices), [])
    if best is None:
        raise IncoherentArgumentError(
            f"Cannot distribute {n_slices} slice(s) over DCN axes {dcn_axes} "
            f"with dims {tuple(int(x) for x in dims)}: the slice count must "
            "factor into the dims of the designated axes."
        )
    dcn = [1, 1, 1]
    for d, f in zip(sel, best[1]):
        dcn[d] = f
    return tuple(dcn), tuple(int(dims[d]) // dcn[d] for d in range(NDIMS))


def arrange_devices(dims, devices, reorder: int = 1, dcn_axes=()):
    """Arrange ``devices`` into a ``dims``-shaped object ndarray.

    Single-granule (one slice / one process) grids use
    `mesh_utils.create_device_mesh` (ICI-contiguous) when ``reorder`` is set
    and the grid spans all devices, else plain enumeration order.

    Multi-granule grids with ``dcn_axes``: the dims of the named axes are
    factored as ``dcn * ici``; granule ``g`` (slice) occupies the block at
    DCN position ``unravel(g, dcn_shape)``, arranged internally over the ICI
    factors — so only the named axes' block boundaries cross DCN. Tries
    `mesh_utils.create_hybrid_device_mesh` first on real hardware; falls
    back to the deterministic block arrangement (also the testable path).
    """
    dims = tuple(int(d) for d in dims)
    n = int(np.prod(dims))
    use = list(devices[:n])
    groups = _slice_groups(use)

    if len(groups) > 1 and dcn_axes:
        dcn_shape, ici_shape = _dcn_factorization(dims, dcn_axes, len(groups))
        per = n // len(groups)
        if any(len(g) != per for g in groups):
            raise IncoherentArgumentError(
                f"Slices contribute unequal device counts "
                f"({[len(g) for g in groups]}); a Cartesian hybrid mesh needs "
                "equal-size slices."
            )
        if reorder:
            try:
                from jax.experimental import mesh_utils

                return mesh_utils.create_hybrid_device_mesh(
                    ici_shape, dcn_shape, use)
            except Exception as e:
                import warnings

                warnings.warn(
                    "create_hybrid_device_mesh failed "
                    f"({e!r}); falling back to the deterministic block "
                    "arrangement — intra-slice device order will not be "
                    "ICI-optimized.")
        out = np.empty(dims, dtype=object)
        for g, devs in enumerate(groups):
            gpos = np.unravel_index(g, dcn_shape)
            block = np.array(devs, dtype=object).reshape(ici_shape)
            sl = tuple(
                slice(gpos[d] * ici_shape[d], (gpos[d] + 1) * ici_shape[d])
                for d in range(NDIMS)
            )
            out[sl] = block
        return out

    if reorder and n == len(devices) and n > 1:
        try:
            from jax.experimental import mesh_utils

            return mesh_utils.create_device_mesh(dims, devices=use)
        except Exception:
            pass  # fall back to plain order below
    return np.array(use, dtype=object).reshape(dims)


def build_mesh(dims, devices, reorder: int = 1, dcn_axes=()):
    """Create the 3-axis mesh from ``devices`` (see `arrange_devices`)."""
    import jax

    dims = tuple(int(d) for d in dims)
    if len(dims) != NDIMS:
        raise InvalidArgumentError(f"dims must have {NDIMS} entries.")
    n = int(np.prod(dims))
    if n > len(devices):
        raise InvalidArgumentError(
            f"Cannot create a {dims[0]}x{dims[1]}x{dims[2]} grid: requires {n} device(s), "
            f"but only {len(devices)} available."
        )
    return jax.sharding.Mesh(arrange_devices(dims, devices, reorder, dcn_axes),
                             AXIS_NAMES)


def controller_coords_of(dev_array, process_index: int) -> np.ndarray:
    """This controller's Cartesian coordinates: the mesh position of its
    first addressable device (the analog of the reference's per-rank
    `MPI.Cart_coords`, `init_global_grid.jl:101-106`). All-zeros in
    single-process runs (the controller owns every shard)."""
    for idx in np.ndindex(dev_array.shape):
        if getattr(dev_array[idx], "process_index", 0) == process_index:
            return np.array(idx, dtype=np.int64)
    return np.zeros(dev_array.ndim, dtype=np.int64)
