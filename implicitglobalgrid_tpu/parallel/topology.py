"""Cartesian process/device topology and the GlobalGrid singleton.

TPU-native re-design of the reference's shared-state + topology layer
(`/root/reference/src/shared.jl:29-127`, `/root/reference/src/init_global_grid.jl:98-107`).

Where the reference derives the topology from MPI (`MPI.Dims_create!`,
`MPI.Cart_create`, `MPI.Cart_shift` — `init_global_grid.jl:99-106`), here the
topology IS a `jax.sharding.Mesh` over the pod's devices: each mesh coordinate
owns one local block of every field, and the *global* grid is never allocated —
it exists only implicitly through

    nxyz_g = dims * (nxyz - overlaps) + overlaps * (periods == 0)

(the implicit-global-grid formula, reference `init_global_grid.jl:107`).

There is no per-rank state: the single controller holds one `GlobalGrid` whose
mesh spans all shards; per-shard coordinates come from `jax.lax.axis_index`
inside `shard_map` (the analog of `MPI.Cart_coords`).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Any

import numpy as np

from ..utils.exceptions import (
    IncoherentArgumentError,
    InvalidArgumentError,
    ModuleInternalError,
    NotInitializedError,
)

__all__ = [
    "NDIMS", "NNEIGHBORS_PER_DIM", "PROC_NULL", "AXIS_NAMES",
    "GlobalGrid", "global_grid", "set_global_grid", "grid_is_initialized",
    "check_initialized", "get_global_grid", "grid_epoch",
    "swap_global_grid", "retain_epoch", "release_epoch", "live_epochs",
    "dims_create", "cart_rank", "cart_coords", "cart_shift", "neighbors_table",
    "ol", "axis_perm_pairs", "StagedDirection", "StagedWireLayout",
    "staged_wire_layout",
]

# Everything is padded to 3-D internally, like the reference (NDIMS_MPI=3,
# `shared.jl:29`): fixed-size coords/neighbors and simple code.
NDIMS = 3
NNEIGHBORS_PER_DIM = 2          # left + right (reference `shared.jl:30`)
PROC_NULL = -1                  # analog of MPI.PROC_NULL (reference `init_global_grid.jl:103`)
AXIS_NAMES = ("gx", "gy", "gz")  # mesh axis names for the three grid dimensions


@dataclass
class GlobalGrid:
    """Singleton grid state (analog of reference `GlobalGrid`, `shared.jl:58-78`).

    Vectors are numpy arrays and the dataclass is mutable on purpose: the
    reference keeps its struct's vectors mutable "useful for writing tests"
    (`shared.jl:57` comment) — tests here simulate topologies the same way.
    """
    nxyz_g: np.ndarray          # implicit global grid size (3,)
    nxyz: np.ndarray            # local block size (3,)
    dims: np.ndarray            # shards per dimension (3,)
    overlaps: np.ndarray        # (3,)
    halowidths: np.ndarray      # (3,)
    nprocs: int                 # number of shards = prod(dims)
    me: int                     # controller process index (jax.process_index())
    coords: np.ndarray          # controller coords; per-shard coords via axis_index
    periods: np.ndarray         # (3,) of 0/1
    disp: int
    reorder: int
    mesh: Any                   # jax.sharding.Mesh with axes AXIS_NAMES (or None)
    device_type: str            # "tpu" | "cpu" | "gpu" | "none"
    use_pallas: np.ndarray      # (3,) bool — pallas pack kernels per dim
    dcn_axes: tuple             # mesh axes that ride DCN (multi-slice)
    quiet: bool
    epoch: int = 0              # bumped at every init; invalidates jit caches
    dcn_granules: tuple = (1, 1, 1)  # ICI granules (slices/hosts) per dim

    def __iter__(self):  # convenience: me, dims, nprocs, coords, mesh unpacking
        return iter((self.me, self.dims, self.nprocs, self.coords, self.mesh))


_NULL = None  # sentinel; module-level singleton mirrors reference `shared.jl:83-94`
_global_grid: GlobalGrid | None = _NULL
_epoch_counter: int = 0


def global_grid() -> GlobalGrid:
    check_initialized()
    return _global_grid


def set_global_grid(gg: GlobalGrid | None) -> None:
    global _global_grid, _epoch_counter
    if gg is not None:
        _epoch_counter += 1
        gg.epoch = _epoch_counter
    _global_grid = gg


def grid_is_initialized() -> bool:
    return _global_grid is not None and _global_grid.nprocs > 0


def check_initialized() -> None:
    if not grid_is_initialized():
        raise NotInitializedError(
            "No function of the module can be called before init_global_grid() "
            "or after finalize_global_grid()."
        )


def get_global_grid() -> GlobalGrid:
    """Return a deep copy of the global grid (reference `shared.jl:93`)."""
    check_initialized()
    return copy.deepcopy(_global_grid)


def grid_epoch() -> int:
    check_initialized()
    return _global_grid.epoch


# ---------------------------------------------------------------------------
# Grid multiplexing (the multi-run scheduler's context-switch primitives)
# ---------------------------------------------------------------------------
# A normal init assigns a FRESH epoch (set_global_grid bumps the counter),
# which is what invalidates every epoch-keyed jit cache after a re-init.
# The scheduler (`service.MeshScheduler`) instead keeps SEVERAL live grids
# over one device pool and switches between them per slice; each keeps the
# epoch it was born with, so each job's compiled runners/exchanges stay
# warm across context switches. The caches learn which epochs are live via
# `retain_epoch`/`live_epochs` and evict only the dead ones.

_retained_epochs: set = set()


def swap_global_grid(gg: GlobalGrid | None) -> GlobalGrid | None:
    """Make ``gg`` the current grid WITHOUT assigning a new epoch, and
    return the previously-current grid (or None). This is the scheduler's
    context switch: the swapped-in grid keeps its original epoch, so the
    epoch-keyed compiled-program caches (chunk runners, halo exchanges,
    drain probes) keep serving it. Ordinary code wants `init_global_grid`
    / `finalize_global_grid`; only hold multiple grids over the SAME
    device pool."""
    global _global_grid
    old = _global_grid
    _global_grid = gg
    return old


def retain_epoch(epoch: int) -> None:
    """Mark ``epoch`` as belonging to a live (scheduler-held) grid: the
    epoch-keyed caches will not evict its entries while retained."""
    _retained_epochs.add(int(epoch))


def release_epoch(epoch: int) -> None:
    """Drop the retention of ``epoch`` (no-op if not retained). The epoch's
    cache entries become evictable at the next cache miss; callers that
    want the memory back NOW sweep the caches themselves (the scheduler
    does, on job completion)."""
    _retained_epochs.discard(int(epoch))


def live_epochs() -> frozenset:
    """Epochs whose compiled-program cache entries must survive: the
    current grid's (if any) plus every retained one."""
    live = set(_retained_epochs)
    if _global_grid is not None:
        live.add(_global_grid.epoch)
    return frozenset(live)


# ---------------------------------------------------------------------------
# Topology math (analog of MPI_Dims_create / Cart_create / Cart_shift)
# ---------------------------------------------------------------------------

def dims_create(nprocs: int, dims) -> np.ndarray:
    """Fill the zero entries of ``dims`` with a balanced factorization of
    ``nprocs`` (behavioral analog of `MPI_Dims_create`, used at reference
    `init_global_grid.jl:99`).

    Fixed (nonzero) entries are kept; the remaining factor of ``nprocs`` is
    split across free entries as evenly as possible, larger factors first
    (matching the MPI spec's "as close to each other as possible,
    non-increasing order" requirement).
    """
    dims = np.asarray(dims, dtype=np.int64).copy()
    if dims.shape != (NDIMS,):
        raise InvalidArgumentError(f"dims must have {NDIMS} entries, got {dims.shape}.")
    if np.any(dims < 0):
        raise InvalidArgumentError("Invalid arguments: dimx, dimy, and dimz cannot be negative.")
    fixed = int(np.prod(dims[dims > 0])) if np.any(dims > 0) else 1
    if nprocs % fixed != 0:
        raise IncoherentArgumentError(
            f"nprocs ({nprocs}) is not divisible by the product of the fixed dims ({fixed})."
        )
    rem = nprocs // fixed
    free = [i for i in range(NDIMS) if dims[i] == 0]
    if not free:
        if rem != 1:
            raise IncoherentArgumentError(
                f"prod(dims) ({fixed}) does not equal nprocs ({nprocs})."
            )
        return dims
    # Balanced split of `rem` into len(free) factors, non-increasing.
    # The search branches only on DIVISORS (computed once in O(sqrt rem)),
    # so even nprocs ~ 2^20 costs microseconds, not a dense integer sweep.
    best = None
    k = len(free)
    divs = []
    f = 1
    while f * f <= rem:
        if rem % f == 0:
            divs.append(f)
            if f != rem // f:
                divs.append(rem // f)
        f += 1
    divs.sort(reverse=True)

    def search(remaining, max_factor, acc):
        nonlocal best
        if len(acc) == k - 1:
            if remaining <= max_factor:
                cand = tuple(acc + [remaining])
                score = (max(cand) - min(cand), max(cand))
                if best is None or score < best[0]:
                    best = (score, cand)
            return
        for f in divs:
            if f <= max_factor and remaining % f == 0:
                search(remaining // f, f, acc + [f])

    search(rem, rem, [])
    if best is None:  # pragma: no cover - rem>=1 always factorizable
        raise ModuleInternalError("dims_create failed to factorize.")
    for i, f in zip(free, best[1]):
        dims[i] = f
    return dims


def cart_rank(coords, dims) -> int:
    """Row-major Cartesian rank (MPI cart order; reference relies on it for
    `gather!` displacements, `gather.jl:40-47`)."""
    c, d = np.asarray(coords), np.asarray(dims)
    return int((c[0] * d[1] + c[1]) * d[2] + c[2])


def cart_coords(rank: int, dims) -> np.ndarray:
    d = np.asarray(dims)
    cz = rank % d[2]
    cy = (rank // d[2]) % d[1]
    cx = rank // (d[1] * d[2])
    return np.array([cx, cy, cz], dtype=np.int64)


def cart_shift(coords, dim: int, disp: int, dims, periods):
    """Left/right neighbor ranks of ``coords`` along ``dim`` (analog of
    `MPI.Cart_shift`, reference `init_global_grid.jl:104-106`). Returns
    ``(left, right)`` with PROC_NULL where no neighbor exists."""
    coords = np.asarray(coords)
    dims = np.asarray(dims)
    out = []
    for sgn in (-1, +1):
        c = coords.copy()
        t = c[dim] + sgn * disp
        if periods[dim]:
            c[dim] = t % dims[dim]
            out.append(cart_rank(c, dims))
        elif 0 <= t < dims[dim]:
            c[dim] = t
            out.append(cart_rank(c, dims))
        else:
            out.append(PROC_NULL)
    return tuple(out)


def neighbors_table(coords, dims=None, periods=None, disp=None) -> np.ndarray:
    """2×3 neighbor table for a shard at ``coords`` (analog of the reference's
    per-rank `neighbors` array, `init_global_grid.jl:103-106`). Row 0 = left
    neighbors (n=1 in the reference's 1-based convention), row 1 = right."""
    if dims is None:
        gg = global_grid()
        dims, periods, disp = gg.dims, gg.periods, gg.disp
    tbl = np.full((NNEIGHBORS_PER_DIM, NDIMS), PROC_NULL, dtype=np.int64)
    for d in range(NDIMS):
        tbl[0, d], tbl[1, d] = cart_shift(coords, d, disp, dims, periods)
    return tbl


# ---------------------------------------------------------------------------
# Field/overlap sugar (analog of reference `shared.jl:104-127`)
# ---------------------------------------------------------------------------

def axis_perm_pairs(D: int, periodic, disp: int):
    """The (forward, backward) single-axis ppermute pairs of an exchanging
    axis — wrap-around when periodic, truncated chains (PROC_NULL edges)
    when not. THE one pair generator: `ops.halo` ships these pairs live,
    `staged_wire_layout` partitions them into intra/cross-granule legs,
    and `analysis.contracts` proves them — a single source so the wire
    pattern can never diverge between layers."""
    D, disp = int(D), int(disp)
    if periodic:
        return ([(i, (i + disp) % D) for i in range(D)],
                [(i, (i - disp) % D) for i in range(D)])
    if disp >= D:
        return [], []
    return ([(i, i + disp) for i in range(D - disp)],
            [(i, i - disp) for i in range(disp, D)])


# ---------------------------------------------------------------------------
# Topology-staged wire layout (hierarchical ICI+DCN exchange routing)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class StagedDirection:
    """One direction's routes of a staged axis exchange. ``axis_pairs``
    are the flat single-axis pairs this direction would ship unstaged;
    they partition into ``intra_pairs`` (same granule — stay a flat
    single-axis ppermute) and ``cross_pairs`` (granule-crossing — replaced
    by the gather/dcn/scatter pipeline). All ``*_lin``/``gather``/``dcn``/
    ``scatter`` pair lists are LINEARIZED over the full mesh (row-major
    over ``dims``, the index space of a ppermute over the whole axis-name
    tuple and of a compiled collective-permute's source_target_pairs)."""

    name: str            # "+" (data moves toward +dim) or "-"
    axis_pairs: tuple    # flat single-axis pairs, axis index space
    intra_pairs: tuple   # same-granule subset, axis index space
    cross_pairs: tuple   # granule-crossing subset, axis index space
    intra_pairs_lin: tuple
    gather_pairs: tuple  # gather_dim k -> k-1 shifts on sending planes
    dcn_pairs: tuple     # leader -> leader across the granule boundary
    scatter_pairs: tuple  # gather_dim k -> k+1 shifts on receiving planes
    cross_sources: tuple  # axis coords that send across a boundary
    cross_targets: tuple  # axis coords that receive across a boundary


@dataclass(frozen=True)
class StagedWireLayout:
    """The staged exchange's complete route table for one mesh axis:
    which single-axis pairs cross a DCN granule boundary, which ICI axis
    the per-granule leaders fold over (``gather_dim``, fold ``fold``),
    and the exact linearized pair set of every stage in both directions.
    Derived ONCE from the grid geometry by `staged_wire_layout` and read
    by the live exchange builder, the static plan, the perf oracle, and
    the contracts — the one place the staged topology is decided."""

    dim: int             # the staged (DCN-crossing) grid dimension
    gather_dim: int      # the perpendicular pure-ICI dim leaders fold over
    fold: int            # dims[gather_dim] — the DCN message-count fold
    granules: int        # DCN granules along `dim`
    block: int           # devices per granule along `dim`
    dims: tuple          # full mesh shape (linearization basis)
    directions: tuple    # (StagedDirection, ...) — "+" then "-"

    @property
    def dcn_pair_count(self) -> int:
        return sum(len(d.dcn_pairs) for d in self.directions)


def staged_wire_layout(gg, dim: int):
    """Derive the staged wire layout of grid dimension ``dim`` from the
    grid's granule metadata, or ``None`` when staging is degenerate there
    (single granule, granule count not dividing the axis, no perpendicular
    pure-ICI axis with extent >= 2, or no granule-crossing pair). Every
    layer that reasons about the staged wire calls THIS function, so a
    degenerate axis falls back to the flat pair identically in the live
    exchange, the plan, the oracle, and the contract."""
    import itertools

    dims = tuple(int(v) for v in gg.dims)
    dim = int(dim)
    D = dims[dim]
    granules = tuple(int(v) for v in getattr(gg, "dcn_granules",
                                             (1, 1, 1)))
    G = granules[dim] if dim < len(granules) else 1
    if D < 2 or G < 2 or D % G != 0:
        return None
    # the gather axis: the largest perpendicular pure-ICI axis
    cands = [g for g in range(NDIMS)
             if g != dim and granules[g] == 1 and dims[g] > 1]
    if not cands:
        return None
    gather_dim = max(cands, key=lambda g: (dims[g], -g))
    F = dims[gather_dim]
    if F < 2:
        return None
    B = D // G
    periodic = bool(gg.periods[dim])
    disp = int(gg.disp)
    perm_p, perm_m = axis_perm_pairs(D, periodic, disp)
    other_dims = [d for d in range(NDIMS) if d not in (dim, gather_dim)]
    other_ranges = [range(dims[d]) for d in other_dims]

    def lin(axis_c, gather_c, other_c):
        c = [0] * NDIMS
        c[dim] = axis_c
        c[gather_dim] = gather_c
        for d, v in zip(other_dims, other_c):
            c[d] = v
        return cart_rank(c, dims)

    directions = []
    for name, pairs in (("+", perm_p), ("-", perm_m)):
        intra = tuple((s, t) for s, t in pairs if s // B == t // B)
        cross = tuple((s, t) for s, t in pairs if s // B != t // B)
        srcs = tuple(sorted({s for s, _ in cross}))
        tgts = tuple(sorted({t for _, t in cross}))
        intra_lin, gather, dcn, scatter = [], [], [], []
        for oc in itertools.product(*other_ranges):
            for s, t in intra:
                for k in range(F):
                    intra_lin.append((lin(s, k, oc), lin(t, k, oc)))
            for s in srcs:
                for k in range(1, F):
                    gather.append((lin(s, k, oc), lin(s, k - 1, oc)))
            for s, t in cross:
                dcn.append((lin(s, 0, oc), lin(t, 0, oc)))
            for t in tgts:
                for k in range(F - 1):
                    scatter.append((lin(t, k, oc), lin(t, k + 1, oc)))
        directions.append(StagedDirection(
            name=name, axis_pairs=tuple(pairs), intra_pairs=intra,
            cross_pairs=cross, intra_pairs_lin=tuple(intra_lin),
            gather_pairs=tuple(gather), dcn_pairs=tuple(dcn),
            scatter_pairs=tuple(scatter), cross_sources=srcs,
            cross_targets=tgts))
    if not any(d.cross_pairs for d in directions):
        return None
    return StagedWireLayout(dim=dim, gather_dim=gather_dim, fold=F,
                            granules=G, block=B, dims=dims,
                            directions=tuple(directions))


def ol(dim: int, local_shape=None) -> int:
    """Overlap of a field along ``dim`` (0-based). For a field whose local
    block shape differs from ``nxyz`` (staggered grids), the overlap grows by
    the size difference — reference `shared.jl:107`:
    ``ol(dim, A) = overlaps[dim] + (size(A, dim) - nxyz[dim])``."""
    gg = global_grid()
    if local_shape is None:
        return int(gg.overlaps[dim])
    size_d = local_shape[dim] if dim < len(local_shape) else 1
    return int(gg.overlaps[dim] + (size_d - gg.nxyz[dim]))
