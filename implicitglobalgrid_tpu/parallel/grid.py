"""Grid lifecycle: `init_global_grid`, `finalize_global_grid`, `select_device`.

TPU-native re-design of the reference's lifecycle layer
(`/root/reference/src/init_global_grid.jl`, `src/finalize_global_grid.jl`,
`src/select_device.jl`). The MPI pieces map as:

- `MPI.Init` / world size        → JAX runtime (+ `jax.distributed.initialize`
                                   in multi-host deployments)
- `MPI.Dims_create!`             → `topology.dims_create`
- `MPI.Cart_create(...,reorder)` → `mesh.build_mesh` (reorder = ICI-aware
                                   device layout via mesh_utils)
- `MPI.Cart_coords/Cart_shift`   → `lax.axis_index` inside shard_map /
                                   `topology.neighbors_table`
- node-local GPU binding (`select_device.jl:15-39`) → no-op: PJRT binds
  devices; kept as an API shim.

Every argument-coherence check of the reference (`init_global_grid.jl:82-91`)
is reproduced with the same message in spirit.
"""

from __future__ import annotations

import numpy as np

from ..utils.config import read_env_config
from ..utils.exceptions import (
    AlreadyInitializedError, IncoherentArgumentError, InvalidArgumentError,
)
from . import topology as top
from .mesh import build_mesh, controller_coords_of, resolve_devices
from .topology import GlobalGrid, NDIMS, dims_create, set_global_grid

__all__ = ["init_global_grid", "finalize_global_grid", "select_device"]

DEVICE_TYPE_NONE = "none"
DEVICE_TYPE_AUTO = "auto"
SUPPORTED_DEVICE_TYPES = ("tpu", "cpu", "gpu")  # analog of shared.jl:33-37


def init_global_grid(
    nx: int, ny: int = 1, nz: int = 1, *,
    dimx: int = 0, dimy: int = 0, dimz: int = 0,
    periodx: int = 0, periody: int = 0, periodz: int = 0,
    overlaps=(2, 2, 2),
    halowidths=None,
    disp: int = 1,
    reorder: int = 1,
    devices=None,
    init_dist: bool | None = None,
    device_type: str = DEVICE_TYPE_AUTO,
    select_device: bool = True,
    quiet: bool = False,
):
    """Initialize the Cartesian device grid, implicitly defining the global grid.

    API parity with the reference (`init_global_grid.jl:41`): ``nx, ny, nz``
    are the size of each LOCAL block; ``dimx/y/z`` fix shards per dimension
    (0 = choose automatically, the `MPI_Dims_create` analog);
    ``periodx/y/z`` make dimensions periodic; ``overlaps``/``halowidths`` as in
    the reference; ``disp`` is the neighbor displacement (`Cart_shift` analog);
    ``reorder`` lets the mesh builder pick an ICI-contiguous device layout.

    TPU-specific replacements:

    - ``devices``: explicit JAX device list (default: all devices of the
      selected backend) — the analog of the ``comm`` kwarg.
    - ``init_dist``: initialize `jax.distributed` (multi-host). Default
      ``None`` auto-initializes only when a cluster environment is detected —
      the analog of ``init_MPI``.
    - ``device_type``: "tpu", "cpu", "gpu", "none" (CPU-only) or "auto"
      (reference `init_global_grid.jl:76-81`).

    Returns ``(me, dims, nprocs, coords, mesh)`` — mesh takes the place of the
    reference's ``comm_cart`` (`init_global_grid.jl:116`).
    """
    if top.grid_is_initialized():
        raise AlreadyInitializedError("The global grid has already been initialized.")

    cfg = read_env_config()

    nxyz = np.array([nx, ny, nz], dtype=np.int64)
    dims = np.array([dimx, dimy, dimz], dtype=np.int64)
    periods = np.array([periodx, periody, periodz], dtype=np.int64)
    overlaps = np.array(list(overlaps), dtype=np.int64)
    if overlaps.shape != (NDIMS,):
        raise InvalidArgumentError("overlaps must have 3 entries.")
    if halowidths is None:
        halowidths = np.maximum(1, overlaps // 2)  # reference default, init_global_grid.jl:41
    halowidths = np.array(list(halowidths), dtype=np.int64)
    if halowidths.shape != (NDIMS,):
        raise InvalidArgumentError("halowidths must have 3 entries.")

    # Argument-coherence checks (reference init_global_grid.jl:76-91).
    if device_type not in (DEVICE_TYPE_NONE, DEVICE_TYPE_AUTO) + SUPPORTED_DEVICE_TYPES:
        raise InvalidArgumentError(
            f"Argument `device_type`: invalid value obtained ({device_type}). Valid values "
            f"are: {', '.join(SUPPORTED_DEVICE_TYPES + (DEVICE_TYPE_NONE, DEVICE_TYPE_AUTO))}"
        )
    if np.any(nxyz < 1):
        raise InvalidArgumentError("Invalid arguments: nx, ny, and nz cannot be less than 1.")
    if np.any(dims < 0):
        raise InvalidArgumentError("Invalid arguments: dimx, dimy, and dimz cannot be negative.")
    if np.any(~np.isin(periods, (0, 1))):
        raise InvalidArgumentError(
            "Invalid arguments: periodx, periody, and periodz must be either 0 or 1."
        )
    if np.any(halowidths < 1):
        raise InvalidArgumentError("Invalid arguments: halowidths cannot be less than 1.")
    if nx == 1:
        raise InvalidArgumentError("Invalid arguments: nx can never be 1.")
    if ny == 1 and nz > 1:
        raise InvalidArgumentError("Invalid arguments: ny cannot be 1 if nz is greater than 1.")
    if np.any((nxyz == 1) & (dims > 1)):
        raise IncoherentArgumentError(
            "Incoherent arguments: if nx, ny, or nz is 1, then the corresponding dimx, dimy "
            "or dimz must not be set (or set 0 or 1)."
        )
    if np.any((nxyz < 2 * overlaps - 1) & (periods > 0)):
        raise IncoherentArgumentError(
            "Incoherent arguments: if nx, ny, or nz is smaller than 2*overlaps[d]-1, then the "
            "corresponding periodx, periody or periodz must not be set (or set 0)."
        )
    if np.any((overlaps > 0) & (halowidths > overlaps // 2)):
        raise IncoherentArgumentError(
            "Incoherent arguments: if overlap is greater than 0, then halowidth cannot be "
            "greater than overlap//2, in each dimension."
        )
    dims[(nxyz == 1) & (dims == 0)] = 1  # reference init_global_grid.jl:91

    # Runtime init (analog of MPI.Init, init_global_grid.jl:92-97).
    import jax

    if init_dist is None:
        import os

        # Auto-detect a cluster environment WITHOUT touching any jax API that
        # would initialize the XLA backend (jax.distributed.initialize must
        # run before backend init).
        init_dist = bool(
            os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")
        ) and not jax.distributed.is_initialized()
    if init_dist:
        if jax.distributed.is_initialized():
            raise AlreadyInitializedError(
                "jax.distributed is already initialized. Pass init_dist=False."
            )
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            raise AlreadyInitializedError(
                f"jax.distributed failed to initialize: {e}. If the runtime was "
                "already set up, pass init_dist=False."
            ) from e

    if devices is None:
        devices, resolved_type = resolve_devices(device_type, cfg.platform)
    else:
        devices = list(devices)
        resolved_type = devices[0].platform if devices else "none"

    # nprocs: with fully-fixed dims, the grid takes prod(dims) devices (a
    # subset is allowed — unlike MPI, the device pool is not the job size);
    # otherwise all devices are used and free dims are filled like
    # MPI_Dims_create (reference init_global_grid.jl:98-99).
    if np.all(dims > 0):
        nprocs = int(np.prod(dims))
    else:
        nprocs = len(devices)
        # Free dims of size-1 grid dimensions were pinned to 1 above. When
        # the pool size is not a multiple of the fixed dims (e.g. 6 devices
        # with dimx=4), fall back to the largest usable device subset —
        # mirroring the fully-fixed case, where a subset is already allowed
        # (unlike MPI, the device pool is not the job size).
        fixed = int(np.prod(dims[dims > 0])) if np.any(dims > 0) else 1
        if fixed > nprocs:
            raise InvalidArgumentError(
                f"The fixed dims require {fixed} shard(s) but only {nprocs} "
                "device(s) are available; reduce dimx/dimy/dimz or pass a "
                "larger device pool via devices=."
            )
        if nprocs % fixed != 0:
            import warnings

            new = (nprocs // fixed) * fixed
            warnings.warn(
                f"Device pool of {nprocs} is not a multiple of the fixed "
                f"dims product ({fixed}); using {new} device(s) — "
                f"{nprocs - new} idle. Adjust dimx/dimy/dimz or pass "
                "devices= to use the full pool.")
            nprocs = new
    dims = dims_create(nprocs, dims)
    if int(np.prod(dims)) > len(devices):
        raise InvalidArgumentError(
            f"Grid of {int(np.prod(dims))} shards exceeds the {len(devices)} available device(s)."
        )

    mesh = build_mesh(tuple(int(d) for d in dims), devices, reorder,
                      cfg.dcn_axes)

    # DCN granule shape — how many ICI granules the mesh spans per axis.
    # Real multi-slice pools derive it from the pool's slice structure
    # (the same factorization `arrange_devices` laid the mesh out with);
    # single-granule dev boxes can declare it via IGG_TPU_DCN_GRANULES so
    # the topology-staged wire and its pricing/contract layers see the
    # pod's granule shape.
    from .mesh import _dcn_factorization, _slice_groups

    groups = _slice_groups(list(devices)[: int(np.prod(dims))])
    if len(groups) > 1 and cfg.dcn_axes:
        dcn_granules, _ = _dcn_factorization(dims, cfg.dcn_axes,
                                             len(groups))
    else:
        dcn_granules = tuple(int(g) for g in cfg.dcn_granules)
        for d in range(NDIMS):
            if dcn_granules[d] > 1 and int(dims[d]) % dcn_granules[d]:
                raise IncoherentArgumentError(
                    f"IGG_TPU_DCN_GRANULES: {dcn_granules[d]} granule(s) "
                    f"along {'xyz'[d]} do not divide the axis' "
                    f"{int(dims[d])} shard(s)."
                )

    me = jax.process_index()
    # This controller's Cartesian coords — its first addressable device's
    # mesh position (reference per-rank `Cart_coords`,
    # `init_global_grid.jl:101-106`). Zeros in single-controller runs, where
    # per-shard coords come from `lax.axis_index` inside shard_map.
    coords = controller_coords_of(mesh.devices, me)

    # THE implicit-global-grid formula (reference init_global_grid.jl:107).
    nxyz_g = dims * (nxyz - overlaps) + overlaps * (periods == 0)

    gg = GlobalGrid(
        nxyz_g=nxyz_g, nxyz=nxyz, dims=dims, overlaps=overlaps,
        halowidths=halowidths, nprocs=nprocs, me=me, coords=coords,
        periods=periods, disp=int(disp), reorder=int(reorder), mesh=mesh,
        device_type=resolved_type,
        # Pallas kernel tier: on by default on TPU (measured ~3x over the
        # broadcast form — bench.py), explicit IGG_USE_PALLAS[=0] overrides.
        use_pallas=np.array(
            [(resolved_type == "tpu") if v is None else v for v in cfg.use_pallas],
            dtype=bool),
        dcn_axes=cfg.dcn_axes, quiet=bool(quiet),
        dcn_granules=dcn_granules,
    )
    set_global_grid(gg)

    if not quiet and me == 0:
        print(
            f"Global grid: {int(nxyz_g[0])}x{int(nxyz_g[1])}x{int(nxyz_g[2])} "
            f"(nprocs: {nprocs}, dims: {int(dims[0])}x{int(dims[1])}x{int(dims[2])}; "
            f"device support: {resolved_type})"
        )

    if select_device and resolved_type not in ("none",):
        _select_device()

    from ..utils.timing import init_timing_functions

    init_timing_functions()
    return me, dims.copy(), nprocs, coords.copy(), mesh


def finalize_global_grid(*, finalize_dist: bool = False) -> None:
    """Finalize the global grid (reference `finalize_global_grid.jl:15-26`):
    free the compiled halo-exchange programs (the buffer-pool analog), reset
    the singleton, optionally shut down `jax.distributed`."""
    import gc

    top.check_initialized()
    from ..ops.halo import free_update_halo_caches
    from ..utils import timing

    free_update_halo_caches()
    # barrier probes: same retention rule as the exchange caches — a
    # scheduler-held tenant's probe survives another tenant's finalize
    for k in [k for k in timing._probe_cache
              if k not in top._retained_epochs]:
        del timing._probe_cache[k]
    timing._t0 = None  # a chronometer from a dead grid epoch is meaningless
    if finalize_dist:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    set_global_grid(None)
    gc.collect()


def node_local_rank():
    """(node-local rank, processes on this host, devices on this host) — the
    analog of the reference's `MPI.Comm_split_type(COMM_TYPE_SHARED)` +
    `Comm_rank` node grouping (`select_device.jl:26-32`).

    COLLECTIVE in multi-process runs (like the reference's MPI call):
    every process must call it, or the callers deadlock in the allgather.
    Processes are grouped by hostname (gathered with a tiny
    `process_allgather` — the shared-memory-communicator analog); the rank
    is this process's index among co-hosted processes in `process_index`
    order. Single-process runs return ``(0, 1, local device count)``
    without any collective."""
    import jax

    n_local = len(jax.local_devices())
    if jax.process_count() == 1:
        return 0, 1, n_local
    import hashlib
    import socket

    import numpy as np
    from jax.experimental import multihost_utils

    # int32-safe hash: without jax_enable_x64 the allgather truncates int64
    h = int.from_bytes(
        hashlib.sha1(socket.gethostname().encode()).digest()[:4], "big",
        signed=True)
    row = np.array([h, n_local], dtype=np.int32)
    allrows = np.asarray(multihost_utils.process_allgather(row))
    mine = jax.process_index()
    same = [i for i in range(allrows.shape[0]) if allrows[i, 0] == h]
    me_l = same.index(mine)
    dev_on_node = int(sum(allrows[i, 1] for i in same))
    return me_l, len(same), dev_on_node


def _select_device():
    """Device binding (reference `_select_device`, `select_device.jl:15-39`).

    The reference computes the node-local rank and binds that GPU, erroring
    when a node hosts more ranks than devices. With PJRT each process's
    devices are already bound at runtime init, so binding is a no-op — but
    the node-grouping semantics and the oversubscription guard are kept:
    more co-hosted controllers than devices on the host is a config error
    (unrepresentable in healthy PJRT deployments, where every process owns
    at least one device — the check guards degenerate runtimes)."""
    import jax

    me_l, n_procs_node, dev_on_node = node_local_rank()
    if n_procs_node > dev_on_node:
        raise IncoherentArgumentError(
            f"This host runs {n_procs_node} processes but only "
            f"{dev_on_node} device(s): it is not possible to run more "
            "processes per node than there are devices on it (reference "
            "select_device.jl:28)."
        )
    return jax.local_devices()[0].id


def select_device() -> int:
    """Return the device id bound to this process after the node-local
    oversubscription check (reference `select_device`, `select_device.jl:15`).

    COLLECTIVE in multi-process runs — every process must call it together,
    exactly like the reference's `MPI.Comm_split_type` inside
    `_select_device` (`select_device.jl:26`). `init_global_grid` calls it
    symmetrically on every process."""
    top.check_initialized()
    return _select_device()
