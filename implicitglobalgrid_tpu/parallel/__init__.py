"""parallel subpackage of implicitglobalgrid_tpu."""
