"""Shared model machinery: whole-loop-jitted runners over pytree state.

Every model follows the same TPU-first shape: a pure LOCAL step function over
per-shard blocks (the reference's per-rank hot loop, e.g.
`/root/reference/examples/diffusion3D_multicpu_novis.jl:41-48`), compiled as
ONE XLA program per chunk of time steps (`lax.fori_loop` with the halo
ppermutes inline) instead of per-step dispatches.
"""

from __future__ import annotations

from ..ops.fields import field_partition_spec
from ..parallel.topology import check_initialized, global_grid

__all__ = ["make_state_runner", "run_chunked", "default_check_vma",
           "resolve_pallas_impl", "fresh_mask", "validate_deep_halo",
           "resolve_comm_every", "interior_first_step",
           "ensemble_partition_spec", "ensemble_state",
           "resolve_ensemble_impl"]

_runner_cache: dict = {}


def resolve_pallas_impl(impl, eligible: bool = True):
    """Shared default-impl rule for every model family: an explicit ``impl``
    wins; otherwise the Pallas kernel tier is the default on TPU grids with
    all IGG_USE_PALLAS flags on (the reference's per-dim copy-kernel toggle,
    `init_global_grid.jl:60,71-75`) when the model has a kernel for this
    configuration (``eligible``), else the XLA path."""
    if impl is not None:
        return impl
    from ..parallel.topology import global_grid

    gg = global_grid()
    if eligible and bool(gg.use_pallas.all()) and gg.device_type == "tpu":
        return "pallas"
    return "xla"


def ensemble_partition_spec(ndim: int):
    """PartitionSpec of an ENSEMBLE-stacked field: a new leading member
    axis (replicated — every shard holds all E members of its block)
    ahead of the usual mesh-axis sharding of the ``ndim`` physical axes.
    The member axis is deliberately mesh-axis-FREE: members never talk to
    each other, so sharding them would only fragment the one batched
    payload per ppermute the ensemble exists to ship."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES

    return P(None, *AXIS_NAMES[:ndim])


def ensemble_state(state, members: int, *, perturb: float = 0.0):
    """Stack ``members`` copies of stacked global field(s) along a NEW
    leading member axis, placed with the ensemble sharding
    (`ensemble_partition_spec`) — the state an ensemble runner
    (`make_state_runner(ensemble=members)`) advances.

    ``state`` may be one array, a tuple/list, or a dict of stacked
    arrays (the `run_resilient` state form); the container shape is
    preserved. ``perturb`` scales member ``m`` by ``1 + perturb * m`` — a
    deterministic parameter ramp that makes members distinct scenarios
    (member 0 is always the unperturbed base, so it stays bit-comparable
    to the solo run)."""
    import jax
    import jax.numpy as jnp

    from ..utils.exceptions import InvalidArgumentError

    check_initialized()
    gg = global_grid()
    E = int(members)
    if E < 1:
        raise InvalidArgumentError(
            f"ensemble_state: members must be >= 1; got {members}.")

    def one(A):
        A = jnp.asarray(A)
        stacked = jnp.broadcast_to(A[None], (E,) + tuple(A.shape))
        if perturb:
            fac = (1.0 + float(perturb)
                   * jnp.arange(E, dtype=jnp.float32)).astype(A.dtype)
            stacked = stacked * fac.reshape((E,) + (1,) * A.ndim)
        sh = jax.sharding.NamedSharding(gg.mesh,
                                        ensemble_partition_spec(A.ndim))
        return jax.device_put(stacked, sh)

    if isinstance(state, dict):
        return {k: one(v) for k, v in state.items()}
    if isinstance(state, (tuple, list)):
        return type(state)(one(v) for v in state)
    return one(state)


def resolve_ensemble_impl(impl, model: str = "step") -> str:
    """The ensemble tier's impl rule: the member axis is a ``vmap`` over
    the step program, validated on the XLA formulation (the fused Pallas
    kernels' batching under vmap is unproven hardware territory) — an
    explicit Pallas request raises instead of silently running a
    different tier; ``None``/"xla" resolve to "xla"."""
    from ..utils.exceptions import InvalidArgumentError

    if impl is not None and not str(impl).startswith("xla"):
        raise InvalidArgumentError(
            f"impl={impl!r} is incompatible with ensemble batching: the "
            f"ensemble axis currently runs the {model} step's XLA tier "
            "(vmap over the fused Pallas kernels is not validated). Pass "
            "impl=None/'xla' or drop ensemble=.")
    return "xla"


def default_check_vma(step_uses_pallas: bool = False) -> bool:
    """shard_map ``check_vma`` value for a step program: variance checking
    stays ON unless Pallas kernels actually appear — either in the step
    itself (``step_uses_pallas``) or via `local_update_halo`'s kernel tier
    on the current grid (`ops.halo.halo_may_use_pallas`)."""
    from ..ops.halo import halo_may_use_pallas

    return not (step_uses_pallas or halo_may_use_pallas())


def fresh_mask(shape, retreat, base_lo, base_hi):
    """Update-region mask for communication-avoiding deep-halo sub-steps
    (True = this cell's stencil dependencies are fresh).

    Per dim ``d``: ``[base_lo[d] + retreat_d·L, n_d - base_hi[d] -
    retreat_d·R)`` where L/R flag a neighbor on that side of THIS shard
    (`lax.axis_index` per mesh axis — one SPMD program serves edge and
    interior shards; periodic sides always have a neighbor, incl. self).
    ``base_lo/hi`` encode the scheme's exchange-fresh update region
    (diffusion interior: 1/1; a face-staggered dim: 1/1; a full-array
    update: 0/0); ``retreat`` is how many sub-steps of staleness the
    field's dependencies have accumulated — a scalar, or a PER-DIM
    sequence under a per-axis cadence (`CommCadence`: each axis's
    staleness advances at its own rate between its own exchanges). The
    skipped cells keep stale values and are overwritten by that axis's
    next k-wide exchange — which is why deep-halo trajectories stay
    bit-identical (tests/test_comm_avoid.py).
    """
    import numpy as np

    import jax.numpy as jnp
    from jax import lax

    from ..parallel.topology import AXIS_NAMES, global_grid

    gg = global_grid()
    per_dim = np.iterable(retreat)
    m = None
    for d in range(len(shape)):
        idx = lax.axis_index(AXIS_NAMES[d])
        per = bool(int(gg.periods[d]))
        has_l = jnp.logical_or(idx > 0, per)
        has_r = jnp.logical_or(idx < int(gg.dims[d]) - 1, per)
        i = jnp.arange(shape[d])
        r_d = retreat[d] if per_dim else retreat
        lo = base_lo[d] + jnp.where(has_l, r_d, 0)
        hi = shape[d] - base_hi[d] - jnp.where(has_r, r_d, 0)
        md = (i >= lo) & (i < hi)
        md = md.reshape([-1 if dd == d else 1
                         for dd in range(len(shape))])
        m = md if m is None else m & md
    return m


def resolve_comm_every(comm_every=None):
    """The models' entry to the per-axis cadence resolver
    (`ops.wire.resolve_comm_every`): int / ``"z:4,x:1"`` / dict /
    `CommCadence` / ``None`` (= consult ``IGG_COMM_EVERY``, default 1)
    -> `CommCadence`."""
    from ..ops.wire import resolve_comm_every as _resolve

    return _resolve(comm_every)


def validate_deep_halo(gg, ndim: int, k, depth_per_step: int = 1) -> None:
    """Shared `comm_every` coherence checks. ``k`` is the cadence — an
    int or a resolved `CommCadence` (per-axis). ``depth_per_step`` is the
    scheme's per-sub-step dependency radius — 1 for radius-1 stencils
    (diffusion, the acoustic leapfrog), 2 for the Stokes PT iteration
    (V needs stresses which need V: the band retreats 2 cells per
    iteration). Every exchanging dim ``d`` needs halo depth >=
    depth_per_step·k_d AND local size >= overlap + depth_per_step·k_d
    (the send slabs must stay inside the LAST sub-step's freshly-updated
    region, or an interior shard silently ships one-sub-step-stale
    values)."""
    from ..utils.exceptions import IncoherentArgumentError

    cad = resolve_comm_every(k)
    for d in range(ndim):
        k_d = cad.for_dim(d)
        need = depth_per_step * k_d
        exchanging = int(gg.dims[d]) > 1 or int(gg.periods[d])
        if not exchanging:
            continue
        if int(gg.halowidths[d]) < need:
            raise IncoherentArgumentError(
                f"comm_every={cad} needs halowidths[{d}] >= {need} on "
                f"every exchanging dim (got {int(gg.halowidths[d])}): "
                f"init the grid with overlaps[{d}] >= {2 * need} and "
                f"halowidths[{d}] = {need}.")
        n_d, ol_d = int(gg.nxyz[d]), int(gg.overlaps[d])
        if n_d < ol_d + need:
            raise IncoherentArgumentError(
                f"comm_every={cad} needs local size >= overlap + {need} "
                f"on dim {d} (got n={n_d}, overlap={ol_d}): the send "
                "slabs would leave the freshly-updated region.")


def interior_first_step(update_fn, outs, aux=(), *, radius: int = 1,
                        n_exchange: int | None = None, coalesce=None,
                        wire_dtype=None):
    """The INTERIOR-FIRST default shape of a step program (the chunk body
    every model's ``overlap=True`` path routes through): boundary-shell
    update -> ONE coalesced exchange round that depends only on the shell
    -> interior update scheduled UNDER the collectives -> stitch. A thin,
    named entry over `ops.overlap.hide_communication`'s multi-field form,
    so model step functions declare the shape instead of re-deriving the
    slab bookkeeping: ``outs`` is the tuple of updated fields (the first
    ``n_exchange`` of them exchanged — the Stokes iteration updates 7
    fields but wires 4), ``aux`` the read-only inputs, ``radius`` the
    update's stencil radius. Semantically identical to
    ``local_update_halo(*update_fn(*outs, *aux))``; the structural
    independence of interior and permutes is HLO-audited
    (tests/test_hlo_audit.py, `ProgramIR.closure`)."""
    from ..ops.overlap import hide_communication

    return hide_communication(update_fn, tuple(outs), *aux, radius=radius,
                              n_exchange=n_exchange, coalesce=coalesce,
                              wire_dtype=wire_dtype)


def make_state_runner(step_local, state_ndims, *, nt_chunk: int, key=None,
                      check_vma: bool | None = None, unroll: int | None = None,
                      post_chunk=None, ensemble: int | None = None):
    """Compile ``state -> state`` advancing ``nt_chunk`` steps.

    ``step_local(state) -> state`` operates on a tuple of LOCAL blocks;
    ``state_ndims`` gives each block's ndim (its sharding spec). ``key``
    (hashable) identifies the step function for caching — required because
    closures are rebuilt per call; pass e.g. (model_name, params, nt_chunk).
    ``check_vma=None`` resolves via `default_check_vma` (off only when the
    halo layer emits Pallas kernels; pass False yourself if the step uses
    Pallas directly).

    ``post_chunk(state) -> aux`` is the in-chunk guard hook (the resilient
    runtime's health probe, `runtime/health.py`): it runs INSIDE the same
    shard_map program once after the time loop, and its (replicated,
    ``P()``-spec'ed) result is appended to the runner's outputs — the
    compiled chunk becomes ``state -> (*state, aux)``. Because it lives in
    the chunk body, whatever it computes rides the one compiled program:
    no extra dispatch, and any reduction it performs (e.g. ONE psum of a
    tiny stats vector) is the only collective added per chunk boundary.
    The hook's module-qualified name joins the cache key (so the guarded
    and unguarded runners, or two different module-level hooks, never
    collide), but — exactly like ``step_local`` itself — the closure's
    CONTENT does not: two distinct hooks sharing a qualname (closures from
    one factory) need distinct ``key``s.

    ``unroll`` (default 4 on TPU, 1 elsewhere) unrolls the time loop body:
    XLA's while-loop buffer assignment pins each carry to ONE buffer, so a
    1-step body pays a full state copy per step to get the step kernel's
    output back into the carry buffer (~30% of the flagship step, measured
    via `overlap_stats`/`op_breakdown` on a v5e trace); an unrolled body
    ping-pongs intermediate buffers and pays that copy once per ``unroll``
    steps (`lax.fori_loop` handles non-divisible trip counts).

    ``ensemble=E`` is the ENSEMBLE axis (ISSUE 12): the compiled chunk
    advances E scenario members per step by ``vmap``-ing ``step_local``
    over a NEW leading member axis of every state array (state arrays are
    ``(E, *physical)``, sharded `ensemble_partition_spec` — build them
    with `ensemble_state`). ``state_ndims`` stays the PHYSICAL per-field
    rank. jax's collective batching rules keep the chunk's collective
    COUNT flat in E: each halo ppermute pair carries all members' (and
    all fields') slabs in one E x payload, and the ``post_chunk`` hook is
    vmapped too, so the health guard's single psum becomes one
    ``f32[E, 2N+R]`` reduction — per-member verdicts behind one
    collective (HLO-audited in tests/test_ensemble.py). XLA tier only —
    route model steps through `resolve_ensemble_impl`."""
    import time

    import jax
    from jax import lax

    from ..telemetry import note_runner_cache

    check_initialized()
    gg = global_grid()
    if ensemble is not None:
        from ..utils.exceptions import InvalidArgumentError

        ensemble = int(ensemble)
        if ensemble < 1:
            raise InvalidArgumentError(
                f"make_state_runner: ensemble must be >= 1; got "
                f"{ensemble}.")
    if check_vma is None:
        check_vma = default_check_vma()
    if unroll is None:
        unroll = 4 if gg.device_type == "tpu" else 1
    unroll = max(1, min(int(unroll), int(nt_chunk)))
    t_build0 = time.monotonic()
    if key is not None:
        # kernel_flags are read at TRACE time inside the kernel builders;
        # keying on them keeps the documented IGG_MP_HANDOFF /
        # IGG_PLANE_RELAY A/B flips honest within one grid epoch (no
        # stale cached runner). Same rule for the halo exchange knobs
        # (IGG_HALO_COALESCE / IGG_HALO_WIRE_DTYPE / IGG_HALO_WIRE_STAGE),
        # resolved at trace time inside `local_update_halo` calls in the
        # step body.
        from ..ops.halo import resolve_halo_coalesce
        from ..ops.pallas_stencil import kernel_flags
        from ..ops.precision import resolve_wire_dtype
        from ..ops.wire import resolve_wire_stage

        hook_id = None if post_chunk is None else (
            getattr(post_chunk, "__module__", None),
            getattr(post_chunk, "__qualname__", repr(post_chunk)))
        full_key = (gg.epoch, key, tuple(state_ndims), int(nt_chunk),
                    bool(check_vma), int(unroll), kernel_flags(),
                    resolve_halo_coalesce(None),
                    str(resolve_wire_dtype(None)),
                    str(resolve_wire_stage(None)), hook_id, ensemble)
        fn = _runner_cache.get(full_key)
        if fn is not None:
            # telemetry: compiled-chunk reuse vs recompile is THE
            # execute/compile split the flight recorder attributes chunks to
            note_runner_cache("hit")
            return fn
        if _runner_cache:
            # evict DEAD epochs only: after a plain re-init that is
            # everything but the current epoch (the historical behavior),
            # but the multi-run scheduler keeps several grids live at once
            # (`topology.retain_epoch`) and their warm runners must survive
            # its context switches
            from ..parallel.topology import live_epochs

            live = live_epochs()
            for k in [k for k in _runner_cache if k[0] not in live]:
                del _runner_cache[k]
    if ensemble is None:
        specs = tuple(field_partition_spec(nd) for nd in state_ndims)
        run_step = step_local
        run_hook = post_chunk
    else:
        # the member axis: ONE vmap over the whole step (and the guard
        # hook) — jax's collective batching rules are what keep the
        # compiled collective count flat in E (each ppermute/psum absorbs
        # the batch dim into its payload instead of replaying per member).
        # The exchange is trace-scoped to the pure-XLA tier: every XLA op
        # batches by rule, while the Pallas halo kernels' vmap batching is
        # unvalidated (`ops.halo.force_xla_exchange`).
        from ..ops.halo import force_xla_exchange

        specs = tuple(ensemble_partition_spec(nd) for nd in state_ndims)
        vstep = jax.vmap(lambda *blocks: tuple(step_local(blocks)))

        def run_step(s):
            with force_xla_exchange():
                return vstep(*s)

        if post_chunk is None:
            run_hook = None
        else:
            vhook = jax.vmap(lambda *blocks: post_chunk(blocks))

            def run_hook(s):
                return vhook(*s)
    out_specs = specs

    if run_hook is None:
        def chunk(*state):
            return lax.fori_loop(0, nt_chunk,
                                 lambda i, s: tuple(run_step(s)),
                                 tuple(state), unroll=unroll)
    else:
        from jax.sharding import PartitionSpec as P

        out_specs = specs + (P(),)

        def chunk(*state):
            out = lax.fori_loop(0, nt_chunk,
                                lambda i, s: tuple(run_step(s)),
                                tuple(state), unroll=unroll)
            return out + (run_hook(out),)

    from ..utils.compat import shard_map

    fn = jax.jit(shard_map(
        chunk, mesh=gg.mesh, in_specs=specs, out_specs=out_specs,
        check_vma=check_vma,
    ))
    if key is not None:
        _runner_cache[full_key] = fn
    # build_s is host-side program construction; the XLA compile itself is
    # paid inside the FIRST dispatch of this runner (a chunk following a
    # `miss` is a cold chunk — `telemetry.run_report` joins the two)
    note_runner_cache("miss" if key is not None else "uncached",
                      build_s=time.monotonic() - t_build0)
    return fn


def run_chunked(runner_factory, state, nt: int, nt_chunk: int):
    """Advance ``nt`` steps using ``runner_factory(chunk_size)``; compiles at
    most two chunk sizes. Returns only after the work actually finished
    (data-dependent `sync` — `block_until_ready` is not a reliable drain on
    all PJRT transports, see `utils.timing.sync`)."""
    from ..utils.timing import sync

    full, rem = divmod(nt, nt_chunk)
    if full:
        run = runner_factory(nt_chunk)
        for _ in range(full):
            state = run(*state)
    if rem:
        state = runner_factory(rem)(*state)
    return sync(state)
