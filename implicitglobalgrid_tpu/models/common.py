"""Shared model machinery: whole-loop-jitted runners over pytree state.

Every model follows the same TPU-first shape: a pure LOCAL step function over
per-shard blocks (the reference's per-rank hot loop, e.g.
`/root/reference/examples/diffusion3D_multicpu_novis.jl:41-48`), compiled as
ONE XLA program per chunk of time steps (`lax.fori_loop` with the halo
ppermutes inline) instead of per-step dispatches.
"""

from __future__ import annotations

from ..ops.fields import field_partition_spec
from ..parallel.topology import check_initialized, global_grid

__all__ = ["make_state_runner", "run_chunked", "default_check_vma",
           "resolve_pallas_impl"]

_runner_cache: dict = {}


def resolve_pallas_impl(impl, eligible: bool = True):
    """Shared default-impl rule for every model family: an explicit ``impl``
    wins; otherwise the Pallas kernel tier is the default on TPU grids with
    all IGG_USE_PALLAS flags on (the reference's per-dim copy-kernel toggle,
    `init_global_grid.jl:60,71-75`) when the model has a kernel for this
    configuration (``eligible``), else the XLA path."""
    if impl is not None:
        return impl
    from ..parallel.topology import global_grid

    gg = global_grid()
    if eligible and bool(gg.use_pallas.all()) and gg.device_type == "tpu":
        return "pallas"
    return "xla"


def default_check_vma(step_uses_pallas: bool = False) -> bool:
    """shard_map ``check_vma`` value for a step program: variance checking
    stays ON unless Pallas kernels actually appear — either in the step
    itself (``step_uses_pallas``) or via `local_update_halo`'s kernel tier
    on the current grid (`ops.halo.halo_may_use_pallas`)."""
    from ..ops.halo import halo_may_use_pallas

    return not (step_uses_pallas or halo_may_use_pallas())


def make_state_runner(step_local, state_ndims, *, nt_chunk: int, key=None,
                      check_vma: bool | None = None, unroll: int | None = None):
    """Compile ``state -> state`` advancing ``nt_chunk`` steps.

    ``step_local(state) -> state`` operates on a tuple of LOCAL blocks;
    ``state_ndims`` gives each block's ndim (its sharding spec). ``key``
    (hashable) identifies the step function for caching — required because
    closures are rebuilt per call; pass e.g. (model_name, params, nt_chunk).
    ``check_vma=None`` resolves via `default_check_vma` (off only when the
    halo layer emits Pallas kernels; pass False yourself if the step uses
    Pallas directly).

    ``unroll`` (default 4 on TPU, 1 elsewhere) unrolls the time loop body:
    XLA's while-loop buffer assignment pins each carry to ONE buffer, so a
    1-step body pays a full state copy per step to get the step kernel's
    output back into the carry buffer (~30% of the flagship step, measured
    via `overlap_stats`/`op_breakdown` on a v5e trace); an unrolled body
    ping-pongs intermediate buffers and pays that copy once per ``unroll``
    steps (`lax.fori_loop` handles non-divisible trip counts)."""
    import jax
    from jax import lax

    check_initialized()
    gg = global_grid()
    if check_vma is None:
        check_vma = default_check_vma()
    if unroll is None:
        unroll = 4 if gg.device_type == "tpu" else 1
    unroll = max(1, min(int(unroll), int(nt_chunk)))
    if key is not None:
        # kernel_flags are read at TRACE time inside the kernel builders;
        # keying on them keeps the documented IGG_MP_HANDOFF /
        # IGG_PLANE_RELAY A/B flips honest within one grid epoch (no
        # stale cached runner).
        from ..ops.pallas_stencil import kernel_flags

        full_key = (gg.epoch, key, tuple(state_ndims), int(nt_chunk),
                    bool(check_vma), int(unroll), kernel_flags())
        fn = _runner_cache.get(full_key)
        if fn is not None:
            return fn
        if _runner_cache and next(iter(_runner_cache))[0] != gg.epoch:
            _runner_cache.clear()
    specs = tuple(field_partition_spec(nd) for nd in state_ndims)

    def chunk(*state):
        out = lax.fori_loop(0, nt_chunk, lambda i, s: tuple(step_local(s)),
                            tuple(state), unroll=unroll)
        return out

    fn = jax.jit(jax.shard_map(
        chunk, mesh=gg.mesh, in_specs=specs, out_specs=specs,
        check_vma=check_vma,
    ))
    if key is not None:
        _runner_cache[full_key] = fn
    return fn


def run_chunked(runner_factory, state, nt: int, nt_chunk: int):
    """Advance ``nt`` steps using ``runner_factory(chunk_size)``; compiles at
    most two chunk sizes. Returns only after the work actually finished
    (data-dependent `sync` — `block_until_ready` is not a reliable drain on
    all PJRT transports, see `utils.timing.sync`)."""
    from ..utils.timing import sync

    full, rem = divmod(nt, nt_chunk)
    if full:
        run = runner_factory(nt_chunk)
        for _ in range(full):
            state = run(*state)
    if rem:
        state = runner_factory(rem)(*state)
    return sync(state)
