"""Model workloads built on the framework (the reference's `examples/` role,
re-designed whole-loop-jitted for TPU)."""

from .common import ensemble_partition_spec, ensemble_state
from .diffusion import (
    DiffusionParams, init_diffusion3d, init_diffusion2d,
    diffusion_step_local, make_step, make_run, make_run_deep,
    make_run_sr,
    run_diffusion,
)
from .acoustic import (
    AcousticParams, init_acoustic3d, acoustic_step_local,
    make_acoustic_run, make_acoustic_run_deep, run_acoustic,
)
from .stokes import (
    StokesParams, init_stokes3d, stokes_step_local,
    make_stokes_run, make_stokes_run_deep, run_stokes,
    stokes_residuals,
)

__all__ = [
    "ensemble_partition_spec", "ensemble_state",
    "DiffusionParams", "init_diffusion3d", "init_diffusion2d",
    "diffusion_step_local", "make_step", "make_run", "make_run_deep",
    "make_run_sr",
    "run_diffusion",
    "AcousticParams", "init_acoustic3d", "acoustic_step_local",
    "make_acoustic_run", "make_acoustic_run_deep", "run_acoustic",
    "StokesParams", "init_stokes3d", "stokes_step_local",
    "make_stokes_run", "make_stokes_run_deep", "run_stokes",
    "stokes_residuals",
]
