"""Model workloads built on the framework (the reference's `examples/` role,
re-designed whole-loop-jitted for TPU)."""

from .diffusion import (
    DiffusionParams, init_diffusion3d, init_diffusion2d,
    diffusion_step_local, make_step, make_run, run_diffusion,
)

__all__ = [
    "DiffusionParams", "init_diffusion3d", "init_diffusion2d",
    "diffusion_step_local", "make_step", "make_run", "run_diffusion",
]
