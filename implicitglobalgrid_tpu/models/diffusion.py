"""3-D (and 2-D) heat diffusion — the framework's flagship workload.

TPU-native re-design of the reference's canonical example
(`/root/reference/examples/diffusion3D_multicpu_novis.jl:11-51`,
`diffusion3D_multigpu_CuArrays_novis.jl:12-54`): Fourier-law fluxes +
energy-conservation update + halo exchange every step.

The TPU-first difference: instead of one dispatched broadcast per operation
per step (the reference's hot loop, which its own README notes leaves >10x
headroom, `README.md:167`), the ENTIRE time loop runs as one compiled XLA
program — `lax.fori_loop` over the fused stencil update with the per-axis
`ppermute` halo exchange inline (`run` below). XLA fuses flux computation,
divergence, and update into a handful of kernels per step and overlaps the
halo collectives with interior compute via its latency-hiding scheduler.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops.alloc import device_put_g, zeros_g
from ..ops.fields import field_partition_spec
from ..ops.halo import local_update_halo
from ..ops.stencil import d_xa, d_xi, d_ya, d_yi, d_za, d_zi, inn
from ..parallel.topology import check_initialized, global_grid
from ..tools import coords_g, nx_g, ny_g, nz_g

__all__ = ["DiffusionParams", "init_diffusion3d", "init_diffusion2d",
           "diffusion_step_local", "make_step", "make_run", "make_run_sr",
           "make_run_deep", "deep_step", "run_diffusion"]


@dataclass(frozen=True)
class DiffusionParams:
    """Physics/numerics constants (static: baked into the compiled program).

    ``overlap`` routes the XLA step through `hide_communication` (shell
    update first, halo ppermutes overlap the interior compute — the
    `@hide_communication` analog). It pays an extra interior-stitch pass, so
    it wins only where collective latency is a significant fraction of the
    step (small local blocks in strong scaling, DCN-crossing axes); at the
    256^3 anchor size on ICI the default data-flow scheduling is faster.
    The Pallas fused step+exchange path structures communication itself and
    ignores this flag.

    ``sr`` enables STOCHASTIC-ROUNDING bf16 storage (`ops/precision.py`):
    the state stays bf16 in HBM (the bandwidth tier) but each step computes
    in f32 and rounds the store stochastically, which removes the
    increment-absorption bias that stagnates plain-bf16 long runs
    (bench_f64_accuracy.py). Runner-level feature (`make_run_sr`/
    `run_diffusion` thread the per-step PRNG); currently XLA-tier only —
    the Pallas kernels would need an in-kernel PRNG, pending hardware
    validation — and, like the Pallas tier, it ignores ``overlap``. No
    effect unless the state dtype is bfloat16.

    ``comm_every`` enables COMMUNICATION-AVOIDING deep-halo stepping: with
    halowidths >= k the exchange runs once per k steps (k-wide slabs), and
    between exchanges each sub-step updates a region that retreats one
    cell per sub-step from every side that has a neighbor — the cells it
    skips are halo-band cells the NEXT exchange overwrites anyway, so the
    interior trajectory is bit-identical to comm_every=1 (asserted by
    tests/test_comm_avoid.py). Same wire bytes per step; 1/k the
    collective count and latency — the lever for latency-bound regimes
    (small blocks in strong scaling, DCN-crossing axes; see
    `exposed_comm_ms_per_step` in WEAK_SCALING.json).

    The cadence is PER MESH AXIS (`ops.wire.resolve_comm_every` — the
    `wire_dtype` spelling family): an int ``k``, a spec like ``"z:4"`` /
    ``"z:4,x:1"`` (axes x/y/z or gx/gy/gz; unnamed axes exchange every
    step), a ``{axis: k}`` dict, or ``None`` to consult
    ``IGG_COMM_EVERY``. A slow DCN-mapped axis can then amortize its
    collective latency over ``k`` steps while ICI axes keep per-step
    exchanges and 1-wide halos — the configuration where a UNIFORM
    cadence loses on slab-width compute (the Stokes COMM_AVOID.json row)
    turns into a win. Each axis ``d`` needs ``halowidths[d] = k_d`` /
    ``overlaps[d] >= 2*k_d``; the compiled super-step advances
    ``lcm(k_d)`` physical steps. XLA tier; ignores ``overlap``."""
    lam: float      # thermal conductivity
    dt: float
    dx: float
    dy: float = 1.0
    dz: float = 1.0
    overlap: bool = False
    sr: bool = False
    sr_seed: int = 0
    comm_every: int | str = 1


def _gaussian(x, amp, cx, w=1.0):
    import jax.numpy as jnp

    return amp * jnp.exp(-(((x - cx) / w) ** 2))


def _upd3(Tb, Cpb, p: DiffusionParams):
    """The 3-D flux/divergence/update stencil — ONE definition shared by
    the plain-XLA, overlap, and stochastic-rounding paths (the accuracy
    bench compares their trajectories; the arithmetic must not fork)."""
    qx = -p.lam * d_xi(Tb) / p.dx
    qy = -p.lam * d_yi(Tb) / p.dy
    qz = -p.lam * d_zi(Tb) / p.dz
    dTdt = (-d_xa(qx) / p.dx - d_ya(qy) / p.dy
            - d_za(qz) / p.dz) / inn(Cpb)
    return Tb.at[1:-1, 1:-1, 1:-1].add(p.dt * dTdt)


def _upd2(Tb, Cpb, p: DiffusionParams):
    """2-D variant of `_upd3`."""
    qx = -p.lam * d_xi(Tb) / p.dx
    qy = -p.lam * d_yi(Tb) / p.dy
    dTdt = (-d_xa(qx) / p.dx - d_ya(qy) / p.dy) / inn(Cpb)
    return Tb.at[1:-1, 1:-1].add(p.dt * dTdt)


def _fresh_mask(shape, retreat):
    """Diffusion's deep-halo sub-step mask: the interior update retreats
    ``retreat`` cells per neighbor side (a scalar, or per-dim under a
    per-axis cadence) — ``[1 + r_d·L, n-1 - r_d·R)`` per dim (see
    `common.fresh_mask` for the shared machinery and the soundness
    argument)."""
    from .common import fresh_mask

    return fresh_mask(shape, retreat, (1,) * len(shape), (1,) * len(shape))


def init_diffusion3d(*, lam=1.0, cp_min=1.0, lx=10.0, ly=10.0, lz=10.0,
                     dtype=None, overlap=False, sr=False, sr_seed=0,
                     comm_every=None):
    """Build (T, Cp, params) with the reference example's initial conditions
    (two Gaussian anomalies each,
    `diffusion3D_multigpu_CuArrays_novis.jl:34-38`) as stacked sharded arrays.

    The grid must be initialized; local size is the grid's ``(nx, ny, nz)``.
    """
    import jax.numpy as jnp

    check_initialized()
    dx = lx / (nx_g() - 1)
    dy = ly / (ny_g() - 1)
    dz = lz / (nz_g() - 1)
    dt = min(dx * dx, dy * dy, dz * dz) * cp_min / lam / 8.1  # example :41

    Tz = zeros_g(dtype=dtype)
    x, y, z = coords_g(dx, dy, dz, Tz)
    x, y, z = (jnp.asarray(np.asarray(v), dtype=Tz.dtype) for v in (x, y, z))
    Cp = cp_min \
        + 5 * jnp.exp(-((x - lx / 1.5) ** 2) - ((y - ly / 2) ** 2) - ((z - lz / 1.5) ** 2)) \
        + 5 * jnp.exp(-((x - lx / 3.0) ** 2) - ((y - ly / 2) ** 2) - ((z - lz / 1.5) ** 2))
    T = 100 * jnp.exp(-(((x - lx / 2) / 2) ** 2) - (((y - ly / 2) / 2) ** 2) - (((z - lz / 3.0) / 2) ** 2)) \
        + 50 * jnp.exp(-(((x - lx / 2) / 2) ** 2) - (((y - ly / 2) / 2) ** 2) - (((z - lz / 1.5) / 2) ** 2))
    T = device_put_g(jnp.broadcast_to(T, Tz.shape).astype(Tz.dtype))
    Cp = device_put_g(jnp.broadcast_to(Cp, Tz.shape).astype(Tz.dtype))
    from .common import resolve_comm_every

    # comm_every=None consults IGG_COMM_EVERY (the wire-policy env
    # convention); stored canonically so the params value is hashable and
    # spelling-independent ("gz:4" and "z:4" build one cached runner)
    return T, Cp, DiffusionParams(lam=lam, dt=dt, dx=dx, dy=dy, dz=dz,
                                  overlap=overlap, sr=sr, sr_seed=sr_seed,
                                  comm_every=str(resolve_comm_every(
                                      comm_every)))


def init_diffusion2d(*, lam=1.0, cp_min=1.0, lx=10.0, ly=10.0, dtype=None):
    """2-D variant (BASELINE config: 2-D diffusion on a 2x2 mesh)."""
    import jax.numpy as jnp

    check_initialized()
    gg = global_grid()
    dx = lx / (nx_g() - 1)
    dy = ly / (ny_g() - 1)
    dt = min(dx * dx, dy * dy) * cp_min / lam / 4.1
    Tz = zeros_g(tuple(int(n) for n in gg.nxyz[:2]), dtype=dtype)
    x, y = coords_g(dx, dy, 1.0, Tz)[:2]
    x, y = (jnp.asarray(np.asarray(v), dtype=Tz.dtype) for v in (x, y))
    Cp = cp_min + 5 * jnp.exp(-((x - lx / 1.5) ** 2) - ((y - ly / 2) ** 2))
    T = 100 * jnp.exp(-(((x - lx / 2) / 2) ** 2) - (((y - ly / 2) / 2) ** 2))
    T = device_put_g(jnp.broadcast_to(T, Tz.shape).astype(Tz.dtype))
    Cp = device_put_g(jnp.broadcast_to(Cp, Tz.shape).astype(Tz.dtype))
    return T, Cp, DiffusionParams(lam=lam, dt=dt, dx=dx, dy=dy)


def diffusion_step_local(T, Cp, p: DiffusionParams, impl: str = "xla",
                         sr_key=None):
    """One time step on a LOCAL block (use inside shard_map) — the reference
    hot loop (`diffusion3D_multicpu_novis.jl:41-47`):

        q = -λ ∇T;   δT/δt = -∇·q / cₚ;   T += dt δT/δt;   update_halo(T)

    ``impl``: "xla" (broadcast flux form, fused by XLA) or "pallas" (fused
    single-pass Pallas TPU kernel, same arithmetic to the last ulp;
    "pallas_interpret" for CPU testing). Pallas covers 3-D and 2-D
    blocks; other ndims fall back to the XLA path.

    ``sr_key`` (with ``p.sr`` and a bfloat16 state) selects the
    stochastic-rounding storage path: f32 flux arithmetic, bf16 store with
    an unbiased round (`ops/precision.py`) — removes the plain-bf16
    stagnation bias. XLA formulation (the kernel tier has no in-kernel
    PRNG yet).
    """
    import jax.numpy as jnp

    if p.sr and T.dtype == jnp.bfloat16 and T.ndim in (2, 3):
        if sr_key is None:
            # make_step/make_run have no PRNG to thread — silently running
            # plain round-to-nearest here would reintroduce the exact
            # stagnation sr=True exists to prevent
            from ..utils.exceptions import InvalidArgumentError

            raise InvalidArgumentError(
                "DiffusionParams(sr=True) with a bfloat16 state needs the "
                "stochastic-rounding runner: use run_diffusion or "
                "make_run_sr (make_step/make_run cannot thread the "
                "per-step PRNG key).")
        from ..ops.precision import shard_unique_fold, stochastic_round_bf16

        key = shard_unique_fold(sr_key)
        upd = _upd3 if T.ndim == 3 else _upd2
        Tf = upd(T.astype(jnp.float32), Cp.astype(jnp.float32), p)
        return local_update_halo(stochastic_round_bf16(Tf, key))
    if impl.startswith("pallas") and T.ndim == 3:
        from ..ops.halo import _dim_exchanges
        from ..ops.pallas_stencil import (
            diffusion3d_step_exchange_pallas, diffusion3d_step_halo_pallas,
            diffusion3d_step_halo_pallas_mp, diffusion3d_step_pallas,
            fusable_halo_dims, mp_supported, step_exchange_modes,
        )

        gg = global_grid()
        interpret = impl == "pallas_interpret"
        kw = dict(lam=p.lam, dt=p.dt, dx=p.dx, dy=p.dy, dz=p.dz,
                  interpret=interpret)
        hws = tuple(int(h) for h in gg.halowidths)
        fuse = fusable_halo_dims(gg)
        covers_all = fuse is not None and not any(
            _dim_exchanges(gg, T.shape, hws, d) for d in range(3)
            if not fuse[d])
        if covers_all:
            # Every exchanging dim is self-neighbor: halo updates fold into
            # the step's output pass for free (in-plane selects / sigma
            # plane resourcing — no slab materialization at all). The
            # multi-plane kernel cuts T read traffic ~2.4x where its shape
            # gates pass.
            if mp_supported(T, interpret=interpret):
                return diffusion3d_step_halo_pallas_mp(T, Cp, fuse=fuse, **kw)
            return diffusion3d_step_halo_pallas(T, Cp, fuse=fuse, **kw)
        ex_modes = step_exchange_modes(gg, T)
        if ex_modes is not None:
            # Multi-shard (or mixed) exchange fused with the step: send
            # slabs computed from thin input slabs, ppermuted while the
            # plane sweep runs, delivered in the same output pass — the
            # pod-scale path (~2 array passes/step regardless of sharding).
            return diffusion3d_step_exchange_pallas(T, Cp, gg, ex_modes, **kw)
        if fuse is not None:
            # Partial fusion (a self-neighbor prefix of the z, x, y order
            # fuses in-kernel; a later dim is nonstandard): exchange only
            # the REMAINING dims afterwards — the suffix of the order, so
            # the reference's sequential-corner semantics hold.
            if mp_supported(T, interpret=interpret):
                T = diffusion3d_step_halo_pallas_mp(T, Cp, fuse=fuse, **kw)
            else:
                T = diffusion3d_step_halo_pallas(T, Cp, fuse=fuse, **kw)
            from ..ops.halo import DEFAULT_DIMS_ORDER

            rem = tuple(d for d in DEFAULT_DIMS_ORDER if not fuse[d])
            return local_update_halo(T, dims=rem)
        if mp_supported(T, interpret=interpret):
            T = diffusion3d_step_halo_pallas_mp(
                T, Cp, fuse=(False, False, False), **kw)
        else:
            T = diffusion3d_step_pallas(T, Cp, **kw)
    elif impl.startswith("pallas") and T.ndim == 2:
        from ..ops.pallas_stencil import (
            diffusion2d_step_exchange_pallas, step_exchange_modes,
            strip_rows_2d,
        )

        gg = global_grid()
        interpret = impl == "pallas_interpret"
        ex_modes = step_exchange_modes(gg, T)
        if ex_modes is not None and strip_rows_2d(
                T, interpret=interpret) is not None:
            # 2-D fused step + exchange (BASELINE config 2): row strips
            # through a double-buffered VMEM window; send slabs from thin
            # XLA slab computes, delivered in the same output pass.
            return diffusion2d_step_exchange_pallas(
                T, Cp, gg, ex_modes, lam=p.lam, dt=p.dt, dx=p.dx, dy=p.dy,
                interpret=interpret)
        return diffusion_step_local(T, Cp, p, impl="xla")
    elif T.ndim == 3:
        def upd(Tb, Cpb):
            return _upd3(Tb, Cpb, p)

        if p.overlap:
            from ..ops.overlap import hide_communication

            return hide_communication(upd, T, Cp, radius=1)
        T = upd(T, Cp)
    else:
        def upd2(Tb, Cpb):
            return _upd2(Tb, Cpb, p)

        if p.overlap:
            from ..ops.overlap import hide_communication

            return hide_communication(upd2, T, Cp, radius=1)
        T = upd2(T, Cp)
    return local_update_halo(T)


def _resolve_impl(impl, ndim=3):
    """Default impl: the grid's IGG_USE_PALLAS flag (the analog of the
    reference's per-dimension copy-kernel toggle IGG_USE_POLYESTER,
    `init_global_grid.jl:60,71-75`) selects the Pallas kernels on TPU grids
    (on by default there). The 3-D and 2-D steps have Pallas kernels —
    other ndims resolve to the XLA path so check_vma stays on for them. The
    fused step kernel covers all dims at once, so ANY explicit per-dim
    opt-out (e.g. IGG_USE_PALLAS_DIMX=0) falls back to the XLA path."""
    from .common import resolve_pallas_impl

    return resolve_pallas_impl(impl, eligible=ndim in (2, 3))


def _reject_comm_every(p: DiffusionParams, what: str):
    """make_step/make_run advance one exchange per step — silently running
    them with a deep cadence would measure nothing; route to
    `make_run_deep`/`run_diffusion` instead (same precedent as sr)."""
    from .common import resolve_comm_every

    if resolve_comm_every(p.comm_every).deep:
        from ..utils.exceptions import InvalidArgumentError

        raise InvalidArgumentError(
            f"DiffusionParams(comm_every={p.comm_every!r}) needs the "
            f"deep-halo runner: use run_diffusion or make_run_deep "
            f"({what} exchanges every step and cannot honor the cadence).")


def make_step(p: DiffusionParams, ndim: int = 3, impl: str | None = None):
    """Controller-level jitted single step on stacked arrays:
    ``T = step(T, Cp)``."""
    import jax

    _reject_comm_every(p, "make_step")
    check_initialized()
    gg = global_grid()
    spec = field_partition_spec(ndim)
    impl = _resolve_impl(impl, ndim)

    def local(T, Cp):
        return diffusion_step_local(T, Cp, p, impl)

    from ..utils.compat import shard_map
    from .common import default_check_vma

    return jax.jit(shard_map(
        local, mesh=gg.mesh, in_specs=(spec, spec), out_specs=spec,
        check_vma=default_check_vma(impl.startswith("pallas")),
    ))


def make_run(p: DiffusionParams, nt_chunk: int, ndim: int = 3,
             impl: str | None = None, ensemble: int | None = None):
    """Whole-loop runner: ONE compiled program advancing ``nt_chunk`` steps
    (`lax.fori_loop` with the halo ppermutes inline) — the TPU-first
    replacement for the reference's per-step dispatch loop. Built on the
    shared epoch-cached runner machinery (`models/common.py`); the state is
    ``(T, Cp)`` with ``Cp`` carried through unchanged.

    ``ensemble=E`` advances E scenario members per step through the SAME
    collectives (the vmapped chunk of `make_state_runner(ensemble=)`):
    state arrays lead with the member axis (`common.ensemble_state`),
    per-member ``Cp``/initial-condition variants included. XLA tier."""
    from .common import make_state_runner, resolve_ensemble_impl

    _reject_comm_every(p, "make_run")
    if ensemble is not None:
        impl = resolve_ensemble_impl(impl, "diffusion")
    else:
        impl = _resolve_impl(impl, ndim)

    def step(state):
        T, Cp = state
        return diffusion_step_local(T, Cp, p, impl), Cp

    return make_state_runner(
        step, (ndim, ndim), nt_chunk=nt_chunk,
        key=("diffusion", p, impl),
        check_vma=False if impl.startswith("pallas") else None,
        ensemble=ensemble,
    )


def make_run_sr(p: DiffusionParams, nt_chunk: int, ndim: int = 3):
    """Stochastic-rounding runner: state is ``(T, Cp, n)`` with ``n`` a
    replicated scalar GLOBAL step counter — the per-step PRNG key is
    ``fold_in(PRNGKey(p.sr_seed), n)``, so randomness never repeats across
    chunk calls (a chunk-local loop index would reuse the same stream
    every chunk, correlating the round directions of successive chunks).
    """
    import jax
    import jax.numpy as jnp

    from .common import make_state_runner

    def step(state):
        T, Cp, n = state
        # 'rbg' keys draw from lax.rng_bit_generator — the TPU's hardware
        # RNG path, much cheaper per bit than threefry's ALU lattice on a
        # bandwidth-bound step (and supported on cpu/gpu backends too)
        key = jax.random.fold_in(jax.random.key(p.sr_seed, impl="rbg"), n)
        T = diffusion_step_local(T, Cp, p, impl="xla", sr_key=key)
        return T, Cp, n + jnp.int32(1)

    return make_state_runner(step, (ndim, ndim, 0), nt_chunk=nt_chunk,
                             key=("diffusion_sr", p))


def deep_step(p: DiffusionParams, ndim: int = 3):
    """The communication-avoiding SUPER-STEP as a local step function:
    ``lcm(k_d)`` masked sub-steps (`_fresh_mask`, per-dim retreats) with
    each mesh axis's k-wide exchange issued only at the sub-steps its
    cadence makes it due (`CommCadence.due_dims` — a ``k_d = 1`` axis
    exchanges every sub-step, a deep axis once per ``k_d``). Validates
    the grid's halo geometry against the cadence; returns ``(step,
    cycle)`` where ``step((T, Cp)) -> (T, Cp)`` advances ``cycle``
    physical steps. The building block of `make_run_deep` and the
    scheduler's tuned builtin jobs (`service.job.builtin_setup`)."""
    import jax.numpy as jnp

    from .common import resolve_comm_every, validate_deep_halo

    check_initialized()
    gg = global_grid()
    cad = resolve_comm_every(p.comm_every)
    validate_deep_halo(gg, ndim, cad)
    K = cad.cycle

    upd = _upd3 if ndim == 3 else _upd2

    def step(state):
        T, Cp = state
        for j in range(K):
            Tn = upd(T, Cp, p)
            r = cad.retreats(j, ndim)
            if any(r):
                T = jnp.where(_fresh_mask(T.shape, r), Tn, T)
            else:
                T = Tn  # all axes fresh: full-interior update
            due = cad.due_dims(j, ndim)
            if due:
                T = local_update_halo(T, dims=due)
        return T, Cp

    return step, K


def make_run_deep(p: DiffusionParams, nt_chunk_super: int, ndim: int = 3,
                  ensemble: int | None = None):
    """Communication-avoiding runner: ONE super-step = the per-axis
    cadence's full cycle of masked sub-steps (`deep_step`), with each
    axis's k-wide exchange once per ``k_d`` sub-steps.
    ``nt_chunk_super`` counts super-steps (physical steps / lcm(k_d)).
    ``ensemble=E`` batches E scenario members through the SAME deep-halo
    collectives (the vmapped chunk of `make_state_runner(ensemble=)` —
    XLA tier, like the cadence itself)."""
    from .common import make_state_runner, resolve_comm_every

    step, _ = deep_step(p, ndim)
    cad = resolve_comm_every(p.comm_every)
    return make_state_runner(step, (ndim, ndim), nt_chunk=nt_chunk_super,
                             key=("diffusion_deep", p, str(cad), ensemble),
                             ensemble=ensemble)


def run_diffusion(T, Cp, p: DiffusionParams, nt: int, *, nt_chunk: int = 100,
                  impl: str | None = None, ensemble: int | None = None):
    """Advance ``nt`` steps, compiling at most two chunk sizes. With
    ``p.sr`` and a bfloat16 state, routes through the stochastic-rounding
    runner (the step counter is threaded internally).

    ``ensemble=E`` advances an E-member batch (``T``/``Cp`` lead with the
    member axis — `common.ensemble_state`): one mesh, one set of
    collectives, E trajectories per step. Composes with ``comm_every``
    deep-halo cadences on the XLA tier (the vmapped deep super-step —
    each batched ppermute now amortizes BOTH ways: E members per payload,
    1/k_d launches per axis); ``sr=True`` stays a solo-run feature."""
    import jax.numpy as jnp

    from ..utils.exceptions import InvalidArgumentError
    from .common import resolve_comm_every, run_chunked

    cad = resolve_comm_every(p.comm_every)
    if ensemble is not None:
        E = int(ensemble)
        if p.sr:
            raise InvalidArgumentError(
                "ensemble batching does not support sr=True "
                "(stochastic-rounding storage is a solo-run feature).")
        if T.ndim < 2 or int(T.shape[0]) != E:
            raise InvalidArgumentError(
                f"ensemble={E} expects T to lead with the member axis "
                f"(shape (E, ...)); got {tuple(T.shape)} — build the "
                "state with models.common.ensemble_state.")
        ndim = T.ndim - 1
        if cad.deep:
            if impl is not None and not impl.startswith("xla"):
                raise InvalidArgumentError(
                    f"impl={impl!r} is incompatible with comm_every="
                    f"{cad}: deep-halo stepping (batched or solo) runs "
                    "only the XLA tier.")
            K = cad.cycle
            if nt % K:
                raise InvalidArgumentError(
                    f"nt={nt} must be a multiple of the cadence cycle "
                    f"{K} (comm_every={cad} defines the trajectory).")
            T, Cp = run_chunked(
                lambda c: make_run_deep(p, c, ndim, ensemble=E),
                (T, Cp), nt // K, max(1, nt_chunk // K))
            return T
        T, Cp = run_chunked(
            lambda c: make_run(p, c, ndim, impl, ensemble=E),
            (T, Cp), nt, nt_chunk)
        return T
    ndim = T.ndim
    if cad.deep:
        from ..utils.exceptions import InvalidArgumentError

        if p.sr and T.dtype == jnp.bfloat16:  # sr is a no-op otherwise
            raise InvalidArgumentError(
                "a deep comm_every cadence with sr=True is not supported "
                "yet (the deep-halo runner has no PRNG threading).")
        if impl is not None and not impl.startswith("xla"):
            raise InvalidArgumentError(
                f"impl={impl!r} is incompatible with comm_every={cad}: "
                "deep-halo stepping currently runs only the XLA tier.")
        K = cad.cycle
        if nt % K:
            raise InvalidArgumentError(
                f"nt={nt} must be a multiple of the cadence cycle {K} "
                f"(comm_every={cad} defines the trajectory).")
        T, Cp = run_chunked(lambda c: make_run_deep(p, c, ndim),
                            (T, Cp), nt // K, max(1, nt_chunk // K))
        return T
    if p.sr and T.dtype == jnp.bfloat16:
        if impl is not None and not impl.startswith("xla"):
            from ..utils.exceptions import InvalidArgumentError

            raise InvalidArgumentError(
                f"impl={impl!r} is incompatible with DiffusionParams(sr="
                "True) on a bfloat16 state: stochastic-rounding storage "
                "currently runs only the XLA tier (the Pallas kernels "
                "have no in-kernel PRNG yet). Pass impl=None/'xla' or "
                "disable sr.")
        T, Cp, _ = run_chunked(lambda c: make_run_sr(p, c, ndim),
                               (T, Cp, jnp.int32(0)), nt, nt_chunk)
        return T
    T, Cp = run_chunked(lambda c: make_run(p, c, ndim, impl), (T, Cp),
                        nt, nt_chunk)
    return T
