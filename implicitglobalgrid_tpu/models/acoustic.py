"""3-D acoustic wave propagation on a staggered grid.

The BASELINE config "3-D acoustic wave w/ @hide_communication overlap"
(`/root/repo/BASELINE.json`): first-order velocity–pressure formulation on a
staggered grid (the classic ParallelStencil miniapp family the reference
ecosystem benchmarks; the reference provides the staggered-field machinery
it runs on — per-field overlaps `shared.jl:107`, staggered coordinates
`tools.jl:98-107`):

    ∂V/∂t = -∇P / ρ          (velocities on cell faces: Vx is (nx+1, ny, nz))
    ∂P/∂t = -K ∇·V           (pressure at cell centers)

Each step exchanges halos of all four fields; with ``overlap=True`` the
pressure update runs through `hide_communication` so the P-halo ppermutes
hide behind interior compute.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops.alloc import device_put_g, zeros_g
from ..ops.halo import local_update_halo
from ..ops.overlap import hide_communication
from ..parallel.topology import check_initialized, global_grid
from ..tools import coords_g, nx_g, ny_g, nz_g
from .common import make_state_runner, run_chunked

__all__ = ["AcousticParams", "init_acoustic3d", "acoustic_step_local",
           "make_acoustic_run", "make_acoustic_run_deep", "deep_step",
           "run_acoustic"]


@dataclass(frozen=True)
class AcousticParams:
    """``comm_every`` enables communication-avoiding deep halos for the
    leapfrog (see `DiffusionParams.comm_every` for the scheme): ONE
    4-field k-wide exchange per k steps replaces the per-step V-round +
    P-round — one collective round where the base scheme does 2k. Between exchanges the V
    updates retreat ``j`` cells per neighbor side at sub-step j (their P
    dependencies are j sub-steps stale) and the P update retreats
    ``j+1`` (it needs the CURRENT sub-step's V). The cadence is PER MESH
    AXIS (``"z:4,x:1"`` / ``IGG_COMM_EVERY`` — see
    `DiffusionParams.comm_every`): along each axis the retreats advance
    at that axis's own staleness and the 4-field exchange fires only on
    the axes due that sub-step. XLA tier; ignores ``overlap``; needs
    ``overlaps[d] >= 2*k_d, halowidths[d] = k_d`` grids.
    Trajectory is bit-identical (tests/test_comm_avoid.py)."""
    rho: float      # density
    K: float        # bulk modulus
    dt: float
    dx: float
    dy: float
    dz: float
    overlap: bool = False   # hide_communication for the P update
    comm_every: int | str = 1


def init_acoustic3d(*, rho=1.0, K=1.0, lx=10.0, ly=10.0, lz=10.0,
                    dtype=None, overlap=False, comm_every=None):
    """State (P, Vx, Vy, Vz) with a Gaussian pressure pulse in the center.
    Velocities live on faces: Vx is local ``(nx+1, ny, nz)`` (staggered —
    exercised exactly like the reference's `Vx = zeros(nx+1, ...)` pattern,
    `tools.jl:88`)."""
    import jax.numpy as jnp

    check_initialized()
    gg = global_grid()
    nx, ny, nz = (int(n) for n in gg.nxyz)
    dx, dy, dz = lx / (nx_g() - 1), ly / (ny_g() - 1), lz / (nz_g() - 1)
    c = float(np.sqrt(K / rho))
    # plain python float: a np.float64 scalar would promote f32 state arrays
    # to f64 under jax_enable_x64
    dt = float(min(dx, dy, dz) / c / np.sqrt(3.1))

    Pz = zeros_g((nx, ny, nz), dtype=dtype)
    x, y, z = coords_g(dx, dy, dz, Pz)
    r2 = ((np.asarray(x) - lx / 2) ** 2 + (np.asarray(y) - ly / 2) ** 2
          + (np.asarray(z) - lz / 2) ** 2)
    P = device_put_g(np.broadcast_to(np.exp(-r2), Pz.shape).astype(Pz.dtype))
    Vx = zeros_g((nx + 1, ny, nz), dtype=dtype)
    Vy = zeros_g((nx, ny + 1, nz), dtype=dtype)
    Vz = zeros_g((nx, ny, nz + 1), dtype=dtype)
    from .common import resolve_comm_every

    return (P, Vx, Vy, Vz), AcousticParams(
        rho=rho, K=K, dt=dt, dx=dx, dy=dy, dz=dz, overlap=overlap,
        comm_every=str(resolve_comm_every(comm_every)))


def acoustic_step_local(state, p: AcousticParams, impl: str = "xla"):
    """One leapfrog step on LOCAL blocks (inside shard_map).

    ``impl``: "xla" (broadcast updates + per-field exchange) or "pallas"
    (ONE fused Pallas pass doing both updates and the full 4-field
    exchange — `ops/pallas_wave.py`; "pallas_interpret" for CPU tests)."""
    from jax import lax

    P, Vx, Vy, Vz = state
    if impl.startswith("pallas"):
        from ..ops.pallas_wave import (
            acoustic_step_exchange_pallas, wave_exchange_modes,
        )

        gg = global_grid()
        modes = wave_exchange_modes(
            gg, (P.shape, Vx.shape, Vy.shape, Vz.shape))
        if modes is not None:
            return acoustic_step_exchange_pallas(
                state, gg, modes, rho=p.rho, K=p.K, dt=p.dt,
                dx=p.dx, dy=p.dy, dz=p.dz,
                interpret=impl == "pallas_interpret")
        # ineligible config: fall through to the XLA formulation

    # velocity update on interior faces: face i sits between cells i-1, i
    def dP(A, d):
        n = A.shape[d]
        return lax.slice_in_dim(A, 1, n, axis=d) - lax.slice_in_dim(A, 0, n - 1, axis=d)

    def v_update(vx, vy, vz, Pc):
        vx = vx.at[1:-1, :, :].add(-p.dt / p.rho * dP(Pc, 0) / p.dx)
        vy = vy.at[:, 1:-1, :].add(-p.dt / p.rho * dP(Pc, 1) / p.dy)
        vz = vz.at[:, :, 1:-1].add(-p.dt / p.rho * dP(Pc, 2) / p.dz)
        return vx, vy, vz

    def p_update(Pc, vx, vy, vz):
        divV = (dP(vx, 0) / p.dx + dP(vy, 1) / p.dy + dP(vz, 2) / p.dz)
        return Pc - p.dt * p.K * divV

    if p.overlap:
        # INTERIOR-FIRST rounds (models/common.interior_first_step): the
        # V shell computes first, its ONE coalesced 3-field ppermute round
        # overlaps the interior V update; then the P round likewise
        # (radius-0 update from the face-staggered exchanged V fields).
        from .common import interior_first_step

        Vx, Vy, Vz = interior_first_step(v_update, (Vx, Vy, Vz), (P,),
                                         radius=1)
        P = hide_communication(p_update, P, Vx, Vy, Vz, radius=0)
    else:
        Vx, Vy, Vz = v_update(Vx, Vy, Vz, P)
        Vx, Vy, Vz = local_update_halo(Vx, Vy, Vz)
        P = p_update(P, Vx, Vy, Vz)
        P = local_update_halo(P)
    return (P, Vx, Vy, Vz)


def deep_step(p: AcousticParams):
    """The deep-halo leapfrog SUPER-STEP as a local step function:
    ``lcm(k_d)`` masked sub-steps with the 4-field k-wide exchange fired
    per axis at its own cadence. Returns ``(step, cycle)``.

    Sub-step masks, per dim ``d`` with staleness ``r_d = j mod k_d``
    (neighbor sides; `common.fresh_mask`):
    - each V field: retreat ``r_d`` with base offset 1 in its staggered
      dim (of its n+1 faces the base update touches ``[1, n)`` —
      ``at[1:-1]``, so base_hi=1 off the n+1 length) and 0 elsewhere —
      its P dependencies are ``r_d`` sub-steps stale along ``d``;
    - P: retreat ``r_d + 1`` with base 0 (the base update touches every
      cell) — it consumes THIS sub-step's V, whose faces have retreated
      ``r_d + 1`` in the staggered dim.
    The skipped bands (<= k_d wide between that axis's exchanges) are
    exactly what the k_d-wide exchange overwrites."""
    import jax.numpy as jnp
    from jax import lax

    from .common import (
        fresh_mask, resolve_comm_every, validate_deep_halo,
    )

    check_initialized()
    gg = global_grid()
    cad = resolve_comm_every(p.comm_every)
    validate_deep_halo(gg, 3, cad)
    K = cad.cycle

    def dP(A, d):
        n = A.shape[d]
        return (lax.slice_in_dim(A, 1, n, axis=d)
                - lax.slice_in_dim(A, 0, n - 1, axis=d))

    def step(state):
        P, Vx, Vy, Vz = state
        for j in range(K):
            r = cad.retreats(j)
            Vn = [Vx.at[1:-1, :, :].add(-p.dt / p.rho * dP(P, 0) / p.dx),
                  Vy.at[:, 1:-1, :].add(-p.dt / p.rho * dP(P, 1) / p.dy),
                  Vz.at[:, :, 1:-1].add(-p.dt / p.rho * dP(P, 2) / p.dz)]
            if any(r):
                Vn = [jnp.where(fresh_mask(
                          Vn[s].shape, r,
                          tuple(1 if d == s else 0 for d in range(3)),
                          tuple(1 if d == s else 0 for d in range(3))),
                          Vn[s], (Vx, Vy, Vz)[s]) for s in range(3)]
            Vx, Vy, Vz = Vn
            Pn = P - p.dt * p.K * (dP(Vx, 0) / p.dx + dP(Vy, 1) / p.dy
                                   + dP(Vz, 2) / p.dz)
            P = jnp.where(fresh_mask(P.shape, tuple(x + 1 for x in r),
                                     (0, 0, 0), (0, 0, 0)),
                          Pn, P)
            due = cad.due_dims(j)
            if due:
                P, Vx, Vy, Vz = local_update_halo(P, Vx, Vy, Vz, dims=due)
        return (P, Vx, Vy, Vz)

    return step, K


def make_acoustic_run_deep(p: AcousticParams, nt_chunk_super: int,
                           ensemble: int | None = None):
    """Deep-halo leapfrog runner: ONE super-step = the cadence cycle of
    masked sub-steps (`deep_step`) with per-axis 4-field k-wide
    exchanges. ``ensemble=E`` batches E members through the same deep
    collectives (XLA tier)."""
    from .common import make_state_runner, resolve_comm_every

    step, _ = deep_step(p)
    cad = resolve_comm_every(p.comm_every)
    return make_state_runner(step, (3, 3, 3, 3), nt_chunk=nt_chunk_super,
                             key=("acoustic3d_deep", p, str(cad), ensemble),
                             ensemble=ensemble)


def _resolve_impl(impl):
    from .common import resolve_pallas_impl

    return resolve_pallas_impl(impl)


def make_acoustic_run(p: AcousticParams, nt_chunk: int,
                      impl: str | None = None,
                      ensemble: int | None = None):
    from .common import resolve_comm_every

    if resolve_comm_every(p.comm_every).deep:
        from ..utils.exceptions import InvalidArgumentError

        raise InvalidArgumentError(
            f"AcousticParams(comm_every={p.comm_every!r}) needs the "
            "deep-halo runner: use run_acoustic or make_acoustic_run_deep "
            "(make_acoustic_run exchanges every step).")
    if ensemble is not None:
        from .common import resolve_ensemble_impl

        impl = resolve_ensemble_impl(impl, "acoustic")
    else:
        impl = _resolve_impl(impl)
    return make_state_runner(
        lambda s: acoustic_step_local(s, p, impl), (3, 3, 3, 3),
        nt_chunk=nt_chunk, key=("acoustic3d", p, impl),
        check_vma=False if impl.startswith("pallas") else None,
        ensemble=ensemble,
    )


def run_acoustic(state, p: AcousticParams, nt: int, *, nt_chunk: int = 100,
                 impl: str | None = None, ensemble: int | None = None):
    from ..utils.exceptions import InvalidArgumentError
    from .common import resolve_comm_every

    cad = resolve_comm_every(p.comm_every)
    if cad.deep:
        if impl is not None and not impl.startswith("xla"):
            raise InvalidArgumentError(
                f"impl={impl!r} is incompatible with comm_every={cad}: "
                "deep-halo stepping currently runs only the XLA tier.")
        K = cad.cycle
        if nt % K:
            raise InvalidArgumentError(
                f"nt={nt} must be a multiple of the cadence cycle {K} "
                f"(comm_every={cad} defines the trajectory).")
        E = None if ensemble is None else int(ensemble)
        return run_chunked(
            lambda c: make_acoustic_run_deep(p, c, ensemble=E), state,
            nt // K, max(1, nt_chunk // K))
    if ensemble is not None:
        return run_chunked(
            lambda c: make_acoustic_run(p, c, impl, ensemble=int(ensemble)),
            state, nt, nt_chunk)
    impl = _resolve_impl(impl)
    return run_chunked(lambda c: make_acoustic_run(p, c, impl), state, nt,
                       nt_chunk)
