"""3-D pseudo-transient (PT) Stokes solver on a staggered grid.

The BASELINE config "3-D pseudo-transient Stokes solver, weak-scale to
v5p-256" (`/root/repo/BASELINE.json`): isoviscous, incompressible Stokes flow
driven by a buoyant spherical inclusion, solved by damped pseudo-transient
iteration — the hydro-mechanical miniapp family the reference's weak-scaling
figure is built on (`reference README.md:6-8`). Built entirely on the
framework's staggered-field machinery (per-field overlaps `shared.jl:107`):

    cell centers: P, τxx, τyy, τzz, ρg      faces: Vx, Vy, Vz
    edges: τxy, τxz, τyz

    divV = ∇·V
    P   ← P − dτ_P divV
    τii ← 2μ (∂iVi − divV/3)
    τij ← μ (∂jVi + ∂iVj)
    R_i = −∂iP + ∂jτij (+ buoyancy)
    dV  ← damp·dV + R          (damped PT momentum)
    V   ← V + dτ_V dV
    halo-exchange V (and P)

One PT iteration is one `step_local` inside the whole-loop-jitted runner;
convergence is monitored by `residuals` (max |divV|, max |R|) — psum-reduced
across the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops.alloc import device_put_g, zeros_g
from ..ops.halo import local_update_halo
from ..parallel.topology import AXIS_NAMES, check_initialized, global_grid
from ..tools import coords_g, nx_g, ny_g, nz_g
from .common import make_state_runner, run_chunked

__all__ = ["StokesParams", "init_stokes3d", "stokes_step_local",
           "make_stokes_run", "make_stokes_run_deep", "deep_step",
           "run_stokes", "stokes_residuals"]


@dataclass(frozen=True)
class StokesParams:
    """``comm_every`` enables communication-avoiding deep halos for the
    PT iteration (see `DiffusionParams.comm_every` for the scheme). The
    PT dependency radius is 2 per iteration (V consumes stresses, which
    consume V), so k iterations need ``halowidths = 2k`` /
    ``overlaps >= 4k`` grids, and the super-step exchange carries SEVEN
    fields (P, V×3, dV×3 — dV is damped state that the base scheme keeps
    consistent by recomputing it at every face every iteration, so the
    deep scheme must exchange it). One 7-field round per k iterations
    replaces k 4-field rounds. The cadence is PER MESH AXIS
    (``"z:2,x:1"`` / ``IGG_COMM_EVERY`` — see
    `DiffusionParams.comm_every`), each axis needing ``halowidths[d] =
    2*k_d`` / ``overlaps[d] >= 4*k_d``: this is the configuration that
    rescues the recorded COMM_AVOID.json LOSING row — a z-only cadence
    amortizes the slow axis's latency without paying the doubled slab
    compute on the fast axes. XLA tier. Trajectory: agrees with the
    per-iteration-exchange scheme to ~1 ulp per super-step pair on
    XLA:CPU (tests/test_comm_avoid.py asserts <=1e-12 rel with five
    decades of headroom; P stays BIT-exact over one super-step pair).
    The residual is a backend-codegen artifact, not a scheme
    error: the masked scheme substitutes a locally computed cell for the
    exchanged copy of the same physical cell, which is exact only when
    codegen rounds identically at different array positions — the CPU
    backend's vector-loop epilogues break that by 1 ulp for this model's
    long expression chain (diagnosed round 5: the k=1 degenerate deep
    runner IS bit-exact vs the base scheme, P — short chain — stays
    bit-exact at every k, and ~25 cells/super-step-pair at
    lane-boundary positions carry the ulp). Immaterial for a PT solver
    converging to a tolerance; expected bit-exact on TPU's uniform
    vector lanes (no epilogues), pending hardware validation.

    ``overlap`` routes the XLA iteration through the INTERIOR-FIRST step
    shape (`models/common.interior_first_step`): the 7 updated fields'
    boundary shells compute first, the single coalesced 4-field
    (Vx, Vy, Vz, Pn) ppermute round depends only on them, and the
    interior update schedules under the collectives. Semantically
    identical to the plain iteration (same caveat about CPU vector-loop
    epilogue ulps as comm_every; asserted under the overlap-equivalence
    tolerance in tests/test_overlap.py). XLA tier; the fused Pallas pass
    structures its own communication and ignores it."""
    mu: float       # shear viscosity
    dt_v: float     # pseudo time step, momentum
    dt_p: float     # pseudo time step, pressure
    damp: float     # PT damping factor
    dx: float
    dy: float
    dz: float
    comm_every: int | str = 1
    overlap: bool = False


def init_stokes3d(*, mu=1.0, lx=10.0, ly=10.0, lz=10.0, rhog_mag=1.0,
                  r_incl=1.0, dtype=None, comm_every=None, overlap=False):
    """State (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog): zero initial flow, a
    buoyant sphere of radius ``r_incl`` at the domain center."""
    check_initialized()
    gg = global_grid()
    nx, ny, nz = (int(n) for n in gg.nxyz)
    dx, dy, dz = lx / (nx_g() - 1), ly / (ny_g() - 1), lz / (nz_g() - 1)
    # standard PT scalings (damped wave equation analogy)
    min_d = min(dx, dy, dz)
    n_max = max(nx_g(), ny_g(), nz_g())
    dt_v = min_d ** 2 / mu / 6.1 / 2.0
    dt_p = 6.1 * mu / n_max
    damp = 1.0 - 6.0 / n_max

    P = zeros_g((nx, ny, nz), dtype=dtype)
    x, y, z = coords_g(dx, dy, dz, P)
    r2 = ((np.asarray(x) - lx / 2) ** 2 + (np.asarray(y) - ly / 2) ** 2
          + (np.asarray(z) - lz / 2) ** 2)
    rhog = device_put_g(
        np.broadcast_to((r2 < r_incl ** 2) * rhog_mag, P.shape).astype(P.dtype))
    Vx = zeros_g((nx + 1, ny, nz), dtype=dtype)
    Vy = zeros_g((nx, ny + 1, nz), dtype=dtype)
    Vz = zeros_g((nx, ny, nz + 1), dtype=dtype)
    # damped-momentum fields mirror the velocity shapes (only interior faces
    # are ever nonzero) — face-aligned full-size arrays keep the Pallas
    # kernel tier's plane mapping uniform across the state
    dVx = zeros_g((nx + 1, ny, nz), dtype=dtype)
    dVy = zeros_g((nx, ny + 1, nz), dtype=dtype)
    dVz = zeros_g((nx, ny, nz + 1), dtype=dtype)
    state = (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog)
    from .common import resolve_comm_every

    return state, StokesParams(mu=mu, dt_v=dt_v, dt_p=dt_p, damp=damp,
                               dx=dx, dy=dy, dz=dz,
                               comm_every=str(resolve_comm_every(
                                   comm_every)),
                               overlap=overlap)


def _d(A, d):
    from jax import lax

    n = A.shape[d]
    return lax.slice_in_dim(A, 1, n, axis=d) - lax.slice_in_dim(A, 0, n - 1, axis=d)


def _inner(A, dims_sel):
    from jax import lax

    for d in dims_sel:
        A = lax.slice_in_dim(A, 1, A.shape[d] - 1, axis=d)
    return A


def _stokes_terms(state, p: StokesParams):
    """Residuals R_i at interior faces (shared by step and monitor)."""
    P, Vx, Vy, Vz, dVx, dVy, dVz, rhog = state
    divV = _d(Vx, 0) / p.dx + _d(Vy, 1) / p.dy + _d(Vz, 2) / p.dz  # centers
    Pn = P - p.dt_p * divV
    txx = 2 * p.mu * (_d(Vx, 0) / p.dx - divV / 3)
    tyy = 2 * p.mu * (_d(Vy, 1) / p.dy - divV / 3)
    tzz = 2 * p.mu * (_d(Vz, 2) / p.dz - divV / 3)
    # edge shear stresses on interior edges
    txy = p.mu * (_inner(_d(Vx, 1), (0,)) / p.dy + _inner(_d(Vy, 0), (1,)) / p.dx)
    txz = p.mu * (_inner(_d(Vx, 2), (0,)) / p.dz + _inner(_d(Vz, 0), (2,)) / p.dx)
    tyz = p.mu * (_inner(_d(Vy, 2), (1,)) / p.dz + _inner(_d(Vz, 1), (2,)) / p.dy)

    Rx = (_inner(_d(txx - Pn, 0), (1, 2)) / p.dx
          + _d(_inner(txy, (2,)), 1) / p.dy
          + _d(_inner(txz, (1,)), 2) / p.dz)
    Ry = (_inner(_d(tyy - Pn, 1), (0, 2)) / p.dy
          + _d(_inner(txy, (2,)), 0) / p.dx
          + _d(_inner(tyz, (0,)), 2) / p.dz)
    rg_face = 0.5 * (_d(rhog, 2) + 2 * rhog[:, :, :-1])  # avg to z-faces
    Rz = (_inner(_d(tzz - Pn, 2), (0, 1)) / p.dz
          + _d(_inner(txz, (1,)), 0) / p.dx
          + _d(_inner(tyz, (0,)), 1) / p.dy
          + _inner(rg_face, (0, 1)))
    return Pn, divV, Rx, Ry, Rz


def stokes_step_local(state, p: StokesParams, impl: str = "xla"):
    """One damped PT iteration on LOCAL blocks (inside shard_map).

    ``impl``: "xla", or "pallas" — ONE fused Pallas pass computing the
    pressure/stress/momentum updates AND delivering the halo exchange of
    (Vx, Vy, Vz, Pn) (`ops/pallas_stokes.py`; "pallas_interpret" on CPU)."""
    P, Vx, Vy, Vz, dVx, dVy, dVz, rhog = state
    if impl.startswith("pallas"):
        from ..ops.pallas_stokes import (
            stokes_exchange_modes, stokes_step_exchange_pallas,
        )

        gg = global_grid()
        modes = stokes_exchange_modes(gg, tuple(a.shape for a in state))
        if modes is not None:
            return stokes_step_exchange_pallas(
                state, gg, modes, p, interpret=impl == "pallas_interpret")
        # ineligible config: fall through to the XLA formulation
    ix = (slice(1, -1),) * 3

    def pt_update(vx, vy, vz, Pc, dvx, dvy, dvz, rh):
        """One PT update on (a slab of) the state — everything but the
        exchange, returning the 7 updated fields in exchange-first order
        (Vx, Vy, Vz, Pn first: the wired round of the interior-first
        shape)."""
        Pn, divV, Rx, Ry, Rz = _stokes_terms(
            (Pc, vx, vy, vz, dvx, dvy, dvz, rh), p)
        dvx_i = p.damp * dvx[ix] + Rx
        dvy_i = p.damp * dvy[ix] + Ry
        dvz_i = p.damp * dvz[ix] + Rz
        return (vx.at[ix].add(p.dt_v * dvx_i),
                vy.at[ix].add(p.dt_v * dvy_i),
                vz.at[ix].add(p.dt_v * dvz_i),
                Pn,
                dvx.at[ix].set(dvx_i),
                dvy.at[ix].set(dvy_i),
                dvz.at[ix].set(dvz_i))

    if p.overlap:
        # interior-first: shells of all 7 updated fields, ONE coalesced
        # (Vx, Vy, Vz, Pn) round depending only on them, interior under
        # the collectives (models/common.interior_first_step)
        from .common import interior_first_step

        Vx, Vy, Vz, Pn, dVx, dVy, dVz = interior_first_step(
            pt_update, (Vx, Vy, Vz, P, dVx, dVy, dVz), (rhog,),
            radius=1, n_exchange=4)
        return (Pn, Vx, Vy, Vz, dVx, dVy, dVz, rhog)
    Vx, Vy, Vz, Pn, dVx, dVy, dVz = pt_update(Vx, Vy, Vz, P,
                                              dVx, dVy, dVz, rhog)
    Vx, Vy, Vz, Pn = local_update_halo(Vx, Vy, Vz, Pn)
    return (Pn, Vx, Vy, Vz, dVx, dVy, dVz, rhog)


def deep_step(p: StokesParams):
    """The deep-halo PT SUPER-STEP as a local step function: ``lcm(k_d)``
    masked iterations with the 7-field 2k-wide exchange (P, V×3, dV×3)
    fired per axis at its own cadence. Returns ``(step, cycle)``.

    Iteration masks, per dim ``d`` with staleness ``r_d = j mod k_d``
    (`common.fresh_mask`; the PT dependency radius is 2 per iteration,
    derived from the pre-update V the terms consume):
    - P: retreat ``2·r_d`` with base 0 (the base update touches every
      cell; its V dependencies are ``2(r_d-1)+2`` deep at staleness
      r_d >= 1);
    - V and dV: retreat ``2·r_d+1`` where ``r_d >= 1`` (0 on a
      just-exchanged axis) with base 1 per dim (base region ``at[1:-1]``;
      they consume THIS iteration's Pn — retreat 2·r_d — plus edge
      stresses one cell deeper).
    The masked bands (<= 2·k_d wide between an axis's exchanges) are
    exactly what that axis's 2k-wide exchange overwrites; dV joins the
    exchange because the base scheme keeps its band consistent by
    recomputing every face every iteration, which the deep scheme's
    masks skip."""
    import jax.numpy as jnp

    from .common import (
        fresh_mask, resolve_comm_every, validate_deep_halo,
    )

    check_initialized()
    gg = global_grid()
    cad = resolve_comm_every(p.comm_every)
    validate_deep_halo(gg, 3, cad, depth_per_step=2)
    K = cad.cycle

    ix = (slice(1, -1),) * 3

    def step(state):
        P, Vx, Vy, Vz, dVx, dVy, dVz, rhog = state
        for j in range(K):
            r = cad.retreats(j)
            Pn, divV, Rx, Ry, Rz = _stokes_terms(
                (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog), p)
            if any(r):
                Pn = jnp.where(fresh_mask(P.shape,
                                          tuple(2 * x for x in r),
                                          (0, 0, 0), (0, 0, 0)), Pn, P)
            upd = []
            for V, dV, R in ((Vx, dVx, Rx), (Vy, dVy, Ry), (Vz, dVz, Rz)):
                dV_i = p.damp * dV[ix] + R
                dVn = dV.at[ix].set(dV_i)
                Vn = V.at[ix].add(p.dt_v * dV_i)
                if any(r):
                    m = fresh_mask(V.shape,
                                   tuple(2 * x + 1 if x else 0 for x in r),
                                   (1, 1, 1), (1, 1, 1))
                    Vn = jnp.where(m, Vn, V)
                    dVn = jnp.where(m, dVn, dV)
                upd.append((Vn, dVn))
            (Vx, dVx), (Vy, dVy), (Vz, dVz) = upd
            P = Pn
            due = cad.due_dims(j)
            if due:
                P, Vx, Vy, Vz, dVx, dVy, dVz = local_update_halo(
                    P, Vx, Vy, Vz, dVx, dVy, dVz, dims=due)
        return (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog)

    return step, K


def make_stokes_run_deep(p: StokesParams, nt_chunk_super: int,
                         ensemble: int | None = None):
    """Deep-halo PT runner: ONE super-step = the cadence cycle of masked
    iterations (`deep_step`) with per-axis 7-field 2k-wide exchanges.
    ``ensemble=E`` batches E member realizations through the same deep
    collectives (XLA tier)."""
    from .common import make_state_runner, resolve_comm_every

    step, _ = deep_step(p)
    cad = resolve_comm_every(p.comm_every)
    return make_state_runner(step, (3,) * 8, nt_chunk=nt_chunk_super,
                             key=("stokes3d_deep", p, str(cad), ensemble),
                             ensemble=ensemble)


def _resolve_impl(impl):
    from .common import resolve_pallas_impl

    return resolve_pallas_impl(impl)


def make_stokes_run(p: StokesParams, nt_chunk: int, impl: str | None = None,
                    ensemble: int | None = None):
    from .common import resolve_comm_every

    if resolve_comm_every(p.comm_every).deep:
        from ..utils.exceptions import InvalidArgumentError

        raise InvalidArgumentError(
            f"StokesParams(comm_every={p.comm_every!r}) needs the "
            "deep-halo runner: use run_stokes or make_stokes_run_deep "
            "(make_stokes_run exchanges every iteration).")
    if ensemble is not None:
        from .common import resolve_ensemble_impl

        impl = resolve_ensemble_impl(impl, "stokes")
    else:
        impl = _resolve_impl(impl)
    return make_state_runner(
        lambda s: stokes_step_local(s, p, impl), (3,) * 8,
        nt_chunk=nt_chunk, key=("stokes3d", p, impl),
        check_vma=False if impl.startswith("pallas") else None,
        ensemble=ensemble,
    )


def run_stokes(state, p: StokesParams, nt: int, *, nt_chunk: int = 100,
               impl: str | None = None, ensemble: int | None = None):
    """Run ``nt`` PT iterations (one compiled program per chunk). With
    ``p.comm_every > 1``, routes through the deep-halo runner.
    ``ensemble=E`` batches E member realizations through one chunk
    (member-stacked state, `common.ensemble_state`; plain XLA tier)."""
    from ..utils.exceptions import InvalidArgumentError
    from .common import resolve_comm_every

    cad = resolve_comm_every(p.comm_every)
    if cad.deep:
        if impl is not None and not impl.startswith("xla"):
            raise InvalidArgumentError(
                f"impl={impl!r} is incompatible with comm_every={cad}: "
                "deep-halo stepping currently runs only the XLA tier.")
        K = cad.cycle
        if nt % K:
            raise InvalidArgumentError(
                f"nt={nt} must be a multiple of the cadence cycle {K} "
                f"(comm_every={cad} defines the trajectory).")
        E = None if ensemble is None else int(ensemble)
        return run_chunked(
            lambda c: make_stokes_run_deep(p, c, ensemble=E), state,
            nt // K, max(1, nt_chunk // K))
    if ensemble is not None:
        return run_chunked(
            lambda c: make_stokes_run(p, c, impl, ensemble=int(ensemble)),
            state, nt, nt_chunk)
    impl = _resolve_impl(impl)
    return run_chunked(lambda c: make_stokes_run(p, c, impl), state, nt,
                       nt_chunk)


_residual_cache: dict = {}


def stokes_residuals(state, p: StokesParams):
    """Global (max |divV|, max |R|) — pmax-reduced over the mesh (the
    convergence monitor of the PT loop). Compiled once per (grid, params)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as Pspec

    check_initialized()
    gg = global_grid()
    key = (gg.epoch, p)
    cached = _residual_cache.get(key)
    if cached is not None:
        a, b = cached(*state)
        return float(a), float(b)
    if _residual_cache and next(iter(_residual_cache))[0] != gg.epoch:
        _residual_cache.clear()
    spec = Pspec(*AXIS_NAMES)

    def local(*s):
        _, divV, Rx, Ry, Rz = _stokes_terms(tuple(s), p)
        err_div = jnp.max(jnp.abs(divV))
        err_mom = jnp.maximum(jnp.maximum(jnp.max(jnp.abs(Rx)),
                                          jnp.max(jnp.abs(Ry))),
                              jnp.max(jnp.abs(Rz)))
        for ax in AXIS_NAMES:
            err_div = lax.pmax(err_div, ax)
            err_mom = lax.pmax(err_mom, ax)
        return err_div, err_mom

    from ..utils.compat import shard_map

    fn = jax.jit(shard_map(
        local, mesh=gg.mesh, in_specs=(spec,) * 8,
        out_specs=(Pspec(), Pspec())))
    _residual_cache[key] = fn
    a, b = fn(*state)
    return float(a), float(b)
