"""3-D pseudo-transient (PT) Stokes solver on a staggered grid.

The BASELINE config "3-D pseudo-transient Stokes solver, weak-scale to
v5p-256" (`/root/repo/BASELINE.json`): isoviscous, incompressible Stokes flow
driven by a buoyant spherical inclusion, solved by damped pseudo-transient
iteration — the hydro-mechanical miniapp family the reference's weak-scaling
figure is built on (`reference README.md:6-8`). Built entirely on the
framework's staggered-field machinery (per-field overlaps `shared.jl:107`):

    cell centers: P, τxx, τyy, τzz, ρg      faces: Vx, Vy, Vz
    edges: τxy, τxz, τyz

    divV = ∇·V
    P   ← P − dτ_P divV
    τii ← 2μ (∂iVi − divV/3)
    τij ← μ (∂jVi + ∂iVj)
    R_i = −∂iP + ∂jτij (+ buoyancy)
    dV  ← damp·dV + R          (damped PT momentum)
    V   ← V + dτ_V dV
    halo-exchange V (and P)

One PT iteration is one `step_local` inside the whole-loop-jitted runner;
convergence is monitored by `residuals` (max |divV|, max |R|) — psum-reduced
across the mesh.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..ops.alloc import device_put_g, zeros_g
from ..ops.halo import local_update_halo
from ..parallel.topology import AXIS_NAMES, check_initialized, global_grid
from ..tools import coords_g, nx_g, ny_g, nz_g
from .common import make_state_runner, run_chunked

__all__ = ["StokesParams", "init_stokes3d", "stokes_step_local",
           "make_stokes_run", "run_stokes", "stokes_residuals"]


@dataclass(frozen=True)
class StokesParams:
    mu: float       # shear viscosity
    dt_v: float     # pseudo time step, momentum
    dt_p: float     # pseudo time step, pressure
    damp: float     # PT damping factor
    dx: float
    dy: float
    dz: float


def init_stokes3d(*, mu=1.0, lx=10.0, ly=10.0, lz=10.0, rhog_mag=1.0,
                  r_incl=1.0, dtype=None):
    """State (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog): zero initial flow, a
    buoyant sphere of radius ``r_incl`` at the domain center."""
    check_initialized()
    gg = global_grid()
    nx, ny, nz = (int(n) for n in gg.nxyz)
    dx, dy, dz = lx / (nx_g() - 1), ly / (ny_g() - 1), lz / (nz_g() - 1)
    # standard PT scalings (damped wave equation analogy)
    min_d = min(dx, dy, dz)
    n_max = max(nx_g(), ny_g(), nz_g())
    dt_v = min_d ** 2 / mu / 6.1 / 2.0
    dt_p = 6.1 * mu / n_max
    damp = 1.0 - 6.0 / n_max

    P = zeros_g((nx, ny, nz), dtype=dtype)
    x, y, z = coords_g(dx, dy, dz, P)
    r2 = ((np.asarray(x) - lx / 2) ** 2 + (np.asarray(y) - ly / 2) ** 2
          + (np.asarray(z) - lz / 2) ** 2)
    rhog = device_put_g(
        np.broadcast_to((r2 < r_incl ** 2) * rhog_mag, P.shape).astype(P.dtype))
    Vx = zeros_g((nx + 1, ny, nz), dtype=dtype)
    Vy = zeros_g((nx, ny + 1, nz), dtype=dtype)
    Vz = zeros_g((nx, ny, nz + 1), dtype=dtype)
    # damped-momentum fields mirror the velocity shapes (only interior faces
    # are ever nonzero) — face-aligned full-size arrays keep the Pallas
    # kernel tier's plane mapping uniform across the state
    dVx = zeros_g((nx + 1, ny, nz), dtype=dtype)
    dVy = zeros_g((nx, ny + 1, nz), dtype=dtype)
    dVz = zeros_g((nx, ny, nz + 1), dtype=dtype)
    state = (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog)
    return state, StokesParams(mu=mu, dt_v=dt_v, dt_p=dt_p, damp=damp,
                               dx=dx, dy=dy, dz=dz)


def _d(A, d):
    from jax import lax

    n = A.shape[d]
    return lax.slice_in_dim(A, 1, n, axis=d) - lax.slice_in_dim(A, 0, n - 1, axis=d)


def _inner(A, dims_sel):
    from jax import lax

    for d in dims_sel:
        A = lax.slice_in_dim(A, 1, A.shape[d] - 1, axis=d)
    return A


def _stokes_terms(state, p: StokesParams):
    """Residuals R_i at interior faces (shared by step and monitor)."""
    P, Vx, Vy, Vz, dVx, dVy, dVz, rhog = state
    divV = _d(Vx, 0) / p.dx + _d(Vy, 1) / p.dy + _d(Vz, 2) / p.dz  # centers
    Pn = P - p.dt_p * divV
    txx = 2 * p.mu * (_d(Vx, 0) / p.dx - divV / 3)
    tyy = 2 * p.mu * (_d(Vy, 1) / p.dy - divV / 3)
    tzz = 2 * p.mu * (_d(Vz, 2) / p.dz - divV / 3)
    # edge shear stresses on interior edges
    txy = p.mu * (_inner(_d(Vx, 1), (0,)) / p.dy + _inner(_d(Vy, 0), (1,)) / p.dx)
    txz = p.mu * (_inner(_d(Vx, 2), (0,)) / p.dz + _inner(_d(Vz, 0), (2,)) / p.dx)
    tyz = p.mu * (_inner(_d(Vy, 2), (1,)) / p.dz + _inner(_d(Vz, 1), (2,)) / p.dy)

    Rx = (_inner(_d(txx - Pn, 0), (1, 2)) / p.dx
          + _d(_inner(txy, (2,)), 1) / p.dy
          + _d(_inner(txz, (1,)), 2) / p.dz)
    Ry = (_inner(_d(tyy - Pn, 1), (0, 2)) / p.dy
          + _d(_inner(txy, (2,)), 0) / p.dx
          + _d(_inner(tyz, (0,)), 2) / p.dz)
    rg_face = 0.5 * (_d(rhog, 2) + 2 * rhog[:, :, :-1])  # avg to z-faces
    Rz = (_inner(_d(tzz - Pn, 2), (0, 1)) / p.dz
          + _d(_inner(txz, (1,)), 0) / p.dx
          + _d(_inner(tyz, (0,)), 1) / p.dy
          + _inner(rg_face, (0, 1)))
    return Pn, divV, Rx, Ry, Rz


def stokes_step_local(state, p: StokesParams, impl: str = "xla"):
    """One damped PT iteration on LOCAL blocks (inside shard_map).

    ``impl``: "xla", or "pallas" — ONE fused Pallas pass computing the
    pressure/stress/momentum updates AND delivering the halo exchange of
    (Vx, Vy, Vz, Pn) (`ops/pallas_stokes.py`; "pallas_interpret" on CPU)."""
    P, Vx, Vy, Vz, dVx, dVy, dVz, rhog = state
    if impl.startswith("pallas"):
        from ..ops.pallas_stokes import (
            stokes_exchange_modes, stokes_step_exchange_pallas,
        )

        gg = global_grid()
        modes = stokes_exchange_modes(gg, tuple(a.shape for a in state))
        if modes is not None:
            return stokes_step_exchange_pallas(
                state, gg, modes, p, interpret=impl == "pallas_interpret")
        # ineligible config: fall through to the XLA formulation
    Pn, divV, Rx, Ry, Rz = _stokes_terms(state, p)
    ix = (slice(1, -1),) * 3
    dVx_i = p.damp * dVx[ix] + Rx
    dVy_i = p.damp * dVy[ix] + Ry
    dVz_i = p.damp * dVz[ix] + Rz
    dVx = dVx.at[ix].set(dVx_i)
    dVy = dVy.at[ix].set(dVy_i)
    dVz = dVz.at[ix].set(dVz_i)
    Vx = Vx.at[ix].add(p.dt_v * dVx_i)
    Vy = Vy.at[ix].add(p.dt_v * dVy_i)
    Vz = Vz.at[ix].add(p.dt_v * dVz_i)
    Vx, Vy, Vz, Pn = local_update_halo(Vx, Vy, Vz, Pn)
    return (Pn, Vx, Vy, Vz, dVx, dVy, dVz, rhog)


def _resolve_impl(impl):
    from .common import resolve_pallas_impl

    return resolve_pallas_impl(impl)


def make_stokes_run(p: StokesParams, nt_chunk: int, impl: str | None = None):
    impl = _resolve_impl(impl)
    return make_state_runner(
        lambda s: stokes_step_local(s, p, impl), (3,) * 8,
        nt_chunk=nt_chunk, key=("stokes3d", p, impl),
        check_vma=False if impl.startswith("pallas") else None,
    )


def run_stokes(state, p: StokesParams, nt: int, *, nt_chunk: int = 100,
               impl: str | None = None):
    """Run ``nt`` PT iterations (one compiled program per chunk)."""
    impl = _resolve_impl(impl)
    return run_chunked(lambda c: make_stokes_run(p, c, impl), state, nt,
                       nt_chunk)


_residual_cache: dict = {}


def stokes_residuals(state, p: StokesParams):
    """Global (max |divV|, max |R|) — pmax-reduced over the mesh (the
    convergence monitor of the PT loop). Compiled once per (grid, params)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as Pspec

    check_initialized()
    gg = global_grid()
    key = (gg.epoch, p)
    cached = _residual_cache.get(key)
    if cached is not None:
        a, b = cached(*state)
        return float(a), float(b)
    if _residual_cache and next(iter(_residual_cache))[0] != gg.epoch:
        _residual_cache.clear()
    spec = Pspec(*AXIS_NAMES)

    def local(*s):
        _, divV, Rx, Ry, Rz = _stokes_terms(tuple(s), p)
        err_div = jnp.max(jnp.abs(divV))
        err_mom = jnp.maximum(jnp.maximum(jnp.max(jnp.abs(Rx)),
                                          jnp.max(jnp.abs(Ry))),
                              jnp.max(jnp.abs(Rz)))
        for ax in AXIS_NAMES:
            err_div = lax.pmax(err_div, ax)
            err_mom = lax.pmax(err_mom, ax)
        return err_div, err_mom

    fn = jax.jit(jax.shard_map(
        local, mesh=gg.mesh, in_specs=(spec,) * 8,
        out_specs=(Pspec(), Pspec())))
    _residual_cache[key] = fn
    a, b = fn(*state)
    return float(a), float(b)
