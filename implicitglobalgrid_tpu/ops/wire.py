"""Canonical wire schema — ONE packing program for every kernel tier.

Before this module, the repo had three independent spellings of "how halo
payloads are laid out on the wire": the XLA coalesced exchange's
ravel+concat pack (`ops.halo._exchange_dim_coalesced`), the quantized
pack/unpack pair (`_quant_pack_group`/`_quant_unpack_group`), and the
Pallas fused kernels' per-field in-kernel permutes (`pallas_wave`,
`pallas_stokes` — which therefore escaped PR 7's collective contracts and
PR 9's quantized wire entirely). TEMPI (arXiv:2012.14363) names the fix:
derive ONE canonical packing program from the datatype/slab spec and reuse
it everywhere.

`WireSchema` is that program. Built from the slab signature alone — slab
shapes x state dtype x exchange axis x `WireFormat` — it fixes:

- the **layout**: ``"slab"`` packs by concatenating the send slabs ALONG
  the exchange axis (slab shape preserved end-to-end: no ravel pass on
  pack, no reshape pass on unpack — the select/concat traffic that put the
  8-field coalesced exchange BELOW the per-field baseline on the CPU mesh,
  BENCH_ALL.json 0.75x); ``"flat"`` ravels and concatenates (required
  whenever the slab cross-shapes differ — staggered multi-field packs — or
  the payload is quantized, whose per-slab f32 scales ride a byte tail
  only a flat buffer has);
- the **wire dtype**: the state dtype, a narrower float cast, or int8
  bytes (bit-packed int4 included) per `precision.wire_format_for`;
- the **byte accounting**: ``payload_bytes`` is exact to the byte and is
  the single number `ops.halo._plan_from_sig`, `halo_comm_plan`,
  `telemetry.predict_step`, and `analysis.contracts` all price — the plan,
  the oracle, and the compiled-program audit can no longer drift apart on
  layout.

`pack(slabs)`/`unpack(buffer)` are the only two entry points; both tiers
call them: the XLA coalesced path packs Python-side slices, the Pallas
fused kernels pack the thin-slab mini-computes of
`ops.halo.exchange_recv_slabs_multi` — one ppermute pair per mesh axis per
round for EVERY tier, which is what lets `analysis.audit.audit_model`
derive real contracts for ``impl='pallas'`` programs.

On TPU grids the ``"slab"`` pack can additionally run as one fused Pallas
kernel (`pallas_halo.wire_pack_pallas` — all fields' slabs written into
the packed buffer in a single launch); `schema.pack` gates that on
`pallas_halo.wire_pack_supported` and falls back to the XLA concat
everywhere else (the CPU mesh measures the XLA slab layout directly).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from ..utils.exceptions import InvalidArgumentError
from .precision import (
    SCALE_BYTES, _AXIS_TOKENS, _DIM_NAMES, decode_scales, dequantize_slab,
    encode_scales, quant_slab_bytes, quantize_slab,
)

__all__ = ["WireSchema", "slab_schema", "schema_for_fields",
           "CommCadence", "resolve_comm_every",
           "WireStagePolicy", "resolve_wire_stage", "StagedWireSchema"]


# ---------------------------------------------------------------------------
# per-axis exchange cadence (the comm_every knob's resolved form)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CommCadence:
    """Resolved PER-MESH-AXIS exchange cadence: one integer ``k >= 1`` per
    grid dimension (x, y, z) — the deep-halo ``comm_every`` knob
    generalized so each mesh axis pays its own collective latency at its
    own rate (the HiCCL per-link-class idea, arXiv:2408.05962, applied to
    the cadence axis the way `precision.WirePolicy` applies it to wire
    precision). Axis ``d`` exchanges once per ``k_d`` steps with
    ``depth * k_d``-wide slabs; ``k_d = 1`` is the exchange-every-step
    default. The canonical string form round-trips through
    `resolve_comm_every` (``"4"`` when uniform, else e.g. ``"z:4"`` —
    unnamed axes are cadence 1)."""

    per_dim: tuple

    def for_dim(self, dim: int) -> int:
        """Cadence along grid dimension ``dim`` (dims beyond the cadence
        — e.g. 2-D fields' missing z — exchange every step)."""
        if 0 <= int(dim) < len(self.per_dim):
            return self.per_dim[int(dim)]
        return 1

    @property
    def uniform(self):
        """The single cadence when every dim shares one, else ``None``."""
        ks = set(self.per_dim)
        return self.per_dim[0] if len(ks) == 1 else None

    @property
    def deep(self) -> bool:
        """Whether any axis runs a deep-halo cadence (``k > 1``)."""
        return any(k > 1 for k in self.per_dim)

    @property
    def cycle(self) -> int:
        """The super-cycle length: lcm of the per-axis cadences — after
        ``cycle`` sub-steps every axis has just exchanged, so the deep
        runners' compiled super-step advances exactly this many physical
        steps."""
        return math.lcm(*self.per_dim)

    def retreats(self, j: int, ndim: int = 3) -> tuple:
        """Per-dim staleness at sub-step ``j`` of a super-cycle: the
        number of sub-steps since the last exchange along each dim
        (``j mod k_d`` — exchanges land after sub-steps where
        ``(j+1) % k_d == 0``)."""
        return tuple(int(j) % self.for_dim(d) for d in range(ndim))

    def due_dims(self, j: int, ndim: int = 3, order=None) -> tuple:
        """Grid dims whose exchange is due after sub-step ``j``, in the
        exchange processing order (default z, x, y — the reference's
        sequential-corner order, `ops.halo.DEFAULT_DIMS_ORDER`)."""
        if order is None:
            from .halo import DEFAULT_DIMS_ORDER

            order = DEFAULT_DIMS_ORDER
        return tuple(d for d in order
                     if d < ndim and (int(j) + 1) % self.for_dim(d) == 0)

    def __str__(self) -> str:
        u = self.uniform
        if u is not None:
            return str(u)
        parts = [f"{_DIM_NAMES[d]}:{k}"
                 for d, k in enumerate(self.per_dim) if k != 1]
        return ",".join(parts) if parts else "1"

    def __repr__(self) -> str:
        return f"CommCadence({self})"


def _parse_cadence_k(token) -> int:
    try:
        k = int(str(token).strip())
    except (TypeError, ValueError):
        raise InvalidArgumentError(
            f"comm_every cadence must be an integer >= 1; got {token!r}.")
    if k < 1:
        raise InvalidArgumentError(
            f"comm_every cadence must be >= 1; got {k}.")
    return k


def resolve_comm_every(comm_every=None) -> CommCadence:
    """Resolve the requested exchange cadence to a `CommCadence`.

    ``comm_every=None`` consults ``IGG_COMM_EVERY``; an explicit argument
    wins over the environment. Accepted forms (the `resolve_wire_dtype`
    spelling family):

    - an integer ``k`` (or its string) — every axis exchanges once per
      ``k`` steps;
    - a per-axis spec ``"z:4,x:1"`` (axes ``x``/``y``/``z`` or
      ``gx``/``gy``/``gz``; unnamed axes stay cadence 1);
    - a ``{axis: k}`` mapping, or a `CommCadence`.

    The default — no argument, no environment — is the uniform cadence 1
    (exchange every step)."""
    import os

    if comm_every is None:
        comm_every = os.environ.get("IGG_COMM_EVERY")
    if comm_every is None or comm_every == "":
        return CommCadence((1, 1, 1))
    if isinstance(comm_every, CommCadence):
        return comm_every
    if isinstance(comm_every, dict):
        items = list(comm_every.items())
    elif isinstance(comm_every, str) and ":" in comm_every:
        items = []
        for part in comm_every.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise InvalidArgumentError(
                    f"Per-axis comm_every spec {comm_every!r}: entry "
                    f"{part!r} must be '<axis>:<k>' (e.g. 'z:4,x:1').")
            axis, k = part.split(":", 1)
            items.append((axis, k))
    else:
        return CommCadence((_parse_cadence_k(comm_every),) * 3)

    per_dim = [1, 1, 1]
    seen = set()
    for axis, k in items:
        key = str(axis).strip().lower()
        dim = _AXIS_TOKENS.get(key)
        if dim is None:
            raise InvalidArgumentError(
                f"Unknown mesh axis {axis!r} in comm_every spec (use "
                "x/y/z or gx/gy/gz).")
        if dim in seen:
            raise InvalidArgumentError(
                f"Mesh axis {axis!r} named twice in comm_every spec.")
        seen.add(dim)
        per_dim[dim] = _parse_cadence_k(k)
    return CommCadence(tuple(per_dim))


# ---------------------------------------------------------------------------
# per-axis topology staging (the IGG_HALO_WIRE_STAGE knob's resolved form)
# ---------------------------------------------------------------------------

# off spellings shared with the wire-dtype family, plus the explicit
# "keep the flat pair" name
_STAGE_OFF = (None, "", "0", "off", "none", "flat", "false")
_STAGE_ON = ("staged", "hier", "hierarchical", "1", "on", "true")


def _parse_stage(token) -> bool:
    if isinstance(token, bool):
        return token
    if isinstance(token, str):
        token = token.strip().lower()
    if token in _STAGE_OFF:
        return False
    if token in _STAGE_ON:
        return True
    raise InvalidArgumentError(
        f"Unsupported halo wire stage {token!r}; supported: 'staged' "
        "(hierarchical gather->DCN->scatter) or 'flat'/'off'.")


@dataclass(frozen=True)
class WireStagePolicy:
    """Resolved PER-MESH-AXIS topology staging: one bool per grid
    dimension (x, y, z) saying whether that axis's exchange runs the
    hierarchical ICI-gather -> striped-DCN -> ICI-scatter pipeline
    instead of the flat ppermute pair (HiCCL hierarchical composition,
    arXiv:2408.05962). OFF everywhere by default; an axis whose granule
    layout is degenerate (one granule, or no perpendicular ICI axis to
    fold over — `parallel.topology.staged_wire_layout` returns ``None``)
    silently keeps the flat pair, so the policy is always safe to
    request. The canonical string form round-trips through
    `resolve_wire_stage` (``"staged"`` when uniform-on, else e.g.
    ``"z:staged"``; ``"off"`` when nothing is staged)."""

    per_dim: tuple

    def for_dim(self, dim: int) -> bool:
        """Whether grid dimension ``dim`` is staged (dims beyond the
        policy — e.g. 2-D fields' missing z — stay flat)."""
        if 0 <= int(dim) < len(self.per_dim):
            return bool(self.per_dim[int(dim)])
        return False

    @property
    def any_staged(self) -> bool:
        return any(self.per_dim)

    @property
    def staged_dims(self) -> tuple:
        """Grid dims requesting the staged pipeline, ascending."""
        return tuple(d for d, s in enumerate(self.per_dim) if s)

    def __str__(self) -> str:
        if not self.any_staged:
            return "off"
        if all(self.per_dim):
            return "staged"
        return ",".join(f"{_DIM_NAMES[d]}:staged"
                        for d in self.staged_dims)

    def __repr__(self) -> str:
        return f"WireStagePolicy({self})"


def resolve_wire_stage(wire_stage=None):
    """Resolve the requested topology staging to a `WireStagePolicy`, or
    ``None`` for the flat wire everywhere (the default).

    ``wire_stage=None`` consults ``IGG_HALO_WIRE_STAGE``; an explicit
    argument (incl. ``"off"``) wins over the environment. Accepted forms
    (the `resolve_wire_dtype` spelling family):

    - ``"staged"`` — every mesh axis with a usable granule layout stages;
    - a per-axis spec ``"z:staged"`` / ``"z:staged,x:flat"`` (axes
      ``x``/``y``/``z`` or ``gx``/``gy``/``gz``; unnamed axes stay flat);
    - a ``{axis: "staged"|bool}`` mapping, or a `WireStagePolicy`."""
    import os

    if wire_stage is None:
        wire_stage = os.environ.get("IGG_HALO_WIRE_STAGE")
    if isinstance(wire_stage, WireStagePolicy):
        return wire_stage if wire_stage.any_staged else None
    if isinstance(wire_stage, str):
        wire_stage = wire_stage.strip().lower()
    if wire_stage in _STAGE_OFF:
        return None
    if isinstance(wire_stage, dict):
        items = list(wire_stage.items())
    elif isinstance(wire_stage, str) and ":" in wire_stage:
        items = []
        for part in wire_stage.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise InvalidArgumentError(
                    f"Per-axis wire stage spec {wire_stage!r}: entry "
                    f"{part!r} must be '<axis>:staged' (e.g. 'z:staged').")
            axis, st = part.split(":", 1)
            items.append((axis, st))
    else:
        return (WireStagePolicy((True,) * 3)
                if _parse_stage(wire_stage) else None)

    per_dim = [False, False, False]
    seen = set()
    for axis, st in items:
        key = str(axis).strip().lower()
        dim = _AXIS_TOKENS.get(key)
        if dim is None:
            raise InvalidArgumentError(
                f"Unknown mesh axis {axis!r} in wire stage spec (use "
                "x/y/z or gx/gy/gz).")
        if dim in seen:
            raise InvalidArgumentError(
                f"Mesh axis {axis!r} named twice in wire stage spec.")
        seen.add(dim)
        per_dim[dim] = _parse_stage(st)
    if not any(per_dim):
        return None
    return WireStagePolicy(tuple(per_dim))


@dataclass(frozen=True)
class WireSchema:
    """One direction's packing program for a group of same-dtype slabs.

    ``shapes`` are the send-slab shapes in pack order; ``dim`` the
    exchange array axis; ``fmt`` the resolved `WireFormat` (``None`` =
    exact wire); ``layout`` is ``"slab"`` or ``"flat"`` (see module
    docstring). Frozen and hashable — derived once per exchange signature
    and shared by the pack, the unpack, and every byte-accounting layer.

    ``members`` is the ENSEMBLE axis (ISSUE 12): an ensemble chunk
    advances E scenario members per step by ``vmap``-ing the member axis
    over the step program, and jax's collective batching rule turns each
    per-member ppermute into ONE ppermute whose payload carries every
    member's slabs — the same pair count, E x the bytes. The live
    `pack`/`unpack` therefore stay PER-MEMBER programs (the vmap batches
    them); ``members`` exists so the byte accounting (`payload_bytes`)
    prices the batched payload the compiler actually ships — including
    E x the per-slab scale tails of a quantized wire, one f32 scale per
    (member, slab) in the same scales-in-band layout.
    """

    dim: int
    shapes: tuple          # per-slab shapes, pack order
    state_dtype: str       # numpy dtype name
    fmt: object = None     # WireFormat | None
    layout: str = "slab"
    members: int = 1       # ensemble members riding one payload

    # -- derived geometry ---------------------------------------------------

    @property
    def n_slabs(self) -> int:
        return len(self.shapes)

    @property
    def cells(self) -> tuple:
        """Per-slab element counts, pack order."""
        return tuple(int(np.prod(s)) for s in self.shapes)

    @property
    def is_quant(self) -> bool:
        return self.fmt is not None and self.fmt.is_quant

    @property
    def wire_dtype(self):
        """The numpy dtype the packed buffer crosses the link in."""
        if self.fmt is not None:
            return np.dtype(self.fmt.dtype)
        return np.dtype(self.state_dtype)

    @property
    def payload_bytes(self) -> int:
        """EXACT bytes of one direction's packed payload — the number every
        wire-reasoning layer prices (`halo_comm_plan` by-dtype rows,
        `predict_step` per-axis pricing, `exchange_contract` wire-byte
        equality against the compiled program). With ``members`` > 1 the
        per-member payload (quantized slabs + their per-slab scales
        included) multiplies by the member count — the vmap-batched
        buffer one ppermute carries."""
        if self.is_quant:
            per_member = (sum(quant_slab_bytes(c, self.fmt)
                              for c in self.cells)
                          + SCALE_BYTES * self.n_slabs)
        else:
            per_member = sum(self.cells) * int(self.wire_dtype.itemsize)
        return per_member * max(1, int(self.members))

    @property
    def wire_key(self) -> str:
        """The `halo_comm_plan` ``by_dtype`` key of this payload (the
        format name for quantized wire, the dtype name otherwise)."""
        return self.fmt.name if self.is_quant else str(self.wire_dtype)

    # -- the packing program ------------------------------------------------

    def pack(self, slabs, *, pallas_mode=None):
        """Pack the per-field send slabs into ONE wire buffer.

        ``slabs`` are arrays of exactly ``self.shapes`` (pack order).
        ``pallas_mode`` is ``None`` (XLA pack) or ``(use_kernel,
        interpret)`` from `pallas_halo.wire_pack_mode` — the fused
        single-launch pack of the slab layout on TPU grids."""
        import jax.numpy as jnp

        self._check(slabs)
        if self.is_quant:
            qs, scales = zip(*(quantize_slab(s.reshape(-1), self.fmt)
                               for s in slabs))
            return jnp.concatenate(list(qs) + [encode_scales(list(scales))])
        if self.layout == "flat":
            buf = jnp.concatenate([s.reshape(-1) for s in slabs])
        elif pallas_mode is not None and pallas_mode[0]:
            from .pallas_halo import wire_pack_pallas

            buf = wire_pack_pallas(list(slabs), dim=self.dim,
                                   interpret=pallas_mode[1])
        elif len(slabs) == 1:
            buf = slabs[0]
        else:
            buf = jnp.concatenate(list(slabs), axis=self.dim)
        if self.fmt is not None:
            buf = buf.astype(self.wire_dtype)
        return buf

    def unpack(self, buf):
        """Inverse of `pack`: the received wire buffer back into per-field
        slabs of ``self.shapes`` in the state dtype (dequantized /
        upcast — boundary masking and delivery stay with the caller)."""
        import jax.numpy as jnp
        from jax import lax

        out_dt = np.dtype(self.state_dtype)
        if self.is_quant:
            cells = self.cells
            qsizes = [quant_slab_bytes(c, self.fmt) for c in cells]
            data = sum(qsizes)
            scales = decode_scales(
                lax.slice_in_dim(buf, data,
                                 data + SCALE_BYTES * self.n_slabs, axis=0),
                self.n_slabs)
            out, off = [], 0
            for k, (c, qb) in enumerate(zip(cells, qsizes)):
                flat = dequantize_slab(
                    lax.slice_in_dim(buf, off, off + qb, axis=0),
                    scales[k], c, self.fmt, out_dt)
                out.append(flat.reshape(self.shapes[k]))
                off += qb
            return out
        if self.fmt is not None:
            buf = buf.astype(out_dt)
        out = []
        if self.layout == "flat":
            off = 0
            for shp, c in zip(self.shapes, self.cells):
                out.append(lax.slice_in_dim(buf, off, off + c,
                                            axis=0).reshape(shp))
                off += c
            return out
        if self.n_slabs == 1:
            return [buf]
        off = 0
        for shp in self.shapes:
            w = int(shp[self.dim])
            out.append(lax.slice_in_dim(buf, off, off + w, axis=self.dim))
            off += w
        return out

    def _check(self, slabs) -> None:
        if len(slabs) != self.n_slabs:
            raise InvalidArgumentError(
                f"WireSchema.pack: {len(slabs)} slabs for a "
                f"{self.n_slabs}-slab schema.")
        for s, shp in zip(slabs, self.shapes):
            if tuple(int(v) for v in s.shape) != shp:
                raise InvalidArgumentError(
                    f"WireSchema.pack: slab shape {tuple(s.shape)} does "
                    f"not match the schema's {shp}.")


def _slab_layout_ok(dim: int, shapes) -> bool:
    """Whether the slab (concat-along-axis) layout applies: every slab must
    share the cross-axis extents (staggered multi-field packs differ there
    and take the flat layout)."""
    cross = None
    for shp in shapes:
        c = tuple(v for d, v in enumerate(shp) if d != dim)
        if cross is None:
            cross = c
        elif c != cross:
            return False
    return True


def slab_schema(dim: int, shapes, state_dtype, fmt=None,
                members: int = 1) -> WireSchema:
    """Derive the canonical schema for one (axis, dtype group) from the
    slab signature alone. ``fmt`` is the resolved `WireFormat` for this
    axis (`precision.wire_format_for`), or ``None`` for exact wire;
    ``members`` is the ensemble member count riding the payload (byte
    accounting only — the live pack stays per-member under vmap)."""
    shapes = tuple(tuple(int(v) for v in s) for s in shapes)
    if not shapes:
        raise InvalidArgumentError("slab_schema needs at least one slab.")
    if int(members) < 1:
        raise InvalidArgumentError(
            f"slab_schema: members must be >= 1; got {members}.")
    quant = fmt is not None and fmt.is_quant
    layout = "flat" if quant or not _slab_layout_ok(dim, shapes) else "slab"
    return WireSchema(dim=int(dim), shapes=shapes,
                      state_dtype=str(np.dtype(state_dtype)), fmt=fmt,
                      layout=layout, members=int(members))


def schema_for_fields(dim: int, shapes, hws, state_dtype,
                      fmt=None, members: int = 1) -> WireSchema:
    """`slab_schema` from FIELD shapes (local blocks) instead of slab
    shapes: the send slab of a field along ``dim`` is its cross extents x
    the halowidth. The one geometry rule (`ops.halo`: slab width = hw)
    lives here so the static plan and the live pack can never disagree."""
    slab_shapes = []
    for shp, hw in zip(shapes, hws):
        s = list(int(v) for v in shp)
        s[dim] = int(hw)
        slab_shapes.append(tuple(s))
    return slab_schema(dim, slab_shapes, state_dtype, fmt, members=members)


# ---------------------------------------------------------------------------
# the staged (hierarchical) wire: one packed payload, three routed stages
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class StagedWireSchema:
    """One staged axis's three-stage wire program: the flat `WireSchema`
    payload (pack/unpack are UNCHANGED — bit-identity by construction)
    plus the `parallel.topology.StagedWireLayout` routes it travels:

    1. **gather** — ``fold - 1`` pipelined ppermute shifts along the
       gather (ICI) axis collect every sending plane's packed slab onto
       the per-granule leaders (payload: one packed buffer per hop);
    2. **dcn** — ONE ppermute per direction whose pairs are leader ->
       leader across the granule boundary, payload ``fold`` concatenated
       buffers (the striped transfer — per-DCN-link message count drops
       by the ICI fold);
    3. **scatter** — ``fold - 1`` reverse shifts fan the pieces back out
       on the far side (payload: one packed buffer per hop).

    Pairs that never cross a granule boundary keep the flat single-axis
    ppermute (the ``intra`` stage). Quantized payloads need no special
    casing: the per-slab f32 scales ride in-band inside the packed buffer
    through all three stages.

    This object is the ONE byte/route ledger for the staged axis —
    `ops.halo._plan_from_sig`, `telemetry.predict_step`, and
    `analysis.contracts` all read the same `stage_table`, so the plan,
    the oracle, and the compiled-program audit cannot drift."""

    schema: WireSchema
    layout: object  # parallel.topology.StagedWireLayout

    @property
    def fold(self) -> int:
        return int(self.layout.fold)

    @property
    def payload_bytes(self) -> int:
        """Bytes of ONE packed buffer (the gather/scatter/intra hop
        payload); the DCN stripe carries ``fold`` of these."""
        return self.schema.payload_bytes

    @property
    def dcn_payload_bytes(self) -> int:
        return self.schema.payload_bytes * self.fold

    def stage_table(self) -> tuple:
        """Per-(direction, stage) records — the staged ledger every
        reasoning layer prices. Each record:
        ``{"direction", "stage", "ops", "pairs", "payload_bytes",
        "wire_bytes"}`` where ``pairs`` counts the LINEARIZED
        source-target pairs of one compiled collective-permute and
        ``wire_bytes = ops * pairs * payload_bytes`` (absolute, whole
        mesh)."""
        out = []
        pb = self.payload_bytes
        f = self.fold
        for d in self.layout.directions:
            if d.intra_pairs_lin:
                out.append({"direction": d.name, "stage": "intra",
                            "ops": 1, "pairs": len(d.intra_pairs_lin),
                            "payload_bytes": pb,
                            "wire_bytes": pb * len(d.intra_pairs_lin)})
            if not d.cross_pairs:
                continue
            out.append({"direction": d.name, "stage": "gather",
                        "ops": f - 1, "pairs": len(d.gather_pairs),
                        "payload_bytes": pb,
                        "wire_bytes": (f - 1) * pb * len(d.gather_pairs)})
            out.append({"direction": d.name, "stage": "dcn",
                        "ops": 1, "pairs": len(d.dcn_pairs),
                        "payload_bytes": pb * f,
                        "wire_bytes": pb * f * len(d.dcn_pairs)})
            out.append({"direction": d.name, "stage": "scatter",
                        "ops": f - 1, "pairs": len(d.scatter_pairs),
                        "payload_bytes": pb,
                        "wire_bytes": (f - 1) * pb * len(d.scatter_pairs)})
        return tuple(out)

    @property
    def ppermute_ops(self) -> int:
        """Total collective-permute ops one exchange round issues on this
        axis (both directions, every stage) — the number the contract
        proves and `predict_step` prices latency against."""
        return sum(r["ops"] for r in self.stage_table())

    @property
    def wire_bytes(self) -> int:
        """Total ABSOLUTE wire bytes of one exchange round on this axis
        across the whole mesh (cf. the flat plan's per-line bytes — the
        gather/scatter pair sets span the perpendicular plane, so the
        per-line abstraction does not factor)."""
        return sum(r["wire_bytes"] for r in self.stage_table())

    @property
    def dcn_pair_count(self) -> int:
        """DCN-crossing source-target pairs per round (both directions) —
        the numerator of the bench's ``staged_dcn_msgs_ratio``."""
        return sum(r["pairs"] for r in self.stage_table()
                   if r["stage"] == "dcn")

    def flat_dcn_pair_count(self) -> int:
        """The flat wire's DCN-crossing pair count for the same axis:
        every granule-crossing single-axis pair replicated over every
        perpendicular line."""
        n_lines = 1
        for d, n in enumerate(self.layout.dims):
            if d != self.layout.dim:
                n_lines *= int(n)
        return sum(len(d.cross_pairs) for d in self.layout.directions) \
            * n_lines
