"""Gather a distributed field to the host — analog of reference `gather!`
(`/root/reference/src/gather.jl:18-54`).

The reference gathers every rank's local array (halo NOT stripped) into one
big array of shape ``dims .* size(A)`` on the root via an MPI derived-subarray
Gatherv. Here the stacked global `jax.Array` already IS that concatenation —
its shards assemble on `device_get` — so the single-controller path is a
device-to-host transfer, and the multi-host path is a
`multihost_utils.process_allgather`. Matching the reference's memory
semantics (`gather.jl:15-16`), only the ``root`` process returns the array.

`gather_interior` additionally strips the overlap duplication and returns the
true implicit global grid (size ``nxyz_g``) — the reference leaves this to
user code (e.g. halo-strip before gather, `README.md:147-148`).

COST: every gather materializes O(global) bytes on the ``root`` host (and
the multi-host collective moves O(global) over DCN) while the step loop
waits. Non-root processes participate in the collective but never
materialize the result (`_to_host(want=False)`). For production output,
prefer the O(shard)-per-process snapshot pipeline: `io.SnapshotWriter` /
`run_resilient(snapshot_dir=...)` during the run, `io.open_snapshot` +
`read_global(box=...)` (gather_interior-identical semantics, O(box)
memory) on the analysis side — see `docs/io.md`.
"""

from __future__ import annotations

import numpy as np

from ..parallel.topology import check_initialized, global_grid
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError
from .fields import local_shape_of

__all__ = ["gather", "gather_interior", "gather_sub"]


def _to_host(A, *, want: bool = True):
    """Assemble ``A`` on the host; ``want=False`` (non-root callers) still
    runs the COLLECTIVE part but skips the host materialization — before
    this, every process of a multi-host run converted the
    `process_allgather` result to a full O(global) numpy array only to
    throw it away, multiplying the gather's footprint by the process
    count."""
    import jax

    if not hasattr(A, "shape"):
        raise InvalidArgumentError("gather expects an array.")
    if hasattr(A, "is_fully_addressable") and not A.is_fully_addressable:
        from jax.experimental import multihost_utils

        g = multihost_utils.process_allgather(A, tiled=True)
        if not want:
            del g  # drop the replicated buffer without a numpy copy
            return None
        return np.asarray(g)
    if not want:
        return None
    return np.asarray(jax.device_get(A))


def gather(A, A_global=None, *, root: int = 0, layout: str | None = None):
    """Gather stacked field ``A`` to the host.

    Returns the full stacked array (shape ``dims .* local_shape`` — identical
    to the reference's ``A_global``) on the ``root`` process, ``None`` on
    others. If ``A_global`` (a numpy array) is given, the result is written
    into it in place (reference in-place signature `gather!(A, A_global)`).
    ``layout`` disambiguates small blocks (see `local_shape_of`).
    """
    import jax

    check_initialized()
    gg = global_grid()
    me = jax.process_index()

    # NOTE: _to_host may be a COLLECTIVE in multi-host runs (process_allgather)
    # — it must run on every process before any root-only validation can
    # raise, or non-root processes would hang in the collective. Only the
    # root materializes the O(global) result (want=).
    host = _to_host(A, want=me == root)
    if me == root and A_global is not None:
        loc = local_shape_of(A.shape, layout)
        expected = tuple(
            int(gg.dims[d]) * int(loc[d]) if d < 3 else int(loc[d])
            for d in range(len(loc))
        )
        if tuple(int(s) for s in A_global.shape) != expected:
            raise IncoherentArgumentError(
                "The size of the global array `size(A_global)` must be equal to the "
                f"product of `size(A)` and `dims` (expected {expected}, got "
                f"{tuple(A_global.shape)})."
            )
    if me != root:
        return None
    if A_global is not None:
        np.copyto(np.asarray(A_global), host)
        return A_global
    return host


def gather_sub(A, box, A_global=None, *, root: int = 0,
               layout: str | None = None):
    """Gather only the shards whose Cartesian coordinates lie in ``box`` —
    the analog of the reference's advanced overload gathering over an
    EXPLICIT sub-communicator (`gather.jl:25-33`, where a caller-built comm
    selects the participating ranks of a sub-grid).

    ``box`` is a per-dimension sequence of ``(lo, hi)`` half-open coordinate
    ranges (up to 3 entries; omitted/None entries mean the full axis). The
    result on ``root`` is the stacked array of the selected shard block,
    shape ``(hi-lo) * local_shape`` per grid dimension; other processes
    return ``None``. ``A_global`` (numpy) receives the result in place like
    `gather`.
    """
    import jax

    check_initialized()
    gg = global_grid()

    loc = local_shape_of(A.shape, layout)
    nd = len(loc)
    for d in range(min(nd, 3)):
        if int(A.shape[d]) != int(gg.dims[d]) * int(loc[d]):
            raise InvalidArgumentError(
                "gather_sub requires a STACKED global array (dims * local "
                f"size); got shape {tuple(A.shape)} (local along dimension "
                f"{d}). The coordinate box selects shard blocks of the "
                "stacked layout."
            )
    box = list(box) + [None] * (3 - len(list(box)))
    if any(b is not None for b in box[nd:]):
        raise InvalidArgumentError(
            f"gather_sub box selects dimension(s) beyond the array's rank "
            f"({nd}-D): {tuple(box)}."
        )
    ranges = []
    for d in range(nd):
        D = int(gg.dims[d]) if d < 3 else 1
        sel = box[d] if d < 3 else None
        if sel is None:
            ranges.append((0, D))
            continue
        lo, hi = (int(sel[0]), int(sel[1]))
        if not (0 <= lo < hi <= D):
            raise InvalidArgumentError(
                f"gather_sub box along dimension {d} must satisfy "
                f"0 <= lo < hi <= dims[{d}]={D}; got ({lo}, {hi})."
            )
        ranges.append((lo, hi))

    # Slice the BOX off first — on a sharded jax.Array the slice stays
    # shard-local, so the collective below moves only the selected block
    # (O(box), like the reference sub-communicator gather), not the full
    # global array. The slice + assembly are collective in multi-host runs:
    # every process must reach them (same ordering rule as `gather`).
    sl = tuple(
        slice(ranges[d][0] * int(loc[d]), ranges[d][1] * int(loc[d]))
        for d in range(nd)
    )
    host = _to_host(A[sl], want=jax.process_index() == root)
    if jax.process_index() != root:
        return None
    sub = host
    if A_global is not None:
        if tuple(int(s) for s in A_global.shape) != sub.shape:
            raise IncoherentArgumentError(
                f"gather_sub: A_global shape {tuple(A_global.shape)} does "
                f"not match the selected block shape {sub.shape}."
            )
        np.copyto(np.asarray(A_global), sub)
        return A_global
    return sub.copy()


def gather_interior(A, *, root: int = 0, layout: str | None = None):
    """Gather ``A`` and strip the overlap duplication, returning the implicit
    global grid (per-array global size, ``nx_g(A) x ny_g(A) x nz_g(A)`` —
    reference `tools.jl:45-59`) on ``root``, ``None`` elsewhere.

    Mapping (from the reference's coordinate formula `tools.jl:100`): local
    cell ``i`` of shard ``c`` is global cell ``c*(n - ol) + i`` (non-periodic;
    shards overlap by ``ol``, later shards win ties harmlessly — overlapping
    cells are equal after `update_halo`). Periodic dims shift by one ghost
    cell and wrap (`tools.jl:102-104`).
    """
    import jax

    check_initialized()
    gg = global_grid()
    host = _to_host(A, want=jax.process_index() == root)
    if jax.process_index() != root:
        return None

    loc = local_shape_of(host.shape, layout)
    nd = len(loc)
    out_shape = []
    for d in range(nd):
        n = int(loc[d])
        if d >= 3 or int(gg.dims[d]) == 1 and not gg.periods[d]:
            dd, ol_d, per = 1, 0, False
        else:
            dd = int(gg.dims[d])
            ol_d = int(gg.overlaps[d] + (n - gg.nxyz[d]))
            per = bool(gg.periods[d])
        out_shape.append(dd * (n - ol_d) if per else dd * (n - ol_d) + ol_d)

    out = np.empty(tuple(out_shape), dtype=host.dtype)
    # Iterate shards; place each local block at its global offset.
    dims3 = [int(gg.dims[d]) if d < 3 else 1 for d in range(nd)]
    for cidx in np.ndindex(*dims3):
        src = [slice(None)] * nd
        dst = [slice(None)] * nd
        ok = True
        for d in range(nd):
            n = int(loc[d])
            dd = dims3[d]
            ol_d = int(gg.overlaps[d] + (n - gg.nxyz[d])) if d < 3 else 0
            per = bool(gg.periods[d]) if d < 3 else False
            c = cidx[d]
            if per:
                # contribute i in [1, n-ol_d]  → global (c*(n-ol_d)+i-1) mod N
                start_g = (c * (n - ol_d)) % out_shape[d]
                src[d] = slice(1, n - ol_d + 1)
                dst[d] = slice(start_g, start_g + (n - ol_d))
            else:
                keep = n if c == dd - 1 else n - ol_d
                src[d] = slice(0, keep)
                dst[d] = slice(c * (n - ol_d), c * (n - ol_d) + keep)
            src_stack = slice(c * n + src[d].start, c * n + src[d].stop)
            src[d] = src_stack
            ok = ok and (dst[d].stop <= out_shape[d])
        if not ok:  # periodic wrap crossing the end: split the copy
            _copy_wrapped(out, host, src, dst, out_shape)
        else:
            out[tuple(dst)] = host[tuple(src)]
    return out


def _copy_wrapped(out, host, src, dst, out_shape):
    """Copy with modulo wrap along dims whose destination crosses the end."""
    nd = len(out_shape)
    # Split recursively on the first wrapping dim.
    for d in range(nd):
        if dst[d].stop > out_shape[d]:
            n1 = out_shape[d] - dst[d].start
            a_src = list(src); a_dst = list(dst)
            b_src = list(src); b_dst = list(dst)
            a_src[d] = slice(src[d].start, src[d].start + n1)
            a_dst[d] = slice(dst[d].start, out_shape[d])
            b_src[d] = slice(src[d].start + n1, src[d].stop)
            b_dst[d] = slice(0, dst[d].stop - out_shape[d])
            _copy_wrapped(out, host, a_src, a_dst, out_shape)
            _copy_wrapped(out, host, b_src, b_dst, out_shape)
            return
    out[tuple(dst)] = host[tuple(src)]
