"""Halo exchange — the hot path.

TPU-native re-design of the reference's `update_halo!`
(`/root/reference/src/update_halo.jl:29-83`). The reference's machinery per
dimension — pack kernels into send buffers (`update_halo.jl:212-269`,
`CUDAExt/update_halo.jl:210-227`), nonblocking `MPI.Isend`/`MPI.Irecv!`
(`update_halo.jl:337-361`), unpack, and a buffer pool (`update_halo.jl:97-201`)
— collapses on TPU into ONE pair of `lax.ppermute` collectives per (axis,
direction) inside `shard_map`:

    slice send slab  →  ppermute over the mesh axis (ICI hop)  →
    dynamic_update_slice into the halo region

XLA fuses the slicing around the collective, owns all buffers, and its
latency-hiding scheduler overlaps the permutes of independent fields — the
roles of the reference's pinned staging buffers, max-priority CUDA streams
(`CUDAExt/update_halo.jl:157`), and multi-field pipelining (`update_halo.jl:17`).

Exchange semantics reproduced exactly (index math from
`update_halo.jl:275-296`, 0-based here):

- send slab, right side (n=2): ``[s-ol, s-ol+hw)``; left (n=1): ``[ol-hw, ol)``
- recv slab, right side (n=2): ``[s-hw, s)``;      left (n=1): ``[0, hw)``
- a field participates along a dim iff ``ol(dim, A) >= 2*hw[dim]``
  (`update_halo.jl:233`)
- dimensions are processed strictly sequentially (default order z, x, y —
  `update_halo.jl:29,45`) so corner/edge values propagate across dims; the
  data dependence through the updated array enforces this under XLA too.
- non-periodic boundary shards keep their halo values (the reference's
  `MPI.PROC_NULL` no-op neighbors, `init_global_grid.jl:103`): masked with a
  select on the mesh coordinate (`lax.axis_index`).
- a periodic axis with a single shard short-circuits to local slab copies
  (the reference's self-neighbor path, `update_halo.jl:62-68,363-380`).

Collective coalescing (default ON; `IGG_HALO_COALESCE=0` or ``coalesce=False``
reverts): when several fields of one dtype exchange along a ppermute axis,
their send slabs pack into ONE buffer per direction on the CANONICAL WIRE
SCHEMA (`ops.wire.WireSchema` — slab layout: concat along the exchange
axis, slab shape preserved end-to-end; flat layout for staggered
cross-shapes and quantized payloads), so the axis costs a single ppermute
pair REGARDLESS of field count — the latency-bound cost of N small
collectives collapses into one message per link (the aggregation result
of HiCCL, arXiv:2408.05962; the reference's analog is its multi-field
pipelining note, `update_halo.jl:17`). The SAME schema drives the fused
Pallas kernels' exchange (`exchange_recv_slabs_multi`) and every
byte-accounting layer (`halo_comm_plan` -> `predict_step` ->
`exchange_contract`). Unpacking splits the receive buffer back into
per-field slabs and delivers them via the multi-field Pallas kernel
(`pallas_halo.halo_write_multi_pallas`, one launch per axis) or per-field
`dynamic_update_slice`; on TPU grids the pack side can likewise run as
one fused launch (`pallas_halo.wire_pack_pallas`). Fields that cannot
ride a packed exchange (lone dtype on an axis, non-participating dims)
fall back to the per-field path; self-neighbor axes have no collective to
coalesce and keep their local copies. Results are bit-identical to the
per-field path (tests/test_update_halo.py) — packing is pure layout, no
arithmetic.

Wire precision (default OFF; `IGG_HALO_WIRE_DTYPE` / ``wire_dtype=``): float
state optionally crosses the link narrowed (the EQuARX play,
arXiv:2506.17615) — either as a narrower float CAST
(convert → pack → ppermute → unpack → convert back, ~2x) or QUANTIZED as
symmetric per-slab-scaled ``int8`` / bit-packed ``int4`` (quantize each
field's send slab against its own max-abs scale, append the f32 scales to
the coalesced flat buffer, ppermute ONE int8 payload per direction,
dequantize on unpack — ~3.5-7.5x less wire traffic). The policy is PER
MESH AXIS (``wire_dtype="z:int8,x:f32"``): a slow DCN-mapped axis can
quantize while ICI axes stay exact (HiCCL, arXiv:2408.05962). Applies to
every ppermute payload (coalesced or per-field; quantized fields always
ride the packed layout, whose flat buffer carries the scales); PROC_NULL
boundary halos and self-neighbor local copies never round-trip through
the wire format. See `ops.precision.wire_format_for`.
"""

from __future__ import annotations

import contextlib

import numpy as np

from ..parallel.topology import (
    AXIS_NAMES, NDIMS, check_initialized, global_grid, grid_epoch,
)
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError
from .fields import (
    Field, check_fields, extract, field_partition_spec, wrap_field,
)
from .precision import resolve_wire_dtype, wire_format_for
from .wire import (
    StagedWireSchema, resolve_wire_stage, schema_for_fields, slab_schema,
)

__all__ = ["update_halo", "local_update_halo", "free_update_halo_caches",
           "halo_may_use_pallas", "resolve_halo_coalesce", "halo_comm_plan",
           "exchange_recv_slabs", "exchange_recv_slabs_multi",
           "force_xla_exchange", "DEFAULT_DIMS_ORDER"]

# Reference default `dims=(3,1,2)` (1-based: z, x, y — update_halo.jl:29).
DEFAULT_DIMS_ORDER = (2, 0, 1)

# jit-compiled exchange functions keyed by (grid epoch, field signature, dims
# order). The analog of the reference's persistent buffer pool + task/stream
# pools (`update_halo.jl:97-201,207`): allocated lazily on first use, reused
# across calls, freed by `finalize_global_grid`.
_exchange_cache: dict = {}

# Static wire plans keyed like the exchange cache (telemetry comm
# accounting: computed once per signature, charged per call).
_plan_cache: dict = {}


def free_update_halo_caches() -> None:
    """Drop compiled exchange programs (analog of
    `free_update_halo_buffers`, reference `update_halo.jl:103-108`).
    Epochs RETAINED by the multi-run scheduler survive (one tenant's
    finalize — e.g. inside an elastic restart — must not cold-start the
    other tenants' exchanges); with nothing retained this is the full
    clear it always was."""
    from ..parallel.topology import _retained_epochs

    for cache in (_exchange_cache, _plan_cache):
        for k in [k for k in cache if k[0] not in _retained_epochs]:
            del cache[k]


def halo_may_use_pallas(gg=None) -> bool:
    """Whether `local_update_halo` may emit Pallas kernels on the current
    grid (in-place halo writes / single-pass self-exchange).

    Enclosing `shard_map`s must pass ``check_vma=False`` when this is True —
    Pallas outputs cannot express the mesh-axis variance the checker wants.
    Model runners consult this instead of assuming from the device type, so
    the variance check stays on for genuinely pure-XLA programs (e.g.
    ``IGG_USE_PALLAS=0`` on a TPU grid)."""
    if gg is None:
        check_initialized()
        gg = global_grid()
    return _FORCE_PALLAS_WRITE_INTERPRET or (
        gg.device_type == "tpu" and bool(gg.use_pallas.any())
    )


def _normalize_dims_order(dims):
    if dims is None:
        return DEFAULT_DIMS_ORDER
    out = tuple(int(d) for d in (dims if np.iterable(dims) else (dims,)))
    if any(d < 0 or d >= NDIMS for d in out):
        raise InvalidArgumentError(
            f"dims must contain 0-based dimension indices in [0, {NDIMS}); got {out}. "
            "(Note: this API is 0-based; the Julia reference's default (3,1,2) is (2,0,1) here.)"
        )
    return out


def resolve_halo_coalesce(coalesce=None) -> bool:
    """Whether multi-field exchanges pack one ppermute pair per (axis, dtype
    group). An explicit argument wins; else ``IGG_HALO_COALESCE`` (default
    ON)."""
    if coalesce is not None:
        return bool(coalesce)
    import os

    v = os.environ.get("IGG_HALO_COALESCE")
    if v is None:
        return True
    try:
        return int(v) > 0
    except ValueError as e:
        raise InvalidArgumentError(
            f"Environment variable IGG_HALO_COALESCE: expected an integer, "
            f"got {v!r}.") from e


def _dim_meta(gg, dim: int):
    """Static per-dimension exchange metadata."""
    D = int(gg.dims[dim])
    periodic = bool(gg.periods[dim])
    disp = int(gg.disp)
    return D, periodic, disp


# Test hook: force the in-place Pallas halo-write kernels in interpret mode
# (CPU) so the kernel path is exercised by the emulated-mesh test suite.
_FORCE_PALLAS_WRITE_INTERPRET = False

# Trace-scoped kernel-tier override: the ensemble runner pins its vmapped
# step to the pure-XLA exchange (every XLA op has a vmap batching rule;
# the Pallas halo kernels' batching is unvalidated hardware territory).
_FORCE_XLA_TIER = False


@contextlib.contextmanager
def force_xla_exchange():
    """Context manager pinning `local_update_halo` to the pure-XLA tier
    (no Pallas halo kernels) for the duration of a TRACE. Used by
    `models.common.make_state_runner(ensemble=...)` around its vmapped
    step: the exchange's slices/permutes/updates all batch by jax rule,
    while a Pallas kernel launched under vmap would lean on `pallas_call`
    batching this repo has never validated on hardware. The flag is
    consulted at trace time by every kernel-tier gate below."""
    global _FORCE_XLA_TIER
    prev = _FORCE_XLA_TIER
    _FORCE_XLA_TIER = True
    try:
        yield
    finally:
        _FORCE_XLA_TIER = prev


def _pallas_write_mode(gg, dim, shape, hw):
    """(use_kernel, interpret) for the halo unpack along ``dim``."""
    from .pallas_halo import halo_write_supported

    if _FORCE_XLA_TIER or not halo_write_supported(shape, dim, hw):
        return False, False
    if _FORCE_PALLAS_WRITE_INTERPRET:
        return True, True
    return bool(gg.use_pallas[dim]) and gg.device_type == "tpu", False


def _pallas_tier_enabled(gg, shape, dims_order) -> bool:
    """Shared gate for the whole-exchange Pallas kernels (self-exchange and
    combined one-pass): default order, 3-D, TPU with all per-dim flags on
    (the kernels cover every dim at once), or the test force flag."""
    if _FORCE_XLA_TIER:
        return False
    if tuple(dims_order) != DEFAULT_DIMS_ORDER or len(shape) != 3:
        return False
    return _FORCE_PALLAS_WRITE_INTERPRET or (
        bool(gg.use_pallas.all()) and gg.device_type == "tpu")


def _self_exchange_plan(gg, shape, hws, dims_order):
    """If every participating dim of a field with this local ``shape`` takes
    the self-neighbor path, return (modes, ols) for the single-pass kernel
    (`pallas_halo.halo_self_exchange_pallas`); else None.

    Only valid when ALL exchanging dims are self-neighbor: a mix would break
    the reference's strict dim sequencing (a later self dim must see an
    earlier ppermute dim's received corners). The kernel hardwires the
    default z, x, y order.
    """
    from .pallas_halo import self_exchange_supported

    if not _pallas_tier_enabled(gg, shape, dims_order):
        return None
    modes = [False, False, False]
    ols = [0, 0, 0]
    for dim in range(3):
        D = int(gg.dims[dim])
        periodic = bool(gg.periods[dim])
        hw = int(hws[dim])
        ol_d = int(gg.overlaps[dim] + (shape[dim] - gg.nxyz[dim]))
        if D == 1 and not periodic:
            continue                      # no exchange
        if ol_d < 2 * hw:
            continue                      # computation-overlap only
        if D != 1 or not periodic or int(gg.disp) != 1:
            return None                   # a ppermute dim: no single-pass
        modes[dim] = True
        ols[dim] = ol_d
    if not self_exchange_supported(shape, modes, hws):
        return None
    return tuple(modes), tuple(ols)


def _dim_exchanges(gg, shape, hws, dim) -> bool:
    """Whether a field of this local ``shape`` exchanges along ``dim`` (the
    participation gates of the per-dim loop)."""
    if dim >= len(shape):
        return False
    D, periodic, disp = _dim_meta(gg, dim)
    if D == 1 and not periodic:
        return False
    if D > 1 and not periodic and disp >= D:
        return False  # Cart_shift beyond the grid: all-PROC_NULL, no-op
    ol_d = int(gg.overlaps[dim] + (shape[dim] - gg.nxyz[dim]))
    return ol_d >= 2 * int(hws[dim])


def _combined_plan(gg, shape, hws, dims_order):
    """Participation modes for the combined one-pass exchange
    (`pallas_halo.halo_write_combined_pallas`), or None if inapplicable.

    Used when dim 2 exchanges with at least one ppermute dim in play (the
    all-self case goes to the cheaper `halo_self_exchange_pallas`): dim 2's
    lane-edge halo forces array-level traffic no matter what, so delivering
    ALL dims' slabs in one full pass beats one array rewrite per dim.
    """
    from .pallas_halo import combined_write_supported

    if not _pallas_tier_enabled(gg, shape, dims_order):
        return None
    modes = tuple(_dim_exchanges(gg, shape, hws, dim) for dim in range(3))
    if not combined_write_supported(shape, modes, hws):
        return None
    return modes


def exchange_recv_slabs_multi(gg, shapes, hws, modes, getters, *,
                              wire=None, coalesce=None):
    """Masked, corner-patched RECEIVED slabs for every (field, dim) — the
    shared slab pipeline of every fused kernel tier, on the CANONICAL wire
    schema: per dim, all participating fields' send slabs pack into ONE
    buffer per direction (`ops.wire.slab_schema`) and the axis costs a
    single ppermute pair per (axis, dtype group) REGARDLESS of field count
    — the same wire the XLA coalesced tier ships, which is what lets the
    collective contracts and the quantized wire cover the Pallas programs
    (`analysis.audit.audit_model(impl='pallas')`).

    Per dim, in the reference's write order (z, x, y — `update_halo.jl:29`):
    extract each field's send slabs via its ``getters[f](dim, start,
    size)`` hook (a plain slice for a standalone exchange, a freshly
    COMPUTED slab when a model fuses its update with the exchange), patch
    them with THAT field's earlier received values (slab-level corner
    propagation — exactly equivalent to the sequential per-dim writes),
    pack + permute (or swap locally for self-neighbor dims), unpack, and
    mask non-periodic boundaries per field with the patched current halos
    (the PROC_NULL no-op, `init_global_grid.jl:103`).

    ``shapes``/``modes``/``getters`` are dicts keyed by field name (the
    dict order is the pack order); ``hws`` is the shared per-dim halowidth
    tuple. ``wire`` is the RESOLVED wire policy (or None = exact);
    ``coalesce=None`` resolves `resolve_halo_coalesce` (OFF restores one
    pair per field). Returns ``{field: {dim: (recv_l, recv_r)}}``.
    """
    import jax.numpy as jnp
    from jax import lax

    if coalesce is None:
        coalesce = resolve_halo_coalesce(None)
    names = list(getters)
    earlier = {f: [] for f in names}  # [(dim, hw, (recv_l, recv_r))]
    recvs = {f: {} for f in names}

    def patch(f, slab, d, start, size):
        """Apply field ``f``'s earlier dims' received halo values to a slab
        spanning [start, start+size) along d (full extent elsewhere)."""
        for e, hw_e, (rl, rr) in earlier[f]:
            rl_s = lax.slice_in_dim(rl, start, start + size, axis=d)
            rr_s = lax.slice_in_dim(rr, start, start + size, axis=d)
            slab = lax.dynamic_update_slice_in_dim(slab, rl_s, 0, axis=e)
            slab = lax.dynamic_update_slice_in_dim(
                slab, rr_s, slab.shape[e] - hw_e, axis=e)
        return slab

    for dim in DEFAULT_DIMS_ORDER:
        parts = [f for f in names if modes[f][dim]]
        if not parts:
            continue
        D, periodic, disp = _dim_meta(gg, dim)
        hw = int(hws[dim])
        sends = {}
        for f in parts:
            s = shapes[f][dim]
            ol_d = int(gg.overlaps[dim] + (shapes[f][dim] - gg.nxyz[dim]))
            send_r = patch(f, getters[f](dim, s - ol_d, hw), dim,
                           s - ol_d, hw)
            send_l = patch(f, getters[f](dim, ol_d - hw, hw), dim,
                           ol_d - hw, hw)
            sends[f] = (send_l, send_r)
        if D == 1:  # periodic self-neighbor: local swap, no wire
            for f in parts:
                send_l, send_r = sends[f]
                recvs[f][dim] = (send_r, send_l)
                earlier[f].append((dim, hw, recvs[f][dim]))
            continue
        perm_p, perm_m = _perm_pairs(D, periodic, disp)
        axis_name = AXIS_NAMES[dim]
        by_dt = {}
        for f in parts:
            by_dt.setdefault(np.dtype(sends[f][0].dtype), []).append(f)
        for dt, fs in by_dt.items():
            fmt = wire_format_for(dt, wire, dim)
            groups = [fs] if coalesce else [[f] for f in fs]
            for g in groups:
                schema = slab_schema(
                    dim, [sends[f][0].shape for f in g], dt, fmt)
                buf_r = schema.pack([sends[f][1] for f in g])
                buf_l = schema.pack([sends[f][0] for f in g])
                rls = schema.unpack(lax.ppermute(buf_r, axis_name, perm_p))
                rrs = schema.unpack(lax.ppermute(buf_l, axis_name, perm_m))
                if not periodic:  # PROC_NULL edges keep current halos EXACT
                    idx = lax.axis_index(axis_name)
                    for k, f in enumerate(g):
                        s = shapes[f][dim]
                        cur_l = patch(f, getters[f](dim, 0, hw), dim, 0, hw)
                        cur_r = patch(f, getters[f](dim, s - hw, hw), dim,
                                      s - hw, hw)
                        rls[k] = jnp.where(idx >= disp, rls[k], cur_l)
                        rrs[k] = jnp.where(idx < D - disp, rrs[k], cur_r)
                for k, f in enumerate(g):
                    recvs[f][dim] = (rls[k], rrs[k])
        for f in parts:
            earlier[f].append((dim, hw, recvs[f][dim]))
    return recvs


def exchange_recv_slabs(gg, shape, hws, modes, get_slab, *, wire=None):
    """Single-field form of `exchange_recv_slabs_multi` (the combined
    one-pass exchange and the single-field fused kernels). Returns
    ``{dim: (recv_l, recv_r)}``."""
    return exchange_recv_slabs_multi(
        gg, {"A": shape}, hws, {"A": modes}, {"A": get_slab},
        wire=wire)["A"]


def _combined_exchange(gg, a, hws, modes, interpret):
    """All-dims exchange with ONE unpack pass: the `exchange_recv_slabs`
    pipeline on plain slices, then `halo_write_combined_pallas` writes every
    received slab in a single full-array pass."""
    from jax import lax

    from .pallas_halo import halo_write_combined_pallas

    recvs = exchange_recv_slabs(
        gg, a.shape, hws, modes,
        lambda dim, start, size: lax.slice_in_dim(a, start, start + size,
                                                  axis=dim))
    return halo_write_combined_pallas(a, recvs, modes=modes, hws=hws,
                                      interpret=interpret)


def _apply_self_exchange(gg, arrays, hws, dims_order):
    """Run the single-pass self-neighbor kernel on every eligible field.
    Mutates ``arrays``; returns ``handled`` flags (True = fully exchanged)."""
    handled = [False] * len(arrays)
    for i, a in enumerate(arrays):
        plan = _self_exchange_plan(gg, a.shape, hws[i], dims_order)
        if plan is not None:
            from .pallas_halo import halo_self_exchange_pallas

            arrays[i] = halo_self_exchange_pallas(
                a, modes=plan[0], ols=plan[1],
                interpret=_FORCE_PALLAS_WRITE_INTERPRET,
            )
            handled[i] = True
    return handled


def _perm_pairs(D, periodic, disp):
    """The (forward, backward) ppermute pairs of an exchanging axis —
    wrap-around when periodic, truncated chains (PROC_NULL edges) when not.
    Delegates to `parallel.topology.axis_perm_pairs`: ONE pair generator
    shared by the per-field path, the coalesced path, the staged wire's
    intra/cross partition, and the contracts, so the wire pattern can
    never diverge between layers."""
    from ..parallel.topology import axis_perm_pairs

    return axis_perm_pairs(D, periodic, disp)


def _staged_layouts(gg, stage) -> dict:
    """``{dim: StagedWireLayout}`` for every dim the resolved
    `WireStagePolicy` stages AND whose granule geometry supports it
    (`parallel.topology.staged_wire_layout`). The one routing decision —
    the live exchange, the static plan, the perf oracle, and the
    contracts all consult this, so a degenerate axis degrades to the
    flat pair identically everywhere."""
    if stage is None:
        return {}
    from ..parallel.topology import staged_wire_layout

    out = {}
    for d in stage.staged_dims:
        lay = staged_wire_layout(gg, d)
        if lay is not None:
            out[d] = lay
    return out


def _check_slab_fit(s, dim, ol_d, hw):
    if not (0 <= s - ol_d and ol_d - hw >= 0 and hw <= s):
        raise IncoherentArgumentError(
            f"Field of local size {s} along dimension {dim} cannot hold send slabs "
            f"(overlap {ol_d}, halowidth {hw})."
        )


def _coalesce_groups(gg, arrays, hws, handled, dims_order, coalesce=True,
                     wire=None, staged_dims=frozenset()):
    """Packing plan for the coalesced exchange: ``{dim: [group, ...]}``
    where each group is a tuple of field indices of ONE dtype that all
    exchange along ppermute axis ``dim``. Without wire quantization a
    group needs >= 2 fields (a lone field per dtype gains nothing from
    packing and keeps the per-field path — the fallback the packer
    declares by simply not grouping). A dtype the policy QUANTIZES along
    ``dim`` always rides the packed path — its payload carries the
    appended per-slab scales, a layout only the flat buffer has — even as
    a singleton, and with ``coalesce=False`` each quantized field packs
    its own buffer (per-field collective count preserved). A dim in
    ``staged_dims`` (the topology-staged wire) likewise forces the packed
    path for EVERY exchanging field — the staged pipeline routes one
    packed buffer per group through gather/DCN/scatter, so even
    singletons pack (packing a lone slab is pure layout: bit-identity to
    the per-field wire is preserved)."""
    out = {}
    for dim in dims_order:
        D, periodic, disp = _dim_meta(gg, dim)
        if D == 1:
            continue  # self-neighbor / no-neighbor axes: nothing to pack
        by_dt = {}
        for i, a in enumerate(arrays):
            if handled[i]:
                continue
            if _dim_exchanges(gg, a.shape, hws[i], dim):
                by_dt.setdefault(np.dtype(a.dtype), []).append(i)
        groups = []
        for dt, idxs in by_dt.items():
            fmt = wire_format_for(dt, wire, dim)
            quant = fmt is not None and fmt.is_quant
            packed = quant or dim in staged_dims
            if packed and not coalesce:
                groups.extend((i,) for i in idxs)
            elif packed or (coalesce and len(idxs) >= 2):
                groups.append(tuple(idxs))
        if groups:
            out[dim] = groups
    return out


def _coalesced_pallas_mode(gg, dim, shapes, hws_dim):
    """(use_multi_kernel, interpret) for the coalesced unpack along
    ``dim`` — the multi-field analog of `_pallas_write_mode`."""
    from .pallas_halo import multi_write_supported

    if _FORCE_XLA_TIER or not multi_write_supported(shapes, dim, hws_dim):
        return False, False
    if _FORCE_PALLAS_WRITE_INTERPRET:
        return True, True
    return bool(gg.use_pallas[dim]) and gg.device_type == "tpu", False


def _wire_pack_mode(gg, dim, shapes, hws_dim, schema):
    """``(use_kernel, interpret)`` for the fused Pallas PACK of a
    slab-layout wire buffer (one launch writes every field's send slab
    into the packed payload — `pallas_halo.wire_pack_pallas`), or ``None``
    for the XLA concat pack. Gated on the same conditions as the
    multi-field unpack kernel (so `_build_exchange_fn`'s check_vma
    accounting holds) plus `pallas_halo.wire_pack_supported`; quantized
    payloads always pack through the flat XLA program (their scale-tail
    arithmetic is elementwise work XLA already fuses well)."""
    from .pallas_halo import wire_pack_supported

    if schema.layout != "slab" or schema.is_quant:
        return None
    use, interp = _coalesced_pallas_mode(gg, dim, shapes, hws_dim)
    # budget with the STATE dtype: the kernel packs the raw slabs and any
    # cast wire narrowing happens after (`WireSchema.pack`)
    if not use or not wire_pack_supported(schema.shapes, dim,
                                          schema.state_dtype):
        return None
    return True, interp


def _exchange_dim_coalesced(gg, arrays, idxs, hws, dim, wire=None):
    """Exchange the halos of fields ``idxs`` (one dtype) along ``dim`` with
    ONE ppermute pair, on the canonical wire schema (`ops.wire`): pack
    every field's send slab into one buffer per direction, permute,
    unpack, deliver. Mutates ``arrays``. With exact wire, values are
    bit-identical to the per-field exchange — the pack stage is pure
    layout (slab layout: one concat along the exchange axis, no
    ravel/reshape passes; the PROC_NULL boundary select runs per-field on
    slab-sized operands). Under a cast wire format the buffer crosses the
    link narrowed; under a QUANT format (int8/int4) each field's slab is
    quantized against its own max-abs scale and the f32 scales ride the
    same flat buffer — still one ppermute pair, wire bytes ~4-8x down."""
    import jax.numpy as jnp
    from jax import lax

    D, periodic, disp = _dim_meta(gg, dim)
    axis_name = AXIS_NAMES[dim]
    perm_p, perm_m = _perm_pairs(D, periodic, disp)

    metas = []  # (i, hw, s, slab_shape)
    sends_r, sends_l, curs_l, curs_r = [], [], [], []
    for i in idxs:
        a = arrays[i]
        hw = int(hws[i][dim])
        s = a.shape[dim]
        ol_d = int(gg.overlaps[dim] + (s - gg.nxyz[dim]))
        _check_slab_fit(s, dim, ol_d, hw)
        send_r = lax.slice_in_dim(a, s - ol_d, s - ol_d + hw, axis=dim)
        send_l = lax.slice_in_dim(a, ol_d - hw, ol_d, axis=dim)
        metas.append((i, hw, s, send_r.shape))
        sends_r.append(send_r)
        sends_l.append(send_l)
        if not periodic:  # exact-precision boundary halos (PROC_NULL no-op)
            curs_l.append(lax.slice_in_dim(a, 0, hw, axis=dim))
            curs_r.append(lax.slice_in_dim(a, s - hw, s, axis=dim))

    state_dt = arrays[idxs[0]].dtype
    fmt = wire_format_for(state_dt, wire, dim)
    schema = slab_schema(dim, [m[3] for m in metas], state_dt, fmt)
    pk = _wire_pack_mode(gg, dim, [arrays[i].shape for i in idxs],
                         [m[1] for m in metas], schema)
    recv_l = schema.unpack(lax.ppermute(
        schema.pack(sends_r, pallas_mode=pk), axis_name, perm_p))
    recv_r = schema.unpack(lax.ppermute(
        schema.pack(sends_l, pallas_mode=pk), axis_name, perm_m))
    if not periodic:  # per-field slab-sized selects (no cur-parts concat)
        idxv = lax.axis_index(axis_name)
        recv_l = [jnp.where(idxv >= disp, rl, cur)
                  for rl, cur in zip(recv_l, curs_l)]
        recv_r = [jnp.where(idxv < D - disp, rr, cur)
                  for rr, cur in zip(recv_r, curs_r)]
    slab_pairs = list(zip(recv_l, recv_r))  # aligned with metas

    use_multi, interp = _coalesced_pallas_mode(
        gg, dim, [arrays[i].shape for i in idxs], [m[1] for m in metas])
    if use_multi:
        from .pallas_halo import halo_write_multi_pallas

        outs = halo_write_multi_pallas(
            [arrays[i] for i in idxs], slab_pairs,
            dim=dim, hw=metas[0][1], interpret=interp)
        for i, o in zip(idxs, outs):
            arrays[i] = o
        return
    for (i, hw, s, _), (rl, rr) in zip(metas, slab_pairs):
        pw, interp = _pallas_write_mode(gg, dim, arrays[i].shape, hw)
        if pw:
            from .pallas_halo import halo_write_inplace

            arrays[i] = halo_write_inplace(arrays[i], rl, rr, dim=dim, hw=hw,
                                           interpret=interp)
        else:
            a = lax.dynamic_update_slice_in_dim(arrays[i], rl, 0, axis=dim)
            arrays[i] = lax.dynamic_update_slice_in_dim(a, rr, s - hw,
                                                        axis=dim)


def _exchange_dim_staged(gg, arrays, idxs, hws, dim, wire, layout):
    """Exchange the halos of fields ``idxs`` (one dtype) along staged dim
    ``dim`` through the hierarchical three-stage pipeline
    (`parallel.topology.StagedWireLayout`):

    1. **gather** — ``fold - 1`` pipelined ppermute shifts along the
       gather (ICI) axis walk every sending plane's packed buffer toward
       the per-granule leader (gather coord 0), which records one slot
       per hop;
    2. **dcn** — the leaders stack their ``fold`` slots and ONE ppermute
       per direction ships the stripe leader -> leader across the granule
       boundary (per-DCN-link message count drops by the ICI fold);
    3. **scatter** — ``fold - 1`` reverse shifts fan the stripe back out:
       the far leader injects pieces farthest-first, every non-leader's
       own slab arrives in the final round.

    Same-granule pairs keep the flat single-axis ppermute (``intra``) and
    a mesh-coordinate select stitches the two results before the shared
    PROC_NULL masking and delivery of the flat path. The payload is the
    SAME `WireSchema.pack` buffer the flat coalesced exchange ships —
    never transformed, only routed — so delivered halos are BIT-IDENTICAL
    to the flat wire, and a quantized payload's per-slab scales ride
    in-band through all three stages. Mutates ``arrays``."""
    import functools

    import jax.numpy as jnp
    from jax import lax

    D, periodic, disp = _dim_meta(gg, dim)
    axis_name = AXIS_NAMES[dim]
    gather_name = AXIS_NAMES[layout.gather_dim]
    F = int(layout.fold)

    metas = []  # (i, hw, s, slab_shape)
    sends_r, sends_l, curs_l, curs_r = [], [], [], []
    for i in idxs:
        a = arrays[i]
        hw = int(hws[i][dim])
        s = a.shape[dim]
        ol_d = int(gg.overlaps[dim] + (s - gg.nxyz[dim]))
        _check_slab_fit(s, dim, ol_d, hw)
        send_r = lax.slice_in_dim(a, s - ol_d, s - ol_d + hw, axis=dim)
        send_l = lax.slice_in_dim(a, ol_d - hw, ol_d, axis=dim)
        metas.append((i, hw, s, send_r.shape))
        sends_r.append(send_r)
        sends_l.append(send_l)
        if not periodic:
            curs_l.append(lax.slice_in_dim(a, 0, hw, axis=dim))
            curs_r.append(lax.slice_in_dim(a, s - hw, s, axis=dim))

    state_dt = arrays[idxs[0]].dtype
    fmt = wire_format_for(state_dt, wire, dim)
    schema = slab_schema(dim, [m[3] for m in metas], state_dt, fmt)
    # staged payloads take the XLA pack (no fused pack kernel: the routed
    # buffer is consumed by collectives, not a single unpack launch)
    buf_r = schema.pack(sends_r)
    buf_l = schema.pack(sends_l)

    g_idx = lax.axis_index(gather_name)
    d_idx = lax.axis_index(axis_name)
    is_leader = g_idx == 0

    def staged_route(buf, dr):
        # gather: F-1 pipelined shifts toward the leader; the leader's
        # slot r holds the buffer that ORIGINATED at gather coord r
        fwd = buf
        slots = [buf]
        for _ in range(F - 1):
            fwd = lax.ppermute(fwd, AXIS_NAMES, dr.gather_pairs)
            slots.append(fwd)
        stripe = jnp.stack(slots)
        # ONE striped DCN transfer per direction (leader -> leader)
        got = lax.ppermute(stripe, AXIS_NAMES, dr.dcn_pairs)
        # scatter: leader injects farthest-first; the piece injected in
        # round r reaches gather coord k = F - r in the final round, so
        # every non-leader's own slab is its LAST received value
        cur = jnp.zeros_like(buf)
        for r in range(1, F):
            send = jnp.where(is_leader, got[F - r], cur)
            cur = lax.ppermute(send, AXIS_NAMES, dr.scatter_pairs)
        return jnp.where(is_leader, got[0], cur)

    def one_direction(buf, dr):
        if not dr.cross_pairs:  # nothing crosses a granule: stay flat
            return lax.ppermute(buf, axis_name, dr.axis_pairs)
        staged = staged_route(buf, dr)
        flat = (lax.ppermute(buf, axis_name, dr.intra_pairs)
                if dr.intra_pairs else jnp.zeros_like(buf))
        tmask = functools.reduce(
            jnp.logical_or, [d_idx == t for t in dr.cross_targets])
        return jnp.where(tmask, staged, flat)

    dir_p, dir_m = layout.directions
    recv_l = schema.unpack(one_direction(buf_r, dir_p))
    recv_r = schema.unpack(one_direction(buf_l, dir_m))
    if not periodic:  # PROC_NULL edges keep current halos EXACT
        recv_l = [jnp.where(d_idx >= disp, rl, cur)
                  for rl, cur in zip(recv_l, curs_l)]
        recv_r = [jnp.where(d_idx < D - disp, rr, cur)
                  for rr, cur in zip(recv_r, curs_r)]
    slab_pairs = list(zip(recv_l, recv_r))

    use_multi, interp = _coalesced_pallas_mode(
        gg, dim, [arrays[i].shape for i in idxs], [m[1] for m in metas])
    if use_multi:
        from .pallas_halo import halo_write_multi_pallas

        outs = halo_write_multi_pallas(
            [arrays[i] for i in idxs], slab_pairs,
            dim=dim, hw=metas[0][1], interpret=interp)
        for i, o in zip(idxs, outs):
            arrays[i] = o
        return
    for (i, hw, s, _), (rl, rr) in zip(metas, slab_pairs):
        pw, interp = _pallas_write_mode(gg, dim, arrays[i].shape, hw)
        if pw:
            from .pallas_halo import halo_write_inplace

            arrays[i] = halo_write_inplace(arrays[i], rl, rr, dim=dim, hw=hw,
                                           interpret=interp)
        else:
            a = lax.dynamic_update_slice_in_dim(arrays[i], rl, 0, axis=dim)
            arrays[i] = lax.dynamic_update_slice_in_dim(a, rr, s - hw,
                                                        axis=dim)


def _exchange_arrays(gg, arrays, hws, dims_order, coalesce=None, wire=None,
                     stage=None):
    """Exchange every field's halos (local view; inside shard_map).
    Mutates and returns ``arrays``. Kernel-path selection per field:
    all-self single-pass kernel > coalesced packed exchange (multi-field
    dtype groups) > combined one-pass unpack > per-dim per-field.

    ``coalesce=None`` resolves `resolve_halo_coalesce` (env default ON);
    ``wire`` is the RESOLVED wire policy (`precision.resolve_wire_dtype`)
    or None for full-precision wire. Wire mode routes its fields through
    the coalesced/per-dim paths (the combined one-pass tier has its own
    full-precision permutes); quantized formats always ride the packed
    path (the scales live in the flat buffer — `_coalesce_groups`).
    ``stage`` is the RESOLVED topology-staging policy
    (`wire.resolve_wire_stage`) or None for the flat wire everywhere: a
    staged dim's fields always ride the packed path and its groups go
    through the hierarchical three-stage exchange
    (`_exchange_dim_staged`) instead of the flat pair."""
    if coalesce is None:
        coalesce = resolve_halo_coalesce(None)
    handled = _apply_self_exchange(gg, arrays, hws, dims_order)
    staged = _staged_layouts(gg, stage)
    groups_by_dim = _coalesce_groups(gg, arrays, hws, handled, dims_order,
                                     coalesce=coalesce, wire=wire,
                                     staged_dims=frozenset(staged))
    grouped = {i for gs in groups_by_dim.values() for g in gs for i in g}
    def wire_touches(a, hw):
        # whether the policy can actually reach one of THIS field's
        # ppermute payloads: a policy-named dim that is unpartitioned
        # (D==1: self-copies stay exact) or that the field does not
        # exchange along is a no-op for it
        return any(
            wire_format_for(a.dtype, wire, d) is not None
            and _dim_meta(gg, d)[0] > 1
            and _dim_exchanges(gg, a.shape, hw, d)
            for d in dims_order)

    def stage_touches(a, hw):
        # staged dims must take the staged route — the combined one-pass
        # tier's permutes are flat
        return any(
            d in staged and _dim_exchanges(gg, a.shape, hw, d)
            for d in dims_order)

    for i, a in enumerate(arrays):
        # wire-affected fields skip the combined tier (its permutes are
        # full-precision); fields the wire policy can never touch (ints,
        # already-narrow floats, fields whose policy-named dims carry no
        # ppermute for them) keep the faster one-pass kernel — evicting
        # those would pay per-dim exchanges for bit-identical results.
        if handled[i] or i in grouped or wire_touches(a, hws[i]) \
                or stage_touches(a, hws[i]):
            continue
        modes = _combined_plan(gg, a.shape, hws[i], dims_order)
        if modes is not None:
            arrays[i] = _combined_exchange(
                gg, a, hws[i], modes, _FORCE_PALLAS_WRITE_INTERPRET)
            handled[i] = True
    for dim in dims_order:
        D, periodic, disp = _dim_meta(gg, dim)
        if D == 1 and not periodic:
            continue  # no neighbors along this axis (reference update_halo.jl:45 note)
        in_group = set()
        for g in groups_by_dim.get(dim, ()):
            in_group.update(g)
            if dim in staged:
                _exchange_dim_staged(gg, arrays, list(g), hws, dim, wire,
                                     staged[dim])
            else:
                _exchange_dim_coalesced(gg, arrays, list(g), hws, dim, wire)
        for i, a in enumerate(arrays):
            if handled[i] or i in in_group or dim >= a.ndim:
                continue
            hw = int(hws[i][dim])
            ol_d = int(gg.overlaps[dim] + (a.shape[dim] - gg.nxyz[dim]))
            if ol_d < 2 * hw:
                continue  # computation overlap only, no halo (update_halo.jl:233)
            pw, interp = _pallas_write_mode(gg, dim, a.shape, hw)
            arrays[i] = _exchange_dim_local(
                a, dim=dim, hw=hw, ol_d=ol_d, D=D, periodic=periodic,
                disp=disp, axis_name=AXIS_NAMES[dim],
                pallas_write=pw, interpret=interp, wire=wire,
            )
    return arrays


def _exchange_dim_local(a, *, dim, hw, ol_d, D, periodic, disp, axis_name,
                        pallas_write=False, interpret=False, wire=None):
    """Exchange the halos of local block ``a`` along array axis ``dim``.

    Runs inside `shard_map`. All shapes/indices are static; only the mesh
    coordinate (`axis_index`) is traced. With ``pallas_write``, the unpack
    writes the halo slabs in place via the Pallas kernels (`pallas_halo.py`)
    instead of full-array `dynamic_update_slice` rewrites. ``wire`` is the
    resolved wire policy: CAST formats narrow the ppermute payloads here
    (`precision.wire_format_for`); QUANT formats never reach this path —
    `_coalesce_groups` routes every quantized field through the packed
    exchange, whose flat buffer carries the per-slab scales. Local
    self-neighbor copies and PROC_NULL boundary halos stay exact.
    """
    import jax.numpy as jnp
    from jax import lax

    s = a.shape[dim]
    _check_slab_fit(s, dim, ol_d, hw)

    def write_halos(a, into_l, into_r):
        """Halo writes: left halo <- ``into_l``, right halo <- ``into_r``."""
        if pallas_write:
            from .pallas_halo import halo_write_inplace

            return halo_write_inplace(a, into_l, into_r, dim=dim, hw=hw,
                                      interpret=interpret)
        a = lax.dynamic_update_slice_in_dim(a, into_l, 0, axis=dim)
        a = lax.dynamic_update_slice_in_dim(a, into_r, s - hw, axis=dim)
        return a

    # Send slabs (reference sendranges, update_halo.jl:275-284).
    send_r = lax.slice_in_dim(a, s - ol_d, s - ol_d + hw, axis=dim)   # n=2
    send_l = lax.slice_in_dim(a, ol_d - hw, ol_d, axis=dim)           # n=1

    if D == 1:
        if not periodic:
            return a
        # Self-neighbor: periodic axis with one shard — pure local copies
        # (reference sendrecv_halo_local, update_halo.jl:363-380):
        # left halo <- own right slab, right halo <- own left slab.
        return write_halos(a, send_r, send_l)

    perm_p, perm_m = _perm_pairs(D, periodic, disp)
    if not perm_p and not perm_m:
        return a

    fmt = wire_format_for(a.dtype, wire, dim)
    wire_dt = None if fmt is None or fmt.is_quant else fmt.dtype
    if wire_dt is not None:
        send_r = send_r.astype(wire_dt)
        send_l = send_l.astype(wire_dt)

    # Both directions posted before any consumption — the analog of the
    # reference posting all Irecv!/Isend before waiting (update_halo.jl:51-60);
    # XLA schedules the two collectives concurrently.
    recv_l = lax.ppermute(send_r, axis_name, perm_p) if perm_p else None  # from coord-disp
    recv_r = lax.ppermute(send_l, axis_name, perm_m) if perm_m else None  # from coord+disp
    if wire_dt is not None:
        recv_l = recv_l.astype(a.dtype) if recv_l is not None else None
        recv_r = recv_r.astype(a.dtype) if recv_r is not None else None

    idx = lax.axis_index(axis_name)
    if not periodic:  # PROC_NULL edges: boundary shards keep their halos
        cur_l = lax.slice_in_dim(a, 0, hw, axis=dim)
        recv_l = jnp.where(idx >= disp, recv_l, cur_l)
        cur_r = lax.slice_in_dim(a, s - hw, s, axis=dim)
        recv_r = jnp.where(idx < D - disp, recv_r, cur_r)
    return write_halos(a, recv_l, recv_r)


def local_update_halo(*fields, dims=None, coalesce=None, wire_dtype=None,
                      wire_stage=None):
    """Halo-exchange local blocks — use INSIDE `shard_map` over the grid mesh.

    This is the local-view programming model of the reference (user code runs
    per rank; `update_halo!(A)` in the hot loop, e.g.
    `examples/diffusion3D_multicpu_novis.jl:47`): call it inside your own
    `shard_map`-mapped step function on per-shard blocks. Functional: returns
    the updated array(s).

    Arguments may be arrays or ``Field(A, halowidths)``; ``dims`` is the
    0-based dimension processing order (default z, x, y like the reference's
    `(3,1,2)`). ``coalesce`` packs multi-field exchanges into one ppermute
    pair per (axis, dtype group) — default from ``IGG_HALO_COALESCE`` (ON);
    ``wire_dtype`` ships float payloads across the link narrowed (float
    casts) or per-slab-scale quantized (``int8``/``int4``), optionally per
    mesh axis (``"z:int8,x:f32"``) — default from ``IGG_HALO_WIRE_DTYPE``
    (OFF); ``wire_stage`` routes a DCN-crossing axis's exchange through
    the hierarchical ICI-gather -> striped-DCN -> ICI-scatter pipeline
    (``"z:staged"``) — default from ``IGG_HALO_WIRE_STAGE`` (OFF); see
    the module docstring.

    NOTE: on a default TPU grid this emits Pallas kernels (in-place halo
    writes / single-pass self-exchange), which cannot pass `shard_map`'s
    variance checker — build your enclosing `shard_map` with
    ``check_vma=not halo_may_use_pallas()`` (the model runners in
    `models/common.py` do this automatically).
    """
    check_initialized()
    gg = global_grid()
    dims_order = _normalize_dims_order(dims)
    fs = [wrap_field(f) for f in fields]
    arrays = _exchange_arrays(gg, [f.A for f in fs],
                              [f.halowidths for f in fs], dims_order,
                              coalesce=resolve_halo_coalesce(coalesce),
                              wire=resolve_wire_dtype(wire_dtype),
                              stage=resolve_wire_stage(wire_stage))
    return arrays[0] if len(arrays) == 1 else tuple(arrays)


def _build_exchange_fn(gg, sig, dims_order, coalesce, wire, stage=None):
    """Compile the jitted shard_map exchange program for a field signature.
    ``coalesce``, ``wire``, and ``stage`` are pre-resolved
    (`update_halo`)."""
    import jax

    from ..utils.compat import shard_map

    ndims_arr = [len(shape) for (shape, _, _) in sig]
    in_specs = tuple(field_partition_spec(nd) for nd in ndims_arr)
    hws = [hw for (_, _, hw) in sig]

    # Pallas kernels under shard_map require check_vma=False (their outputs
    # can't express the mesh-axis variance the checker wants — same rule as
    # the model step kernels, models/diffusion.py). The per-field plans are
    # a superset of the coalesced path's kernel gates (`multi_write_supported`
    # is strictly tighter than per-field `halo_write_supported`), so this
    # stays correct when coalescing reroutes fields.
    any_pallas = any(
        _self_exchange_plan(gg, shape, hw, dims_order) is not None
        or _combined_plan(gg, shape, hw, dims_order) is not None
        or any(
            _dim_exchanges(gg, shape, hw, dim)
            and _pallas_write_mode(gg, dim, shape, int(hw[dim]))[0]
            for dim in dims_order
        )
        for (shape, _, hw) in sig
    )

    def exchange(*locals_):
        return tuple(_exchange_arrays(gg, list(locals_), hws, dims_order,
                                      coalesce=coalesce, wire=wire,
                                      stage=stage))

    shmapped = shard_map(
        exchange, mesh=gg.mesh, in_specs=in_specs, out_specs=in_specs,
        check_vma=not any_pallas,
    )
    return jax.jit(shmapped)


class _SigField:
    """Shape/dtype stand-in for a field signature entry, so the routing
    helpers (`_coalesce_groups`, `_dim_exchanges`) serve the static wire
    plan without real arrays."""

    __slots__ = ("shape", "dtype", "ndim")

    def __init__(self, shape, dtype):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self.ndim = len(self.shape)


def _plan_from_sig(gg, sig, dims_order, coalesce, wire,
                   ensemble=None, stage=None) -> dict:
    """Static comm accounting for one exchange signature: collective
    counts and bytes-on-wire derived purely from shapes/overlaps/wire
    dtype — no tracing, no device work (the TPU analog of the reference's
    printed GB/s estimate, computed instead of measured).

    The wire pattern is invariant across kernel tiers (Pallas unpack,
    combined one-pass, plain `dynamic_update_slice` all consume the SAME
    permuted slabs), so the plan only branches on what actually changes
    the wire: coalescing (one packed ppermute pair per (axis, dtype
    group) instead of one pair per field) and the wire policy (narrowed
    or quantized payloads — a quantized group's bytes count the int8/
    packed-int4 slabs PLUS the `SCALE_BYTES` f32 scale per slab, exactly
    the buffer `WireSchema.pack` ships, so the plan stays exact to the
    byte). ``wire_bytes`` sums the payload over every source->dest
    link of the permute (all shards), both directions;
    ``local_copy_bytes`` counts self-neighbor slab swaps that never touch
    the interconnect.

    ``ensemble`` prices the ENSEMBLE axis (ISSUE 12): an E-member chunk
    vmaps the member axis over the step, so jax's collective batching
    keeps the ppermute COUNT identical while every payload (and every
    self-neighbor local copy) carries E members' slabs — bytes x E,
    launches flat in E. The schema's ``members`` field is the single
    byte source, so quantized payloads price E x the per-(member, slab)
    scale tails exactly as `WireSchema.payload_bytes` ships them.

    ``stage`` is the resolved `WireStagePolicy` (or None): a staged
    axis's record switches to the hierarchical three-stage accounting
    (`StagedWireSchema`) — its permute count is the exact collective
    launch total (``2*(2*fold - 1)`` per cross direction plus the intra
    pair) and its bytes are the ABSOLUTE full-mesh wire total (the
    per-line abstraction the flat records use does not divide the
    gather/scatter pipelines evenly), flagged by a ``staged`` detail
    dict (fold, gather axis, per-stage op table, DCN pair counts) so
    downstream mergers skip the per-line scaling for it."""
    E = 1
    if ensemble is not None:
        E = int(ensemble)
        if E < 1:
            # loud, like every runner-side layer: a silently clamped plan
            # would hand a tuner valid-looking solo numbers for a
            # configuration the runtime rejects
            raise InvalidArgumentError(
                f"halo_comm_plan: ensemble must be >= 1; got {ensemble}.")
    fields = [_SigField(shape, dt) for (shape, dt, _) in sig]
    hws = [tuple(int(h) for h in hw) for (_, _, hw) in sig]

    def slab_cells(i, dim):
        shp = fields[i].shape
        return int(np.prod(shp)) // shp[dim] * hws[i][dim]

    axes: dict = {}

    def axis_rec(dim):
        return axes.setdefault(
            AXIS_NAMES[dim], {"ppermutes": 0, "wire_bytes": 0,
                              "by_dtype": {}})

    def add_wire(dim, payload_bytes, key, npairs):
        rec = axis_rec(dim)
        rec["ppermutes"] += 2
        b = payload_bytes * npairs
        rec["wire_bytes"] += b
        rec["by_dtype"][key] = rec["by_dtype"].get(key, 0) + b

    local_bytes = 0
    # per-AXIS split of the self-neighbor copy traffic: a per-axis
    # comm_every cadence amortizes each axis's local swaps at that axis's
    # own rate, so the oracle needs the split, not just the total
    local_by_axis: dict = {}
    staged = _staged_layouts(gg, stage)
    groups_by_dim = _coalesce_groups(
        gg, fields, hws, [False] * len(fields), dims_order,
        coalesce=coalesce, wire=wire, staged_dims=frozenset(staged))
    for dim in dims_order:
        D, periodic, disp = _dim_meta(gg, dim)
        if D == 1 and not periodic:
            continue
        perm_p, perm_m = _perm_pairs(D, periodic, disp)
        npairs = len(perm_p) + len(perm_m)
        in_group = set()
        for g in groups_by_dim.get(dim, ()):  # groups only form on D>1 axes
            in_group.update(g)
            f0 = fields[g[0]]
            fmt = wire_format_for(f0.dtype, wire, dim)
            # ONE pricing source for every packed payload: the canonical
            # schema the live exchange ships (`ops.wire`) — exact to the
            # byte incl. quantized slabs + their `SCALE_BYTES` scale tail
            schema = schema_for_fields(
                dim, [fields[i].shape for i in g],
                [hws[i][dim] for i in g], f0.dtype, fmt, members=E)
            if dim in staged:
                sws = StagedWireSchema(schema=schema, layout=staged[dim])
                rec = axis_rec(dim)
                rec["ppermutes"] += sws.ppermute_ops
                rec["wire_bytes"] += sws.wire_bytes
                rec["by_dtype"][schema.wire_key] = (
                    rec["by_dtype"].get(schema.wire_key, 0) + sws.wire_bytes)
                det = rec.setdefault("staged", {
                    "fold": int(sws.layout.fold),
                    "gather_axis": AXIS_NAMES[sws.layout.gather_dim],
                    "granules": int(sws.layout.granules),
                    "dcn_pairs": sws.dcn_pair_count,
                    "flat_dcn_pairs": sws.flat_dcn_pair_count(),
                    "stages": [],
                })
                det["stages"].extend(
                    dict(s, group=tuple(g)) for s in sws.stage_table())
                continue
            add_wire(dim, schema.payload_bytes, schema.wire_key, npairs)
        for i, f in enumerate(fields):
            if i in in_group or not _dim_exchanges(gg, f.shape, hws[i], dim):
                continue
            if D == 1:  # periodic self-neighbor: local slab swap, no wire
                b = 2 * slab_cells(i, dim) * f.dtype.itemsize * E
                local_bytes += b
                local_by_axis[AXIS_NAMES[dim]] = (
                    local_by_axis.get(AXIS_NAMES[dim], 0) + b)
                continue
            fmt = wire_format_for(f.dtype, wire, dim)
            wd = np.dtype(fmt.dtype if fmt is not None else f.dtype)
            add_wire(dim, slab_cells(i, dim) * wd.itemsize * E, str(wd),
                     npairs)
    return {
        "fields": len(fields),
        "coalesce": bool(coalesce),
        "wire_dtype": None if wire is None else str(wire),
        "wire_stage": None if stage is None else str(stage),
        "staged_axes": tuple(sorted(AXIS_NAMES[d] for d in staged)),
        "ensemble": E,
        "axes": axes,
        "ppermutes": sum(r["ppermutes"] for r in axes.values()),
        "wire_bytes": sum(r["wire_bytes"] for r in axes.values()),
        "local_copy_bytes": local_bytes,
        "local_copy_by_axis": local_by_axis,
    }


def _normalized_fields(fields):
    """`update_halo`'s argument normalization: ``(A, hw)`` tuples ->
    `Field`, pytrees exploded (reference `update_halo.jl:31-32`), ndim
    and per-field coherence validated."""
    fs = []
    for f in fields:
        if isinstance(f, tuple) and not isinstance(f, Field) and len(f) == 2 \
                and hasattr(f[0], "shape") and not hasattr(f[1], "shape"):
            fs.append(wrap_field(f[0], f[1]))
        else:
            fs.extend(wrap_field(x) for x in extract(f))
    if not fs:
        raise InvalidArgumentError("update_halo requires at least one field.")
    for f in fs:
        if not hasattr(f.A, "shape"):
            raise InvalidArgumentError("update_halo requires array inputs.")
        if not (1 <= f.A.ndim <= NDIMS):
            raise InvalidArgumentError(
                f"update_halo supports 1-D to {NDIMS}-D arrays; got {f.A.ndim}-D."
            )
    check_fields(fs)
    return fs


def _stacked_sig(gg, fs) -> tuple:
    """The exchange signature of normalized fields: LOCAL shapes (stacked
    sizes divided by ``dims`` — validated even), dtype strings, halowidths.

    Dtypes are CANONICALIZED the way ``jnp.asarray`` will canonicalize the
    arrays (x64-disabled jax demotes f64 -> f32), so the signature — and
    everything keyed on it: the compiled-exchange cache, the wire plan —
    always describes the arrays actually exchanged."""
    import jax

    for f in fs:
        for d in range(f.A.ndim):
            if int(f.A.shape[d]) % int(gg.dims[d]) != 0:
                raise IncoherentArgumentError(
                    f"Global (stacked) array size {f.A.shape[d]} along dimension {d} is not "
                    f"divisible by dims[{d}]={int(gg.dims[d])}. update_halo operates on "
                    "stacked global arrays (dims * local size); see local_update_halo for "
                    "the local view."
                )
    return tuple(
        (
            tuple(int(s) // int(gg.dims[d]) for d, s in enumerate(f.A.shape)),
            str(jax.dtypes.canonicalize_dtype(np.dtype(f.A.dtype))),
            tuple(int(h) for h in f.halowidths),
        )
        for f in fs
    )


def halo_comm_plan(*fields, dims=None, coalesce=None, wire_dtype=None,
                   ensemble=None, wire_stage=None) -> dict:
    """Static bytes-on-wire / collective-count plan for an `update_halo`
    call with these stacked fields — derived from shapes, overlaps, and
    the wire dtype alone; nothing is compiled or dispatched (zero device
    syncs). Fields accept the same forms as `update_halo` (arrays,
    `Field`, ``(A, hw)`` tuples, pytrees) and anything with
    ``shape``/``dtype`` (e.g. `jax.ShapeDtypeStruct`) works.

    ``ensemble=E`` prices the exchange inside an E-member ensemble chunk
    (`models.common.make_state_runner(ensemble=E)`): the PHYSICAL field
    shapes stay what you pass here (no member axis — the plan describes
    one member's geometry) while every payload multiplies by E behind
    the SAME ppermute pairs (jax's collective batching under vmap;
    ``ppermutes`` is flat in E by construction).

    ``wire_stage`` prices the topology-staged wire (default from
    ``IGG_HALO_WIRE_STAGE``): a staged axis's record carries the exact
    hierarchical collective counts/bytes plus a ``staged`` detail dict
    (see `_plan_from_sig`).

    Returns ``{fields, coalesce, wire_dtype, wire_stage, staged_axes,
    ensemble, axes: {axis: {ppermutes, wire_bytes, by_dtype[, staged]}},
    ppermutes, wire_bytes, local_copy_bytes, local_copy_by_axis}``.
    `update_halo` charges exactly this plan to the telemetry registry
    (``igg_halo_*`` counters) on every call."""
    check_initialized()
    gg = global_grid()
    dims_order = _normalize_dims_order(dims)
    fs = _normalized_fields(fields)
    sig = _stacked_sig(gg, fs)
    return _plan_from_sig(gg, sig, dims_order,
                          resolve_halo_coalesce(coalesce),
                          resolve_wire_dtype(wire_dtype),
                          ensemble=ensemble,
                          stage=resolve_wire_stage(wire_stage))


def update_halo(*fields, dims=None, coalesce=None, wire_dtype=None,
                wire_stage=None):
    """Update the halo of the given global (stacked) array(s).

    Controller-side API of the reference's `update_halo!`
    (`/root/reference/src/update_halo.jl:29-36`): arrays are stacked/global
    `jax.Array`s (shape ``dims * local_shape``, sharded over the grid mesh —
    each shard is one reference rank-local array). JAX arrays are immutable, so
    the call is FUNCTIONAL and returns the updated array(s)::

        T = update_halo(T)
        A, B, C = update_halo(A, B, (C, (2, 2, 2)))   # per-field halowidths

    Fields may be arrays, ``Field(A, halowidths)``, ``(A, halowidths)`` tuples,
    or pytrees of arrays (the CellArray analog, reference `shared.jl:133-137`).
    Group several fields in one call for best performance — same-dtype fields
    COALESCE into one ppermute pair per mesh axis (``coalesce``, default from
    ``IGG_HALO_COALESCE``: ON), the stronger form of the reference's
    multi-field pipelining note (`update_halo.jl:17-18`). ``wire_dtype``
    (default from ``IGG_HALO_WIRE_DTYPE``: OFF) ships float payloads across
    the link at reduced precision — float casts or per-slab-scaled
    ``int8``/``int4`` quantization, per mesh axis (``"z:int8,x:f32"``);
    ``wire_stage`` (default from ``IGG_HALO_WIRE_STAGE``: OFF) stages a
    DCN-crossing axis's exchange hierarchically — per-granule ICI
    leaders gather the packed slabs, ONE striped transfer per
    granule-pair crosses DCN, the far leader scatters back over ICI
    (``"z:staged"``; bit-identical halos, per-DCN-link message count
    divided by the ICI fold); see the module docstring.

    Example (doctest):

    >>> import numpy as np
    >>> import implicitglobalgrid_tpu as igg
    >>> _ = igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2,
    ...                          periodx=1, quiet=True)
    >>> T = igg.ones_g(dtype=np.float32)    # stacked (8, 8, 8)
    >>> T = igg.update_halo(T)
    >>> tuple(T.shape)
    (8, 8, 8)
    >>> igg.finalize_global_grid()
    """
    import jax.numpy as jnp

    check_initialized()
    gg = global_grid()
    dims_order = _normalize_dims_order(dims)

    # Normalize (tuples (A, hw) → Field; pytrees exploded, reference :31-32)
    # and validate the stacked layout: every sharded dim must divide evenly.
    fs = _normalized_fields(fields)
    arrays = [jnp.asarray(f.A) for f in fs]
    # Signature uses LOCAL shapes: the exchange math runs on per-shard blocks.
    sig = _stacked_sig(gg, fs)
    coalesce_r = resolve_halo_coalesce(coalesce)
    wire_r = resolve_wire_dtype(wire_dtype)
    stage_r = resolve_wire_stage(wire_stage)
    key = (grid_epoch(), sig, dims_order, _FORCE_PALLAS_WRITE_INTERPRET,
           coalesce_r, str(wire_r), str(stage_r))
    fn = _exchange_cache.get(key)
    if fn is None:
        fn = _build_exchange_fn(gg, sig, dims_order, coalesce_r, wire_r,
                                stage_r)
        _exchange_cache[key] = fn
    # Static comm accounting: charge the signature's wire plan per call
    # (computed once per signature, pure host arithmetic — no syncs).
    plan = _plan_cache.get(key)
    if plan is None:
        plan = _plan_from_sig(gg, sig, dims_order, coalesce_r, wire_r,
                              stage=stage_r)
        _plan_cache[key] = plan
    from ..telemetry import account_halo_exchange

    account_halo_exchange(plan)
    out = fn(*arrays)
    return out[0] if len(out) == 1 else tuple(out)
