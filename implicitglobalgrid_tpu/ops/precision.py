"""Stochastic-rounding bf16 storage — a TPU-native capability the
reference cannot express.

bf16 is the TPU's storage currency (half the HBM traffic of f32 on a
bandwidth-bound stencil), but `bench_f64_accuracy.py` proves plain bf16
state storage STAGNATES long diffusion runs: with round-to-nearest, a
per-step increment smaller than half a ulp of the state is absorbed, every
step, in the same direction (max_rel 0.85 after 400 steps regardless of
compute precision). Stochastic rounding removes the bias: round up with
probability equal to the discarded fraction, so E[stored] equals the
exact f32 value and sub-ulp increments accumulate in expectation instead
of vanishing. (The reference's CUDA tier has no analog — its bf16 story
is Float32/Float64 only.)

The primitive is a pure bit trick, identical on every XLA backend: an
IEEE float's magnitude bits order monotonically, so adding a uniform
16-bit integer to the f32 bit pattern and truncating to the top 16 bits
(= the bf16 pattern) rounds away from zero with exactly the discarded
fraction's probability. No data-dependent control flow; fuses into the
surrounding stencil kernel.
"""

from __future__ import annotations

__all__ = ["stochastic_round_bf16", "shard_unique_fold",
           "resolve_wire_dtype", "wire_dtype_for"]


# ---------------------------------------------------------------------------
# Halo wire-precision mode (EQuARX-style reduced-precision collectives,
# arXiv:2506.17615): f32/f64 state optionally crosses the ICI link as a
# narrower float — convert → pack → ppermute → unpack → convert back
# (`ops.halo`). OFF by default: the exchange stays bit-identical unless the
# user opts in via `IGG_HALO_WIRE_DTYPE` or the `wire_dtype=` kwarg of
# `update_halo`/`local_update_halo`.
# ---------------------------------------------------------------------------

_WIRE_OFF = (None, "", "0", "off", "none")


def resolve_wire_dtype(wire_dtype=None):
    """Resolve the requested halo wire dtype to a canonical numpy dtype, or
    ``None`` for full-precision wire (the default).

    ``wire_dtype=None`` consults ``IGG_HALO_WIRE_DTYPE``; an explicit
    argument (incl. ``"off"``) wins over the environment. Accepted wire
    formats: ``bfloat16``, ``float16``, ``float32`` (the narrowing target
    per state dtype is decided by :func:`wire_dtype_for`)."""
    import os

    from ..utils.exceptions import InvalidArgumentError

    if wire_dtype is None:
        wire_dtype = os.environ.get("IGG_HALO_WIRE_DTYPE")
    if isinstance(wire_dtype, str):
        wire_dtype = wire_dtype.strip().lower()
    if wire_dtype in _WIRE_OFF:
        return None

    import numpy as np

    import jax.numpy as jnp

    named = {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
             "float16": np.float16, "f16": np.float16, "fp16": np.float16,
             "float32": np.float32, "f32": np.float32}
    if isinstance(wire_dtype, str):
        if wire_dtype not in named:
            raise InvalidArgumentError(
                f"Unsupported halo wire dtype {wire_dtype!r}; supported: "
                "bfloat16, float16, float32 (or 'off').")
        return np.dtype(named[wire_dtype])
    dt = np.dtype(wire_dtype)
    if dt not in {np.dtype(v) for v in named.values()}:
        raise InvalidArgumentError(
            f"Unsupported halo wire dtype {dt}; supported: bfloat16, "
            "float16, float32 (or 'off').")
    return dt


def wire_dtype_for(state_dtype, wire):
    """The on-wire dtype for halo payloads of ``state_dtype`` under resolved
    wire mode ``wire`` (from :func:`resolve_wire_dtype`), or ``None`` when
    the payload ships at full precision.

    Only genuine narrowings of real floating state apply: ints, bools,
    complex, and states already at or below the wire width are never
    converted (a widening round trip would waste bandwidth; int/complex
    conversion would corrupt values)."""
    if wire is None:
        return None
    import numpy as np

    import jax.numpy as jnp

    sd = np.dtype(state_dtype)
    if not jnp.issubdtype(sd, jnp.floating):
        return None
    wd = np.dtype(wire)
    if wd.itemsize >= sd.itemsize:
        return None
    return wd


def stochastic_round_bf16(x, key):
    """Round f32 ``x`` to bf16 stochastically (unbiased: ``E[out] == x``).

    ``key`` is a jax PRNG key; one uniform u16 per element decides the
    round direction. Non-finite inputs pass through round-to-nearest (the
    bit trick would otherwise walk an inf/nan payload). At the finite
    upper boundary the carry can round into inf — the correct SR
    behavior for a value within a ulp of the representable range's end.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(x, jnp.float32)
    bits = jax.random.bits(key, shape=x.shape, dtype=jnp.uint16)
    u = lax.bitcast_convert_type(x, jnp.uint32) + bits.astype(jnp.uint32)
    sr = lax.bitcast_convert_type(
        (u >> 16).astype(jnp.uint16), jnp.bfloat16)
    return jnp.where(jnp.isfinite(x), sr, x.astype(jnp.bfloat16))


def shard_unique_fold(key):
    """Fold every mesh-axis index of the CURRENT shard into ``key`` so each
    shard of a `shard_map`-ed step draws independent round directions —
    without this, all shards would reuse one stream and the x/y/z-halo
    copies of a cell would round identically (a spatially correlated
    bias at block seams)."""
    import jax
    from jax import lax

    from ..parallel.topology import global_grid

    for ax in global_grid().mesh.axis_names:
        key = jax.random.fold_in(key, lax.axis_index(ax))
    return key
