"""Stochastic-rounding bf16 storage — a TPU-native capability the
reference cannot express.

bf16 is the TPU's storage currency (half the HBM traffic of f32 on a
bandwidth-bound stencil), but `bench_f64_accuracy.py` proves plain bf16
state storage STAGNATES long diffusion runs: with round-to-nearest, a
per-step increment smaller than half a ulp of the state is absorbed, every
step, in the same direction (max_rel 0.85 after 400 steps regardless of
compute precision). Stochastic rounding removes the bias: round up with
probability equal to the discarded fraction, so E[stored] equals the
exact f32 value and sub-ulp increments accumulate in expectation instead
of vanishing. (The reference's CUDA tier has no analog — its bf16 story
is Float32/Float64 only.)

The primitive is a pure bit trick, identical on every XLA backend: an
IEEE float's magnitude bits order monotonically, so adding a uniform
16-bit integer to the f32 bit pattern and truncating to the top 16 bits
(= the bf16 pattern) rounds away from zero with exactly the discarded
fraction's probability. No data-dependent control flow; fuses into the
surrounding stencil kernel.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["stochastic_round_bf16", "shard_unique_fold",
           "resolve_wire_dtype", "wire_dtype_for", "wire_format_for",
           "WireFormat", "WirePolicy", "SCALE_BYTES",
           "quantize_slab", "dequantize_slab", "encode_scales",
           "decode_scales", "quant_slab_bytes"]


# ---------------------------------------------------------------------------
# Halo wire-precision mode (EQuARX-style reduced-precision collectives,
# arXiv:2506.17615): float state optionally crosses the link as a narrower
# float (convert → pack → ppermute → unpack → convert back) or as a
# symmetric per-slab-scaled integer (quantize → pack q + f32 scales into
# ONE flat buffer → ppermute → dequantize — `ops.halo`). The policy is
# PER MESH AXIS (``"z:int8,x:f32"``): a slow DCN-mapped axis can quantize
# while ICI axes stay exact (the HiCCL per-link-aggressiveness idea,
# arXiv:2408.05962). OFF by default: the exchange stays bit-identical
# unless the user opts in via `IGG_HALO_WIRE_DTYPE` or the `wire_dtype=`
# kwarg of `update_halo`/`local_update_halo`.
# ---------------------------------------------------------------------------

_WIRE_OFF = (None, "", "0", "off", "none")

# bytes of the f32 per-slab scale appended (bitcast to the payload's int8)
# to each quantized field slab on the wire
SCALE_BYTES = 4

# symmetric quantization levels: q in [-L, L]
_QUANT_LEVELS = {"int8": 127, "int4": 7}


@dataclass(frozen=True)
class WireFormat:
    """One on-wire format: a float cast (``bfloat16``/``float16``/
    ``float32``) or a symmetric per-slab-scaled integer quantization
    (``int8``, bit-packed ``int4``). ``name`` is canonical."""

    name: str

    @property
    def is_quant(self) -> bool:
        return self.name in _QUANT_LEVELS

    @property
    def levels(self) -> int:
        """Quantization levels L (q in [-L, L]); quant formats only."""
        return _QUANT_LEVELS[self.name]

    @property
    def dtype(self):
        """The numpy dtype elements of this format occupy on the wire
        (quantized payloads — including bit-packed int4 — ship as int8
        bytes)."""
        import numpy as np

        if self.is_quant:
            return np.dtype(np.int8)
        import jax.numpy as jnp

        named = {"bfloat16": jnp.bfloat16, "float16": np.float16,
                 "float32": np.float32}
        return np.dtype(named[self.name])

    def __str__(self) -> str:
        return self.name

    def __repr__(self) -> str:
        return f"WireFormat({self.name!r})"


# canonical names for every accepted wire-format spelling
_FORMAT_NAMES = {
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "float16": "float16", "f16": "float16", "fp16": "float16",
    "float32": "float32", "f32": "float32",
    "int8": "int8", "s8": "int8", "i8": "int8",
    "int4": "int4", "s4": "int4", "i4": "int4",
}
# per-axis spec tokens -> grid dimension index (accepts the short spatial
# names of the ISSUE syntax and the mesh axis names gx/gy/gz)
_AXIS_TOKENS = {"x": 0, "y": 1, "z": 2, "gx": 0, "gy": 1, "gz": 2}
_DIM_NAMES = ("x", "y", "z")


def _parse_format(token):
    """One format token -> WireFormat | None (for the 'off' spellings)."""
    from ..utils.exceptions import InvalidArgumentError

    if isinstance(token, WireFormat):
        return token
    if isinstance(token, str):
        token = token.strip().lower()
    if token in _WIRE_OFF:
        return None
    name = None
    if isinstance(token, str):
        name = _FORMAT_NAMES.get(token)
    else:
        import numpy as np

        try:
            dt = np.dtype(token)
        except TypeError:
            dt = None
        if dt is not None:
            name = _FORMAT_NAMES.get(dt.name)
    if name is None:
        raise InvalidArgumentError(
            f"Unsupported halo wire format {token!r}; supported: bfloat16, "
            "float16, float32, int8, int4 (or 'off').")
    return WireFormat(name)


@dataclass(frozen=True)
class WirePolicy:
    """Resolved per-mesh-axis wire policy: one `WireFormat` (or ``None``
    = exact) per grid dimension (x, y, z). The canonical string form
    round-trips through `resolve_wire_dtype` (``"int8"`` when uniform,
    else e.g. ``"x:float32,z:int8"``)."""

    per_dim: tuple

    def for_dim(self, dim: int):
        """The requested format along grid dimension ``dim`` (None=exact;
        dims beyond the policy — e.g. 2-D fields' missing z — are exact)."""
        if 0 <= int(dim) < len(self.per_dim):
            return self.per_dim[int(dim)]
        return None

    @property
    def uniform(self):
        """The single format when every dim shares one, else ``None``."""
        fmts = set(self.per_dim)
        return self.per_dim[0] if len(fmts) == 1 else None

    @property
    def casts_any_below(self) -> bool:
        """Whether any dim requests a narrow FLOAT cast (< 4 bytes) — the
        formats a backend float-normalization pass can rewrite away
        (`analysis.audit` audits the lowered module for those)."""
        return any(f is not None and not f.is_quant
                   and f.dtype.itemsize < 4 for f in self.per_dim)

    def __str__(self) -> str:
        u = self.uniform
        if u is not None:
            return str(u)
        parts = [f"{_DIM_NAMES[d]}:{f}"
                 for d, f in enumerate(self.per_dim) if f is not None]
        return ",".join(parts) if parts else "off"

    def __repr__(self) -> str:
        return f"WirePolicy({self})"


def _uniform_policy(fmt):
    return None if fmt is None else WirePolicy((fmt,) * 3)


def resolve_wire_dtype(wire_dtype=None):
    """Resolve the requested halo wire mode to a `WirePolicy`, or ``None``
    for full-precision wire (the default).

    ``wire_dtype=None`` consults ``IGG_HALO_WIRE_DTYPE``; an explicit
    argument (incl. ``"off"``) wins over the environment. Accepted forms:

    - a single format — ``"bfloat16"``/``"float16"``/``"float32"`` (float
      casts), ``"int8"``/``"int4"`` (per-slab-scaled quantization), or a
      numpy/jax dtype — applied on every mesh axis;
    - a per-axis spec ``"z:int8,x:f32"`` (axes ``x``/``y``/``z`` or
      ``gx``/``gy``/``gz``; unnamed axes stay exact);
    - a ``{axis: format}`` mapping, a `WireFormat`, or a `WirePolicy`.

    The narrowing per state dtype is decided by :func:`wire_format_for`."""
    import os

    from ..utils.exceptions import InvalidArgumentError

    if wire_dtype is None:
        wire_dtype = os.environ.get("IGG_HALO_WIRE_DTYPE")
    if isinstance(wire_dtype, WirePolicy):
        return wire_dtype
    if isinstance(wire_dtype, str):
        wire_dtype = wire_dtype.strip().lower()
    if wire_dtype in _WIRE_OFF:
        return None
    if isinstance(wire_dtype, dict):
        items = list(wire_dtype.items())
    elif isinstance(wire_dtype, str) and ":" in wire_dtype:
        items = []
        for part in wire_dtype.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise InvalidArgumentError(
                    f"Per-axis wire spec {wire_dtype!r}: entry {part!r} "
                    "must be '<axis>:<format>' (e.g. 'z:int8,x:f32').")
            axis, fmt = part.split(":", 1)
            items.append((axis, fmt))
    else:
        return _uniform_policy(_parse_format(wire_dtype))

    per_dim = [None, None, None]
    seen = set()
    for axis, fmt in items:
        key = str(axis).strip().lower()
        dim = _AXIS_TOKENS.get(key)
        if dim is None:
            raise InvalidArgumentError(
                f"Unknown mesh axis {axis!r} in wire spec (use x/y/z or "
                "gx/gy/gz).")
        if dim in seen:
            raise InvalidArgumentError(
                f"Mesh axis {axis!r} named twice in wire spec.")
        seen.add(dim)
        per_dim[dim] = _parse_format(fmt)
    if all(f is None for f in per_dim):
        return None
    return WirePolicy(tuple(per_dim))


def _as_policy(wire):
    """Back-compat: accept a pre-resolved `WirePolicy` (the new contract)
    or the raw dtype-likes older call sites passed around."""
    if wire is None or isinstance(wire, WirePolicy):
        return wire
    if isinstance(wire, WireFormat):
        return _uniform_policy(wire)
    return _uniform_policy(_parse_format(wire))


def wire_format_for(state_dtype, wire, dim: int = 0):
    """The `WireFormat` halo payloads of ``state_dtype`` travel in along
    grid dimension ``dim`` under resolved policy ``wire`` (from
    :func:`resolve_wire_dtype`), or ``None`` when the payload ships
    exact.

    Only genuine narrowings of real floating state apply: ints, bools,
    complex never convert (quantizing/conversion would corrupt values);
    a float cast must strictly narrow (a widening round trip would waste
    bandwidth); quantization applies to every real float state (int8 is
    1 byte, int4 half of one — below bf16/f16 too)."""
    policy = _as_policy(wire)
    if policy is None:
        return None
    fmt = policy.for_dim(dim)
    if fmt is None:
        return None
    import numpy as np

    import jax.numpy as jnp

    sd = np.dtype(state_dtype)
    if not jnp.issubdtype(sd, jnp.floating):
        return None
    if fmt.is_quant:
        return fmt
    if fmt.dtype.itemsize >= sd.itemsize:
        return None
    return fmt


def wire_dtype_for(state_dtype, wire, dim: int = 0):
    """The on-wire numpy dtype for halo payloads of ``state_dtype`` under
    resolved policy ``wire`` along ``dim``, or ``None`` for exact wire
    (quantized payloads report int8 — the dtype their bytes occupy)."""
    fmt = wire_format_for(state_dtype, wire, dim)
    return None if fmt is None else fmt.dtype


# ---------------------------------------------------------------------------
# symmetric per-slab quantization (the int8/int4 wire payload codec)
# ---------------------------------------------------------------------------

def quant_slab_bytes(cells: int, fmt) -> int:
    """Wire bytes of one quantized slab of ``cells`` elements, EXCLUDING
    its `SCALE_BYTES` scale: one byte per element for int8, one per
    nibble pair (odd slabs pad one nibble) for int4."""
    cells = int(cells)
    return (cells + 1) // 2 if fmt.name == "int4" else cells


def _pack_int4(q):
    """Bit-pack int8 values in [-7, 7] two-per-byte (low nibble first;
    odd-length input pads one zero nibble)."""
    import jax.numpy as jnp

    if q.size % 2:
        q = jnp.concatenate([q, jnp.zeros((1,), jnp.int8)])
    lo = q[0::2] & 0x0F
    hi = (q[1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def _unpack_int4(b, n: int):
    """Inverse of `_pack_int4`: ``n`` sign-extended int8 values."""
    import jax.numpy as jnp

    lo = b & 0x0F
    hi = (b >> 4) & 0x0F
    q = jnp.stack([lo, hi], axis=-1).reshape(-1)[:n]
    return ((q ^ 8) - 8).astype(jnp.int8)


def quantize_slab(flat, fmt):
    """Quantize one flat float slab symmetrically against its own max-abs
    scale: returns ``(payload_bytes, scale)`` where ``payload_bytes`` is
    the int8 wire payload (`quant_slab_bytes` long) and ``scale`` the
    f32[1] per-slab scale (= the slab's max |finite value|).

    The codec is exact for constant slabs (q hits ±L exactly and
    dequantize computes ``q / L * scale``, so ``±1 * scale`` reproduces
    the f32 value bit-for-bit) and NaN/Inf-safe: any non-finite element
    poisons the SLAB's scale to NaN, so the dequantized halo is wholly
    non-finite — a NaN can narrow to "this slab went bad" but can never
    be laundered into a plausible finite value (the resilient runtime's
    guard still trips). All-zero slabs use scale 1 (exact zeros).

    Deliberately, f64 magnitudes BEYOND f32 range poison the same way
    (finiteness is judged after the f32 cast): the wire format's scale
    is f32, so such a slab is unrepresentable — poisoning fails loudly
    at the guard, where a clamped scale would hand back plausible finite
    halos that are wrong by orders of magnitude. State living out there
    should not opt into an f32-scaled int8 wire."""
    import jax.numpy as jnp

    x = flat.astype(jnp.float32)
    finite = jnp.isfinite(x)
    amax = jnp.max(jnp.where(finite, jnp.abs(x), 0.0))
    scale = jnp.where(amax > 0, amax, 1.0)
    L = fmt.levels
    q = jnp.clip(jnp.round(jnp.where(finite, x, 0.0) / scale * L),
                 -L, L).astype(jnp.int8)
    scale = jnp.where(jnp.all(finite), scale, jnp.float32(jnp.nan))
    if fmt.name == "int4":
        q = _pack_int4(q)
    return q, scale.reshape(1)


def dequantize_slab(payload, scale, n: int, fmt, out_dtype):
    """Inverse of `quantize_slab`: int8 wire ``payload`` + f32 ``scale``
    -> ``n`` elements of ``out_dtype``."""
    import jax.numpy as jnp

    q = _unpack_int4(payload, n) if fmt.name == "int4" else payload
    x = (q.astype(jnp.float32) / fmt.levels) * scale.reshape(())
    return x.astype(out_dtype)


def encode_scales(scales):
    """Bitcast a list of f32[1] per-slab scales into the int8 tail rider
    of the quantized flat buffer (`SCALE_BYTES` bytes each)."""
    import jax.numpy as jnp
    from jax import lax

    v = jnp.concatenate([s.astype(jnp.float32) for s in scales])
    return lax.bitcast_convert_type(v, jnp.int8).reshape(-1)


def decode_scales(tail, n: int):
    """Inverse of `encode_scales`: int8[4n] tail -> f32[n] scales."""
    import jax.numpy as jnp
    from jax import lax

    return lax.bitcast_convert_type(tail.reshape(n, SCALE_BYTES),
                                    jnp.float32)


def stochastic_round_bf16(x, key):
    """Round f32 ``x`` to bf16 stochastically (unbiased: ``E[out] == x``).

    ``key`` is a jax PRNG key; one uniform u16 per element decides the
    round direction. Non-finite inputs pass through round-to-nearest (the
    bit trick would otherwise walk an inf/nan payload). At the finite
    upper boundary the carry can round into inf — the correct SR
    behavior for a value within a ulp of the representable range's end.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(x, jnp.float32)
    bits = jax.random.bits(key, shape=x.shape, dtype=jnp.uint16)
    u = lax.bitcast_convert_type(x, jnp.uint32) + bits.astype(jnp.uint32)
    sr = lax.bitcast_convert_type(
        (u >> 16).astype(jnp.uint16), jnp.bfloat16)
    return jnp.where(jnp.isfinite(x), sr, x.astype(jnp.bfloat16))


def shard_unique_fold(key):
    """Fold every mesh-axis index of the CURRENT shard into ``key`` so each
    shard of a `shard_map`-ed step draws independent round directions —
    without this, all shards would reuse one stream and the x/y/z-halo
    copies of a cell would round identically (a spatially correlated
    bias at block seams)."""
    import jax
    from jax import lax

    from ..parallel.topology import global_grid

    for ax in global_grid().mesh.axis_names:
        key = jax.random.fold_in(key, lax.axis_index(ax))
    return key
