"""Pallas TPU kernels for the diffusion stencil step.

The reference's GPU extension hand-writes pack kernels (`write_d2x!`,
`/root/reference/src/CUDAExt/update_halo.jl:210-227`) because CUDA broadcasts
leave >10x on the table (`reference README.md:167`). The TPU analog of that
native-kernel tier is Pallas: this module fuses one full diffusion time step
(flux computation + divergence + update) into a single pass over the local
block, pipelined plane-by-plane through VMEM — removing the intermediate
full-array materializations the XLA broadcast formulation pays for.

The arithmetic is the exact flux-form sequence of the reference example
(`examples/diffusion3D_multicpu_novis.jl:42-46`):

    qx = -λ dT/dx (faces);  dT/dt = -div q / cp;  T += dt dT/dt   (interior)

in the same accumulation order as the XLA flux-form step, so results agree to
the last ulp or two (exact bitwise equality across the two compilers is not
guaranteed — fma contraction differs).

Kernel shape requirements: 3-D local blocks, last dim a multiple of 128
(lane width) and second-to-last a multiple of 8 for peak efficiency; other
shapes work but pad internally in the Mosaic compiler. Use
``diffusion3d_step_pallas(..., interpret=True)`` on CPU (tests).
"""

from __future__ import annotations

from functools import partial

__all__ = ["diffusion3d_step_pallas", "pallas_supported"]


def pallas_supported(T) -> bool:
    """Whether the Pallas step kernel supports this local block."""
    return T.ndim == 3 and T.shape[0] >= 3


def _plane_kernel(Tm_ref, Tc_ref, Tp_ref, Cp_ref, out_ref, *,
                  lam, dt, dx, dy, dz):
    """Compute one x-plane of the updated temperature.

    Inputs are (1, ny, nz) planes: x-1, x, x+1 of T and x of Cp. Boundary
    planes (first/last x, and y/z edges) keep their input values — the
    reference stencil updates the interior only
    (`diffusion3D_multicpu_novis.jl:47` writes `T[2:end-1,2:end-1,2:end-1]`).
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    n = pl.num_programs(0)
    tm = Tm_ref[0]
    tc = Tc_ref[0]
    tp = Tp_ref[0]
    cp = Cp_ref[0]
    ny, nz = tc.shape

    # Flux form in the EXACT arithmetic/accumulation order of the reference
    # example (`-d_xa(qx)/dx - d_ya(qy)/dy - d_za(qz)/dz`, then `/Cp`, then
    # `T + dt*dTdt`) so results are bitwise identical to the XLA flux-form
    # step for the same dtype.
    qxr = -lam * (tp - tc) / dx
    qxl = -lam * (tc - tm) / dx
    acc = -((qxr - qxl) / dx)                     # (ny, nz)

    qy = -lam * (tc[1:, :] - tc[:-1, :]) / dy     # (ny-1, nz)
    div_y = (qy[1:, :] - qy[:-1, :]) / dy         # (ny-2, nz)
    acc = acc - jnp.pad(div_y, ((1, 1), (0, 0)))

    qz = -lam * (tc[:, 1:] - tc[:, :-1]) / dz     # (ny, nz-1)
    div_z = (qz[:, 1:] - qz[:, :-1]) / dz         # (ny, nz-2)
    acc = acc - jnp.pad(div_z, ((0, 0), (1, 1)))

    upd = tc + dt * (acc / cp)

    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
    interior_x = (i > 0) & (i < n - 1)
    out_ref[0] = jnp.where(interior_yz & interior_x, upd, tc)


def diffusion3d_step_pallas(T, Cp, *, lam, dt, dx, dy, dz, interpret=False):
    """One fused diffusion step on a LOCAL 3-D block (no halo exchange —
    compose with `local_update_halo`). Grid over x-planes; each program
    streams 3 T-planes + 1 Cp-plane through VMEM and writes 1 plane."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nx, ny, nz = T.shape
    plane = (1, ny, nz)

    # Physics constants are baked into the kernel as compile-time Python
    # floats (pallas forbids captured traced values), cast to the block dtype
    # at trace time inside the kernel.
    dtp = T.dtype.type
    kernel = partial(
        _plane_kernel,
        lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy), dz=dtp(dz),
    )

    def clamp(f):
        return lambda i: (jnp.clip(f(i), 0, nx - 1), 0, 0)

    try:  # inside shard_map, outputs must declare their mesh-axis variance
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=jax.typeof(T).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=[
            pl.BlockSpec(plane, clamp(lambda i: i - 1)),
            pl.BlockSpec(plane, clamp(lambda i: i)),
            pl.BlockSpec(plane, clamp(lambda i: i + 1)),
            pl.BlockSpec(plane, clamp(lambda i: i)),
        ],
        out_specs=pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(T, T, T, Cp)
