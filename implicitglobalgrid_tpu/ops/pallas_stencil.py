"""Pallas TPU kernels for the diffusion stencil step.

The reference's GPU extension hand-writes pack kernels (`write_d2x!`,
`/root/reference/src/CUDAExt/update_halo.jl:210-227`) because CUDA broadcasts
leave >10x on the table (`reference README.md:167`). The TPU analog of that
native-kernel tier is Pallas: this module fuses one full diffusion time step
(flux computation + divergence + update) into a single pass over the local
block, pipelined plane-by-plane through VMEM — removing the intermediate
full-array materializations the XLA broadcast formulation pays for.

The arithmetic is the exact flux-form sequence of the reference example
(`examples/diffusion3D_multicpu_novis.jl:42-46`):

    qx = -λ dT/dx (faces);  dT/dt = -div q / cp;  T += dt dT/dt   (interior)

in the same accumulation order as the XLA flux-form step, so results agree to
the last ulp or two (exact bitwise equality across the two compilers is not
guaranteed — fma contraction differs).

Kernel shape requirements: 3-D local blocks, last dim a multiple of 128
(lane width) and second-to-last a multiple of 8 for peak efficiency; other
shapes work but pad internally in the Mosaic compiler. Use
``diffusion3d_step_pallas(..., interpret=True)`` on CPU (tests).
"""

from __future__ import annotations

from functools import partial

__all__ = ["diffusion3d_step_pallas", "diffusion3d_step_halo_pallas",
           "diffusion3d_step_halo_pallas_mp", "mp_supported",
           "pallas_supported", "fusable_halo_dims",
           "step_exchange_modes", "diffusion3d_step_exchange_pallas",
           "strip_rows_2d", "diffusion2d_step_exchange_pallas"]


def pallas_supported(T) -> bool:
    """Whether the Pallas step kernel supports this local block."""
    return T.ndim == 3 and T.shape[0] >= 3


def fusable_halo_dims(gg, ndim: int = 3):
    """Which dims' halo exchange can fuse into the step kernel output pass.

    A dim is fusable when it takes the reference's self-neighbor local path
    (periodic axis, single shard — `update_halo.jl:62-68`) with the default
    overlap/halowidth (ol=2, hw=1), i.e. the halo write is a pure in-plane
    copy. Fusion must respect the reference's strict dim sequencing
    (z, x, y — `update_halo.jl:45`): a dim may fuse only if every dim
    BEFORE it in the order either fuses too or exchanges nothing — otherwise
    its send slabs would miss the earlier dims' received corners. Returns
    (fuse_x, fuse_y, fuse_z) or None if nothing can fuse.
    """
    if ndim != 3:
        return None
    fuse = [False, False, False]
    for dim in (2, 0, 1):  # DEFAULT_DIMS_ORDER
        D = int(gg.dims[dim])
        periodic = bool(gg.periods[dim])
        if D == 1 and not periodic:
            continue  # no exchange on this dim — doesn't block later fusion
        if (D == 1 and periodic and int(gg.overlaps[dim]) == 2
                and int(gg.halowidths[dim]) == 1 and int(gg.disp) == 1):
            fuse[dim] = True
        else:
            break  # multi-shard (or nonstandard) exchange: later dims can't fuse
    if not any(fuse):
        return None
    return tuple(fuse)


def _plane_halo_kernel(Tm_ref, Tc_ref, Tp_ref, Cp_ref, out_ref, *,
                       lam, dt, dx, dy, dz, nx, fuse):
    """One output x-plane of the fused step + self-neighbor halo update.

    Inputs are (1, ny, nz) planes: source plane and its two x-neighbors of T
    plus Cp. The flux arithmetic is in the EXACT accumulation order of the
    reference example (`-d_xa(qx)/dx - d_ya(qy)/dy - d_za(qz)/dz`, then
    `/Cp`, then `T + dt*dTdt` — `diffusion3D_multicpu_novis.jl:42-47`) so
    results match the XLA flux-form step to the last ulp or two. Boundary
    planes/rows/lanes keep their input values (the reference updates the
    interior only), then come the halo writes of the reference's
    self-neighbor local path (`update_halo.jl:62-68`) folded into the same
    output pass, in the reference's exact dim order z, x, y
    (`update_halo.jl:29,45`):

    - z/y halos are in-plane copies (lane/row selects on the computed plane);
    - the x halo re-sources output plane 0 from updated plane nx-2 and plane
      nx-1 from updated plane 1 (``sigma`` in the BlockSpec index maps), so
      the halo planes are recomputed rather than staged — two extra
      plane-triple reads total, no extra array pass.

    Corner semantics match the reference because the z edits are applied to
    the computed plane BEFORE it is used as an x/y halo source, exactly like
    the sequential exchange.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    fuse_x, fuse_y, fuse_z = fuse
    i = pl.program_id(0)
    tc = Tc_ref[0]
    ny, nz = tc.shape
    upd = _stencil_plane(Tm_ref[0], tc, Tp_ref[0], Cp_ref[0],
                         lam=lam, dt=dt, dx=dx, dy=dy, dz=dz)

    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    sp = _sigma(i, nx) if fuse_x else i
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
    u = jnp.where(interior_yz & (sp > 0) & (sp < nx - 1), upd, tc)
    if fuse_z:  # halo lanes <- own interior lanes (broadcast column selects)
        u = jnp.where(col == 0, u[:, nz - 2:nz - 1], u)
        u = jnp.where(col == nz - 1, u[:, 1:2], u)
    if fuse_y:  # after z (and x via sigma), like the sequential exchange
        u = jnp.where(row == 0, u[ny - 2:ny - 1, :], u)
        u = jnp.where(row == ny - 1, u[1:2, :], u)
    out_ref[0] = u


def _sigma(i, nx):
    """Source plane of output plane ``i`` under the fused x halo update."""
    import jax.numpy as jnp

    return jnp.where(i == 0, nx - 2, jnp.where(i == nx - 1, 1, i))


def diffusion3d_step_halo_pallas(T, Cp, *, lam, dt, dx, dy, dz, fuse,
                                 interpret=False):
    """Fused diffusion step + self-neighbor halo exchange on a LOCAL 3-D
    block. ``fuse`` = (fuse_x, fuse_y, fuse_z) from `fusable_halo_dims`;
    non-fused dims behave exactly like `diffusion3d_step_pallas` (exchange
    them afterwards with `local_update_halo`)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nx, ny, nz = T.shape
    plane = (1, ny, nz)
    fuse_x = bool(fuse[0])
    dtp = _const_dtype(T.dtype)
    kernel = partial(
        _plane_halo_kernel,
        lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy), dz=dtp(dz),
        nx=nx, fuse=tuple(bool(f) for f in fuse),
    )

    def src(off):
        def index_map(i):
            s = _sigma(i, nx) if fuse_x else i
            return (jnp.clip(s + off, 0, nx - 1), 0, 0)
        return index_map

    try:
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=jax.typeof(T).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=[
            pl.BlockSpec(plane, src(-1)),
            pl.BlockSpec(plane, src(0)),
            pl.BlockSpec(plane, src(+1)),
            pl.BlockSpec(plane, src(0)),
        ],
        out_specs=pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(T, T, T, Cp)


def diffusion3d_step_pallas(T, Cp, *, lam, dt, dx, dy, dz, interpret=False):
    """One fused diffusion step on a LOCAL 3-D block (no halo exchange —
    compose with `local_update_halo`). The ``fuse=(False, False, False)``
    specialization of `diffusion3d_step_halo_pallas` — one shared kernel so
    the ulp-sensitive accumulation order cannot diverge between the paths."""
    return diffusion3d_step_halo_pallas(
        T, Cp, lam=lam, dt=dt, dx=dx, dy=dy, dz=dz,
        fuse=(False, False, False), interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Fused step + MULTI-SHARD exchange: the flagship path on real pods.
#
# `fusable_halo_dims` only covers self-neighbor (single-shard periodic) dims;
# on a pod every axis is multi-shard and the round-1 design fell back to
# step-kernel + separate exchange (~4 array passes/step). This path keeps the
# whole step at ~2 passes regardless of sharding:
#
#   1. compute the POST-update send slabs from thin input slabs (XLA — a few
#      planes/rows/lanes, negligible traffic; valid because the update is a
#      radius-1 stencil and the send slabs sit >= 1 cell inside the block);
#   2. run the `exchange_recv_slabs` pipeline on them (ppermutes / local
#      swaps, slab-level corner patching, PROC_NULL masking) — the permutes
#      depend ONLY on the thin slabs, so XLA's scheduler overlaps them with
#      the step kernel's plane sweep;
#   3. ONE Pallas pass computes the update for the whole block AND writes
#      the received slabs (z lanes -> x planes -> y rows precedence, same
#      corner argument as `halo_write_combined_pallas`).
# ---------------------------------------------------------------------------


def step_exchange_modes(gg, T):
    """Participation modes for the fused step+exchange, or None.

    Eligible when every EXCHANGING dim has the default overlap 2 and
    halowidth 1 and the block is unstaggered (``T.shape == nxyz`` — the
    flagship model's fields), with at least one exchanging dim. Self and
    multi-shard dims mix freely (self dims become local swaps in the slab
    pipeline). 2-D blocks are eligible too (the returned 3-tuple then has
    ``modes[2] = False``; grid dims beyond the array's rank never apply to
    it, mirroring `ops.halo._dim_exchanges`)."""
    if T.ndim not in (2, 3) or T.shape[0] < 3:
        return None
    if tuple(int(s) for s in T.shape) != tuple(
            int(n) for n in gg.nxyz[:T.ndim]):
        return None
    modes = [False, False, False]
    for dim in range(T.ndim):
        D = int(gg.dims[dim])
        periodic = bool(gg.periods[dim])
        disp = int(gg.disp)
        if D == 1 and not periodic:
            continue
        if D > 1 and not periodic and disp >= D:
            continue
        if int(gg.overlaps[dim]) != 2 or int(gg.halowidths[dim]) != 1:
            return None
        modes[dim] = True
    if not any(modes):
        return None
    return tuple(modes)


def _xla_update_slab(T, Cp, dim, start, size, consts):
    """Updated-state values at ``[start, start+size)`` along ``dim`` (full
    extent elsewhere), computed from a thin input slab grown by the stencil
    radius (1). Works for 3-D and 2-D blocks (`_stencil_plane` /
    `_stencil_row` arithmetic respectively).

    Cells on the GLOBAL block boundary keep their input values. Slab-edge
    x-neighbors are edge-clones; this is sound because for every range this
    is called with (send slabs at depth >= 1, current-halo slabs at the
    boundary itself) the emitted cells either have their true neighbors
    in-slab or are boundary cells masked back to their input values."""
    import jax.numpy as jnp
    from jax import lax

    s = T.shape[dim]
    lo = max(start - 1, 0)
    hi = min(start + size + 1, s)
    Ts = lax.slice_in_dim(T, lo, hi, axis=dim)
    Cs = lax.slice_in_dim(Cp, lo, hi, axis=dim)
    tm = jnp.concatenate([Ts[:1], Ts[:-1]], axis=0)
    tp = jnp.concatenate([Ts[1:], Ts[-1:]], axis=0)
    stencil = _stencil_plane if T.ndim == 3 else _stencil_row
    upd = stencil(tm, Ts, tp, Cs, **consts)
    # global-interior mask (dim positions offset by lo; other dims span the
    # full block so slab positions are global)
    m = None
    for d in range(T.ndim):
        pos = lax.broadcasted_iota(jnp.int32, Ts.shape, d)
        if d == dim:
            pos = pos + lo
            n_d = s
        else:
            n_d = Ts.shape[d]
        md = (pos > 0) & (pos < n_d - 1)
        m = md if m is None else m & md
    out = jnp.where(m, upd, Ts)
    return lax.slice_in_dim(out, start - lo, start - lo + size, axis=dim)


def _plane_step_recv_kernel(*refs, nx, modes, lam, dt, dx, dy, dz):
    """One output plane of the fused step + exchange: compute the update,
    then deliver the received halo slabs (z lanes, then x whole planes, then
    y rows — the reference's write order restricted to this plane; received
    planes replace the computed one entirely, carrying their own corners)."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    it = iter(refs)
    tm_ref, tc_ref, tp_ref, cp_ref = (next(it) for _ in range(4))
    rx_ref = next(it) if modes[0] else None
    ry_ref = next(it) if modes[1] else None
    rz_ref = next(it) if modes[2] else None
    o_ref = refs[-1]

    i = pl.program_id(0)
    tc = tc_ref[0]
    ny, nz = tc.shape
    upd = _stencil_plane(tm_ref[0], tc, tp_ref[0], cp_ref[0],
                         lam=lam, dt=dt, dx=dx, dy=dy, dz=dz)
    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
    u = jnp.where(interior_yz & (i > 0) & (i < nx - 1), upd, tc)
    if modes[2]:  # halowidth 1 throughout (step_exchange_modes)
        u = jnp.where(col == 0, rz_ref[0, :, 0:1], u)
        u = jnp.where(col == nz - 1, rz_ref[0, :, 1:2], u)
    if modes[0]:
        u = jnp.where(i == 0, rx_ref[0], jnp.where(i == nx - 1, rx_ref[1], u))
    if modes[1]:
        u = jnp.where(row == 0, ry_ref[0, 0:1, :], u)
        u = jnp.where(row == ny - 1, ry_ref[0, 1:2, :], u)
    o_ref[0] = u


def _mp_step_recv_kernel(*refs, nx, P, modes, lam, dt, dx, dy, dz,
                         handoff=False):
    """Multi-plane form of `_plane_step_recv_kernel`: P output planes per
    program from a double-buffered (P+2)-plane T window (`_window_pipeline`
    — the same HBM-traffic win as `_mp_kernel`), each delivered its
    received slabs in the z, x, y order."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    it = iter(refs)
    T_hbm = next(it)
    cp_ref = next(it)                              # (P, ny, nz)
    rx_ref = next(it) if modes[0] else None        # (2, ny, nz) const
    ry_ref = next(it) if modes[1] else None        # (P, 2, nz)
    rz_ref = next(it) if modes[2] else None        # (P, ny, 2)
    out_ref = refs[-3]
    scratch = refs[-2]
    sems = refs[-1]

    if handoff:   # static: VMEM overlap handoff, 1.0x T reads
        win, l0 = _window_pipeline_handoff(T_hbm, scratch, sems, nx=nx, B=P)
    else:
        win, l0 = _window_pipeline(T_hbm, scratch, sems, nx=nx, B=P)
    g0 = pl.program_id(0) * P

    ny, nz = out_ref.shape[1:]
    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)

    for j in range(P):
        g = g0 + j
        l = l0 + j
        tc = win[pl.ds(l, 1)][0]
        tm = win[pl.ds(jnp.maximum(l - 1, 0), 1)][0]
        tp = win[pl.ds(jnp.minimum(l + 1, P + 1), 1)][0]
        upd = _stencil_plane(tm, tc, tp, cp_ref[j],
                             lam=lam, dt=dt, dx=dx, dy=dy, dz=dz)
        u = jnp.where(interior_yz & (g > 0) & (g < nx - 1), upd, tc)
        if modes[2]:  # halowidth 1 throughout (step_exchange_modes)
            u = jnp.where(col == 0, rz_ref[j, :, 0:1], u)
            u = jnp.where(col == nz - 1, rz_ref[j, :, 1:2], u)
        if modes[0]:
            u = jnp.where(g == 0, rx_ref[0],
                          jnp.where(g == nx - 1, rx_ref[1], u))
        if modes[1]:
            u = jnp.where(row == 0, ry_ref[j, 0:1, :], u)
            u = jnp.where(row == ny - 1, ry_ref[j, 1:2, :], u)
        out_ref[j] = u


def diffusion3d_step_exchange_pallas(T, Cp, gg, modes, *, lam, dt, dx, dy,
                                     dz, interpret=False):
    """Fused diffusion step + full halo exchange for arbitrary shardings
    (see module comment above): thin-slab send computation -> the shared
    `exchange_recv_slabs` pipeline -> one Pallas pass for update + delivery.
    Uses the multi-plane window kernel where the shape gate passes
    ((1+2/P)x T reads), else the plane-per-program form (3x). Matches
    `diffusion3d_step_pallas` followed by the exchange to ulp level:
    the slab computes share `_stencil_plane`'s accumulation order, but they
    run through XLA while the block runs through Mosaic, and fma contraction
    can differ in the last ulp between the compilers (module docstring)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .halo import exchange_recv_slabs

    nx, ny, nz = T.shape
    plane = (1, ny, nz)
    dtp = _const_dtype(T.dtype)
    consts = dict(lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy), dz=dtp(dz))

    from .precision import resolve_wire_dtype

    recvs = exchange_recv_slabs(
        gg, T.shape, (1, 1, 1), modes,
        lambda dim, start, size: _xla_update_slab(T, Cp, dim, start, size,
                                                  consts),
        wire=resolve_wire_dtype(None))

    P = mp_planes(T, interpret=interpret)
    mp = P is not None
    blk = (P, ny, nz) if mp else plane

    operands = []
    in_specs = []
    if mp:
        operands += [T, Cp]
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),      # T: manual DMA window
            pl.BlockSpec(blk, lambda i: (i, 0, 0)),
        ]
    else:
        operands += [T, T, T, Cp]
        in_specs += [
            pl.BlockSpec(plane, lambda i: (jnp.maximum(i - 1, 0), 0, 0)),
            pl.BlockSpec(plane, lambda i: (i, 0, 0)),
            pl.BlockSpec(plane, lambda i: (jnp.minimum(i + 1, nx - 1), 0, 0)),
            pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        ]
    if modes[0]:
        rx = jnp.concatenate(recvs[0], axis=0)          # (2, ny, nz)
        operands.append(rx)
        in_specs.append(pl.BlockSpec((2, ny, nz), lambda i: (0, 0, 0)))
    if modes[1]:
        ry = jnp.concatenate(recvs[1], axis=1)          # (nx, 2, nz)
        operands.append(ry)
        in_specs.append(pl.BlockSpec((blk[0], 2, nz), lambda i: (i, 0, 0)))
    if modes[2]:
        rz = jnp.concatenate(recvs[2], axis=2)          # (nx, ny, 2)
        operands.append(rz)
        in_specs.append(pl.BlockSpec((blk[0], ny, 2), lambda i: (i, 0, 0)))

    vma = None
    try:
        vma = jax.typeof(T).vma
        for op in operands[1:]:
            vma = vma | jax.typeof(op).vma
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    if mp:
        kernel = partial(_mp_step_recv_kernel, nx=nx, P=P,
                         handoff=mp_handoff(T, interpret=interpret),
                         modes=tuple(bool(m) for m in modes), **consts)
        return pl.pallas_call(
            kernel,
            grid=(nx // P,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(blk, lambda i: (i, 0, 0)),
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((2, P + 2, ny, nz), T.dtype),
                            pltpu.SemaphoreType.DMA((2,))],
            interpret=interpret,
            **_sequential_grid_params(interpret),
        )(*operands)

    kernel = partial(_plane_step_recv_kernel, nx=nx,
                     modes=tuple(bool(m) for m in modes), **consts)
    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)


# ---------------------------------------------------------------------------
# Multi-plane variant: P output planes per program through a DMA'd window.
# ---------------------------------------------------------------------------

_MP_CANDIDATES = (32, 16, 8, 4)    # preferred plane counts, best first


_MP_VMEM_BUDGET = 13 * 1024 * 1024  # leave headroom under the ~16 MB VMEM


_MP_TEMP_PLANES = 6  # slack for Mosaic stencil temporaries (qy/qz/acc/masks)


def _compute_itemsize(dtype) -> int:
    """Bytes per element of the stencil's COMPUTE dtype: bf16 states are
    computed in f32 (`_stencil_plane`), so their temporaries cost 4 B."""
    return max(int(dtype.itemsize), 4) if dtype.itemsize < 4 \
        else int(dtype.itemsize)


def _const_dtype(dtype):
    """Scalar constructor for the kernel constants: f32 for bf16 states
    (quantizing dx/dt to bf16 would put ~0.4% systematic error into every
    flux term that the f32 compute path is meant to avoid), the state's own
    dtype otherwise."""
    import jax.numpy as jnp
    import numpy as np

    if dtype == jnp.bfloat16:
        return np.float32
    return dtype.type


def _sublane_tile(dtype) -> int:
    """Rows per native sublane tile: 8 for f32, 16 for bf16 (the (8,128)
    f32 / (16,128) bf16 TPU tilings). The single source of truth for every
    alignment gate and the strip kernel's halo-tile height."""
    import numpy as np

    return max(1, 32 // int(np.dtype(dtype).itemsize))


def window_dma_ok(shape, dtype) -> bool:
    """Whether the manual HBM->VMEM window DMA of `_window_pipeline` is
    known-good for blocks whose last two dims are ``shape[-2:]``: the copy
    requires NATIVE-TILE alignment — lane dim a multiple of 128 and sublane
    dim a multiple of the dtype's sublane tile (8 for f32, 16 for bf16).
    Mosaic rejects the dynamic-start HBM slice on partially-tiled shapes
    (verified on v5e: (…, 192)-lane windows fail to compile), so callers
    must fall back to the BlockSpec-pipelined kernels."""
    return (int(shape[-1]) % 128 == 0
            and int(shape[-2]) % _sublane_tile(dtype) == 0)


def mp_planes(T, interpret=False):
    """Plane count P for the multi-plane kernel, or None if unsupported.

    Picks the largest candidate P that divides the plane axis with >= 2
    programs and whose VMEM working set fits: double-buffered (P+2)-plane T
    windows (2*(P+2)) plus double-buffered Cp in and out blocks (2*P each)
    in STORAGE dtype, plus per-plane temporaries slack in COMPUTE dtype
    (bf16 computes in f32). Larger P amortizes the 2-plane window overlap
    (T read amplification 1+2/P); the plane-per-program kernel is the
    fallback for everything else — including lane/sublane-unaligned
    blocks, which the window DMA cannot copy (`window_dma_ok`; a
    Mosaic-compile-only constraint, so interpret mode skips it and keeps
    the multi-plane kernels under test at small shapes)."""
    if T.ndim != 3:
        return None
    if not interpret and not window_dma_ok(T.shape, T.dtype):
        return None
    cells = int(T.shape[1]) * int(T.shape[2])
    plane_store = cells * T.dtype.itemsize
    plane_compute = cells * _compute_itemsize(T.dtype)
    for P in _MP_CANDIDATES:
        if T.shape[0] % P or T.shape[0] < 2 * P:
            continue
        working_set = (6 * P + 4) * plane_store \
            + _MP_TEMP_PLANES * plane_compute
        if working_set <= _MP_VMEM_BUDGET:
            return P
    return None


def mp_supported(T, interpret=False) -> bool:
    """Whether the multi-plane kernel applies (see `mp_planes`)."""
    return mp_planes(T, interpret=interpret) is not None


def _stencil_plane(tm, tc, tp, cp, *, lam, dt, dx, dy, dz):
    """The flux-form update of one plane (or a 3-D slab — y/z derivatives
    run over the LAST two axes) — the single shared arithmetic (same
    accumulation order as the reference example and the plane-per-program
    kernel). bfloat16 inputs are computed in f32 and cast back (bf16
    storage, f32 arithmetic — the TPU-native mixed-precision recipe; the
    flux differences would otherwise lose most of their bits)."""
    import jax.numpy as jnp

    out_dt = tc.dtype
    if out_dt == jnp.bfloat16:
        tm, tc, tp, cp = (a.astype(jnp.float32) for a in (tm, tc, tp, cp))
    zeros = [(0, 0)] * (tc.ndim - 2)
    qxr = -lam * (tp - tc) / dx
    qxl = -lam * (tc - tm) / dx
    acc = -((qxr - qxl) / dx)
    qy = -lam * (tc[..., 1:, :] - tc[..., :-1, :]) / dy
    acc = acc - jnp.pad((qy[..., 1:, :] - qy[..., :-1, :]) / dy,
                        zeros + [(1, 1), (0, 0)])
    qz = -lam * (tc[..., :, 1:] - tc[..., :, :-1]) / dz
    acc = acc - jnp.pad((qz[..., :, 1:] - qz[..., :, :-1]) / dz,
                        zeros + [(0, 0), (1, 1)])
    return (tc + dt * (acc / cp)).astype(out_dt)


def _stencil_row(tm, tc, tp, cp, *, lam, dt, dx, dy):
    """2-D flux-form update of a row strip: the x-derivative comes from the
    ``tm``/``tc``/``tp`` row triple, the y-derivative runs over the LAST
    axis — same accumulation order as the XLA 2-D step
    (`models/diffusion.upd2`, mirroring the reference example's sequence).
    bfloat16 inputs compute in f32 like `_stencil_plane`."""
    import jax.numpy as jnp

    out_dt = tc.dtype
    if out_dt == jnp.bfloat16:
        tm, tc, tp, cp = (a.astype(jnp.float32) for a in (tm, tc, tp, cp))
    zeros = [(0, 0)] * (tc.ndim - 1)
    qxr = -lam * (tp - tc) / dx
    qxl = -lam * (tc - tm) / dx
    acc = -((qxr - qxl) / dx)
    qy = -lam * (tc[..., 1:] - tc[..., :-1]) / dy
    acc = acc - jnp.pad((qy[..., 1:] - qy[..., :-1]) / dy, zeros + [(1, 1)])
    return (tc + dt * (acc / cp)).astype(out_dt)


def _window_pipeline_general(ref, scratch, sems, *, size, start_fn):
    """Double-buffered HBM->VMEM window fetch across SEQUENTIAL grid
    programs: program i starts the DMA of window i+1 into the other buffer
    slot before waiting on its own, so the next window's reads ride under
    this window's compute. Window g covers ``[start_fn(g), +size)`` along
    axis 0 (uniform size). The grid MUST run in order — callers pass
    ``dimension_semantics=("arbitrary",)``. Returns this program's window
    ref."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    nprog = pl.num_programs(0)

    def window_dma(slot, g):
        return pltpu.make_async_copy(
            ref.at[pl.ds(start_fn(g), size)], scratch.at[slot],
            sems.at[slot])

    @pl.when(i == 0)
    def _():
        window_dma(0, 0).start()

    @pl.when(i + 1 < nprog)
    def _():
        window_dma((i + 1) % 2, i + 1).start()

    slot = i % 2
    window_dma(slot, i).wait()
    return scratch.at[slot]


def _window_pipeline(T_hbm, scratch, sems, *, nx, B):
    """The stencil kernels' standard window: ``[clip(g*B-1, 0, nx-(B+2)),
    +B+2)`` (one neighbor plane each side, clamped at the global edges).
    Returns ``(window_ref, l0)`` where ``l0`` is the window index of global
    position ``i*B``."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def wstart(g):
        return jnp.clip(g * B - 1, 0, nx - (B + 2))

    win = _window_pipeline_general(T_hbm, scratch, sems, size=B + 2,
                                   start_fn=wstart)
    i = pl.program_id(0)
    return win, i * B - wstart(i)


def _window_pipeline_handoff(ref, scratch, sems, *, nx, B):
    """`_window_pipeline` with a VMEM HANDOFF of the window overlap:
    program i copies the 2-3 overlap planes from the tail of ITS window
    into the head of the next window's slot and prefetches only the NEW
    planes from HBM — total T reads become exactly ``nx`` planes (1.0x)
    instead of the plain pipeline's (1+2/P)x re-read.

    Overlap bookkeeping (windows ``[clip(g*B-1, 0, nx-(B+2)), +B+2)``,
    ``nx % B == 0``, ``m = nx//B`` programs): the clamp at both global
    edges makes the overlap 3 planes into windows 1 and m-1 and 2 planes
    into every interior window; with m == 2 it would be 4 (callers use the
    plain pipeline there). Total fetched = (B+2) + 2(B-1) + (m-3)B = mB =
    nx exactly.

    The prefetch DMA (head-disjoint) still starts BEFORE this window's
    wait, so next-window HBM reads ride under this window's compute; the
    handoff copy runs after the wait (its source must be complete) as
    plane-aligned direct stores (an async VMEM->VMEM DMA form tripped an
    XLA CPU fusion codegen crash in interpret mode), and the sequential
    grid guarantees it lands before program i+1 reads it. Requires m >= 3
    and the same in-order execution as the plain pipeline."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    m = pl.num_programs(0)
    S = B + 2

    def wstart(g):
        return jnp.clip(g * B - 1, 0, nx - S)

    def full_dma(slot, g):
        return pltpu.make_async_copy(
            ref.at[pl.ds(wstart(g), S)], scratch.at[slot], sems.at[slot])

    def partial_dma(slot, g, o):  # fetch the S-o NEW planes (o static)
        return pltpu.make_async_copy(
            ref.at[pl.ds(wstart(g) + o, S - o)],
            scratch.at[slot, pl.ds(o, S - o)], sems.at[slot])

    cur, nxt = i % 2, (i + 1) % 2
    edge_next = (i + 1 == 1) | (i + 1 == m - 1)
    edge_cur = (i == 1) | (i == m - 1)

    @pl.when(i == 0)
    def _():
        full_dma(0, 0).start()

    # prefetch next window's NEW planes (disjoint from its handoff head)
    @pl.when((i + 1 < m) & edge_next)
    def _():
        partial_dma(nxt, i + 1, 3).start()

    @pl.when((i + 1 < m) & ~edge_next)
    def _():
        partial_dma(nxt, i + 1, 2).start()

    # wait on OUR window (descriptor must match the copy that filled it)
    @pl.when(i == 0)
    def _():
        full_dma(0, 0).wait()

    @pl.when((i > 0) & edge_cur)
    def _():
        partial_dma(cur, i, 3).wait()

    @pl.when((i > 0) & ~edge_cur)
    def _():
        partial_dma(cur, i, 2).wait()

    # hand the overlap planes to the next window in VMEM (direct stores:
    # plane-aligned, static sizes)
    @pl.when((i + 1 < m) & edge_next)
    def _():
        scratch[nxt, pl.ds(0, 3)] = scratch[cur, pl.ds(S - 3, 3)]

    @pl.when((i + 1 < m) & ~edge_next)
    def _():
        scratch[nxt, pl.ds(0, 2)] = scratch[cur, pl.ds(S - 2, 2)]

    return scratch.at[cur], i * B - wstart(i)


def window_handoff_enabled() -> bool:
    """`IGG_MP_HANDOFF=0` forces the plain re-reading window pipeline in
    every kernel family (A/B measurement)."""
    import os

    return os.environ.get("IGG_MP_HANDOFF", "1") != "0"


def plane_relay_enabled() -> bool:
    """`IGG_PLANE_RELAY=0` restores the HBM ``[i-1]`` input streams in the
    plane-per-program kernels (A/B measurement / Mosaic escape hatch)."""
    import os

    return os.environ.get("IGG_PLANE_RELAY", "1") != "0"


def kernel_flags() -> tuple:
    """Trace-time kernel-variant flags — part of every runner cache key so
    flipping either env var retraces instead of replaying stale kernels."""
    return (window_handoff_enabled(), plane_relay_enabled())


def handoff_ok(nx, P) -> bool:
    """The shared window-handoff gate for every kernel family: >= 3
    windows (the 2-window case has a 4-plane overlap) and the env flag."""
    return P is not None and nx // P >= 3 and window_handoff_enabled()


def mp_handoff(T, interpret=False) -> bool:
    """Whether the multi-plane kernel uses the VMEM window handoff (1.0x T
    reads) for this shape: needs >= 3 windows; `IGG_MP_HANDOFF=0` forces
    the plain (1+2/P)x pipeline for A/B measurement."""
    return handoff_ok(int(T.shape[0]), mp_planes(T, interpret=interpret))


def mp_bytes_per_cell(T, interpret=False):
    """Traffic model of the multi-plane kernel for this shape (bench.py's
    roofline accounting): T reads 1.0x with the window handoff else
    (1+2/P)x, + Cp read 1x + T write 1x, in storage itemsize."""
    P = mp_planes(T, interpret=interpret)
    t_reads = 1.0 if mp_handoff(T, interpret=interpret) \
        else (1.0 + 2.0 / P if P else 3.0)
    return (t_reads + 2.0) * T.dtype.itemsize


def _window_pipeline_aligned_handoff(ref, scratch, sems, *, size, B):
    """Handoff form of the ALIGNED window ``[g*B, g*B+size)`` (uniform
    overlap ``o = size - B``, no clamping — e.g. the acoustic Vx face
    window, size=P+1): program i hands the o overlap planes across in
    VMEM and prefetches only the B new planes. Total fetch = size +
    (m-1)*B = nx + o exactly. Works for any m >= 2 (the overlap is
    uniform, unlike the clamped `_window_pipeline_handoff`). Same
    sequential-grid contract as the other pipelines."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    m = pl.num_programs(0)
    o = size - B

    def full_dma(slot, g):
        return pltpu.make_async_copy(
            ref.at[pl.ds(g * B, size)], scratch.at[slot], sems.at[slot])

    def partial_dma(slot, g):
        return pltpu.make_async_copy(
            ref.at[pl.ds(g * B + o, B)],
            scratch.at[slot, pl.ds(o, B)], sems.at[slot])

    cur, nxt = i % 2, (i + 1) % 2

    @pl.when(i == 0)
    def _():
        full_dma(0, 0).start()

    @pl.when(i + 1 < m)
    def _():
        partial_dma(nxt, i + 1).start()

    @pl.when(i == 0)
    def _():
        full_dma(0, 0).wait()

    @pl.when(i > 0)
    def _():
        partial_dma(cur, i).wait()

    @pl.when(i + 1 < m)
    def _():
        scratch[nxt, pl.ds(0, o)] = scratch[cur, pl.ds(size - o, o)]

    return scratch.at[cur]


def _sequential_grid_params(interpret):
    """pallas_call kwargs forcing in-order grid execution (required by the
    cross-program DMA handoff of `_window_pipeline`)."""
    if interpret:
        return {}
    from jax.experimental.pallas import tpu as pltpu

    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=("arbitrary",))}


def _mp_kernel(T_hbm, Cp_ref, out_ref, scratch, sems, *,
               lam, dt, dx, dy, dz, nx, P, fuse, handoff=False):
    """Compute P output planes from a (P+2)-plane VMEM window of T.

    The window is DMA'd once per program, so interior T planes are read
    ~(1+2/P)x instead of the 3x of the plane-per-program kernel's three
    BlockSpec streams — the stencil's dominant HBM term. The window DMA is
    DOUBLE-BUFFERED across grid programs (program i starts the fetch of
    window i+1 before computing window i, the standard overlap pattern), so
    the HBM reads of the next window ride under this window's VPU work just
    like the auto-pipelined Cp/out streams; the grid must therefore execute
    sequentially ("arbitrary" dimension semantics, set by the caller).
    z/y halo edits are in-plane selects like `_plane_halo_kernel`; x halo
    planes (if fused) are NOT handled here —
    `diffusion3d_step_halo_pallas_mp` patches them with the in-place dim-0
    halo write afterwards.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    fuse_x, fuse_y, fuse_z = fuse
    if handoff:   # static: VMEM overlap handoff, 1.0x T reads
        win, l0 = _window_pipeline_handoff(T_hbm, scratch, sems, nx=nx, B=P)
    else:
        win, l0 = _window_pipeline(T_hbm, scratch, sems, nx=nx, B=P)
    g0 = pl.program_id(0) * P                    # first output plane

    ny, nz = out_ref.shape[1:]
    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)

    for j in range(P):
        g = g0 + j
        l = l0 + j
        tc = win[pl.ds(l, 1)][0]
        tm = win[pl.ds(jnp.maximum(l - 1, 0), 1)][0]      # clamps at g==0
        tp = win[pl.ds(jnp.minimum(l + 1, P + 1), 1)][0]  # ... at g==nx-1
        upd = _stencil_plane(tm, tc, tp, Cp_ref[j],
                             lam=lam, dt=dt, dx=dx, dy=dy, dz=dz)
        u = jnp.where(interior_yz & (g > 0) & (g < nx - 1), upd, tc)
        if fuse_z:
            u = jnp.where(col == 0, u[:, nz - 2:nz - 1], u)
            u = jnp.where(col == nz - 1, u[:, 1:2], u)
        if fuse_y:
            u = jnp.where(row == 0, u[ny - 2:ny - 1, :], u)
            u = jnp.where(row == ny - 1, u[1:2, :], u)
        out_ref[j] = u


def diffusion3d_step_halo_pallas_mp(T, Cp, *, lam, dt, dx, dy, dz, fuse,
                                    interpret=False):
    """Multi-plane fused step (+ self-neighbor halo): the faster form of
    `diffusion3d_step_halo_pallas` for blocks with `mp_supported` shapes.
    Identical semantics; x halo planes (when fused) are recomputed at slab
    level in XLA and written in place by the dim-0 halo kernel."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nx, ny, nz = T.shape
    P = mp_planes(T, interpret=interpret)
    blk = (P, ny, nz)
    dtp = _const_dtype(T.dtype)
    consts = dict(lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy), dz=dtp(dz))
    handoff = mp_handoff(T, interpret=interpret)
    kernel = partial(_mp_kernel, nx=nx, P=P, handoff=handoff,
                     fuse=tuple(bool(f) for f in fuse), **consts)

    try:
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=jax.typeof(T).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    kwargs = _sequential_grid_params(interpret)
    U = pl.pallas_call(
        kernel,
        grid=(nx // P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),          # T: manual DMA window
            pl.BlockSpec(blk, lambda i: (i, 0, 0)),     # Cp
        ],
        out_specs=pl.BlockSpec(blk, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((2, P + 2, ny, nz), T.dtype),
                        pltpu.SemaphoreType.DMA((2,))],
        interpret=interpret,
        **kwargs,
    )(T, Cp)

    if not fuse[0]:
        return U
    # Fused x halo: plane 0 <- updated plane nx-2 (with z edits), plane nx-1
    # <- updated plane 1, then their y halo rows — computed at slab level
    # (reference order z, x, y; same corner argument as _plane_halo_kernel)
    # and written IN PLACE by the dim-0 halo kernel (2-plane write).
    from .pallas_halo import halo_write_inplace

    def patch(src):  # src: global index of the source plane
        tm = lax.slice_in_dim(T, src - 1, src, axis=0)[0]
        tc = lax.slice_in_dim(T, src, src + 1, axis=0)[0]
        tp = lax.slice_in_dim(T, src + 1, src + 2, axis=0)[0]
        cp = lax.slice_in_dim(Cp, src, src + 1, axis=0)[0]
        upd = _stencil_plane(tm, tc, tp, cp, **consts)
        row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
        col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
        interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
        u = jnp.where(interior_yz, upd, tc)     # src planes are x-interior
        if fuse[2]:
            u = jnp.where(col == 0, u[:, nz - 2:nz - 1], u)
            u = jnp.where(col == nz - 1, u[:, 1:2], u)
        if fuse[1]:
            u = jnp.where(row == 0, u[ny - 2:ny - 1, :], u)
            u = jnp.where(row == ny - 1, u[1:2, :], u)
        return u[None]

    return halo_write_inplace(U, patch(nx - 2), patch(1), dim=0, hw=1,
                              interpret=interpret)


# ---------------------------------------------------------------------------
# 2-D fused step + exchange (BASELINE config 2): row strips through a
# double-buffered VMEM window, same structure as the 3-D multi-plane path.
# ---------------------------------------------------------------------------

_STRIP2D_CANDIDATES = (256, 128, 64, 32, 16, 8)


def strip_rows_2d(T, interpret=False):
    """Rows per program R for the 2-D strip kernel, or None if unsupported.

    Working set: double-buffered R-row T bodies (+2 halo rows) plus
    double-buffered Cp in and out blocks (2R rows each) in STORAGE dtype,
    plus the shifted-window temporaries of the vectorized strip compute
    (~6R rows) in COMPUTE dtype (bf16 computes in f32). Compiled mode
    additionally requires native-tile-aligned shapes for the strip DMA
    (`window_dma_ok`); interpret mode (tests) has no such constraint."""
    if T.ndim != 2:
        return None
    row_store = int(T.shape[1]) * T.dtype.itemsize
    row_compute = int(T.shape[1]) * _compute_itemsize(T.dtype)
    if not interpret and not window_dma_ok(T.shape, T.dtype):
        return None
    sublane = _sublane_tile(T.dtype)
    for R in _STRIP2D_CANDIDATES:
        if T.shape[0] % R or T.shape[0] < 2 * R:
            continue
        if R % sublane:
            # Body slices must start on tile-row boundaries, and the halo
            # tiles' clamp arithmetic assumes R % H == 0 — in interpret mode
            # too (the kernel's row picks would silently be wrong otherwise).
            continue
        if (6 * R + 8) * row_store + 6 * R * row_compute <= _MP_VMEM_BUDGET:
            return R
    return None


def _strip2d_kernel(*refs, nx, R, H, modes, lam, dt, dx, dy,
                    handoff=False):
    """Compute R output rows from a manually DMA'd VMEM strip of T, then
    deliver the received halo slabs: x whole rows first, then y lanes — the
    exchange order for 2-D blocks (dims 0 then 1 of the z, x, y default;
    the y slabs carry x's received corners via the slab pipeline's
    patching).

    The strip fetch is split into an ALIGNED R-row body plus two H-row halo
    tiles bracketing it (H = the dtype's sublane tile): 2-D arrays are
    tiled in BOTH dims, so every HBM slice must be tile-row aligned and
    sized (Mosaic rejects 1-row slices and dynamic-offset multi-row vector
    loads alike); the rows just above/below the strip are the last/first
    rows of those tiles. Tile fetches clamp at the global edges, where the
    garbage row only reaches globally-masked boundary rows. All three are
    double-buffered across the sequential grid like `_window_pipeline`;
    tm/tp are edge-patched shifts of the body."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    it = iter(refs)
    T_hbm = next(it)
    cp_ref = next(it)
    rx_ref = next(it) if modes[0] else None       # (2, ny)
    ry_ref = next(it) if modes[1] else None       # (R, 2) strip
    o_ref = refs[-5]                              # outs precede scratches
    body_scr, above_scr, below_scr, sems = refs[-4:]

    i = pl.program_id(0)
    nprog = pl.num_programs(0)

    def ds(start, size):
        # every start is a multiple of the H-row tile by construction
        # (R % H == 0, nx % H == 0 — `strip_rows_2d`); Mosaic needs the
        # explicit hint to slice the row-tiled 2-D memref at a traced index
        return pl.ds(pl.multiple_of(start, H), size)

    def body_dma(slot, g):
        return pltpu.make_async_copy(
            T_hbm.at[ds(g * R, R)], body_scr.at[slot], sems.at[slot, 0])

    def above_dma(slot, g):
        return pltpu.make_async_copy(
            T_hbm.at[ds(jnp.maximum(g * R - H, 0), H)],
            above_scr.at[slot], sems.at[slot, 1])

    def below_dma(slot, g):
        return pltpu.make_async_copy(
            T_hbm.at[ds(jnp.minimum(g * R + R, nx - H), H)],
            below_scr.at[slot], sems.at[slot, 2])

    @pl.when(i == 0)
    def _():
        body_dma(0, 0).start()
        below_dma(0, 0).start()
        above_dma(0, 0).start()

    @pl.when(i + 1 < nprog)
    def _():
        body_dma((i + 1) % 2, i + 1).start()
        below_dma((i + 1) % 2, i + 1).start()
        if not handoff:
            above_dma((i + 1) % 2, i + 1).start()

    slot = i % 2
    body_dma(slot, i).wait()
    below_dma(slot, i).wait()
    if handoff:
        # the above tile for g >= 1 is the tail of the PREVIOUS body —
        # handed across in VMEM by the previous program (below); only
        # program 0 fetched its (edge-clamped) above tile from HBM
        @pl.when(i == 0)
        def _():
            above_dma(0, 0).wait()

        @pl.when(i + 1 < nprog)
        def _():
            above_scr[(i + 1) % 2] = body_scr[slot][R - H:, :]
    else:
        above_dma(slot, i).wait()

    g0 = i * R
    tc = body_scr[slot]                                        # (R, ny)
    row_above = above_scr[slot][H - 1:H]  # last row of the tile ending at g0
    row_below = below_scr[slot][0:1]   # first row of the tile after the body
    tm = jnp.concatenate([row_above, tc[:-1]], axis=0)
    tp = jnp.concatenate([tc[1:], row_below], axis=0)
    upd = _stencil_row(tm, tc, tp, cp_ref[...], lam=lam, dt=dt, dx=dx, dy=dy)

    ny = tc.shape[1]
    g = g0 + lax.broadcasted_iota(jnp.int32, (R, ny), 0)   # global row index
    col = lax.broadcasted_iota(jnp.int32, (R, ny), 1)
    interior = (g > 0) & (g < nx - 1) & (col > 0) & (col < ny - 1)
    u = jnp.where(interior, upd, tc)
    if modes[0]:  # x rows first (received rows replace them entirely)
        u = jnp.where(g == 0, rx_ref[0:1], u)
        u = jnp.where(g == nx - 1, rx_ref[1:2], u)
    if modes[1]:  # then y lanes (their slabs carry x's received corners)
        u = jnp.where(col == 0, ry_ref[:, 0:1], u)
        u = jnp.where(col == ny - 1, ry_ref[:, 1:2], u)
    o_ref[...] = u


def diffusion2d_step_exchange_pallas(T, Cp, gg, modes, *, lam, dt, dx, dy,
                                     interpret=False):
    """Fused 2-D diffusion step + halo exchange for arbitrary shardings —
    the 2-D analog of `diffusion3d_step_exchange_pallas`: thin-slab send
    computation in XLA -> the shared `exchange_recv_slabs` pipeline
    (ppermutes / local swaps / PROC_NULL masking) -> one strip-pipelined
    Pallas pass for update + delivery."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .halo import exchange_recv_slabs

    nx, ny = T.shape
    R = strip_rows_2d(T, interpret=interpret)
    dtp = _const_dtype(T.dtype)
    consts = dict(lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy))

    from .precision import resolve_wire_dtype

    recvs = exchange_recv_slabs(
        gg, T.shape, (1, 1), modes,
        lambda dim, start, size: _xla_update_slab(T, Cp, dim, start, size,
                                                  consts),
        wire=resolve_wire_dtype(None))

    blk = (R, ny)
    operands = [T, Cp]
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),            # T: manual DMA window
        pl.BlockSpec(blk, lambda i: (i, 0)),          # Cp
    ]
    if modes[0]:
        rx = jnp.concatenate(recvs[0], axis=0)        # (2, ny)
        operands.append(rx)
        in_specs.append(pl.BlockSpec((2, ny), lambda i: (0, 0)))
    if modes[1]:
        ry = jnp.concatenate(recvs[1], axis=1)        # (nx, 2)
        operands.append(ry)
        in_specs.append(pl.BlockSpec((R, 2), lambda i: (i, 0)))

    try:
        vma = jax.typeof(T).vma
        for op in operands[1:]:
            vma = vma | jax.typeof(op).vma
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    H = _sublane_tile(T.dtype)
    kernel = partial(_strip2d_kernel, nx=nx, R=R, H=H,
                     # above-tile handoff: the overlap is uniform and
                     # `strip_rows_2d` guarantees >= 2 strips, so only
                     # the env flag gates it
                     handoff=window_handoff_enabled(),
                     modes=tuple(bool(m) for m in modes), **consts)
    kwargs = _sequential_grid_params(interpret)
    return pl.pallas_call(
        kernel,
        grid=(nx // R,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(blk, lambda i: (i, 0)),
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((2, R, ny), T.dtype),
                        pltpu.VMEM((2, H, ny), T.dtype),
                        pltpu.VMEM((2, H, ny), T.dtype),
                        pltpu.SemaphoreType.DMA((2, 3))],
        interpret=interpret,
        **kwargs,
    )(*operands)
