"""Pallas TPU kernels for the diffusion stencil step.

The reference's GPU extension hand-writes pack kernels (`write_d2x!`,
`/root/reference/src/CUDAExt/update_halo.jl:210-227`) because CUDA broadcasts
leave >10x on the table (`reference README.md:167`). The TPU analog of that
native-kernel tier is Pallas: this module fuses one full diffusion time step
(flux computation + divergence + update) into a single pass over the local
block, pipelined plane-by-plane through VMEM — removing the intermediate
full-array materializations the XLA broadcast formulation pays for.

The arithmetic is the exact flux-form sequence of the reference example
(`examples/diffusion3D_multicpu_novis.jl:42-46`):

    qx = -λ dT/dx (faces);  dT/dt = -div q / cp;  T += dt dT/dt   (interior)

in the same accumulation order as the XLA flux-form step, so results agree to
the last ulp or two (exact bitwise equality across the two compilers is not
guaranteed — fma contraction differs).

Kernel shape requirements: 3-D local blocks, last dim a multiple of 128
(lane width) and second-to-last a multiple of 8 for peak efficiency; other
shapes work but pad internally in the Mosaic compiler. Use
``diffusion3d_step_pallas(..., interpret=True)`` on CPU (tests).
"""

from __future__ import annotations

from functools import partial

__all__ = ["diffusion3d_step_pallas", "diffusion3d_step_halo_pallas",
           "diffusion3d_step_halo_pallas_mp", "mp_supported",
           "pallas_supported", "fusable_halo_dims",
           "step_exchange_modes", "diffusion3d_step_exchange_pallas"]


def pallas_supported(T) -> bool:
    """Whether the Pallas step kernel supports this local block."""
    return T.ndim == 3 and T.shape[0] >= 3


def fusable_halo_dims(gg, ndim: int = 3):
    """Which dims' halo exchange can fuse into the step kernel output pass.

    A dim is fusable when it takes the reference's self-neighbor local path
    (periodic axis, single shard — `update_halo.jl:62-68`) with the default
    overlap/halowidth (ol=2, hw=1), i.e. the halo write is a pure in-plane
    copy. Fusion must respect the reference's strict dim sequencing
    (z, x, y — `update_halo.jl:45`): a dim may fuse only if every dim
    BEFORE it in the order either fuses too or exchanges nothing — otherwise
    its send slabs would miss the earlier dims' received corners. Returns
    (fuse_x, fuse_y, fuse_z) or None if nothing can fuse.
    """
    if ndim != 3:
        return None
    fuse = [False, False, False]
    for dim in (2, 0, 1):  # DEFAULT_DIMS_ORDER
        D = int(gg.dims[dim])
        periodic = bool(gg.periods[dim])
        if D == 1 and not periodic:
            continue  # no exchange on this dim — doesn't block later fusion
        if (D == 1 and periodic and int(gg.overlaps[dim]) == 2
                and int(gg.halowidths[dim]) == 1 and int(gg.disp) == 1):
            fuse[dim] = True
        else:
            break  # multi-shard (or nonstandard) exchange: later dims can't fuse
    if not any(fuse):
        return None
    return tuple(fuse)


def _plane_halo_kernel(Tm_ref, Tc_ref, Tp_ref, Cp_ref, out_ref, *,
                       lam, dt, dx, dy, dz, nx, fuse):
    """One output x-plane of the fused step + self-neighbor halo update.

    Inputs are (1, ny, nz) planes: source plane and its two x-neighbors of T
    plus Cp. The flux arithmetic is in the EXACT accumulation order of the
    reference example (`-d_xa(qx)/dx - d_ya(qy)/dy - d_za(qz)/dz`, then
    `/Cp`, then `T + dt*dTdt` — `diffusion3D_multicpu_novis.jl:42-47`) so
    results match the XLA flux-form step to the last ulp or two. Boundary
    planes/rows/lanes keep their input values (the reference updates the
    interior only), then come the halo writes of the reference's
    self-neighbor local path (`update_halo.jl:62-68`) folded into the same
    output pass, in the reference's exact dim order z, x, y
    (`update_halo.jl:29,45`):

    - z/y halos are in-plane copies (lane/row selects on the computed plane);
    - the x halo re-sources output plane 0 from updated plane nx-2 and plane
      nx-1 from updated plane 1 (``sigma`` in the BlockSpec index maps), so
      the halo planes are recomputed rather than staged — two extra
      plane-triple reads total, no extra array pass.

    Corner semantics match the reference because the z edits are applied to
    the computed plane BEFORE it is used as an x/y halo source, exactly like
    the sequential exchange.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    fuse_x, fuse_y, fuse_z = fuse
    i = pl.program_id(0)
    tc = Tc_ref[0]
    ny, nz = tc.shape
    upd = _stencil_plane(Tm_ref[0], tc, Tp_ref[0], Cp_ref[0],
                         lam=lam, dt=dt, dx=dx, dy=dy, dz=dz)

    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    sp = _sigma(i, nx) if fuse_x else i
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
    u = jnp.where(interior_yz & (sp > 0) & (sp < nx - 1), upd, tc)
    if fuse_z:  # halo lanes <- own interior lanes (broadcast column selects)
        u = jnp.where(col == 0, u[:, nz - 2:nz - 1], u)
        u = jnp.where(col == nz - 1, u[:, 1:2], u)
    if fuse_y:  # after z (and x via sigma), like the sequential exchange
        u = jnp.where(row == 0, u[ny - 2:ny - 1, :], u)
        u = jnp.where(row == ny - 1, u[1:2, :], u)
    out_ref[0] = u


def _sigma(i, nx):
    """Source plane of output plane ``i`` under the fused x halo update."""
    import jax.numpy as jnp

    return jnp.where(i == 0, nx - 2, jnp.where(i == nx - 1, 1, i))


def diffusion3d_step_halo_pallas(T, Cp, *, lam, dt, dx, dy, dz, fuse,
                                 interpret=False):
    """Fused diffusion step + self-neighbor halo exchange on a LOCAL 3-D
    block. ``fuse`` = (fuse_x, fuse_y, fuse_z) from `fusable_halo_dims`;
    non-fused dims behave exactly like `diffusion3d_step_pallas` (exchange
    them afterwards with `local_update_halo`)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nx, ny, nz = T.shape
    plane = (1, ny, nz)
    fuse_x = bool(fuse[0])
    dtp = T.dtype.type
    kernel = partial(
        _plane_halo_kernel,
        lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy), dz=dtp(dz),
        nx=nx, fuse=tuple(bool(f) for f in fuse),
    )

    def src(off):
        def index_map(i):
            s = _sigma(i, nx) if fuse_x else i
            return (jnp.clip(s + off, 0, nx - 1), 0, 0)
        return index_map

    try:
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=jax.typeof(T).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=[
            pl.BlockSpec(plane, src(-1)),
            pl.BlockSpec(plane, src(0)),
            pl.BlockSpec(plane, src(+1)),
            pl.BlockSpec(plane, src(0)),
        ],
        out_specs=pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(T, T, T, Cp)


def diffusion3d_step_pallas(T, Cp, *, lam, dt, dx, dy, dz, interpret=False):
    """One fused diffusion step on a LOCAL 3-D block (no halo exchange —
    compose with `local_update_halo`). The ``fuse=(False, False, False)``
    specialization of `diffusion3d_step_halo_pallas` — one shared kernel so
    the ulp-sensitive accumulation order cannot diverge between the paths."""
    return diffusion3d_step_halo_pallas(
        T, Cp, lam=lam, dt=dt, dx=dx, dy=dy, dz=dz,
        fuse=(False, False, False), interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Fused step + MULTI-SHARD exchange: the flagship path on real pods.
#
# `fusable_halo_dims` only covers self-neighbor (single-shard periodic) dims;
# on a pod every axis is multi-shard and the round-1 design fell back to
# step-kernel + separate exchange (~4 array passes/step). This path keeps the
# whole step at ~2 passes regardless of sharding:
#
#   1. compute the POST-update send slabs from thin input slabs (XLA — a few
#      planes/rows/lanes, negligible traffic; valid because the update is a
#      radius-1 stencil and the send slabs sit >= 1 cell inside the block);
#   2. run the `exchange_recv_slabs` pipeline on them (ppermutes / local
#      swaps, slab-level corner patching, PROC_NULL masking) — the permutes
#      depend ONLY on the thin slabs, so XLA's scheduler overlaps them with
#      the step kernel's plane sweep;
#   3. ONE Pallas pass computes the update for the whole block AND writes
#      the received slabs (z lanes -> x planes -> y rows precedence, same
#      corner argument as `halo_write_combined_pallas`).
# ---------------------------------------------------------------------------


def step_exchange_modes(gg, T):
    """Participation modes for the fused step+exchange, or None.

    Eligible when every EXCHANGING dim has the default overlap 2 and
    halowidth 1 and the block is unstaggered (``T.shape == nxyz`` — the
    flagship model's fields), with at least one exchanging dim. Self and
    multi-shard dims mix freely (self dims become local swaps in the slab
    pipeline)."""
    if T.ndim != 3 or T.shape[0] < 3:
        return None
    if tuple(int(s) for s in T.shape) != tuple(int(n) for n in gg.nxyz):
        return None
    modes = [False, False, False]
    for dim in range(3):
        D = int(gg.dims[dim])
        periodic = bool(gg.periods[dim])
        disp = int(gg.disp)
        if D == 1 and not periodic:
            continue
        if D > 1 and not periodic and disp >= D:
            continue
        if int(gg.overlaps[dim]) != 2 or int(gg.halowidths[dim]) != 1:
            return None
        modes[dim] = True
    if not any(modes):
        return None
    return tuple(modes)


def _xla_update_slab(T, Cp, dim, start, size, consts):
    """Updated-state values at ``[start, start+size)`` along ``dim`` (full
    extent elsewhere), computed from a thin input slab grown by the stencil
    radius (1).

    Cells on the GLOBAL block boundary keep their input values. Slab-edge
    x-neighbors are edge-clones; this is sound because for every range this
    is called with (send slabs at depth >= 1, current-halo slabs at the
    boundary itself) the emitted cells either have their true neighbors
    in-slab or are boundary cells masked back to their input values."""
    import jax.numpy as jnp
    from jax import lax

    s = T.shape[dim]
    lo = max(start - 1, 0)
    hi = min(start + size + 1, s)
    Ts = lax.slice_in_dim(T, lo, hi, axis=dim)
    Cs = lax.slice_in_dim(Cp, lo, hi, axis=dim)
    tm = jnp.concatenate([Ts[:1], Ts[:-1]], axis=0)
    tp = jnp.concatenate([Ts[1:], Ts[-1:]], axis=0)
    upd = _stencil_plane(tm, Ts, tp, Cs, **consts)
    # global-interior mask (dim positions offset by lo; other dims span the
    # full block so slab positions are global)
    m = None
    for d in range(3):
        pos = lax.broadcasted_iota(jnp.int32, Ts.shape, d)
        if d == dim:
            pos = pos + lo
            n_d = s
        else:
            n_d = Ts.shape[d]
        md = (pos > 0) & (pos < n_d - 1)
        m = md if m is None else m & md
    out = jnp.where(m, upd, Ts)
    return lax.slice_in_dim(out, start - lo, start - lo + size, axis=dim)


def _plane_step_recv_kernel(*refs, nx, modes, lam, dt, dx, dy, dz):
    """One output plane of the fused step + exchange: compute the update,
    then deliver the received halo slabs (z lanes, then x whole planes, then
    y rows — the reference's write order restricted to this plane; received
    planes replace the computed one entirely, carrying their own corners)."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    it = iter(refs)
    tm_ref, tc_ref, tp_ref, cp_ref = (next(it) for _ in range(4))
    rx_ref = next(it) if modes[0] else None
    ry_ref = next(it) if modes[1] else None
    rz_ref = next(it) if modes[2] else None
    o_ref = refs[-1]

    i = pl.program_id(0)
    tc = tc_ref[0]
    ny, nz = tc.shape
    upd = _stencil_plane(tm_ref[0], tc, tp_ref[0], cp_ref[0],
                         lam=lam, dt=dt, dx=dx, dy=dy, dz=dz)
    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
    u = jnp.where(interior_yz & (i > 0) & (i < nx - 1), upd, tc)
    if modes[2]:  # halowidth 1 throughout (step_exchange_modes)
        u = jnp.where(col == 0, rz_ref[0, :, 0:1], u)
        u = jnp.where(col == nz - 1, rz_ref[0, :, 1:2], u)
    if modes[0]:
        u = jnp.where(i == 0, rx_ref[0], jnp.where(i == nx - 1, rx_ref[1], u))
    if modes[1]:
        u = jnp.where(row == 0, ry_ref[0, 0:1, :], u)
        u = jnp.where(row == ny - 1, ry_ref[0, 1:2, :], u)
    o_ref[0] = u


def diffusion3d_step_exchange_pallas(T, Cp, gg, modes, *, lam, dt, dx, dy,
                                     dz, interpret=False):
    """Fused diffusion step + full halo exchange for arbitrary shardings
    (see module comment above): thin-slab send computation -> the shared
    `exchange_recv_slabs` pipeline -> one Pallas pass for update + delivery.
    Matches `diffusion3d_step_pallas` followed by the exchange to ulp level:
    the slab computes share `_stencil_plane`'s accumulation order, but they
    run through XLA while the block runs through Mosaic, and fma contraction
    can differ in the last ulp between the compilers (module docstring)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from .halo import exchange_recv_slabs

    nx, ny, nz = T.shape
    plane = (1, ny, nz)
    dtp = T.dtype.type
    consts = dict(lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy), dz=dtp(dz))

    recvs = exchange_recv_slabs(
        gg, T.shape, (1, 1, 1), modes,
        lambda dim, start, size: _xla_update_slab(T, Cp, dim, start, size,
                                                  consts))

    operands = [T, T, T, Cp]
    in_specs = [
        pl.BlockSpec(plane, lambda i: (jnp.maximum(i - 1, 0), 0, 0)),
        pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        pl.BlockSpec(plane, lambda i: (jnp.minimum(i + 1, nx - 1), 0, 0)),
        pl.BlockSpec(plane, lambda i: (i, 0, 0)),
    ]
    if modes[0]:
        rx = jnp.concatenate(recvs[0], axis=0)          # (2, ny, nz)
        operands.append(rx)
        in_specs.append(pl.BlockSpec((2, ny, nz), lambda i: (0, 0, 0)))
    if modes[1]:
        ry = jnp.concatenate(recvs[1], axis=1)          # (nx, 2, nz)
        operands.append(ry)
        in_specs.append(pl.BlockSpec((1, 2, nz), lambda i: (i, 0, 0)))
    if modes[2]:
        rz = jnp.concatenate(recvs[2], axis=2)          # (nx, ny, 2)
        operands.append(rz)
        in_specs.append(pl.BlockSpec((1, ny, 2), lambda i: (i, 0, 0)))

    vma = None
    try:
        vma = jax.typeof(T).vma
        for op in operands[1:]:
            vma = vma | jax.typeof(op).vma
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    kernel = partial(_plane_step_recv_kernel, nx=nx,
                     modes=tuple(bool(m) for m in modes), **consts)
    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)


# ---------------------------------------------------------------------------
# Multi-plane variant: P output planes per program through a DMA'd window.
# ---------------------------------------------------------------------------

_MP_PLANES = 8


_MP_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom under the ~16 MB VMEM


def mp_supported(T) -> bool:
    """Whether the multi-plane kernel applies: enough planes, divisible by
    the block factor, and the VMEM working set fits — scratch (P+2 planes)
    plus double-buffered Cp in and out blocks (2*P planes each). The
    plane-per-program kernel is the fallback for everything else."""
    if not (T.ndim == 3 and T.shape[0] % _MP_PLANES == 0
            and T.shape[0] >= 2 * _MP_PLANES):
        return False
    plane_bytes = int(T.shape[1]) * int(T.shape[2]) * T.dtype.itemsize
    working_set = (5 * _MP_PLANES + 2) * plane_bytes
    return working_set <= _MP_VMEM_BUDGET


def _stencil_plane(tm, tc, tp, cp, *, lam, dt, dx, dy, dz):
    """The flux-form update of one plane (or a 3-D slab — y/z derivatives
    run over the LAST two axes) — the single shared arithmetic (same
    accumulation order as the reference example and the plane-per-program
    kernel)."""
    import jax.numpy as jnp

    zeros = [(0, 0)] * (tc.ndim - 2)
    qxr = -lam * (tp - tc) / dx
    qxl = -lam * (tc - tm) / dx
    acc = -((qxr - qxl) / dx)
    qy = -lam * (tc[..., 1:, :] - tc[..., :-1, :]) / dy
    acc = acc - jnp.pad((qy[..., 1:, :] - qy[..., :-1, :]) / dy,
                        zeros + [(1, 1), (0, 0)])
    qz = -lam * (tc[..., :, 1:] - tc[..., :, :-1]) / dz
    acc = acc - jnp.pad((qz[..., :, 1:] - qz[..., :, :-1]) / dz,
                        zeros + [(0, 0), (1, 1)])
    return tc + dt * (acc / cp)


def _mp_kernel(T_hbm, Cp_ref, out_ref, scratch, sem, *,
               lam, dt, dx, dy, dz, nx, fuse):
    """Compute P output planes from a (P+2)-plane VMEM window of T.

    The window is DMA'd once per program, so interior T planes are read
    ~(1+2/P)x instead of the 3x of the plane-per-program kernel's three
    BlockSpec streams — the stencil's dominant HBM term. z/y halo edits are
    in-plane selects like `_plane_halo_kernel`; x halo planes (if fused) are
    NOT handled here — `diffusion3d_step_halo_pallas_mp` patches them with
    the in-place dim-0 halo write afterwards.
    """
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    P = _MP_PLANES
    fuse_x, fuse_y, fuse_z = fuse
    i = pl.program_id(0)
    g0 = i * P                                   # first output plane
    start = jnp.clip(g0 - 1, 0, nx - (P + 2))    # window start (uniform size)
    cp_dma = pltpu.make_async_copy(T_hbm.at[pl.ds(start, P + 2)], scratch, sem)
    cp_dma.start()
    cp_dma.wait()
    l0 = g0 - start                              # window index of plane g0

    ny, nz = out_ref.shape[1:]
    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)

    for j in range(P):
        g = g0 + j
        l = l0 + j
        tc = scratch[pl.ds(l, 1)][0]
        tm = scratch[pl.ds(jnp.maximum(l - 1, 0), 1)][0]      # clamps at g==0
        tp = scratch[pl.ds(jnp.minimum(l + 1, P + 1), 1)][0]  # ... at g==nx-1
        upd = _stencil_plane(tm, tc, tp, Cp_ref[j],
                             lam=lam, dt=dt, dx=dx, dy=dy, dz=dz)
        u = jnp.where(interior_yz & (g > 0) & (g < nx - 1), upd, tc)
        if fuse_z:
            u = jnp.where(col == 0, u[:, nz - 2:nz - 1], u)
            u = jnp.where(col == nz - 1, u[:, 1:2], u)
        if fuse_y:
            u = jnp.where(row == 0, u[ny - 2:ny - 1, :], u)
            u = jnp.where(row == ny - 1, u[1:2, :], u)
        out_ref[j] = u


def diffusion3d_step_halo_pallas_mp(T, Cp, *, lam, dt, dx, dy, dz, fuse,
                                    interpret=False):
    """Multi-plane fused step (+ self-neighbor halo): the faster form of
    `diffusion3d_step_halo_pallas` for blocks with `mp_supported` shapes.
    Identical semantics; x halo planes (when fused) are recomputed at slab
    level in XLA and written in place by the dim-0 halo kernel."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nx, ny, nz = T.shape
    P = _MP_PLANES
    blk = (P, ny, nz)
    dtp = T.dtype.type
    consts = dict(lam=dtp(lam), dt=dtp(dt), dx=dtp(dx), dy=dtp(dy), dz=dtp(dz))
    kernel = partial(_mp_kernel, nx=nx,
                     fuse=tuple(bool(f) for f in fuse), **consts)

    try:
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype, vma=jax.typeof(T).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(T.shape, T.dtype)

    U = pl.pallas_call(
        kernel,
        grid=(nx // P,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),          # T: manual DMA window
            pl.BlockSpec(blk, lambda i: (i, 0, 0)),     # Cp
        ],
        out_specs=pl.BlockSpec(blk, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((P + 2, ny, nz), T.dtype),
                        pltpu.SemaphoreType.DMA],
        interpret=interpret,
    )(T, Cp)

    if not fuse[0]:
        return U
    # Fused x halo: plane 0 <- updated plane nx-2 (with z edits), plane nx-1
    # <- updated plane 1, then their y halo rows — computed at slab level
    # (reference order z, x, y; same corner argument as _plane_halo_kernel)
    # and written IN PLACE by the dim-0 halo kernel (2-plane write).
    from .pallas_halo import halo_write_inplace

    def patch(src):  # src: global index of the source plane
        tm = lax.slice_in_dim(T, src - 1, src, axis=0)[0]
        tc = lax.slice_in_dim(T, src, src + 1, axis=0)[0]
        tp = lax.slice_in_dim(T, src + 1, src + 2, axis=0)[0]
        cp = lax.slice_in_dim(Cp, src, src + 1, axis=0)[0]
        upd = _stencil_plane(tm, tc, tp, cp, **consts)
        row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
        col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
        interior_yz = (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
        u = jnp.where(interior_yz, upd, tc)     # src planes are x-interior
        if fuse[2]:
            u = jnp.where(col == 0, u[:, nz - 2:nz - 1], u)
            u = jnp.where(col == nz - 1, u[:, 1:2], u)
        if fuse[1]:
            u = jnp.where(row == 0, u[ny - 2:ny - 1, :], u)
            u = jnp.where(row == ny - 1, u[1:2, :], u)
        return u[None]

    return halo_write_inplace(U, patch(nx - 2), patch(1), dim=0, hw=1,
                              interpret=interpret)
