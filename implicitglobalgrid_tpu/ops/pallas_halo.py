"""In-place Pallas halo-write kernels — the unpack stage of the exchange.

The reference's GPU extension hand-writes pack/unpack kernels
(`write_d2x!`/`read_x2d!`, `/root/reference/src/CUDAExt/update_halo.jl:210-227`)
so halo traffic is slab-sized instead of array-sized. The XLA analog of the
unpack — `dynamic_update_slice` on the full block — makes the compiler
rewrite the whole array per updated side (several full HBM round trips per
exchange). These kernels restore slab-sized traffic: a ``pallas_call`` with
``input_output_aliases`` updates the halo regions IN PLACE and never touches
the rest of the buffer.

Per-dimension strategy (TPU tiling constraints — (8, 128) sublane x lane
tiles on f32 — forbid misaligned writes along the last two axes):

- dim 0 (x): halo planes are whole (ny, nz) tiles — write them directly from
  the received slabs; nothing else is read or written.
- dim 1 (y): read-modify-write the first/last 8-row-aligned strip of every
  x-plane; traffic = 2*ceil(hw/8)*8 rows per plane.
- dim 2 (z): NO kernel — its halo tiles are 128-lane strips whose rows are
  128-element chunks strided by the full row pitch (~25% DMA efficiency);
  measured slower than XLA's contiguous full-array `dynamic_update_slice`
  rewrite, which stays the dim-2 unpack path.

Additionally, when EVERY exchanging dim is the self-neighbor case,
`halo_self_exchange_pallas` does the whole exchange in one full array pass
with no slab extraction at all (see below).

`halo_write_supported` gates on the alignment preconditions; callers fall
back to the XLA `dynamic_update_slice` path when it returns False (non-TPU
platforms, dim 2, tiny blocks, exotic halowidths).
"""

from __future__ import annotations

from functools import partial

__all__ = ["halo_write_supported", "halo_write_inplace",
           "self_exchange_supported", "halo_self_exchange_pallas",
           "combined_write_supported", "halo_write_combined_pallas",
           "multi_write_supported", "halo_write_multi_pallas",
           "wire_pack_supported", "wire_pack_pallas"]

_SUBLANE = 8
_LANE = 128

# single-step pack kernel: every slab + the packed buffer live in VMEM at
# once — slabs are hw-thin planes/strips, so this bound is generous
_WIRE_PACK_VMEM = 4 * 1024 * 1024


def wire_pack_supported(slab_shapes, dim: int, pack_dtype) -> bool:
    """Whether `wire_pack_pallas` can pack these send slabs along ``dim``:
    3-D slabs, dims 0/1 only (dim 2 concat writes partial lane tiles —
    the same DMA-efficiency cliff as `halo_write_supported`), uniform
    cross extents (the slab layout's own precondition), and the whole
    working set (slabs + packed buffer, double) under the VMEM budget.
    ``pack_dtype`` is the dtype the kernel actually packs — the STATE
    dtype (a narrower cast wire format converts AFTER the pack,
    `WireSchema.pack`), so callers must not budget with the wire dtype."""
    import numpy as np

    shapes = [tuple(int(v) for v in s) for s in slab_shapes]
    if dim not in (0, 1) or any(len(s) != 3 for s in shapes):
        return False
    cross = {tuple(v for d, v in enumerate(s) if d != dim) for s in shapes}
    if len(cross) != 1:
        return False
    cells = sum(int(np.prod(s)) for s in shapes)
    return 2 * cells * int(np.dtype(pack_dtype).itemsize) <= _WIRE_PACK_VMEM


def wire_pack_pallas(slabs, *, dim: int, interpret: bool = False):
    """Fused PACK of the slab-layout wire buffer: ONE kernel launch writes
    every field's send slab into the packed payload (the concat along the
    exchange axis of `ops.wire.WireSchema`) — K fields cost one launch
    and one slab-sized write instead of the XLA concat's per-operand
    copies. Gate with `wire_pack_supported`; bit-identical to the XLA
    concat (pure layout, no arithmetic)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    widths = [int(s.shape[dim]) for s in slabs]
    out_shape_dims = list(slabs[0].shape)
    out_shape_dims[dim] = sum(widths)
    try:
        vma = jax.typeof(slabs[0]).vma
        for s in slabs[1:]:
            vma = vma | jax.typeof(s).vma
        out_shape = jax.ShapeDtypeStruct(tuple(out_shape_dims),
                                         slabs[0].dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(tuple(out_shape_dims),
                                         slabs[0].dtype)

    def kernel(*refs):
        o_ref = refs[-1]
        off = 0
        for k, w in enumerate(widths):
            if dim == 0:
                o_ref[off:off + w] = refs[k][...]
            else:
                o_ref[:, off:off + w] = refs[k][...]
            off += w

    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec(tuple(s.shape), lambda i, nd=s.ndim: (0,) * nd)
                  for s in slabs],
        out_specs=pl.BlockSpec(tuple(out_shape_dims),
                               lambda i: (0,) * len(out_shape_dims)),
        out_shape=out_shape,
        interpret=interpret,
    )(*slabs)


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def halo_write_supported(shape, dim: int, hw: int) -> bool:
    """Whether the in-place kernel can write halo slabs of width ``hw`` along
    ``dim`` for a local block of this shape (3-D only).

    dim 2 is deliberately unsupported: its halo tiles are 128-lane strips
    whose rows are 128-element chunks strided by the full row pitch, so the
    strip RMW runs at ~25% DMA efficiency — measured SLOWER than letting XLA
    rewrite the array contiguously (dynamic_update_slice fusion). dims 0/1
    write contiguous planes / 8-row strips and win.
    """
    if len(shape) != 3 or dim == 2:
        return False
    s = int(shape[dim])
    if dim == 0:
        return s >= 2 * hw
    strip = _ceil_to(hw, _SUBLANE)
    # top and bottom strips must be disjoint and block-aligned
    return s >= 2 * strip and s % strip == 0


def halo_write_inplace(a, slab_l, slab_r, *, dim: int, hw: int,
                       interpret: bool = False):
    """Return ``a`` with ``slab_l`` written into its ``[0, hw)`` halo and
    ``slab_r`` into its ``[s-hw, s)`` halo along ``dim`` — in place (the
    output aliases ``a``'s buffer; only the halo tiles move through VMEM).

    ``slab_l``/``slab_r`` have ``hw`` extent along ``dim``; the slabs must
    not alias the written regions (guaranteed by the exchange's ``ol >= 2*hw``
    participation gate, reference `update_halo.jl:233`).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nx, ny, nz = a.shape
    s = a.shape[dim]

    try:  # inside shard_map, outputs must declare their mesh-axis variance
        vma = jax.typeof(a).vma | jax.typeof(slab_l).vma | jax.typeof(slab_r).vma
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype)

    if dim == 0:
        # Halo planes are whole tiles: write them straight from the slabs.
        slabs = jnp.concatenate([slab_l, slab_r], axis=0)     # (2hw, ny, nz)
        plane = (1, ny, nz)

        def kernel(s_ref, a_ref, o_ref):
            o_ref[...] = s_ref[...]

        return pl.pallas_call(
            kernel,
            grid=(2 * hw,),
            in_specs=[
                pl.BlockSpec(plane, lambda i: (i, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),      # aliased, untouched
            ],
            out_specs=pl.BlockSpec(
                plane, lambda i: (jnp.where(i < hw, i, s - 2 * hw + i), 0, 0)
            ),
            out_shape=out_shape,
            input_output_aliases={1: 0},
            interpret=interpret,
        )(slabs, a)

    # dim 1: RMW the minimal 8-row-aligned edge strip of every x-plane.
    strip = _ceil_to(hw, _SUBLANE)
    pad = strip - hw
    # slabs (nx, hw, nz) -> (2, nx, strip, nz); left slab occupies rows
    # [0, hw), right slab rows [strip-hw, strip) of its strip.
    slabs = jnp.stack([
        jnp.pad(slab_l, ((0, 0), (0, pad), (0, 0))),
        jnp.pad(slab_r, ((0, 0), (pad, 0), (0, 0))),
    ])
    blk_a = (1, strip, nz)
    n_blocks = ny // strip
    blk_s = (1,) + blk_a

    kernel = partial(_rmw_kernel, dim=dim, hw=hw, strip=strip)

    def a_map(i, j):
        return (i, j * (n_blocks - 1), 0)          # j=0: first, j=1: last

    return pl.pallas_call(
        kernel,
        grid=(nx, 2),
        in_specs=[
            pl.BlockSpec(blk_s, lambda i, j: (j, i, 0, 0)),
            pl.BlockSpec(blk_a, a_map),
        ],
        out_specs=pl.BlockSpec(blk_a, a_map),
        out_shape=out_shape,
        input_output_aliases={1: 0},
        interpret=interpret,
    )(slabs, a)


def _rmw_kernel(s_ref, a_ref, o_ref, *, dim, hw, strip):
    """Merge the slab into the aligned edge strip: side j=0 overwrites the
    first ``hw`` rows/lanes, side j=1 the last ``hw`` of the strip."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    del dim  # only dim 1 reaches the RMW kernel (halo_write_supported)
    j = pl.program_id(1)
    cur = a_ref[0]
    sl = s_ref[0, 0]
    pos = lax.broadcasted_iota(jnp.int32, cur.shape, 0)
    left = j == 0  # scalar-predicate select over bool vectors won't legalize
    mask = (left & (pos < hw)) | (~left & (pos >= strip - hw))
    o_ref[0] = jnp.where(mask, sl, cur)


# ---------------------------------------------------------------------------
# Multi-field unpack: the delivery stage of the COALESCED exchange
# (`ops.halo._exchange_dim_coalesced`). After the per-axis packed ppermute
# pair, every participating field's received slabs are written into its halo
# regions by ONE pallas_call — one kernel launch per (axis, dtype group)
# instead of one per field, with the same in-place slab-sized traffic as
# `halo_write_inplace` (all field buffers aliased input->output).
# ---------------------------------------------------------------------------

def multi_write_supported(shapes, dim: int, hws_dim) -> bool:
    """Whether `halo_write_multi_pallas` can deliver along ``dim`` for
    fields of these local ``shapes``: every field passes the single-field
    gate (`halo_write_supported` — 3-D, dims 0/1 only), all fields share
    the halowidth along ``dim`` (it sizes the shared pallas grid), and for
    the dim-1 strip RMW all fields share the plane count ``shape[0]``."""
    hws_dim = [int(h) for h in hws_dim]
    if len(set(hws_dim)) != 1:
        return False
    hw = hws_dim[0]
    if not all(halo_write_supported(s, dim, hw) for s in shapes):
        return False
    if dim == 1 and len({int(s[0]) for s in shapes}) != 1:
        return False
    return True


def _dim0_multi_out_map(i, *, s, hw):
    import jax.numpy as jnp

    return (jnp.where(i < hw, i, s - 2 * hw + i), 0, 0)


def halo_write_multi_pallas(arrays, slab_pairs, *, dim: int, hw: int,
                            interpret: bool = False):
    """Write EVERY field's ``(slab_l, slab_r)`` halos along ``dim`` in one
    pallas_call (gate: `multi_write_supported`). Returns the updated arrays
    in order; each output aliases its field's buffer, so only the halo
    tiles move through VMEM — K fields cost one kernel launch, not K."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    K = len(arrays)
    out_shapes = []
    for a, (sl, sr) in zip(arrays, slab_pairs):
        try:
            vma = jax.typeof(a).vma | jax.typeof(sl).vma | jax.typeof(sr).vma
            out_shapes.append(jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma))
        except (AttributeError, TypeError):
            out_shapes.append(jax.ShapeDtypeStruct(a.shape, a.dtype))

    if dim == 0:
        slab_ops, slab_specs, arr_specs, out_specs = [], [], [], []
        for a, (sl, sr) in zip(arrays, slab_pairs):
            _, ny, nz = a.shape
            slab_ops.append(jnp.concatenate([sl, sr], axis=0))  # (2hw, ny, nz)
            slab_specs.append(pl.BlockSpec((1, ny, nz), lambda i: (i, 0, 0)))
            arr_specs.append(pl.BlockSpec(memory_space=pl.ANY))
            out_specs.append(pl.BlockSpec(
                (1, ny, nz),
                partial(_dim0_multi_out_map, s=a.shape[0], hw=hw)))

        def kernel(*refs):
            for k in range(K):
                refs[2 * K + k][...] = refs[k][...]

        return pl.pallas_call(
            kernel,
            grid=(2 * hw,),
            in_specs=slab_specs + arr_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            input_output_aliases={K + k: k for k in range(K)},
            interpret=interpret,
        )(*slab_ops, *arrays)

    # dim 1: per-field RMW of the aligned edge strips, shared (nx, 2) grid.
    strip = _ceil_to(hw, _SUBLANE)
    pad = strip - hw
    nx = arrays[0].shape[0]
    slab_ops, slab_specs, arr_specs, out_specs = [], [], [], []
    for a, (sl, sr) in zip(arrays, slab_pairs):
        nz = a.shape[2]
        slab_ops.append(jnp.stack([
            jnp.pad(sl, ((0, 0), (0, pad), (0, 0))),
            jnp.pad(sr, ((0, 0), (pad, 0), (0, 0))),
        ]))                                          # (2, nx, strip, nz)
        blk_a = (1, strip, nz)
        slab_specs.append(pl.BlockSpec((1,) + blk_a,
                                       lambda i, j: (j, i, 0, 0)))
        a_map = partial(_dim1_multi_a_map, last=a.shape[1] // strip - 1)
        arr_specs.append(pl.BlockSpec(blk_a, a_map))
        out_specs.append(pl.BlockSpec(blk_a, a_map))

    kernel = partial(_multi_rmw_kernel, K=K, hw=hw, strip=strip)
    return pl.pallas_call(
        kernel,
        grid=(nx, 2),
        in_specs=slab_specs + arr_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        input_output_aliases={K + k: k for k in range(K)},
        interpret=interpret,
    )(*slab_ops, *arrays)


def _dim1_multi_a_map(i, j, *, last):
    return (i, j * last, 0)                        # j=0: first, j=1: last strip


def _multi_rmw_kernel(*refs, K, hw, strip):
    """Per (x-plane, side) grid step: merge every field's slab into its
    aligned edge strip (the K-field form of `_rmw_kernel`)."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    left = j == 0  # scalar-predicate select over bool vectors won't legalize
    for k in range(K):
        cur = refs[K + k][0]
        sl = refs[k][0, 0]
        pos = lax.broadcasted_iota(jnp.int32, cur.shape, 0)
        mask = (left & (pos < hw)) | (~left & (pos >= strip - hw))
        refs[2 * K + k][0] = jnp.where(mask, sl, cur)


# ---------------------------------------------------------------------------
# Single-pass self-neighbor exchange: the reference's 1-process periodic path
# (`update_halo.jl:62-68,363-380`) for ALL dims in ONE array pass.
# ---------------------------------------------------------------------------

def self_exchange_supported(shape, modes, hws) -> bool:
    """Whether `halo_self_exchange_pallas` can run: 3-D block, every
    participating dim in self-neighbor mode with halowidth 1 (wider halos
    need in-register lane/row shifts that don't pay off), at least one
    participating dim, and >= 3 planes when dim 0 participates."""
    if len(shape) != 3 or not any(modes):
        return False
    for d in range(3):
        if modes[d] and int(hws[d]) != 1:
            return False
    if modes[0] and int(shape[0]) < 3:
        return False
    return True


def halo_self_exchange_pallas(a, *, modes, ols, interpret=False):
    """Exchange all self-neighbor halos of local block ``a`` in ONE pass.

    ``modes[d]`` = True when dim ``d`` is a periodic single-shard axis (the
    reference's self-neighbor path); ``ols[d]`` = its overlap. Halowidth 1.
    Equivalent to the sequential z, x, y slab copies of
    `ops.halo._exchange_dim_local` but costs a single full read+write of the
    block instead of one array rewrite per side — and no slab extraction at
    all: z/y halos are in-plane broadcast selects, x halo planes are sourced
    directly from their interior source plane via the BlockSpec index maps
    (the corner-ordering argument is the same as the fused step kernel,
    `pallas_stencil._plane_halo_kernel`).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nx, ny, nz = a.shape
    plane = (1, ny, nz)
    modes = tuple(bool(m) for m in modes)
    ols = tuple(int(o) for o in ols)

    def sigma(i):
        if not modes[0]:
            return i
        return jnp.where(i == 0, nx - ols[0],
                         jnp.where(i == nx - 1, ols[0] - 1, i))

    kernel = partial(_self_exchange_kernel, modes=modes, ols=ols)

    try:
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype, vma=jax.typeof(a).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype)

    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=[pl.BlockSpec(plane, lambda i: (sigma(i), 0, 0))],
        out_specs=pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(a)


def combined_write_supported(shape, modes, hws) -> bool:
    """Whether `halo_write_combined_pallas` can deliver received slabs for
    this local block: 3-D, dim 2 participating (otherwise the slab kernels
    of `halo_write_inplace` already cost slab-level traffic and a full pass
    would be a loss), and participating dims 1/2 with halowidth 1 (their
    halo rows/lanes are placed by broadcast selects; wider halos fall back
    to the per-dim path). dim 0's halowidth is unrestricted (whole planes).
    """
    if len(shape) != 3 or not modes[2]:
        return False
    if (modes[1] and int(hws[1]) != 1) or int(hws[2]) != 1:
        return False
    if modes[0] and int(shape[0]) < 2 * int(hws[0]):
        return False
    return True


def halo_write_combined_pallas(a, recvs, *, modes, hws, interpret=False):
    """Write ALL received halo slabs into ``a`` in ONE full-array pass.

    The per-dim exchange pays roughly one full-array rewrite per dimension
    on TPU (XLA's `dynamic_update_slice` unpack; the reference's analog is
    its per-dim unpack kernels, `CUDAExt/update_halo.jl:210-227`). When
    dim 2 participates its lane-edge tiles force array-level traffic anyway
    (see `halo_write_supported`), so the optimal unpack is a single pass
    that delivers every dim's slabs at once: read each x-plane, replace its
    halo rows/lanes/planes, write it back — 1x read + 1x write total,
    instead of ~3 rewrites.

    ``recvs[d] = (recv_l, recv_r)`` for each participating dim ``d`` (slab
    extent ``hws[d]`` along ``d``); caller has already applied boundary
    masking, self-neighbor routing, and the sequential-corner patching
    (`ops.halo._combined_exchange`), so precedence here is simply: base
    plane (dim 0 halo planes come from ``recvs[0]``), then dim 2 lanes,
    then dim 1 rows — the reference's z, x, y write order restricted to
    this plane.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nx, ny, nz = a.shape
    modes = tuple(bool(m) for m in modes)
    hwx = int(hws[0])

    operands = [a]
    in_specs = [pl.BlockSpec((1, ny, nz), lambda i: (i, 0, 0))]
    vma = None
    try:
        vma = jax.typeof(a).vma
    except (AttributeError, TypeError):
        pass

    if modes[0]:
        rx = jnp.concatenate(recvs[0], axis=0)          # (2*hwx, ny, nz)
        if vma is not None:
            vma = vma | jax.typeof(rx).vma

        def rx_map(i, nx=nx, hwx=hwx):
            return (jnp.where(i < hwx, i,
                              jnp.where(i >= nx - hwx, i - (nx - 2 * hwx), 0)),
                    0, 0)

        operands.append(rx)
        in_specs.append(pl.BlockSpec((1, ny, nz), rx_map))
    if modes[1]:
        ry = jnp.concatenate(recvs[1], axis=1)          # (nx, 2, nz)
        if vma is not None:
            vma = vma | jax.typeof(ry).vma
        operands.append(ry)
        in_specs.append(pl.BlockSpec((1, 2, nz), lambda i: (i, 0, 0)))
    if modes[2]:
        rz = jnp.concatenate(recvs[2], axis=2)          # (nx, ny, 2)
        if vma is not None:
            vma = vma | jax.typeof(rz).vma
        operands.append(rz)
        in_specs.append(pl.BlockSpec((1, ny, 2), lambda i: (i, 0, 0)))

    if vma is not None:
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma)
    else:
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype)

    kernel = partial(_combined_write_kernel, nx=nx, hwx=hwx, modes=modes)
    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, ny, nz), lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)


def _combined_write_kernel(*refs, nx, hwx, modes):
    """One output plane: base (own plane or a dim-0 halo plane from the
    received stack), then dim 2 halo lanes, then dim 1 halo rows — the
    reference's z, x, y precedence for this plane."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    it = iter(refs)
    a_ref = next(it)
    rx_ref = next(it) if modes[0] else None
    ry_ref = next(it) if modes[1] else None
    rz_ref = next(it) if modes[2] else None
    o_ref = refs[-1]

    u = a_ref[0]
    ny, nz = u.shape
    if modes[2]:  # z lanes first (halowidth 1, combined_write_supported)
        col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
        u = jnp.where(col == 0, rz_ref[0, :, 0:1], u)
        u = jnp.where(col == nz - 1, rz_ref[0, :, 1:2], u)
    if modes[0]:
        # dim 0 halo planes replace the whole plane INCLUDING its z lanes —
        # the received planes carry the correct post-z-exchange corners
        # (patched into the send slabs by the sender, ops.halo).
        i = pl.program_id(0)
        in_halo = (i < hwx) | (i >= nx - hwx)
        u = jnp.where(in_halo, rx_ref[0], u)
    if modes[1]:
        row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
        u = jnp.where(row == 0, ry_ref[0, 0:1, :], u)
        u = jnp.where(row == ny - 1, ry_ref[0, 1:2, :], u)
    o_ref[0] = u


def _self_exchange_kernel(a_ref, o_ref, *, modes, ols):
    """Write one output plane: the sourced plane with its z (lane) and y
    (row) halo edges replaced by their periodic in-plane sources, in the
    reference's z, x, y order (x is realized by the plane sourcing)."""
    import jax.numpy as jnp
    from jax import lax

    u = a_ref[0]
    ny, nz = u.shape
    if modes[2]:  # z halos first (reference dim order z, x, y)
        col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
        u = jnp.where(col == 0, u[:, nz - ols[2]:nz - ols[2] + 1], u)
        u = jnp.where(col == nz - 1, u[:, ols[2] - 1:ols[2]], u)
    if modes[1]:  # y halos last, after the x plane sourcing
        row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
        u = jnp.where(row == 0, u[ny - ols[1]:ny - ols[1] + 1, :], u)
        u = jnp.where(row == ny - 1, u[ols[1] - 1:ols[1], :], u)
    o_ref[0] = u
