"""In-place Pallas halo-write kernels — the unpack stage of the exchange.

The reference's GPU extension hand-writes pack/unpack kernels
(`write_d2x!`/`read_x2d!`, `/root/reference/src/CUDAExt/update_halo.jl:210-227`)
so halo traffic is slab-sized instead of array-sized. The XLA analog of the
unpack — `dynamic_update_slice` on the full block — makes the compiler
rewrite the whole array per updated side (several full HBM round trips per
exchange). These kernels restore slab-sized traffic: a ``pallas_call`` with
``input_output_aliases`` updates the halo regions IN PLACE and never touches
the rest of the buffer.

Per-dimension strategy (TPU tiling constraints — (8, 128) sublane x lane
tiles on f32 — forbid misaligned writes along the last two axes):

- dim 0 (x): halo planes are whole (ny, nz) tiles — write them directly from
  the received slabs; nothing else is read or written.
- dim 1 (y): read-modify-write the first/last 8-row-aligned strip of every
  x-plane; traffic = 2*ceil(hw/8)*8 rows per plane.
- dim 2 (z): NO kernel — its halo tiles are 128-lane strips whose rows are
  128-element chunks strided by the full row pitch (~25% DMA efficiency);
  measured slower than XLA's contiguous full-array `dynamic_update_slice`
  rewrite, which stays the dim-2 unpack path.

Additionally, when EVERY exchanging dim is the self-neighbor case,
`halo_self_exchange_pallas` does the whole exchange in one full array pass
with no slab extraction at all (see below).

`halo_write_supported` gates on the alignment preconditions; callers fall
back to the XLA `dynamic_update_slice` path when it returns False (non-TPU
platforms, dim 2, tiny blocks, exotic halowidths).
"""

from __future__ import annotations

from functools import partial

__all__ = ["halo_write_supported", "halo_write_inplace",
           "self_exchange_supported", "halo_self_exchange_pallas"]

_SUBLANE = 8
_LANE = 128


def _ceil_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def halo_write_supported(shape, dim: int, hw: int) -> bool:
    """Whether the in-place kernel can write halo slabs of width ``hw`` along
    ``dim`` for a local block of this shape (3-D only).

    dim 2 is deliberately unsupported: its halo tiles are 128-lane strips
    whose rows are 128-element chunks strided by the full row pitch, so the
    strip RMW runs at ~25% DMA efficiency — measured SLOWER than letting XLA
    rewrite the array contiguously (dynamic_update_slice fusion). dims 0/1
    write contiguous planes / 8-row strips and win.
    """
    if len(shape) != 3 or dim == 2:
        return False
    s = int(shape[dim])
    if dim == 0:
        return s >= 2 * hw
    strip = _ceil_to(hw, _SUBLANE)
    # top and bottom strips must be disjoint and block-aligned
    return s >= 2 * strip and s % strip == 0


def halo_write_inplace(a, slab_l, slab_r, *, dim: int, hw: int,
                       interpret: bool = False):
    """Return ``a`` with ``slab_l`` written into its ``[0, hw)`` halo and
    ``slab_r`` into its ``[s-hw, s)`` halo along ``dim`` — in place (the
    output aliases ``a``'s buffer; only the halo tiles move through VMEM).

    ``slab_l``/``slab_r`` have ``hw`` extent along ``dim``; the slabs must
    not alias the written regions (guaranteed by the exchange's ``ol >= 2*hw``
    participation gate, reference `update_halo.jl:233`).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nx, ny, nz = a.shape
    s = a.shape[dim]

    try:  # inside shard_map, outputs must declare their mesh-axis variance
        vma = jax.typeof(a).vma | jax.typeof(slab_l).vma | jax.typeof(slab_r).vma
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype)

    if dim == 0:
        # Halo planes are whole tiles: write them straight from the slabs.
        slabs = jnp.concatenate([slab_l, slab_r], axis=0)     # (2hw, ny, nz)
        plane = (1, ny, nz)

        def kernel(s_ref, a_ref, o_ref):
            o_ref[...] = s_ref[...]

        return pl.pallas_call(
            kernel,
            grid=(2 * hw,),
            in_specs=[
                pl.BlockSpec(plane, lambda i: (i, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),      # aliased, untouched
            ],
            out_specs=pl.BlockSpec(
                plane, lambda i: (jnp.where(i < hw, i, s - 2 * hw + i), 0, 0)
            ),
            out_shape=out_shape,
            input_output_aliases={1: 0},
            interpret=interpret,
        )(slabs, a)

    # dim 1: RMW the minimal 8-row-aligned edge strip of every x-plane.
    strip = _ceil_to(hw, _SUBLANE)
    pad = strip - hw
    # slabs (nx, hw, nz) -> (2, nx, strip, nz); left slab occupies rows
    # [0, hw), right slab rows [strip-hw, strip) of its strip.
    slabs = jnp.stack([
        jnp.pad(slab_l, ((0, 0), (0, pad), (0, 0))),
        jnp.pad(slab_r, ((0, 0), (pad, 0), (0, 0))),
    ])
    blk_a = (1, strip, nz)
    n_blocks = ny // strip
    blk_s = (1,) + blk_a

    kernel = partial(_rmw_kernel, dim=dim, hw=hw, strip=strip)

    def a_map(i, j):
        return (i, j * (n_blocks - 1), 0)          # j=0: first, j=1: last

    return pl.pallas_call(
        kernel,
        grid=(nx, 2),
        in_specs=[
            pl.BlockSpec(blk_s, lambda i, j: (j, i, 0, 0)),
            pl.BlockSpec(blk_a, a_map),
        ],
        out_specs=pl.BlockSpec(blk_a, a_map),
        out_shape=out_shape,
        input_output_aliases={1: 0},
        interpret=interpret,
    )(slabs, a)


def _rmw_kernel(s_ref, a_ref, o_ref, *, dim, hw, strip):
    """Merge the slab into the aligned edge strip: side j=0 overwrites the
    first ``hw`` rows/lanes, side j=1 the last ``hw`` of the strip."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    del dim  # only dim 1 reaches the RMW kernel (halo_write_supported)
    j = pl.program_id(1)
    cur = a_ref[0]
    sl = s_ref[0, 0]
    pos = lax.broadcasted_iota(jnp.int32, cur.shape, 0)
    left = j == 0  # scalar-predicate select over bool vectors won't legalize
    mask = (left & (pos < hw)) | (~left & (pos >= strip - hw))
    o_ref[0] = jnp.where(mask, sl, cur)


# ---------------------------------------------------------------------------
# Single-pass self-neighbor exchange: the reference's 1-process periodic path
# (`update_halo.jl:62-68,363-380`) for ALL dims in ONE array pass.
# ---------------------------------------------------------------------------

def self_exchange_supported(shape, modes, hws) -> bool:
    """Whether `halo_self_exchange_pallas` can run: 3-D block, every
    participating dim in self-neighbor mode with halowidth 1 (wider halos
    need in-register lane/row shifts that don't pay off), at least one
    participating dim, and >= 3 planes when dim 0 participates."""
    if len(shape) != 3 or not any(modes):
        return False
    for d in range(3):
        if modes[d] and int(hws[d]) != 1:
            return False
    if modes[0] and int(shape[0]) < 3:
        return False
    return True


def halo_self_exchange_pallas(a, *, modes, ols, interpret=False):
    """Exchange all self-neighbor halos of local block ``a`` in ONE pass.

    ``modes[d]`` = True when dim ``d`` is a periodic single-shard axis (the
    reference's self-neighbor path); ``ols[d]`` = its overlap. Halowidth 1.
    Equivalent to the sequential z, x, y slab copies of
    `ops.halo._exchange_dim_local` but costs a single full read+write of the
    block instead of one array rewrite per side — and no slab extraction at
    all: z/y halos are in-plane broadcast selects, x halo planes are sourced
    directly from their interior source plane via the BlockSpec index maps
    (the corner-ordering argument is the same as the fused step kernel,
    `pallas_stencil._plane_halo_kernel`).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    nx, ny, nz = a.shape
    plane = (1, ny, nz)
    modes = tuple(bool(m) for m in modes)
    ols = tuple(int(o) for o in ols)

    def sigma(i):
        if not modes[0]:
            return i
        return jnp.where(i == 0, nx - ols[0],
                         jnp.where(i == nx - 1, ols[0] - 1, i))

    kernel = partial(_self_exchange_kernel, modes=modes, ols=ols)

    try:
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype, vma=jax.typeof(a).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct(a.shape, a.dtype)

    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=[pl.BlockSpec(plane, lambda i: (sigma(i), 0, 0))],
        out_specs=pl.BlockSpec(plane, lambda i: (i, 0, 0)),
        out_shape=out_shape,
        interpret=interpret,
    )(a)


def _self_exchange_kernel(a_ref, o_ref, *, modes, ols):
    """Write one output plane: the sourced plane with its z (lane) and y
    (row) halo edges replaced by their periodic in-plane sources, in the
    reference's z, x, y order (x is realized by the plane sourcing)."""
    import jax.numpy as jnp
    from jax import lax

    u = a_ref[0]
    ny, nz = u.shape
    if modes[2]:  # z halos first (reference dim order z, x, y)
        col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
        u = jnp.where(col == 0, u[:, nz - ols[2]:nz - ols[2] + 1], u)
        u = jnp.where(col == nz - 1, u[:, ols[2] - 1:ols[2]], u)
    if modes[1]:  # y halos last, after the x plane sourcing
        row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
        u = jnp.where(row == 0, u[ny - ols[1]:ny - ols[1] + 1, :], u)
        u = jnp.where(row == ny - 1, u[ols[1] - 1:ols[1], :], u)
    o_ref[0] = u
