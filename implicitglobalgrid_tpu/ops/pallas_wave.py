"""Fused Pallas pass for the acoustic leapfrog step — the kernel tier for
the staggered-grid wave model (BASELINE config 4).

The XLA formulation of one acoustic step (`models/acoustic.py`) costs ~5
array passes over 4 fields: the three velocity updates, their 3-field halo
exchange, the pressure update, and its exchange. This module fuses the WHOLE
step — both updates AND both exchanges — into one plane-pipelined Pallas
pass over all four fields (the staggered-field analog of
`pallas_stencil.diffusion3d_step_exchange_pallas`, and of the reference's
kernel tier serving every field type, `CUDAExt/update_halo.jl:143-146`).

Why one pass is semantically sound (halowidth-1 fields):

- The velocity update touches only INTERIOR faces and reads only P — no
  received values needed.
- Velocity SEND slabs sit >= 1 face inside the block, so they are computed
  from local P alone (`_xla_update_slab`-style thin-slab computes); the
  received slabs come from the shared PACKED pipeline
  (`exchange_recv_slabs_multi`: all four fields' slabs ride ONE ppermute
  pair per mesh axis on the canonical wire schema — the same wire, and
  the same `IGG_HALO_WIRE_DTYPE` policy, the XLA tier ships — plus local
  swaps / PROC_NULL masking / corner patching).
- The pressure update needs post-exchange V faces ONLY at cells that are
  themselves P halo cells: every surviving cell of every P send slab is
  interior in the cross dimensions (its cross-dim edge cells are either
  patched from earlier dims' recvs before sending or overwritten by later
  dims' recvs after delivery — the z, x, y order), and interior cells read
  only locally-updated faces. At PROC_NULL edges the kept faces are the
  un-updated boundary faces — exactly the local raw values. Hence the P
  send slabs are computed from LOCAL updated V values only, and the fused
  pass reproduces the sequential update->exchange->update->exchange result.

Delivery order inside the kernel is the reference's z, x, y per field; Vx's
x-extent (nx+1 planes) exceeds the grid (nx programs), so its two x halo
planes are written afterwards by the in-place dim-0 kernel with slabs whose
y rows are patched from the y recvs (preserving the x-before-y order).
"""

from __future__ import annotations

from functools import partial

from .pallas_common import deliver_recvs as _deliver
from .pallas_common import slab1 as _slab

__all__ = ["wave_exchange_modes", "acoustic_step_exchange_pallas"]


def wave_exchange_modes(gg, shapes):
    """Per-field participation modes for the fused acoustic step, or None.

    ``shapes`` = (P, Vx, Vy, Vz) local shapes. Eligible when the shapes
    follow the model's staggering pattern (faces on +1 axes) and every
    grid halowidth is 1 (the delivery selects hardwire width-1 halos).
    Returns a dict ``{"P": modes, "Vx": modes, ...}`` of 3-tuples
    (all-False modes mean a pure fused update with no deliveries)."""
    from .halo import _dim_exchanges

    sp, sx, sy, sz = (tuple(int(v) for v in s) for s in shapes)
    if len(sp) != 3 or sp[0] < 3:
        return None
    if sp != tuple(int(n) for n in gg.nxyz):
        return None
    nx, ny, nz = sp
    if sx != (nx + 1, ny, nz) or sy != (nx, ny + 1, nz) \
            or sz != (nx, ny, nz + 1):
        return None
    if any(int(h) != 1 for h in gg.halowidths):
        return None
    hws = (1, 1, 1)
    out = {}
    for name, s in (("P", sp), ("Vx", sx), ("Vy", sy), ("Vz", sz)):
        out[name] = tuple(_dim_exchanges(gg, s, hws, d) for d in range(3))
    # all-False modes are still eligible: the kernel then fuses both
    # updates into one pass with no deliveries (single-chip non-periodic)
    return out


def _upd_vx_plane(Vx, P, f, c):
    """Updated Vx face plane ``f`` (static index): interior faces get the
    leapfrog P-gradient update, boundary faces (0, nx) keep their values
    (reference `Vx.at[1:-1].add`, `models/acoustic.py`)."""
    from jax import lax

    nx1 = Vx.shape[0]
    v = lax.slice_in_dim(Vx, f, f + 1, axis=0)
    if f < 1 or f > nx1 - 2:
        return v
    pm = lax.slice_in_dim(P, f - 1, f, axis=0)
    pc = lax.slice_in_dim(P, f, f + 1, axis=0)
    return v + c * (pc - pm)


def _upd_v_inplane(V, P, axis, c):
    """All ``axis``-faces of V updated from P within a slab spanning the
    full ``axis`` extent: interior faces via the padded P difference (the
    pad zeroes the update at boundary faces, keeping them raw)."""
    import jax.numpy as jnp
    from jax import lax

    n = P.shape[axis]
    d = (lax.slice_in_dim(P, 1, n, axis=axis)
         - lax.slice_in_dim(P, 0, n - 1, axis=axis))
    pads = [(0, 0)] * P.ndim
    pads[axis] = (1, 1)
    return V + c * jnp.pad(d, pads)


def _make_v_get_slab(V, P, axis, c):
    """get_slab for a velocity field staggered along ``axis``: returns the
    POST-update values of the width-1 slab at ``start`` along ``dim``."""
    def get(dim, start, size):
        assert size == 1
        if dim == axis:
            if axis == 0:
                return _upd_vx_plane(V, P, start, c)
            Vs = _slab(V, dim, start)  # one face layer; needs P start-1,start
            if start < 1 or start > V.shape[dim] - 2:
                return Vs
            return Vs + c * (_slab(P, dim, start) - _slab(P, dim, start - 1))
        # slab across the staggered axis: update all its axis-faces locally
        return _upd_v_inplane(_slab(V, dim, start), _slab(P, dim, start),
                              axis, c)
    return get


def _make_p_get_slab(P, Vx, Vy, Vz, cx, cy, cz, dtK, dx, dy, dz):
    """get_slab for P: POST-update pressure on the width-1 slab, computed
    from LOCALLY updated faces only (see module docstring for why received
    faces are never needed on surviving cells)."""
    from jax import lax

    def div_term(Vn, axis, dd):
        n = Vn.shape[axis]
        return (lax.slice_in_dim(Vn, 1, n, axis=axis)
                - lax.slice_in_dim(Vn, 0, n - 1, axis=axis)) / dd

    def get(dim, start, size):
        assert size == 1
        Ps = _slab(P, dim, start)
        if dim == 0:
            vxa = _upd_vx_plane(Vx, P, start, cx)
            vxb = _upd_vx_plane(Vx, P, start + 1, cx)
            divx = (vxb - vxa) / dx
            vyn = _upd_v_inplane(_slab(Vy, 0, start), Ps, 1, cy)
            vzn = _upd_v_inplane(_slab(Vz, 0, start), Ps, 2, cz)
            return Ps - dtK * (divx + div_term(vyn, 1, dy)
                               + div_term(vzn, 2, dz))
        axis, c, dd, Vs = ((1, cy, dy, Vy) if dim == 1 else (2, cz, dz, Vz))

        def vface(g):  # updated face layer g of the staggered-axis field
            Vf = _slab(Vs, dim, g)
            if g < 1 or g > Vs.shape[dim] - 2:
                return Vf
            return Vf + c * (_slab(P, dim, g) - _slab(P, dim, g - 1))

        divs = (vface(start + 1) - vface(start)) / dd
        vxn = _upd_v_inplane(_slab(Vx, dim, start), Ps, 0, cx)
        oa, oc, od, oV = ((2, cz, dz, Vz) if dim == 1 else (1, cy, dy, Vy))
        von = _upd_v_inplane(_slab(oV, dim, start), Ps, oa, oc)
        return Ps - dtK * (div_term(vxn, 0, dx) + divs
                           + div_term(von, oa, od))
    return get


from .pallas_common import self_deliver as _self_deliver


def _wave_plane_body(g, nx, p_m, p_c, p_p, vx_c, vx_p, vy_c, vz_c,
                     rP, rVx, rVy, rVz, *, modes, cx, cy, cz, dtK,
                     dx, dy, dz, self_ols=None):
    """The fused-step arithmetic for ONE global x-plane ``g``: velocity
    updates, velocity halo delivery, pressure update from the delivered
    faces, pressure halo delivery. Shared by the plane-per-program and
    multi-plane-window kernels. Returns (p_new, vx, vy, vz).

    ``self_ols`` (all-self-neighbor grids): ``{field: (ol_y, ol_z)}`` —
    y/z halos become in-plane selects via `_self_deliver` (the r* dicts
    then carry only the "x" slabs)."""
    import jax.numpy as jnp

    ny, nz = p_c.shape

    # --- velocity updates (interior faces only; x-masks are dynamic in g)
    vx = jnp.where((g >= 1) & (g <= nx - 1), vx_c + cx * (p_c - p_m), vx_c)
    vxp = jnp.where(g + 1 <= nx - 1, vx_p + cx * (p_p - p_c), vx_p)
    dyv = p_c[1:, :] - p_c[:-1, :]
    vy = vy_c + cy * jnp.pad(dyv, ((1, 1), (0, 0)))
    dzv = p_c[:, 1:] - p_c[:, :-1]
    vz = vz_c + cz * jnp.pad(dzv, ((0, 0), (1, 1)))

    if self_ols is not None:
        vx = _self_deliver(vx, g, nx, modes["Vx"], None, *self_ols["Vx"])
        vy = _self_deliver(vy, g, nx, modes["Vy"], rVy["x"], *self_ols["Vy"])
        vz = _self_deliver(vz, g, nx, modes["Vz"], rVz["x"], *self_ols["Vz"])
        divx = (vxp - vx) / dx
        divy = (vy[1:, :] - vy[:-1, :]) / dy
        divz = (vz[:, 1:] - vz[:, :-1]) / dz
        p_new = p_c - dtK * (divx + divy + divz)
        p_new = _self_deliver(p_new, g, nx, modes["P"], rP["x"],
                              *self_ols["P"])
        return p_new, vx, vy, vz

    # --- velocity halo delivery (z, x, y; Vx's x planes are post-kernel)
    vx = _deliver(vx, g, nx, modes["Vx"], None, rVx["y"], rVx["z"],
                  ny - 1, nz - 1)
    vy = _deliver(vy, g, nx, modes["Vy"], rVy["x"], rVy["y"], rVy["z"],
                  ny, nz - 1)
    vz = _deliver(vz, g, nx, modes["Vz"], rVz["x"], rVz["y"], rVz["z"],
                  ny - 1, nz)

    # --- pressure update from the DELIVERED faces (vxp undelivered: its
    # values only reach P halo cells, where they match the sequential
    # semantics — see module docstring)
    divx = (vxp - vx) / dx
    divy = (vy[1:, :] - vy[:-1, :]) / dy
    divz = (vz[:, 1:] - vz[:, :-1]) / dz
    p_new = p_c - dtK * (divx + divy + divz)
    p_new = _deliver(p_new, g, nx, modes["P"], rP["x"], rP["y"], rP["z"],
                     ny - 1, nz - 1)
    return p_new, vx, vy, vz


from .pallas_common import recv_kinds as _wave_recv_kinds


def _wave_kernel(*refs, nx, modes, cx, cy, cz, dtK, dx, dy, dz,
                 self_ols=None, relay=True):
    """Plane-per-program form of the fused step (`_wave_plane_body`).
    With ``relay``, P[i-1] arrives by VMEM relay instead of a third HBM
    pressure stream."""
    from jax.experimental import pallas as pl

    from .pallas_common import take_recvs

    it = iter(refs)
    if relay:
        p_c, p_p = (next(it)[0] for _ in range(2))
    else:
        p_m, p_c, p_p = (next(it)[0] for _ in range(3))
    vx_c, vx_p = (next(it)[0] for _ in range(2))
    vy_c = next(it)[0]
    vz_c = next(it)[0]
    kinds = dict(_wave_recv_kinds(self_ols is not None))
    rP = take_recvs(it, modes, "P", kinds["P"])
    rVx = take_recvs(it, modes, "Vx", kinds["Vx"])
    rVy = take_recvs(it, modes, "Vy", kinds["Vy"])
    rVz = take_recvs(it, modes, "Vz", kinds["Vz"])

    i = pl.program_id(0)
    if relay:
        from .pallas_common import plane_relay

        oP, oVx, oVy, oVz = refs[-5:-1]
        p_m = plane_relay(refs[-1], i, p_c)
    else:
        oP, oVx, oVy, oVz = refs[-4:]
    p_new, vx, vy, vz = _wave_plane_body(
        i, nx, p_m, p_c, p_p, vx_c, vx_p, vy_c, vz_c, rP, rVx, rVy, rVz,
        modes=modes, cx=cx, cy=cy, cz=cz, dtK=dtK, dx=dx, dy=dy, dz=dz,
        self_ols=self_ols)
    oP[0] = p_new
    oVx[0] = vx
    oVy[0] = vy
    oVz[0] = vz


# The wave kernel keeps more per-plane temporaries live than the diffusion
# stencil (three P planes, five velocity planes, div terms, p_new, recvs) —
# its own slack constant, sized above the stencil's 6.
_WAVE_TEMP_PLANES = 12


def wave_mp_planes(p_shape, dtype, interpret=False):
    """Plane count P for the multi-plane acoustic kernel, or None.

    VMEM model (in P-plane units of the pressure plane): double-buffered
    manual windows for P (2*(P+2)) and Vx (2*(P+1)), auto-pipelined Vy/Vz
    input blocks (2P each, slightly larger), and double-buffered outputs
    for all four fields (~8P) — ~(18P + 6) planes plus temporaries.
    Lane/sublane-unaligned planes cannot use the manual window DMA
    (`pallas_stencil.window_dma_ok` — a Mosaic-compile-only constraint:
    interpret mode skips it, keeping the kernel under test at small
    shapes) and take the plane-per-program form."""
    from .pallas_stencil import (
        _MP_VMEM_BUDGET, _compute_itemsize, window_dma_ok,
    )

    nx, ny, nz = (int(v) for v in p_shape)
    import numpy as np

    if not interpret and not window_dma_ok((ny, nz), dtype):
        return None
    plane_store = ny * nz * np.dtype(dtype).itemsize
    plane_compute = ny * nz * _compute_itemsize(np.dtype(dtype))
    for P in (8, 4):
        if nx % P or nx < 2 * P:
            continue
        if (18 * P + 6) * plane_store \
                + _WAVE_TEMP_PLANES * plane_compute <= _MP_VMEM_BUDGET:
            return P
    return None


def _wave_mp_kernel(*refs, nx, P, modes, cx, cy, cz, dtK, dx, dy, dz,
                    self_ols=None, handoff=False):
    """Multi-plane form: P output planes per program; the pressure planes
    come from a double-buffered (P+2)-window and the Vx faces from a
    (P+1)-window (faces g0..g0+P — exact, no clamping), cutting their HBM
    reads from 3x/2x to (1+2/P)x/(1+1/P)x — and to 1.0x pressure reads
    with the VMEM window handoff (`handoff`, >= 3 windows)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from .pallas_stencil import (
        _window_pipeline, _window_pipeline_aligned_handoff,
        _window_pipeline_general, _window_pipeline_handoff,
    )

    it = iter(refs)
    P_hbm = next(it)
    Vx_hbm = next(it)
    vy_blk = next(it)                              # (P, ny+1, nz)
    vz_blk = next(it)                              # (P, ny, nz+1)
    # x recvs arrive as (2, rows, cols) constants; y/z recvs as
    # (P, 2, cols)/(P, rows, 2) per-plane blocks — load raw here (same
    # field/kind iteration order as `add_recv_operands`/`take_recvs`).
    from .pallas_common import AXIS_OF

    got = {}
    for field, kinds in _wave_recv_kinds(self_ols is not None):
        d = {}
        for k in kinds:
            if not modes[field][AXIS_OF[k]]:
                d[k] = None
                continue
            d[k] = next(it)[...]
        got[field] = d
    # outs (4) precede scratches (4: P window, Vx window, 2 sem arrays)
    oP, oVx, oVy, oVz = refs[-8:-4]
    p_scr, vx_scr, p_sems, vx_sems = refs[-4:]

    g0 = pl.program_id(0) * P
    if handoff:   # static: VMEM overlap handoff — 1.0x pressure reads and
        # (nx+1)-plane total Vx fetches (the aligned window's uniform
        # 1-plane overlap is handed across instead of re-read)
        p_win, l0 = _window_pipeline_handoff(P_hbm, p_scr, p_sems,
                                             nx=nx, B=P)
        vx_win = _window_pipeline_aligned_handoff(
            Vx_hbm, vx_scr, vx_sems, size=P + 1, B=P)
    else:
        p_win, l0 = _window_pipeline(P_hbm, p_scr, p_sems, nx=nx, B=P)
        vx_win = _window_pipeline_general(
            Vx_hbm, vx_scr, vx_sems, size=P + 1, start_fn=lambda g: g * P)

    def per_plane(field, k, j):
        r = got[field][k]
        if r is None:
            return None
        return r if k == "x" else r[j]

    kinds = dict(_wave_recv_kinds(self_ols is not None))
    for j in range(P):
        g = g0 + j
        l = l0 + j
        p_m = p_win[pl.ds(jnp.maximum(l - 1, 0), 1)][0]
        p_c = p_win[pl.ds(l, 1)][0]
        p_p = p_win[pl.ds(jnp.minimum(l + 1, P + 1), 1)][0]
        vx_c = vx_win[pl.ds(j, 1)][0]
        vx_p = vx_win[pl.ds(j + 1, 1)][0]
        rPj = {k: per_plane("P", k, j) for k in kinds["P"]}
        rVxj = {k: per_plane("Vx", k, j) for k in kinds["Vx"]}
        rVyj = {k: per_plane("Vy", k, j) for k in kinds["Vy"]}
        rVzj = {k: per_plane("Vz", k, j) for k in kinds["Vz"]}
        p_new, vx, vy, vz = _wave_plane_body(
            g, nx, p_m, p_c, p_p, vx_c, vx_p, vy_blk[j], vz_blk[j],
            rPj, rVxj, rVyj, rVzj,
            modes=modes, cx=cx, cy=cy, cz=cz, dtK=dtK, dx=dx, dy=dy, dz=dz,
            self_ols=self_ols)
        oP[j] = p_new
        oVx[j] = vx
        oVy[j] = vy
        oVz[j] = vz


def acoustic_step_exchange_pallas(state, gg, modes, *, rho, K, dt,
                                  dx, dy, dz, interpret=False):
    """One fused acoustic step (updates + full exchange of all four fields)
    for arbitrary shardings. ``modes`` from `wave_exchange_modes`."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    from .halo import exchange_recv_slabs_multi
    from .precision import resolve_wire_dtype

    P, Vx, Vy, Vz = state
    nx, ny, nz = P.shape
    dtp = P.dtype.type
    cx, cy, cz = (dtp(-dt / rho / d) for d in (dx, dy, dz))
    dtK = dtp(dt * K)
    dxp, dyp, dzp = (dtp(v) for v in (dx, dy, dz))
    hws = (1, 1, 1)

    # ALL-SELF fast path (single-shard periodic on every exchanging dim —
    # the reference's sendrecv_halo_local situation): y/z halos become
    # in-plane selects INSIDE the kernel and the x slabs are the raw
    # updated source planes (`_self_deliver` re-applies the z/y edits), so
    # the whole slab pipeline (per-dim mini-computes, corner patching,
    # local swaps — measured at ~2/3 of the step on v5e) collapses to at
    # most four 2-plane computes.
    from .pallas_common import all_self_exchange, self_recvs_and_ols

    getters = {
        "Vx": _make_v_get_slab(Vx, P, 0, cx),
        "Vy": _make_v_get_slab(Vy, P, 1, cy),
        "Vz": _make_v_get_slab(Vz, P, 2, cz),
        "P": _make_p_get_slab(P, Vx, Vy, Vz, cx, cy, cz, dtK, dxp, dyp, dzp),
    }
    shapes = {"P": P.shape, "Vx": Vx.shape, "Vy": Vy.shape, "Vz": Vz.shape}
    all_self = all_self_exchange(gg, modes)
    self_ols = None
    if all_self:
        recvs, self_ols = self_recvs_and_ols(gg, shapes, modes, getters)
    else:
        # the shared packed pipeline: ONE ppermute pair per mesh axis for
        # all four fields (the same canonical wire schema — and the same
        # wire POLICY — the XLA tier ships; `exchange_recv_slabs_multi`)
        recvs = exchange_recv_slabs_multi(gg, shapes, hws, modes, getters,
                                          wire=resolve_wire_dtype(None))

    def spec(shape, index_map):
        return pl.BlockSpec(shape, index_map)

    Pmp = wave_mp_planes(P.shape, P.dtype, interpret=interpret)
    mp = Pmp is not None
    B = Pmp if mp else 1

    if mp:
        operands = [P, Vx, Vy, Vz]
        in_specs = [
            pl.BlockSpec(memory_space=pl.ANY),      # P: manual DMA window
            pl.BlockSpec(memory_space=pl.ANY),      # Vx: manual DMA window
            spec((B, ny + 1, nz), lambda i: (i, 0, 0)),
            spec((B, ny, nz + 1), lambda i: (i, 0, 0)),
        ]
    else:
        from .pallas_stencil import plane_relay_enabled

        relay = plane_relay_enabled()
        operands = ([P, P, Vx, Vx, Vy, Vz] if relay
                    else [P, P, P, Vx, Vx, Vy, Vz])
        in_specs = [
            spec((1, ny, nz), lambda i: (jnp.maximum(i - 1, 0), 0, 0)),
            spec((1, ny, nz), lambda i: (i, 0, 0)),
            spec((1, ny, nz), lambda i: (jnp.minimum(i + 1, nx - 1), 0, 0)),
            spec((1, ny, nz), lambda i: (i, 0, 0)),
            spec((1, ny, nz), lambda i: (i + 1, 0, 0)),
            spec((1, ny + 1, nz), lambda i: (i, 0, 0)),
            spec((1, ny, nz + 1), lambda i: (i, 0, 0)),
        ]
        if relay:
            del in_specs[0]   # P[i-1]: replaced by the VMEM relay

    from .pallas_common import add_recv_operands, out_shape_with_vma

    def add_recvs(field, kinds, shapes_specs):
        add_recv_operands(operands, in_specs, modes, recvs, field, kinds,
                          shapes_specs)

    c0 = lambda i: (0, 0, 0)
    ci = lambda i: (i, 0, 0)
    all_specs = {
        "P": [(0, (2, ny, nz), c0), (1, (B, 2, nz), ci),
              (2, (B, ny, 2), ci)],
        "Vx": [(1, (B, 2, nz), ci), (2, (B, ny, 2), ci)],
        "Vy": [(0, (2, ny + 1, nz), c0), (1, (B, 2, nz), ci),
               (2, (B, ny + 1, 2), ci)],
        "Vz": [(0, (2, ny, nz + 1), c0), (1, (B, 2, nz + 1), ci),
               (2, (B, ny, 2), ci)],
    }
    from .pallas_common import add_all_recvs

    add_all_recvs(operands, in_specs, modes, recvs, all_specs, all_self)

    def out_shape_of(a):
        return out_shape_with_vma(a, operands)

    kmod = {k: tuple(bool(b) for b in v) for k, v in modes.items()}
    out_specs = [
        pl.BlockSpec((B, ny, nz), lambda i: (i, 0, 0)),
        pl.BlockSpec((B, ny, nz), lambda i: (i, 0, 0)),
        pl.BlockSpec((B, ny + 1, nz), lambda i: (i, 0, 0)),
        pl.BlockSpec((B, ny, nz + 1), lambda i: (i, 0, 0)),
    ]
    out_shapes = [out_shape_of(P), out_shape_of(Vx), out_shape_of(Vy),
                  out_shape_of(Vz)]
    if mp:
        from jax.experimental.pallas import tpu as pltpu

        from .pallas_stencil import _sequential_grid_params, handoff_ok

        kernel = partial(_wave_mp_kernel, nx=nx, P=Pmp, modes=kmod,
                         cx=cx, cy=cy, cz=cz, dtK=dtK, dx=dxp, dy=dyp,
                         dz=dzp, self_ols=self_ols,
                         handoff=handoff_ok(nx, Pmp))
        Pn, Vxn, Vyn, Vzn = pl.pallas_call(
            kernel,
            grid=(nx // Pmp,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            scratch_shapes=[pltpu.VMEM((2, Pmp + 2, ny, nz), P.dtype),
                            pltpu.VMEM((2, Pmp + 1, ny, nz), Vx.dtype),
                            pltpu.SemaphoreType.DMA((2,)),
                            pltpu.SemaphoreType.DMA((2,))],
            interpret=interpret,
            **_sequential_grid_params(interpret),
        )(*operands)
    else:
        kernel = partial(
            _wave_kernel, nx=nx, modes=kmod, relay=relay,
            cx=cx, cy=cy, cz=cz, dtK=dtK, dx=dxp, dy=dyp, dz=dzp,
            self_ols=self_ols)
        if relay:
            from jax.experimental.pallas import tpu as pltpu

            from .pallas_stencil import _sequential_grid_params

            extra = dict(
                scratch_shapes=[pltpu.VMEM((2, ny, nz), P.dtype)],
                **_sequential_grid_params(interpret),
            )
        else:
            extra = {}
        Pn, Vxn, Vyn, Vzn = pl.pallas_call(
            kernel,
            grid=(nx,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shapes,
            interpret=interpret,
            **extra,
        )(*operands)

    # The kernel wrote Vx planes 0..nx-1 of the (nx+1)-plane output; plane
    # nx is ALWAYS written here (it would otherwise be uninitialized), and
    # plane 0 is rewritten with its final value (`vx_extra_plane_slabs`).
    from .pallas_common import vx_extra_plane_slabs
    from .pallas_halo import halo_write_inplace

    if all_self:
        from .pallas_common import vx_extra_planes_self

        plane0, planeN = vx_extra_planes_self(
            Vx, Vxn, recvs["Vx"], modes["Vx"], self_ols["Vx"], nx)
    else:
        plane0, planeN = vx_extra_plane_slabs(Vx, Vxn, recvs["Vx"],
                                              modes["Vx"], nx)
    Vxn = halo_write_inplace(Vxn, plane0, planeN, dim=0, hw=1,
                             interpret=interpret)
    return (Pn, Vxn, Vyn, Vzn)
