"""Fused Pallas pass for the pseudo-transient Stokes iteration — the kernel
tier for BASELINE config 5 (`models/stokes.py`).

One PT iteration reads the 8-field state and writes 7 arrays, with a
4-field halo exchange at the end. The XLA formulation materializes the
stress intermediates and pays ~2 extra passes for the exchange unpack; this
module runs the WHOLE iteration — divergence, pressure, stresses, damped
momentum, velocity updates, AND the (Vx, Vy, Vz, Pn) halo delivery — as one
plane-pipelined Pallas pass (the Stokes analog of
`pallas_wave.acoustic_step_exchange_pallas`).

Soundness of fusing the exchange: every update reads only the PRE-step
state (the sequential order is update-everything, then exchange), so the
send slabs are computed from local thin windows. The slab computes reuse
`models.stokes._stokes_terms` on MINI-STATES — all 8 fields sliced to a
3-cell (cell-target) or 2-cell (face-target) window around the slab — whose
central values are exactly the full-step values (the stencil radius fits
the window; `_inner`'s trims align the mini interior with the target).
Received slabs flow through the shared PACKED pipeline
(`exchange_recv_slabs_multi`: the 4 exchanged fields' slabs ride ONE
ppermute pair per mesh axis on the canonical wire schema — wire policy
included — plus local swaps / PROC_NULL masking / per-field corner
patching), and are delivered in the kernel's output pass in the
reference's z, x, y order. Vx's extra face plane (and dVx's, which is not exchanged) is
written post-kernel like the acoustic kernel's.

Requires the full-size face-aligned dV state of `init_stokes3d` and
halowidth-1 grids; `stokes_exchange_modes` gates eligibility.
"""

from __future__ import annotations

from functools import partial

from .pallas_common import slab1 as _slab

__all__ = ["stokes_exchange_modes", "stokes_step_exchange_pallas"]


def stokes_exchange_modes(gg, shapes):
    """Per-field participation modes for the fused PT iteration, or None.

    ``shapes`` = the 8 state shapes (P, Vx, Vy, Vz, dVx, dVy, dVz, rhog).
    Eligible when the shapes follow the model's staggering pattern (dV
    mirroring V) and every halowidth is 1. Returns ``{"P": modes, "Vx":
    ..., "Vy": ..., "Vz": ...}`` for the exchanged fields (all-False modes
    mean a pure fused update with no deliveries)."""
    from .halo import _dim_exchanges

    sp, sx, sy, sz, sdx, sdy, sdz, srh = (
        tuple(int(v) for v in s) for s in shapes)
    if len(sp) != 3 or sp[0] < 3:
        return None
    if sp != tuple(int(n) for n in gg.nxyz) or srh != sp:
        return None
    nx, ny, nz = sp
    if sx != (nx + 1, ny, nz) or sy != (nx, ny + 1, nz) \
            or sz != (nx, ny, nz + 1):
        return None
    if (sdx, sdy, sdz) != (sx, sy, sz):
        return None
    if any(int(h) != 1 for h in gg.halowidths):
        return None
    hws = (1, 1, 1)
    out = {}
    for name, s in (("P", sp), ("Vx", sx), ("Vy", sy), ("Vz", sz)):
        out[name] = tuple(_dim_exchanges(gg, s, hws, d) for d in range(3))
    # all-False modes are still eligible: the kernel then fuses the whole
    # PT iteration into one pass with no deliveries (single-chip
    # non-periodic — the BASELINE bench configuration)
    return out


def _mini_state(state, dim, lo, hi):
    """All 8 fields sliced to the cell-window ``[lo, hi)`` along ``dim``
    (face-staggered fields get one extra layer)."""
    from jax import lax

    nc = state[0].shape[dim]
    out = []
    for a in state:
        hi_a = hi + 1 if a.shape[dim] == nc + 1 else hi
        out.append(lax.slice_in_dim(a, lo, hi_a, axis=dim))
    return tuple(out)


def _pn_get_slab(state, p):
    """get_slab for Pn: the pressure update on a width-1 cell window (the
    update is unmasked — every cell, incl. boundaries, gets it). Computed
    directly (same div+update arithmetic as `_stokes_terms`) because the
    1-cell window is too narrow for the stress terms' `_inner` trims."""
    from ..models.stokes import _d

    def get(dim, start, size):
        assert size == 1
        Pm, Vxm, Vym, Vzm = _mini_state(state, dim, start, start + 1)[:4]
        divV = (_d(Vxm, 0) / p.dx + _d(Vym, 1) / p.dy + _d(Vzm, 2) / p.dz)
        return Pm - p.dt_p * divV
    return get


def _v_get_slab(state, p, which):
    """get_slab for velocity ``which`` (0=x,1=y,2=z): the full PT update on
    a mini-state window; non-interior targets return raw slices (faces on
    the global boundary are never updated)."""
    from ..models.stokes import _stokes_terms

    V = state[1 + which]

    def get(dim, start, size):
        assert size == 1
        n = V.shape[dim]
        if start < 1 or start > n - 2:
            return _slab(V, dim, start)
        stag = which == dim
        lo, hi = (start - 1, start + 1) if stag else (start - 1, start + 2)
        mini = _mini_state(state, dim, lo, hi)
        terms = _stokes_terms(mini, p)
        R = terms[2:][which]                  # (Rx, Ry, Rz)[which]
        Vm = mini[1 + which]
        dVm = mini[4 + which]
        ix = (slice(1, -1),) * 3
        dnew = p.damp * dVm[ix] + R
        Vn = Vm.at[ix].add(p.dt_v * dnew)
        return _slab(Vn, dim, start - lo)
    return get


from .pallas_common import recv_kinds as _stokes_recv_kinds


def _stokes_kernel(*refs, nx, modes, mu, dt_v, dt_p, damp, dx, dy, dz,
                   self_ols=None, relay=True):
    """One x-plane of the fused PT iteration. Arithmetic mirrors
    `models.stokes._stokes_terms` term-for-term (same accumulation order)
    restricted to this plane; then the interior-masked dV/V updates and the
    halo deliveries (z, x, y per field; Vx's x planes post-kernel).

    Every intermediate stays at FULL plane size, positioned on a canonical
    grid and shifted with the edge-cloning operators of `pallas_common`
    (Mosaic cannot lower interior-slice-then-pad — see `shift_up`); edge
    garbage only ever reaches rows/lanes the interior masks cut away.
    Canonical grids: cell quantities on (ny, nz); x-y edge stresses
    ``txyE[e] = txy(edge e-1/2)`` on (ny, nz); x-z edges ``txzE[:, f]`` on
    (ny, nz); y-z edges ``tyzE[f, g]`` on (ny, nz) (valid from index 1 in
    each edge direction)."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    from .pallas_common import deliver_recvs as _deliver
    from .pallas_common import shift_down, shift_left, shift_right, shift_up

    it = iter(refs)
    if relay:
        # the [i-1] planes arrive by VMEM relay (below), not HBM streams
        p_c = next(it)[0]
        vxc, vxp = (next(it)[0] for _ in range(2))
        vyc, vyp = (next(it)[0] for _ in range(2))
        vzc, vzp = (next(it)[0] for _ in range(2))
    else:
        p_m, p_c = (next(it)[0] for _ in range(2))
        vxm, vxc, vxp = (next(it)[0] for _ in range(3))
        vym, vyc, vyp = (next(it)[0] for _ in range(3))
        vzm, vzc, vzp = (next(it)[0] for _ in range(3))
    dvxc = next(it)[0]
    dvyc = next(it)[0]
    dvzc = next(it)[0]
    rhc = next(it)[0]

    from .pallas_common import take_recvs

    kinds = dict(_stokes_recv_kinds(self_ols is not None))
    rP = take_recvs(it, modes, "P", kinds["P"])
    rVx = take_recvs(it, modes, "Vx", kinds["Vx"])
    rVy = take_recvs(it, modes, "Vy", kinds["Vy"])
    rVz = take_recvs(it, modes, "Vz", kinds["Vz"])

    i = pl.program_id(0)
    if relay:
        from .pallas_common import plane_relay

        oP, oVx, oVy, oVz, odVx, odVy, odVz = refs[-11:-4]
        relP, relVx, relVy, relVz = refs[-4:]
        p_m = plane_relay(relP, i, p_c)
        vxm = plane_relay(relVx, i, vxc)
        vym = plane_relay(relVy, i, vyc)
        vzm = plane_relay(relVz, i, vzc)
    else:
        oP, oVx, oVy, oVz, odVx, odVy, odVz = refs[-7:]
    ny, nz = p_c.shape

    def d_y(a):  # cell-centred face difference (full size: (ny+1,.) -> (ny,.))
        return a[1:, :] - a[:-1, :]

    def d_z(a):
        return a[:, 1:] - a[:, :-1]

    # --- _stokes_terms restricted to cells i (c) and i-1 (m) --------------
    divc = (vxp - vxc) / dx + d_y(vyc) / dy + d_z(vzc) / dz
    divm = (vxc - vxm) / dx + d_y(vym) / dy + d_z(vzm) / dz
    pnc = p_c - dt_p * divc
    pnm = p_m - dt_p * divm
    txxc = 2 * mu * ((vxp - vxc) / dx - divc / 3)
    txxm = 2 * mu * ((vxc - vxm) / dx - divm / 3)
    tyyc = 2 * mu * (d_y(vyc) / dy - divc / 3)
    tzzc = 2 * mu * (d_z(vzc) / dz - divc / 3)
    # edge stresses on canonical full-size grids: txyE[e] at y-edge e-1/2 of
    # the x-edge carried by face i; txyEp at face i+1 (valid rows e >= 1)
    txyE = mu * ((vxc - shift_down(vxc)) / dy + (vyc - vym)[:ny] / dx)
    txyEp = mu * ((vxp - shift_down(vxp)) / dy + (vyp - vyc)[:ny] / dx)
    txzE = mu * ((vxc - shift_right(vxc)) / dz + (vzc - vzm)[:, :nz] / dx)
    txzEp = mu * ((vxp - shift_right(vxp)) / dz + (vzp - vzc)[:, :nz] / dx)
    tyzE = mu * ((vyc - shift_right(vyc))[:ny] / dz
                 + (vzc - shift_down(vzc))[:, :nz] / dy)

    # residuals, full size (same accumulation order as `_stokes_terms`):
    # RxF on cells (valid 1..ny-2, 1..nz-2), RyF on y-faces 1..ny-1 (cell
    # cols 1..nz-2), RzF on z-faces 1..nz-1 (cell rows 1..ny-2)
    RxF = (((txxc - pnc) - (txxm - pnm)) / dx
           + (shift_up(txyE) - txyE) / dy
           + (shift_left(txzE) - txzE) / dz)
    Ty = tyyc - pnc
    RyF = ((Ty - shift_down(Ty)) / dy + (txyEp - txyE) / dx
           + (shift_left(tyzE) - tyzE) / dz)
    Tz = tzzc - pnc
    RzF = ((Tz - shift_right(Tz)) / dz + (txzEp - txzE) / dx
           + (shift_up(tyzE) - tyzE) / dy
           + 0.5 * (rhc + shift_right(rhc)))

    # --- interior-masked damped-momentum + velocity updates ---------------
    row = lax.broadcasted_iota(jnp.int32, (ny, nz), 0)
    col = lax.broadcasted_iota(jnp.int32, (ny, nz), 1)
    rowy = lax.broadcasted_iota(jnp.int32, (ny + 1, nz), 0)
    coly = lax.broadcasted_iota(jnp.int32, (ny + 1, nz), 1)
    rowz = lax.broadcasted_iota(jnp.int32, (ny, nz + 1), 0)
    colz = lax.broadcasted_iota(jnp.int32, (ny, nz + 1), 1)
    face_ok = (i >= 1) & (i <= nx - 1)
    cell_ok = (i >= 1) & (i <= nx - 2)

    mx = face_ok & (row > 0) & (row < ny - 1) & (col > 0) & (col < nz - 1)
    dnx = damp * dvxc + RxF
    u_dvx = jnp.where(mx, dnx, dvxc)
    u_vx = jnp.where(mx, vxc + dt_v * dnx, vxc)

    my = cell_ok & (rowy > 0) & (rowy < ny) & (coly > 0) & (coly < nz - 1)
    dny = damp * dvyc + jnp.concatenate([RyF, RyF[-1:]], axis=0)
    u_dvy = jnp.where(my, dny, dvyc)
    u_vy = jnp.where(my, vyc + dt_v * dny, vyc)

    mz = cell_ok & (rowz > 0) & (rowz < ny - 1) & (colz > 0) & (colz < nz)
    dnz = damp * dvzc + jnp.concatenate([RzF, RzF[:, -1:]], axis=1)
    u_dvz = jnp.where(mz, dnz, dvzc)
    u_vz = jnp.where(mz, vzc + dt_v * dnz, vzc)

    # --- halo deliveries (z, x, y per field) ------------------------------
    if self_ols is not None:
        from .pallas_common import self_deliver

        u_vx = self_deliver(u_vx, i, nx, modes["Vx"], None,
                            *self_ols["Vx"])
        u_vy = self_deliver(u_vy, i, nx, modes["Vy"], rVy["x"],
                            *self_ols["Vy"])
        u_vz = self_deliver(u_vz, i, nx, modes["Vz"], rVz["x"],
                            *self_ols["Vz"])
        pn = self_deliver(pnc, i, nx, modes["P"], rP["x"], *self_ols["P"])
    else:
        u_vx = _deliver(u_vx, i, nx, modes["Vx"], None, rVx["y"], rVx["z"],
                        ny - 1, nz - 1)
        u_vy = _deliver(u_vy, i, nx, modes["Vy"], rVy["x"], rVy["y"],
                        rVy["z"], ny, nz - 1)
        u_vz = _deliver(u_vz, i, nx, modes["Vz"], rVz["x"], rVz["y"],
                        rVz["z"], ny - 1, nz)
        pn = _deliver(pnc, i, nx, modes["P"], rP["x"], rP["y"], rP["z"],
                      ny - 1, nz - 1)

    oP[0] = pn
    oVx[0] = u_vx
    oVy[0] = u_vy
    oVz[0] = u_vz
    odVx[0] = u_dvx
    odVy[0] = u_dvy
    odVz[0] = u_dvz


def stokes_step_exchange_pallas(state, gg, modes, p, *, interpret=False):
    """One fused PT iteration (all updates + the 4-field halo exchange) for
    arbitrary shardings. ``modes`` from `stokes_exchange_modes`."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    from .halo import exchange_recv_slabs_multi
    from .precision import resolve_wire_dtype

    P, Vx, Vy, Vz, dVx, dVy, dVz, rhog = state
    nx, ny, nz = P.shape
    dtp = P.dtype.type
    hws = (1, 1, 1)

    from .pallas_common import all_self_exchange, self_recvs_and_ols

    getters = {
        "Vx": _v_get_slab(state, p, 0),
        "Vy": _v_get_slab(state, p, 1),
        "Vz": _v_get_slab(state, p, 2),
        "P": _pn_get_slab(state, p),
    }
    shapes = {"P": P.shape, "Vx": Vx.shape, "Vy": Vy.shape, "Vz": Vz.shape}
    all_self = all_self_exchange(gg, modes)
    self_ols = None
    if all_self:
        # single-shard periodic on every exchanging dim: y/z halos become
        # in-plane selects inside the kernel, x slabs are raw updated
        # source planes (see pallas_wave / pallas_common.self_deliver)
        recvs, self_ols = self_recvs_and_ols(gg, shapes, modes, getters)
    else:
        # the shared packed pipeline: ONE ppermute pair per mesh axis for
        # the 4 exchanged fields, on the canonical wire schema + policy
        recvs = exchange_recv_slabs_multi(gg, shapes, hws, modes, getters,
                                          wire=resolve_wire_dtype(None))

    def spec(shape, index_map):
        return pl.BlockSpec(shape, index_map)

    from .pallas_stencil import plane_relay_enabled

    relay = plane_relay_enabled()
    cP = (1, ny, nz)
    cY = (1, ny + 1, nz)
    cZ = (1, ny, nz + 1)
    operands = [P, P, Vx, Vx, Vx, Vy, Vy, Vy, Vz, Vz, Vz,
                dVx, dVy, dVz, rhog]
    in_specs = [
        spec(cP, lambda i: (jnp.maximum(i - 1, 0), 0, 0)),    # P[i-1]
        spec(cP, lambda i: (i, 0, 0)),                        # P[i]
        spec(cP, lambda i: (jnp.maximum(i - 1, 0), 0, 0)),    # Vx[i-1]
        spec(cP, lambda i: (i, 0, 0)),                        # Vx[i]
        spec(cP, lambda i: (i + 1, 0, 0)),                    # Vx[i+1]
        spec(cY, lambda i: (jnp.maximum(i - 1, 0), 0, 0)),    # Vy[i-1]
        spec(cY, lambda i: (i, 0, 0)),                        # Vy[i]
        spec(cY, lambda i: (jnp.minimum(i + 1, nx - 1), 0, 0)),
        spec(cZ, lambda i: (jnp.maximum(i - 1, 0), 0, 0)),    # Vz[i-1]
        spec(cZ, lambda i: (i, 0, 0)),                        # Vz[i]
        spec(cZ, lambda i: (jnp.minimum(i + 1, nx - 1), 0, 0)),
        spec(cP, lambda i: (i, 0, 0)),                        # dVx[i]
        spec(cY, lambda i: (i, 0, 0)),                        # dVy[i]
        spec(cZ, lambda i: (i, 0, 0)),                        # dVz[i]
        spec(cP, lambda i: (i, 0, 0)),                        # rhog[i]
    ]
    if relay:
        # [i-1] streams (operand indices: P 0, Vx 2, Vy 5, Vz 8) replaced
        # by the in-kernel VMEM relay: 11 HBM input streams instead of 15
        for idx in (8, 5, 2, 0):
            del operands[idx]
            del in_specs[idx]

    from .pallas_common import add_recv_operands, out_shape_with_vma

    def add_recvs(field, kinds, shapes_specs):
        add_recv_operands(operands, in_specs, modes, recvs, field, kinds,
                          shapes_specs)

    c0 = lambda i: (0, 0, 0)
    ci = lambda i: (i, 0, 0)
    all_specs = {
        "P": [(0, (2, ny, nz), c0), (1, (1, 2, nz), ci),
              (2, (1, ny, 2), ci)],
        "Vx": [(1, (1, 2, nz), ci), (2, (1, ny, 2), ci)],
        "Vy": [(0, (2, ny + 1, nz), c0), (1, (1, 2, nz), ci),
               (2, (1, ny + 1, 2), ci)],
        "Vz": [(0, (2, ny, nz + 1), c0), (1, (1, 2, nz + 1), ci),
               (2, (1, ny, 2), ci)],
    }
    from .pallas_common import add_all_recvs

    add_all_recvs(operands, in_specs, modes, recvs, all_specs, all_self)

    def out_shape_of(a):
        return out_shape_with_vma(a, operands)

    kernel = partial(
        _stokes_kernel, nx=nx, relay=relay,
        modes={k: tuple(bool(b) for b in v) for k, v in modes.items()},
        mu=dtp(p.mu), dt_v=dtp(p.dt_v), dt_p=dtp(p.dt_p), damp=dtp(p.damp),
        dx=dtp(p.dx), dy=dtp(p.dy), dz=dtp(p.dz), self_ols=self_ols)

    if relay:
        from jax.experimental.pallas import tpu as pltpu

        from .pallas_stencil import _sequential_grid_params

        extra = dict(
            scratch_shapes=[pltpu.VMEM((2, ny, nz), P.dtype),
                            pltpu.VMEM((2, ny, nz), Vx.dtype),
                            pltpu.VMEM((2, ny + 1, nz), Vy.dtype),
                            pltpu.VMEM((2, ny, nz + 1), Vz.dtype)],
            **_sequential_grid_params(interpret),  # relay needs in-order
        )
    else:
        extra = {}

    Pn, Vxn, Vyn, Vzn, dVxn, dVyn, dVzn = pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=in_specs,
        out_specs=[
            spec(cP, lambda i: (i, 0, 0)),
            spec(cP, lambda i: (i, 0, 0)),
            spec(cY, lambda i: (i, 0, 0)),
            spec(cZ, lambda i: (i, 0, 0)),
            spec(cP, lambda i: (i, 0, 0)),
            spec(cY, lambda i: (i, 0, 0)),
            spec(cZ, lambda i: (i, 0, 0)),
        ],
        out_shape=[out_shape_of(P), out_shape_of(Vx), out_shape_of(Vy),
                   out_shape_of(Vz), out_shape_of(dVx), out_shape_of(dVy),
                   out_shape_of(dVz)],
        interpret=interpret,
        **extra,
    )(*operands)

    # Vx plane nx (the kernel grid covers planes 0..nx-1): delivered like
    # the acoustic kernel's; dVx plane nx is never updated nor exchanged —
    # rewritten with its raw values.
    from .pallas_common import vx_extra_plane_slabs, vx_extra_planes_self
    from .pallas_halo import halo_write_inplace

    if all_self:
        plane0, planeN = vx_extra_planes_self(
            Vx, Vxn, recvs["Vx"], modes["Vx"], self_ols["Vx"], nx)
    else:
        plane0, planeN = vx_extra_plane_slabs(Vx, Vxn, recvs["Vx"],
                                              modes["Vx"], nx)
    Vxn = halo_write_inplace(Vxn, plane0, planeN, dim=0, hw=1,
                             interpret=interpret)
    dVxn = halo_write_inplace(
        dVxn, lax.slice_in_dim(dVx, 0, 1, axis=0),
        lax.slice_in_dim(dVx, nx, nx + 1, axis=0), dim=0, hw=1,
        interpret=interpret)
    return (Pn, Vxn, Vyn, Vzn, dVxn, dVyn, dVzn, rhog)
