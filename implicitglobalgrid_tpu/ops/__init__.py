"""ops subpackage of implicitglobalgrid_tpu."""
