"""Field abstraction: array + per-field halo widths.

TPU-native analog of the reference's field layer (`/root/reference/src/shared.jl:43-55,133-148`):
a "field" is a NamedTuple ``(A, halowidths)``; plain arrays are auto-wrapped
with the grid-default halowidths (`shared.jl:139-143`); pytrees of arrays take
the role of CellArrays (`shared.jl:133-137` extract) — struct-of-arrays is the
native JAX layout, so `extract` simply flattens the pytree leaves.

Two array layouts are understood everywhere:

- **stacked/global layout** — one `jax.Array` of shape ``dims * local_shape``
  sharded over the mesh; each device shard is exactly the reference's
  rank-local array (overlap cells duplicated between neighbors). This is the
  controller-side handle users hold between jitted steps.
- **local layout** — the per-shard block seen inside `shard_map` (what
  reference user code sees on every MPI rank).
"""

from __future__ import annotations

from typing import NamedTuple, Any

import numpy as np

from ..parallel.topology import (
    AXIS_NAMES, NDIMS, check_initialized, global_grid, ol,
)
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError

__all__ = [
    "Field", "wrap_field", "extract", "check_fields",
    "local_shape_of", "stacked_shape", "field_partition_spec", "has_halo",
]


class Field(NamedTuple):
    """A field = array + per-dimension halo widths (reference GGField,
    `shared.jl:50`)."""
    A: Any
    halowidths: tuple


def wrap_field(x, halowidths=None) -> Field:
    """Wrap ``x`` into a `Field`, defaulting halowidths from the grid
    (reference `wrap_field`, `shared.jl:139-143`). Accepts a `Field`, a
    mapping with keys ``A``/``halowidths``, or a bare array."""
    check_initialized()
    if isinstance(x, Field):
        if halowidths is not None:
            raise InvalidArgumentError("halowidths given both in the field and as argument.")
        return Field(x.A, tuple(int(h) for h in x.halowidths))
    if isinstance(x, dict) and "A" in x:
        hw = x.get("halowidths", halowidths)
        return wrap_field(x["A"], hw)
    if hasattr(x, "_fields") and "A" in getattr(x, "_fields", ()):  # NamedTuple-like
        return wrap_field(x.A, getattr(x, "halowidths", halowidths))
    if halowidths is None:
        halowidths = tuple(int(h) for h in global_grid().halowidths)
    elif np.isscalar(halowidths):
        halowidths = (int(halowidths),) * NDIMS
    else:
        halowidths = tuple(int(h) for h in halowidths)
        if len(halowidths) != NDIMS:
            raise InvalidArgumentError(f"halowidths must have {NDIMS} entries.")
    return Field(x, halowidths)


def extract(x):
    """Explode a pytree (dict/list/tuple of arrays — the CellArray analog,
    reference `extract`/`bitsarrays`, `shared.jl:133-137,174-176`) into a flat
    tuple of arrays/Fields."""
    if isinstance(x, Field) or hasattr(x, "shape"):
        return (x,)
    if isinstance(x, dict):
        if "A" in x:
            return (x,)
        return tuple(leaf for v in x.values() for leaf in extract(v))
    if isinstance(x, (list, tuple)):
        return tuple(leaf for v in x for leaf in extract(v))
    raise InvalidArgumentError(f"Unsupported field type: {type(x)}.")


# ---------------------------------------------------------------------------
# Layout inference
# ---------------------------------------------------------------------------

def local_shape_of(shape, layout: str | None = None) -> tuple:
    """Infer the LOCAL (per-shard) shape of an array of ``shape``.

    An array can be stacked/global (``shape[d] == dims[d] * l`` with ``l``
    within one overlap of ``nxyz[d]`` — staggered fields differ from nxyz by at
    most the extra staggering cells) or already local (``shape[d]`` itself
    within one overlap of ``nxyz[d]``). Staggering tolerance mirrors the
    reference's per-field overlap rule `ol(dim, A)` (`shared.jl:107`).

    ``layout`` overrides the inference: ``"local"`` (the shape IS per-shard),
    ``"stacked"`` (divide every sharded dim by ``dims[d]``), or ``None``
    (infer). Pass it when block sizes are small enough to be ambiguous
    (sizes within one overlap of ``dims*nxyz``).
    """
    if layout not in (None, "local", "stacked"):
        raise InvalidArgumentError(
            f"layout must be None, 'local' or 'stacked'; got {layout!r}.")
    gg = global_grid()
    if layout == "local":
        return tuple(int(s) for s in shape)
    local = []
    for d in range(len(shape)):
        s = int(shape[d])
        dd = int(gg.dims[d]) if d < NDIMS else 1
        n = int(gg.nxyz[d]) if d < NDIMS else 1
        tol = int(gg.overlaps[d]) + 1 if d < NDIMS else 1
        if layout == "stacked":
            if s % dd != 0:
                raise IncoherentArgumentError(
                    f"Stacked array size {s} along dimension {d} is not divisible "
                    f"by dims[{d}]={dd}.")
            local.append(s // dd)
            continue
        if dd == 1:
            local.append(s)
            continue
        # Priority: exact/±1 local match (typical staggering, reference
        # examples use nx±1) → stacked (within staggering tolerance) →
        # loosely-staggered local. Ambiguity only arises for arrays a few
        # cells big; stacked arrays are dims[d]-times larger.
        if abs(s - n) <= 1:
            local.append(s)
        elif s % dd == 0 and abs(s // dd - n) <= tol:
            local.append(s // dd)
        elif abs(s - n) <= tol:
            local.append(s)
        else:
            raise IncoherentArgumentError(
                f"Array size {s} along dimension {d} is neither a stacked-global size "
                f"(dims[{d}]={dd} times ~nxyz[{d}]={n}) nor a local size (~{n})."
            )
    return tuple(local)


def stacked_shape(local_shape) -> tuple:
    gg = global_grid()
    return tuple(
        int(gg.dims[d]) * int(local_shape[d]) if d < NDIMS else int(local_shape[d])
        for d in range(len(local_shape))
    )


def field_partition_spec(ndim: int):
    """PartitionSpec sharding the first ``ndim`` array axes over the mesh
    axes. Ranks beyond `NDIMS` lead with replicated (``None``) axes — the
    ensemble/member layout (ISSUE 12): a rank-4 array is ``(member, x, y,
    z)`` with every shard holding ALL members of its block, which is what
    lets the checkpoint/snapshot layers round-trip ensemble state with the
    same block keys as the solo run."""
    from jax.sharding import PartitionSpec as P

    if ndim > NDIMS:
        return P(*([None] * (ndim - NDIMS)), *AXIS_NAMES)
    return P(*AXIS_NAMES[:ndim])


def has_halo(local_shape, halowidths, dim: int) -> bool:
    """A field participates in the halo update along ``dim`` iff its overlap is
    at least twice its halowidth (reference `update_halo.jl:233,260,340`)."""
    if dim >= len(local_shape):
        return False
    return ol(dim, local_shape) >= 2 * int(halowidths[dim])


# ---------------------------------------------------------------------------
# Input validation (reference `check_fields`, update_halo.jl:410-472)
# ---------------------------------------------------------------------------

def check_fields(fields) -> None:
    """Validate fields for `update_halo` — the reference's seven checks
    (`update_halo.jl:410-472`), minus the ones that cannot arise with JAX
    arrays (bits-type elements, contiguity) and minus the all-same-type
    restriction, which existed only for MPI staging-buffer reuse
    (`update_halo.jl:465-471`) — XLA owns all buffers here.
    """
    # halowidth < 1 (reference :411-417)
    bad = [i for i, f in enumerate(fields)
           if any(int(f.halowidths[d]) < 1 for d in range(min(len(f.A.shape), NDIMS)))]
    if bad:
        raise InvalidArgumentError(
            f"The field(s) at position(s) {[i + 1 for i in bad]} have a halowidth less than 1."
        )

    # no halo in any dimension (reference :419-431)
    no_halo = []
    for i, f in enumerate(fields):
        loc = local_shape_of(f.A.shape)
        if all(not has_halo(loc, f.halowidths, d) for d in range(len(loc))):
            no_halo.append(i)
    if no_halo:
        raise IncoherentArgumentError(
            f"The field(s) at position(s) {[i + 1 for i in no_halo]} have no halo; "
            "remove them from the call."
        )

    # duplicates (reference :433-439)
    dup = [(i, j) for i in range(len(fields)) for j in range(i + 1, len(fields))
           if fields[i].A is fields[j].A]
    if dup:
        i, j = dup[0]
        raise IncoherentArgumentError(
            f"The field at position {j + 1} is a duplicate of the one at position {i + 1}; "
            "remove the duplicate from the call."
        )

    # supported array type (reference :457-463): anything array-like that jnp accepts
    unsupported = [i for i, f in enumerate(fields) if not hasattr(f.A, "shape")]
    if unsupported:
        raise InvalidArgumentError(
            f"The field(s) at position(s) {[i + 1 for i in unsupported]} do not have a "
            "supported array type."
        )

    # dtype must be a numeric/bool dtype (analog of the isbits check :441-447).
    # Extended TPU float dtypes (bfloat16, fp8 — ml_dtypes extension types with
    # numpy kind 'V') are numbers too; classify via jnp.issubdtype.
    import jax.numpy as jnp

    for i, f in enumerate(fields):
        dt = np.dtype(getattr(f.A, "dtype", None) or np.asarray(f.A).dtype)
        if dt.kind not in "biufc" and not jnp.issubdtype(dt, jnp.number):
            raise InvalidArgumentError(
                f"The field at position {i + 1} has unsupported element type {dt}."
            )
