"""Shared machinery of the fused multi-field Pallas passes
(`pallas_wave.py`, `pallas_stokes.py`): recv-operand wiring, kernel-side
recv unpacking, vma-aware output shapes, and the post-kernel delivery of a
face-staggered field's extra x plane (the grid covers one plane fewer than
the array)."""

from __future__ import annotations

__all__ = ["slab1", "take_recvs", "add_recv_operands", "out_shape_with_vma",
           "vx_extra_plane_slabs", "deliver_recvs", "AXIS_OF",
           "shift_up", "shift_down", "shift_left", "shift_right",
           "self_deliver", "all_self_exchange", "self_recvs_and_ols",
           "vx_extra_planes_self", "recv_kinds", "add_all_recvs"]

AXIS_OF = {"x": 0, "y": 1, "z": 2}


# Full-size shift operators for kernel-side stencil arithmetic. Mosaic
# cannot lower `jnp.pad`/concat of values carrying DIFFERENT implicit
# sublane+lane offsets ("offset mismatch on non-concat dimension" — hit by
# interior-slice-then-pad formulations); these helpers keep every
# intermediate at full plane size with offset-0 layouts, cloning the edge
# row/lane (callers mask the garbage edge through their interior masks).

def plane_relay(rel_ref, i, cur):
    """The previous grid program's plane (``cur`` itself at i == 0,
    matching the edge-clamped ``[max(i-1, 0)]`` stream it replaces), while
    storing ``cur`` for the next program: one HBM input stream per field
    becomes a VMEM relay across the IN-ORDER grid ("arbitrary" dimension
    semantics required). ``rel_ref``: VMEM ``(2, *plane)`` scratch.
    Alignment-free — works for staggered (ny+1 / nz+1) planes where the
    manual window DMA cannot (`window_dma_ok`)."""
    import jax.numpy as jnp
    from jax import lax

    prev = rel_ref[(i + 1) % 2]
    # vector mask (scalar-predicate selects are Mosaic-fragile)
    row = lax.broadcasted_iota(jnp.int32, cur.shape, 0)
    out = jnp.where((row >= 0) & (i > 0), prev, cur)
    rel_ref[i % 2] = cur
    return out


def shift_up(a):
    """out[r] = a[r+1]; last row clones a[-1] (garbage — mask it)."""
    import jax.numpy as jnp

    return jnp.concatenate([a[1:], a[-1:]], axis=0)


def shift_down(a):
    """out[r] = a[r-1]; first row clones a[0] (garbage — mask it)."""
    import jax.numpy as jnp

    return jnp.concatenate([a[:1], a[:-1]], axis=0)


def shift_left(a):
    """out[:, c] = a[:, c+1]; last lane clones a[:, -1] (garbage)."""
    import jax.numpy as jnp

    return jnp.concatenate([a[:, 1:], a[:, -1:]], axis=1)


def shift_right(a):
    """out[:, c] = a[:, c-1]; first lane clones a[:, 0] (garbage)."""
    import jax.numpy as jnp

    return jnp.concatenate([a[:, :1], a[:, :-1]], axis=1)


def slab1(A, dim, start):
    """Width-1 slice along ``dim``."""
    from jax import lax

    return lax.slice_in_dim(A, start, start + 1, axis=dim)


def take_recvs(it, modes, field, kinds):
    """Kernel-side: pull this field's recv refs off the operand iterator.

    x recv blocks are (2, rows, cols) plane pairs — loaded whole; y/z recv
    blocks are (1, ...) per-plane streams — the leading axis is dropped.
    Non-participating kinds yield None (their operand was never passed)."""
    got = {}
    for k in kinds:
        if not modes[field][AXIS_OF[k]]:
            got[k] = None
            continue
        ref = next(it)
        got[k] = ref[...] if k == "x" else ref[0]
    return got


def add_recv_operands(operands, in_specs, modes, recvs, field, kinds,
                      shapes_specs):
    """Host-side: append a field's participating recv slabs (concatenated
    left+right) and their BlockSpecs, in the same order `take_recvs` reads
    them."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    for k, (cat, blk, imap) in zip(kinds, shapes_specs):
        if not modes[field][AXIS_OF[k]]:
            continue
        rl, rr = recvs[field][AXIS_OF[k]]
        operands.append(jnp.concatenate([rl, rr], axis=cat))
        in_specs.append(pl.BlockSpec(blk, imap))


def out_shape_with_vma(a, operands):
    """ShapeDtypeStruct for ``a`` carrying the joint mesh-axis variance of
    every operand (shard_map's vma tracking), when the jax version has it."""
    import jax

    try:
        vma = jax.typeof(a).vma
        for op in operands:
            vma = vma | jax.typeof(op).vma
        return jax.ShapeDtypeStruct(a.shape, a.dtype, vma=vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(a.shape, a.dtype)


def vx_extra_plane_slabs(Vx, Vxn, recvs_vx, modes_vx, nx):
    """Final values of an x-staggered field's planes 0 and nx.

    The fused kernels' grid has nx programs but the field has nx+1 planes:
    plane nx is delivered (or kept raw) here, and plane 0 is rewritten with
    its final value, via the in-place dim-0 halo write. The slab patching
    preserves the z, x, y exchange order: the x recv slabs already carry z
    corners (pipeline patching); the y recvs' corner rows go on top."""
    from jax import lax

    def lane_patch(plane, xpos):
        if not modes_vx[2]:
            return plane
        zl, zr = recvs_vx[2]
        zls = lax.slice_in_dim(zl, xpos, xpos + 1, axis=0)
        zrs = lax.slice_in_dim(zr, xpos, xpos + 1, axis=0)
        plane = lax.dynamic_update_slice_in_dim(plane, zls, 0, axis=2)
        return lax.dynamic_update_slice_in_dim(
            plane, zrs, plane.shape[2] - 1, axis=2)

    def row_patch(plane, xpos):
        if not modes_vx[1]:
            return plane
        yl, yr = recvs_vx[1]
        yls = lax.slice_in_dim(yl, xpos, xpos + 1, axis=0)
        yrs = lax.slice_in_dim(yr, xpos, xpos + 1, axis=0)
        plane = lax.dynamic_update_slice_in_dim(plane, yls, 0, axis=1)
        return lax.dynamic_update_slice_in_dim(
            plane, yrs, plane.shape[1] - 1, axis=1)

    if modes_vx[0]:
        rl, rr = recvs_vx[0]         # z corners already patched in-pipeline
        return row_patch(rl, 0), row_patch(rr, nx)
    # no x exchange: plane nx keeps its raw values with the z then y recvs
    # applied; plane 0 is already final in the kernel output.
    planeN = row_patch(lane_patch(
        lax.slice_in_dim(Vx, nx, nx + 1, axis=0), nx), nx)
    plane0 = lax.slice_in_dim(Vxn, 0, 1, axis=0)
    return plane0, planeN


def self_deliver(u, g, nx_planes, fmodes, rx, ol_y, ol_z):
    """ALL-SELF-NEIGHBOR delivery of one computed plane (halowidth 1).

    The single-shard-periodic analog of `deliver_recvs`, with NO received
    slabs for y/z: their halo rows/lanes are in-plane copies of the
    plane's own interior (the reference's `sendrecv_halo_local`,
    `update_halo.jl:363-380`), and the x halo planes are replaced by
    ``rx`` — the RAW updated source planes — BEFORE the selects, so the
    z-then-y edits land on them exactly as the sequential z, x, y order
    produces (an x slab extracted post-z == the raw slab with the z
    select re-applied, because z's sources are the slab's own lanes).

    ``ol_y``/``ol_z`` are the field's overlaps along y/z (source index
    ``ol-1`` fills the right halo, ``extent-ol`` the left), or None when
    that dim doesn't exchange for this field."""
    import jax.numpy as jnp
    from jax import lax

    rows, cols = u.shape
    if fmodes[0] and rx is not None:
        u = jnp.where(g == 0, rx[0], jnp.where(g == nx_planes - 1, rx[1], u))
    if fmodes[2] and ol_z is not None:
        col = lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
        u = jnp.where(col == 0, u[:, cols - ol_z:cols - ol_z + 1], u)
        u = jnp.where(col == cols - 1, u[:, ol_z - 1:ol_z], u)
    if fmodes[1] and ol_y is not None:
        row = lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
        u = jnp.where(row == 0, u[rows - ol_y:rows - ol_y + 1, :], u)
        u = jnp.where(row == rows - 1, u[ol_y - 1:ol_y, :], u)
    return u


def all_self_exchange(gg, modes) -> bool:
    """Whether every exchanging dim of a multi-field kernel takes the
    self-neighbor path (single shard, periodic) — the gate for the
    in-kernel `self_deliver` fast path."""
    exch = [d for d in range(3) if any(m[d] for m in modes.values())]
    return bool(exch) and all(
        int(gg.dims[d]) == 1 and bool(gg.periods[d]) for d in exch)


def self_recvs_and_ols(gg, shapes, modes, getters):
    """Host-side wiring of the all-self fast path: per field, the raw
    updated x source slabs (recv_l <- own right send slab and vice versa
    — `sendrecv_halo_local` routing) and the (ol_y, ol_z) select overlaps
    for `self_deliver`. Returns (recvs, self_ols)."""
    recvs = {}
    self_ols = {}
    for f, shape in shapes.items():
        ol = [int(gg.overlaps[d]) + (int(shape[d]) - int(gg.nxyz[d]))
              for d in range(3)]
        self_ols[f] = (ol[1] if modes[f][1] else None,
                       ol[2] if modes[f][2] else None)
        if modes[f][0]:
            s0 = int(shape[0])
            recvs[f] = {0: (getters[f](0, s0 - ol[0], 1),
                            getters[f](0, ol[0] - 1, 1))}
        else:
            recvs[f] = {}
    return recvs, self_ols


def recv_kinds(all_self: bool):
    """(field, kinds) recv-operand order — the kernel<->host protocol of
    every 4-field fused pass (`pallas_wave`, `pallas_stokes`); both the
    kernel-side `take_recvs` unpacking and the host-side
    `add_recv_operands` wiring iterate THIS tuple. All-self grids pass
    only the x slabs (y/z become in-plane selects, `self_deliver`)."""
    if all_self:
        return (("P", ("x",)), ("Vx", ()), ("Vy", ("x",)), ("Vz", ("x",)))
    return (("P", ("x", "y", "z")), ("Vx", ("y", "z")),
            ("Vy", ("x", "y", "z")), ("Vz", ("x", "y", "z")))


def add_all_recvs(operands, in_specs, modes, recvs, all_specs, all_self):
    """Host-side recv wiring for the 4-field fused passes: append every
    participating field/kind's slabs in `recv_kinds` order, with the
    BlockSpec rows of ``all_specs[field]`` matched by concat axis."""
    for field, kinds in recv_kinds(all_self):
        rows = [ss for k in kinds for ss in all_specs[field]
                if ss[0] == AXIS_OF[k]]
        add_recv_operands(operands, in_specs, modes, recvs, field, kinds,
                          rows)


def vx_extra_planes_self(Vx, Vxn, recvs_vx, modes_vx, ols_vx, nx):
    """Final values of an x-staggered field's planes 0 and nx on an
    ALL-SELF grid: both x halo planes come from the raw updated source
    slabs (plane 0 <- updated plane nx-ol, plane nx <- updated plane
    ol-1) with the z-then-y in-plane selects applied — the same
    order/argument as `self_deliver`. When x doesn't exchange, plane 0 is
    already final in the kernel output and plane nx keeps its raw values
    + selects."""
    from jax import lax

    ol_y, ol_z = ols_vx

    def selects(plane):
        return self_deliver(plane[0], 0, 1,
                            (False, modes_vx[1], modes_vx[2]), None,
                            ol_y, ol_z)[None]

    if modes_vx[0]:
        plane0 = selects(recvs_vx[0][0])
        planeN = selects(recvs_vx[0][1])
    else:
        plane0 = lax.slice_in_dim(Vxn, 0, 1, axis=0)
        planeN = selects(lax.slice_in_dim(Vx, nx, nx + 1, axis=0))
    return plane0, planeN


def deliver_recvs(u, i, nx_planes, modes, rx, ry, rz, row_hi, col_hi):
    """Apply a field's received halo slabs to its computed plane ``u``, in
    the reference order z, x, y. ``rx`` is None for fields whose x planes
    are written post-kernel (Vx). ``row_hi``/``col_hi`` are the last
    row/lane indices of the plane (staggered extents differ)."""
    import jax.numpy as jnp
    from jax import lax

    rows, cols = u.shape
    row = lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
    col = lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
    if modes[2]:
        u = jnp.where(col == 0, rz[:, 0:1], u)
        u = jnp.where(col == col_hi, rz[:, 1:2], u)
    if modes[0] and rx is not None:
        u = jnp.where(i == 0, rx[0], jnp.where(i == nx_planes - 1, rx[1], u))
    if modes[1]:
        u = jnp.where(row == 0, ry[0:1, :], u)
        u = jnp.where(row == row_hi, ry[1:2, :], u)
    return u
