"""Stencil difference helpers.

jnp analogs of the reference examples' broadcast stencil macros
(`/root/reference/examples/diffusion3D_multicpu_novis.jl:4-10`):
``d_xa``/``d_ya``/``d_za`` difference along an axis over the full extent of the
other axes; ``d_xi``/``d_yi``/``d_zi`` difference along an axis over the INNER
extent of the other axes; ``inn`` the interior. They work on local blocks (use
inside `shard_map`) and on stacked arrays alike, for 1-D to 3-D, and XLA fuses
them into the surrounding arithmetic (no temporaries — the TPU analog of the
reference's note that broadcast kernels leave >10x headroom vs native kernels,
`README.md:167`, is simply closed by XLA fusion here).
"""

from __future__ import annotations

__all__ = ["d_xa", "d_ya", "d_za", "d_xi", "d_yi", "d_zi", "inn"]


def _d_a(A, axis: int):
    from jax import lax

    n = A.shape[axis]
    return lax.slice_in_dim(A, 1, n, axis=axis) - lax.slice_in_dim(A, 0, n - 1, axis=axis)


def _inner_others(A, axis: int):
    from jax import lax

    for ax in range(A.ndim):
        if ax != axis:
            A = lax.slice_in_dim(A, 1, A.shape[ax] - 1, axis=ax)
    return A


def d_xa(A):
    """``A[2:end,...] - A[1:end-1,...]`` (reference `d_xa`, examples:4)."""
    return _d_a(A, 0)


def d_ya(A):
    return _d_a(A, 1)


def d_za(A):
    return _d_a(A, 2)


def d_xi(A):
    """Difference along x over the inner extent of the other dims
    (reference `d_xi`, examples:5)."""
    return _d_a(_inner_others(A, 0), 0)


def d_yi(A):
    return _d_a(_inner_others(A, 1), 1)


def d_zi(A):
    return _d_a(_inner_others(A, 2), 2)


def inn(A):
    """Interior of ``A`` (reference `inn`, examples:10)."""
    from jax import lax

    for ax in range(A.ndim):
        A = lax.slice_in_dim(A, 1, A.shape[ax] - 1, axis=ax)
    return A
