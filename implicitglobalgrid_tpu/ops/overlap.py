"""Communication/computation overlap — the `@hide_communication` analog,
generalized to whole multi-field step programs.

The reference ecosystem hides halo-exchange latency behind interior compute
via ParallelStencil's `@hide_communication` (referenced from
`/root/reference/README.md:10`; the reference package itself only enables
overlap through per-field max-priority CUDA streams,
`src/CUDAExt/update_halo.jl:157`). The TPU-native mechanism is data-flow:
XLA's latency-hiding scheduler overlaps an async collective with any compute
it does not depend on. `hide_communication` restructures one stencil step so
that dependency structure exists — the INTERIOR-FIRST step shape every
model's chunk program can take (`models/common.py`):

1. compute the updated BOUNDARY SHELL (slabs of width ``ol`` per exchanged
   dim, ``ol + stagger`` for face-staggered outputs) from thin input slabs;
2. run the halo exchange — ONE coalesced `local_update_halo` round of every
   exchanged output, whose ppermutes depend only on the shell (and ride the
   canonical wire schema, so the overlapped step keeps any reduced/quantized
   wire policy bit-for-bit);
3. compute the INTERIOR update — independent of (2), so XLA schedules it
   under the collectives;
4. stitch interior + shell + received halos.

Semantically identical to ``update(state)`` followed by ``update_halo`` (the
shell cells see exactly the same neighborhoods); verified by tests against
the plain path for all three model families, and structurally audited at
the HLO level (`ProgramIR.closure`: no SSA path between the interior update
and any collective-permute — tests/test_hlo_audit.py, golden fixture
tests/data/hlo/overlap_interior_first.stablehlo.txt).

Multi-field form: ``T`` may be a tuple of output fields (face-staggered
extents allowed, the reference's `shared.jl:107` convention) and
``update_fn(*outs, *aux) -> tuple(outs)`` the whole step update;
``n_exchange`` limits the exchange to the leading outputs (the Stokes PT
iteration updates 7 fields but wires only 4). This is what turns the
single-field helper into the default shape of a step program: the acoustic
V-round (3 staggered fields, one coalesced round) and the full Stokes
iteration route through the same four phases.
"""

from __future__ import annotations

from ..parallel.topology import check_initialized, global_grid
from ..utils.exceptions import InvalidArgumentError
from .halo import _normalize_dims_order, local_update_halo

__all__ = ["hide_communication"]


def _exchanged_dims(gg, a_ndim, dims_order):
    out = []
    for d in dims_order:
        if d >= a_ndim:
            continue
        D = int(gg.dims[d])
        if D > 1 or bool(gg.periods[d]):
            out.append(d)
    return out


def hide_communication(update_fn, T, *aux, radius: int = 1, dims=None,
                       halowidths=None, coalesce=None, wire_dtype=None,
                       n_exchange: int | None = None):
    """One overlapped (interior-first) step on LOCAL blocks (use inside
    `shard_map`): ``T_new = hide_communication(update_fn, T, Cp, ...)`` or,
    multi-field, ``Vx, Vy, Vz = hide_communication(upd, (Vx, Vy, Vz), P)``.

    ``T`` is one array or a tuple of output arrays; ``update_fn(*T_blocks,
    *aux_blocks)`` returns the updated block(s) (same structure as ``T``)
    and must be a pure local stencil of radius ``radius``: it may update
    only cells whose full neighborhood lies inside the block, leaving edge
    cells unchanged (the shape every reference-style stencil already has,
    e.g. `diffusion3D_multicpu_novis.jl:42-47`). ``radius=0`` means every
    cell's update is independent of its neighbors within the outputs (e.g.
    a divergence update from face-staggered fields).

    Output and ``aux`` arrays may be face-staggered — larger than the base
    (elementwise-minimum) extent by 0 or 1 cells per dimension (the
    reference's staggered-field convention, `shared.jl:107`): a slab of
    cells ``[lo, hi)`` takes faces ``[lo, hi + stagger)``, and a staggered
    output's shell/stitch regions grow by its stagger.

    The exchange is ONE coalesced `local_update_halo` round of the first
    ``n_exchange`` outputs (default: all of them) — one ppermute pair per
    mesh axis for the whole round on the canonical wire schema.
    ``coalesce``/``wire_dtype`` forward to it (defaults resolve from
    ``IGG_HALO_COALESCE`` / ``IGG_HALO_WIRE_DTYPE``) — a wire-precision or
    QUANTIZED run keeps its reduced wire format through the overlapped
    step, bit-identically to the plain path (the send slabs are extracted
    from the shell, whose values equal the plain update's, so per-slab
    quantization scales cannot diverge). ``halowidths`` (single-field form
    only) forwards per-field halowidths to the exchange.

    Returns the updated, halo-exchanged block(s) — semantically identical
    to ``local_update_halo(*update_fn(T, *aux))`` but with the exchange
    overlappable with the interior compute.
    """
    from jax import lax

    check_initialized()
    gg = global_grid()
    r = int(radius)
    if r < 0:
        raise InvalidArgumentError("radius must be >= 0.")
    multi = isinstance(T, (tuple, list))
    outs = tuple(T) if multi else (T,)
    nex = len(outs) if n_exchange is None else int(n_exchange)
    if not (1 <= nex <= len(outs)):
        raise InvalidArgumentError(
            f"n_exchange={n_exchange} must name 1..{len(outs)} leading "
            "outputs.")
    if multi and halowidths is not None:
        raise InvalidArgumentError(
            "halowidths is supported in the single-field form only (the "
            "multi-field exchange uses the grid halowidths).")
    dims_order = _normalize_dims_order(dims)
    ndim = outs[0].ndim
    base = tuple(min(int(o.shape[d]) for o in outs) for d in range(ndim))
    ex_dims = _exchanged_dims(gg, ndim, dims_order)

    def stagger_of(a, what):
        st = tuple(int(a.shape[d]) - base[d] for d in range(ndim))
        if any(s < 0 or s > 1 for s in st):
            raise InvalidArgumentError(
                f"hide_communication {what} arrays must match the base "
                "extent or be face-staggered (+1) per dimension.")
        return st

    out_stags = [stagger_of(o, "output") for o in outs]
    aux_stags = [stagger_of(a, "aux") for a in aux]

    def as_outs(res):
        res = tuple(res) if isinstance(res, (tuple, list)) else (res,)
        if len(res) != len(outs):
            raise InvalidArgumentError(
                f"update_fn returned {len(res)} outputs for "
                f"{len(outs)} output fields.")
        return res

    def region(arrays, stags, d, lo, hi):
        return tuple(
            lax.slice_in_dim(a, lo, hi + st[d], axis=d)
            for a, st in zip(arrays, stags)
        )

    def exchange(fields):
        if halowidths is not None:
            fields = [{"A": f, "halowidths": halowidths} for f in fields]
        out = local_update_halo(*fields, dims=dims_order, coalesce=coalesce,
                                wire_dtype=wire_dtype)
        return list(out) if isinstance(out, tuple) else [out]

    def finish(new_outs):
        return tuple(new_outs) if multi else new_outs[0]

    def plain_fallback():
        new_outs = list(as_outs(update_fn(*outs, *aux)))
        new_outs[:nex] = exchange(new_outs[:nex])
        return finish(new_outs)

    arrays = outs + aux
    all_stags = out_stags + aux_stags
    if not ex_dims:
        return finish(as_outs(update_fn(*outs, *aux)))

    shells = list(outs)
    interior_lohi = {}
    for d in ex_dims:
        s = base[d]
        ol_d = int(gg.overlaps[d])
        if s < 2 * (ol_d + r) + 1 or r > ol_d:
            # block too thin to split (or stencil radius exceeds the overlap,
            # so shell slices would go out of range): plain path
            return plain_fallback()
        # left shell: input cells [0, ol+r) -> valid output [0, ol+st)
        lsl = as_outs(update_fn(*region(arrays, all_stags, d, 0, ol_d + r)))
        # right shell: input cells [s-ol-r, s) -> valid output last ol+st
        rsl = as_outs(update_fn(
            *region(arrays, all_stags, d, s - ol_d - r, s)))
        for f in range(len(outs)):
            st = out_stags[f][d]
            w = ol_d + st
            shells[f] = lax.dynamic_update_slice_in_dim(
                shells[f], lax.slice_in_dim(lsl[f], 0, w, axis=d), 0, axis=d)
            shells[f] = lax.dynamic_update_slice_in_dim(
                shells[f], lax.slice_in_dim(rsl[f], r, r + w, axis=d),
                shells[f].shape[d] - w, axis=d)
        interior_lohi[d] = (ol_d, s - ol_d)

    # (2) exchange: ONE coalesced round, depends only on the shell slabs.
    exchanged = exchange(shells[:nex]) + shells[nex:]

    # (3) interior: input = interior grown by r in exchanged dims.
    int_in, int_stags = arrays, all_stags
    for d in ex_dims:
        lo, hi = interior_lohi[d]
        int_in = region(int_in, int_stags, d, lo - r, hi + r)
    int_out = list(as_outs(update_fn(*int_in)))
    for f in range(len(outs)):
        for d in reversed(ex_dims):
            lo, hi = interior_lohi[d]
            st = out_stags[f][d]
            int_out[f] = lax.slice_in_dim(
                int_out[f], r + st, r + (hi - lo), axis=d)

    # (4) stitch interior into the exchanged arrays. The barrier stops XLA
    # from fusing the (permute-independent) interior compute INTO the
    # stitch — which depends on every permute and would serialize the
    # interior after the collectives, defeating the whole construction
    # (observed on the CPU backend: the interior stencil landed inside the
    # ROOT stitch fusion). With the barrier, the interior stays its own
    # fusion with no path to/from the permutes, which is exactly what the
    # latency-hiding scheduler needs to run it under them
    # (tests/test_hlo_audit.py::test_overlap_interior_independent_of_permutes).
    exchanged, int_out = lax.optimization_barrier(
        (tuple(exchanged), tuple(int_out)))
    new_outs = []
    for f in range(len(outs)):
        starts = [0] * ndim
        for d in ex_dims:
            starts[d] = interior_lohi[d][0] + out_stags[f][d]
        new_outs.append(lax.dynamic_update_slice(
            exchanged[f], int_out[f], tuple(starts)))
    return finish(new_outs)
