"""Communication/computation overlap — the `@hide_communication` analog.

The reference ecosystem hides halo-exchange latency behind interior compute
via ParallelStencil's `@hide_communication` (referenced from
`/root/reference/README.md:10`; the reference package itself only enables
overlap through per-field max-priority CUDA streams,
`src/CUDAExt/update_halo.jl:157`). The TPU-native mechanism is data-flow:
XLA's latency-hiding scheduler overlaps an async collective with any compute
it does not depend on. `hide_communication` restructures one stencil step so
that dependency structure exists:

1. compute the updated BOUNDARY SHELL (slabs of width ``ol`` per exchanged
   dim) from thin input slabs;
2. run the halo exchange — its ppermutes depend only on the shell;
3. compute the INTERIOR update — independent of (2), so XLA schedules it
   under the collectives;
4. stitch interior + shell + received halos.

Semantically identical to ``update(T)`` followed by ``update_halo`` (the
shell cells see exactly the same neighborhoods); verified by tests against
the plain path.
"""

from __future__ import annotations

from ..parallel.topology import check_initialized, global_grid
from ..utils.exceptions import InvalidArgumentError
from .halo import _normalize_dims_order, local_update_halo

__all__ = ["hide_communication"]


def _exchanged_dims(gg, a_ndim, dims_order):
    out = []
    for d in dims_order:
        if d >= a_ndim:
            continue
        D = int(gg.dims[d])
        if D > 1 or bool(gg.periods[d]):
            out.append(d)
    return out


def hide_communication(update_fn, T, *aux, radius: int = 1, dims=None,
                       halowidths=None, coalesce=None, wire_dtype=None):
    """One overlapped step on a LOCAL block (use inside `shard_map`):
    ``T_new = hide_communication(update_fn, T, Cp, ...)``.

    ``coalesce``/``wire_dtype`` forward to the embedded exchange
    (`local_update_halo`; defaults resolve from ``IGG_HALO_COALESCE`` /
    ``IGG_HALO_WIRE_DTYPE``) — a wire-precision run keeps its reduced
    wire format through the overlapped step.

    ``update_fn(T_block, *aux_blocks) -> T_block_updated`` must be a pure
    local stencil of radius ``radius`` in ``T``: it may update only cells
    whose full neighborhood lies inside the block, leaving edge cells
    unchanged (the shape every reference-style stencil already has, e.g.
    `diffusion3D_multicpu_novis.jl:42-47`). ``radius=0`` means every cell's
    update is independent of its ``T`` neighbors (e.g. a divergence update
    from face-staggered fields).

    ``aux`` arrays are sliced along with ``T``; they may be face-staggered
    — larger than ``T`` by 0 or 1 cells per dimension (the reference's
    staggered-field convention, `shared.jl:107`): a slab of cells
    ``[lo, hi)`` takes aux faces ``[lo, hi + stagger)``.

    Returns the updated, halo-exchanged block — bit-identical to
    ``local_update_halo(update_fn(T, *aux))`` but with the exchange
    overlappable with the interior compute.
    """
    from jax import lax

    check_initialized()
    gg = global_grid()
    r = int(radius)
    if r < 0:
        raise InvalidArgumentError("radius must be >= 0.")
    dims_order = _normalize_dims_order(dims)
    ex_dims = _exchanged_dims(gg, T.ndim, dims_order)
    staggers = []
    for a in aux:
        st = tuple(a.shape[d] - T.shape[d] for d in range(T.ndim))
        if any(s < 0 or s > 1 for s in st):
            raise InvalidArgumentError(
                "hide_communication aux arrays must match T's shape or be "
                "face-staggered (+1) per dimension."
            )
        staggers.append(st)
    if not ex_dims:
        return update_fn(T, *aux)

    def region(arrays, stags, d, lo, hi):
        return tuple(
            lax.slice_in_dim(a, lo, hi + st[d], axis=d)
            for a, st in zip(arrays, stags)
        )

    def exchange(U):
        f = U if halowidths is None else {"A": U, "halowidths": halowidths}
        return local_update_halo(f, dims=dims_order, coalesce=coalesce,
                                 wire_dtype=wire_dtype)

    def plain_fallback():
        return exchange(update_fn(T, *aux))

    arrays = (T,) + aux
    all_stags = [(0,) * T.ndim] + staggers
    shell = T
    interior_lohi = {}
    for d in ex_dims:
        s = T.shape[d]
        ol_d = int(gg.overlaps[d])
        if s < 2 * (ol_d + r) + 1 or r > ol_d:
            # block too thin to split (or stencil radius exceeds the overlap,
            # so shell slices would go out of range): plain path
            return plain_fallback()
        # left shell: input cells [0, ol+r) -> valid output [0, ol)
        lsl = update_fn(*region(arrays, all_stags, d, 0, ol_d + r))
        shell = lax.dynamic_update_slice_in_dim(
            shell, lax.slice_in_dim(lsl, 0, ol_d, axis=d), 0, axis=d)
        # right shell: input cells [s-ol-r, s) -> valid output last ol cells
        rsl = update_fn(*region(arrays, all_stags, d, s - ol_d - r, s))
        shell = lax.dynamic_update_slice_in_dim(
            shell, lax.slice_in_dim(rsl, r, ol_d + r, axis=d), s - ol_d, axis=d)
        interior_lohi[d] = (ol_d, s - ol_d)

    # (2) exchange: depends only on the shell slabs.
    exchanged = exchange(shell)

    # (3) interior: input = interior grown by r in exchanged dims.
    int_in, int_stags = arrays, all_stags
    for d in ex_dims:
        lo, hi = interior_lohi[d]
        int_in = region(int_in, int_stags, d, lo - r, hi + r)
    int_out = update_fn(*int_in)
    for d in reversed(ex_dims):
        lo, hi = interior_lohi[d]
        int_out = lax.slice_in_dim(int_out, r, r + (hi - lo), axis=d)

    # (4) stitch interior into the exchanged array. The barrier stops XLA
    # from fusing the (permute-independent) interior compute INTO the
    # stitch — which depends on every permute and would serialize the
    # interior after the collectives, defeating the whole construction
    # (observed on the CPU backend: the interior stencil landed inside the
    # ROOT stitch fusion). With the barrier, the interior stays its own
    # fusion with no path to/from the permutes, which is exactly what the
    # latency-hiding scheduler needs to run it under them
    # (tests/test_hlo_audit.py::test_overlap_interior_independent_of_permutes).
    exchanged, int_out = lax.optimization_barrier((exchanged, int_out))
    starts = [0] * T.ndim
    for d in ex_dims:
        starts[d] = interior_lohi[d][0]
    return lax.dynamic_update_slice(exchanged, int_out, tuple(starts))
