"""Allocation of global (stacked) fields with the grid's sharding.

The reference's users allocate plain per-rank arrays (`zeros(nx, ny, nz)`,
e.g. `/root/reference/examples/diffusion3D_multicpu_novis.jl:26-31`). The
TPU-native analog allocates ONE sharded `jax.Array` whose per-device shards
are those rank-local blocks; memory lives in each chip's HBM from the start
(no host round-trip). Pass the LOCAL block shape — exactly the shape a
reference user would pass — including staggering (`zeros_g((nx+1, ny, nz))`).
"""

from __future__ import annotations

import numpy as np

from ..parallel.topology import check_initialized, global_grid
from ..utils.exceptions import InvalidArgumentError
from .fields import field_partition_spec, stacked_shape

__all__ = ["zeros_g", "ones_g", "full_g", "sharding_of", "device_put_g"]


def _default_local_shape():
    gg = global_grid()
    return tuple(int(n) for n in gg.nxyz)


def sharding_of(ndim: int):
    """NamedSharding that lays a ``ndim``-D stacked array over the grid mesh."""
    import jax

    check_initialized()
    return jax.sharding.NamedSharding(global_grid().mesh, field_partition_spec(ndim))


def full_g(local_shape=None, fill_value=0.0, dtype=None):
    """Stacked global array with every shard a ``local_shape`` block of
    ``fill_value``. ``local_shape=None`` uses the grid's ``(nx, ny, nz)``."""
    import jax.numpy as jnp

    check_initialized()
    if local_shape is None:
        local_shape = _default_local_shape()
    local_shape = tuple(int(s) for s in local_shape)
    if len(local_shape) < 1 or len(local_shape) > 3:
        raise InvalidArgumentError("local_shape must have 1 to 3 dimensions.")
    shape = stacked_shape(local_shape)
    return jnp.full(shape, fill_value, dtype=dtype, device=sharding_of(len(shape)))


def zeros_g(local_shape=None, dtype=None):
    """`zeros(nx, ny, nz)` analog (reference example
    `diffusion3D_multicpu_novis.jl:26`)."""
    return full_g(local_shape, 0.0, dtype)


def ones_g(local_shape=None, dtype=None):
    return full_g(local_shape, 1.0, dtype)


def device_put_g(A):
    """Shard a host/replicated array ``A`` (stacked layout) over the grid mesh."""
    import jax

    check_initialized()
    A = np.asarray(A) if not hasattr(A, "dtype") else A
    return jax.device_put(A, sharding_of(A.ndim))
