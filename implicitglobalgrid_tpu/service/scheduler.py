"""`MeshScheduler` — the device mesh as a persistent, multiplexed resource.

`run_resilient` owns the mesh for exactly one job; the scheduler inverts
that: IT owns the mesh (and the ops surface — the long-lived /metrics +
/healthz endpoint, the flight journal) and advances QUEUED jobs through
it in chunk-granular slices:

    sched = igg.service.MeshScheduler(policy="fair", flight_dir="/logs/q",
                                      metrics_port=9100)
    sched.submit(igg.service.JobSpec(name="a", setup=..., nt=2000,
                                     grid=dict(nx=64, ny=64, nz=64)))
    sched.submit(...)                      # different model/grid size: fine
    sched.run()                            # drain the queue
    final_states = sched.results()

Mechanics, in one paragraph: every job gets its OWN grid over the shared
device pool (`init_global_grid` at admission — jobs may have different
models, grid sizes, even decompositions) and its own `ResilientRun`
machine (checkpoint slots, snapshot writer, perf watch, audit budgets,
flight recorder — the whole PR 2-7 per-run surface becomes per-tenant).
A context switch is two pointer swaps: `topology.swap_global_grid` makes
the job's grid current WITHOUT a new epoch, and
`use_flight_recorder` routes the driver's events into the job's JSONL.
Because the compiled-program caches are epoch-keyed and scheduler-held
epochs are RETAINED (`topology.retain_epoch`), each job's chunk runners,
halo exchanges, and drain probes stay warm across switches — the cold
XLA compile is paid once, inside the first slice of the job that needs
it (visible as that job's ``cold`` chunk in its flight stream), and a
warm switch costs ~1 ms of bookkeeping (measured in bench_service.py,
gated < 2% of the chunk work a slice carries).

Isolation: a guard trip, rollback, elastic restart, or injected fault in
one job runs entirely inside that job's slice, against that job's
checkpoints, on that job's grid — the other tenants' trajectories are
bit-identical to their solo runs (the PR-2 fault-injection harness is
the tenant-isolation test bed, tests/test_service.py). A job that
exhausts its retry budget FAILS alone; the scheduler records the error
and keeps serving the rest.

Preemption is only ever at chunk boundaries (one `advance()` per granted
slice), so the scheduling policy (`fifo` | `round_robin` | `fair`)
affects latency and fairness, never results.
"""

from __future__ import annotations

import os
import time

from ..parallel import topology as top
from ..runtime.driver import ResilientRun
from ..telemetry import hooks
from ..telemetry.live import AlertEngine
from ..telemetry.recorder import FlightRecorder, use_flight_recorder
from ..telemetry.tracectx import TraceContext
from ..utils.exceptions import InvalidArgumentError
from .autoscale import Autoscaler, AutoscalePolicy
from .backend import DirectoryBackend, QueueBackend
from .job import Job, JobSpec, JobState, jobspec_from_json
from .policies import resolve_policy

__all__ = ["MeshScheduler"]


class _DeadlineRejected(Exception):
    """Internal control flow: `_admit`'s deadline pricing refused the
    job. Carries the journaled verdict record; `_slice` turns it into
    `JobState.REJECTED` (a verdict, not a failure)."""

    def __init__(self, verdict: dict):
        super().__init__(
            f"admission rejected: priced {verdict['admit_price_s']:.3g}s "
            f"of mesh time > {verdict['budget_s']:.3g}s left of "
            f"deadline_s={verdict['deadline_s']:.6g}")
        self.verdict = verdict


def _evict_epoch_caches(epoch: int) -> None:
    """Drop a finished job's compiled programs from every epoch-keyed
    cache NOW (release_epoch alone only makes them evictable later)."""
    from ..models import common
    from ..ops import halo
    from ..utils import timing

    for cache in (common._runner_cache, halo._exchange_cache,
                  halo._plan_cache, timing._drain_cache):
        for k in [k for k in cache if k[0] == epoch]:
            del cache[k]
    timing._probe_cache.pop(epoch, None)


class MeshScheduler:
    """Single-process persistent-mesh scheduler (see module docstring).

    ``policy``: ``"fifo"`` | ``"round_robin"`` | ``"fair"`` (or a
    `SchedulingPolicy` instance). ``flight_dir``: per-job flight JSONLs
    (``job_<name>.jsonl``) plus the scheduler's own journal
    (``scheduler.jsonl``) land here — `igg.run_report(flight_dir)`
    reconstructs the interleaved schedule and
    `service.export_service_trace` renders one Perfetto track per job;
    the directory doubles as the CLI's control channel (`tools jobs
    cancel|drain|resize` file requests, polled at slice boundaries).
    ``metrics_port`` starts the scheduler-OWNED live endpoint for the
    scheduler's lifetime: per-job labeled gauges, queue depth, and a
    /healthz that judges the SCHEDULER heartbeat (a wedged single job
    must not 503 the service; its staleness shows in
    ``igg_job_heartbeat_timestamp_seconds{job=...}``). A
    `run_resilient(metrics_port=...)` running under (or next to) the
    scheduler ATTACHES to this server instead of failing to bind.

    The scheduler is a context manager; `close()` releases every job's
    resources and restores whatever grid was current at construction."""

    def __init__(self, *, policy="fifo", flight_dir=None,
                 metrics_port: int | None = None,
                 healthz_max_age_s: float | None = None,
                 queue: QueueBackend | None = None,
                 alerts=None, alert_sinks=(), autoscale=None):
        self.policy = resolve_policy(policy)
        self.flight_dir = None if flight_dir is None else str(flight_dir)
        self.jobs: dict = {}
        self._order: list = []
        self._n_submitted = 0
        self.slices = 0
        self._closed = False
        # per-tenant audit attribution baseline: slices are serialized, so
        # the global finding-counter's growth during a slice belongs to
        # the job that ran it — ONE scheduler-level baseline (a per-job
        # zero would hand each first slice every earlier tenant's total)
        self._audit_seen = self._audit_total()
        self._draining = False
        self._journal = None
        self._server = None
        if self.flight_dir is not None:
            os.makedirs(self.flight_dir, exist_ok=True)
            self._journal = FlightRecorder(
                os.path.join(self.flight_dir, "scheduler.jsonl"),
                run_id="scheduler")
        # the queue backend: where out-of-process producers (the CLI,
        # serve.JobApiServer, a peer scheduler's overflow) enqueue job
        # records and file control requests. A flight_dir implies the
        # directory backend over it — the PR-8 control-file protocol,
        # verbatim — so existing deployments change nothing; an explicit
        # backend can be SHARED between schedulers (atomic-rename claims
        # partition the jobs, zero double-admissions).
        if queue is not None and not isinstance(queue, QueueBackend):
            raise InvalidArgumentError(
                f"queue must be a service.QueueBackend; got "
                f"{type(queue).__name__}.")
        self.queue = queue
        if queue is None and self.flight_dir is not None:
            self.queue = DirectoryBackend(self.flight_dir)
        # the in-process alert engine (ISSUE 18): ``alerts=True`` turns
        # on the default rule pack, an iterable of AlertRules customizes
        # it, a ready AlertEngine is adopted as-is (sinks appended). It
        # evaluates over the scheduler's OWN live state after every
        # granted slice and journals every transition through the
        # scheduler's single-writer journal — `telemetry.LiveAggregate`
        # is the observer-side twin tailing the same directory.
        self.alert_engine = None
        if isinstance(alerts, AlertEngine):
            self.alert_engine = alerts
            self.alert_engine.sinks.extend(alert_sinks)
            if self.alert_engine.journal is None:
                self.alert_engine.journal = self._log
        elif alerts is True or alerts == "default":
            self.alert_engine = AlertEngine(sinks=alert_sinks,
                                            journal=self._log)
        elif alerts:
            self.alert_engine = AlertEngine(list(alerts),
                                            sinks=alert_sinks,
                                            journal=self._log)
        elif alert_sinks:
            raise InvalidArgumentError(
                "alert_sinks without alerts: pass alerts=True (default "
                "rule pack), a rule list, or an AlertEngine.")
        if self.alert_engine is not None \
                and getattr(self.alert_engine, "tracer", None) is None:
            # alert transitions join the affected job's trace (a fresh
            # child span) BEFORE journal+sinks, so an alert-driven
            # control action can carry the alert's span as its parent
            self.alert_engine.tracer = self._alert_trace
        # the closed-loop autoscaler (ISSUE 19): ``autoscale=True`` turns
        # on the default policy, an AutoscalePolicy (or its kwargs dict)
        # customizes it, a ready Autoscaler is adopted as-is. It
        # evaluates over the SAME live snapshot as the alert engine after
        # every granted slice and actuates through the control path —
        # priced, hysteresis-damped, journaled (service.autoscale).
        self.autoscaler = None
        if isinstance(autoscale, Autoscaler):
            self.autoscaler = autoscale
        elif isinstance(autoscale, (AutoscalePolicy, dict)):
            self.autoscaler = Autoscaler(autoscale)
        elif autoscale is True or autoscale == "default":
            self.autoscaler = Autoscaler()
        elif autoscale:
            raise InvalidArgumentError(
                "autoscale must be True (default policy), an "
                "AutoscalePolicy (or its kwargs dict), or an Autoscaler; "
                f"got {type(autoscale).__name__}.")
        if self.autoscaler is not None:
            self.autoscaler.attach(self)
        try:
            if metrics_port is not None:
                from ..telemetry.server import start_metrics_server

                self._server = start_metrics_server(
                    int(metrics_port),
                    healthz_max_age_s=healthz_max_age_s)
            elif healthz_max_age_s is not None:
                raise InvalidArgumentError(
                    "healthz_max_age_s needs metrics_port (it configures "
                    "the /healthz endpoint the scheduler starts).")
        except BaseException:
            if self._journal is not None:
                self._journal.close()
            raise
        hooks.note_scheduler_heartbeat()
        self._log("scheduler_start", policy=self.policy.name,
                  wall=time.time(),
                  metrics_port=None if self._server is None
                  else self._server.port,
                  queue_owner=None if self.queue is None
                  else getattr(self.queue, "owner", None),
                  autoscale=None if self.autoscaler is None
                  else self.autoscaler.policy.describe())

    @staticmethod
    def _audit_total() -> float:
        fam = hooks.metrics_registry().get(hooks.AUDIT_FINDINGS)
        return sum(v for _, v in fam.samples()) if fam is not None else 0.0

    # -- journal -----------------------------------------------------------

    def _log(self, kind: str, **fields) -> None:
        if self._journal is None:
            return
        # the ONE trace-stamping chokepoint: every job-scoped journal
        # event (claim, admission verdict, slices, resize chains, alert
        # transitions, state changes) becomes a fresh CHILD span of the
        # job's root context. Explicit trace fields in the call win;
        # untraced jobs journal byte-identically to before.
        if "trace_id" not in fields and fields.get("job") is not None:
            job = self.jobs.get(fields["job"])
            tr = getattr(job, "trace", None)
            if tr is not None:
                fields.update(tr.child().fields())
        self._journal.event(kind, **fields)

    # -- submission --------------------------------------------------------

    def submit(self, spec: JobSpec, *,
               trace: TraceContext | None = None) -> Job:
        """Queue one job. Admission (grid + state construction) is LAZY —
        it happens inside the job's first granted slice, so its cost is
        attributed to the job that pays it, not to the submitter.
        ``trace`` is the job's ROOT span (`telemetry.tracectx`) — set by
        the queue-claim path from the record's ``traceparent``; every
        journal event and flight span of the job becomes its child."""
        self._check_open()
        if not isinstance(spec, JobSpec):
            raise InvalidArgumentError(
                f"submit takes a JobSpec; got {type(spec).__name__}.")
        if spec.name in self.jobs:
            raise InvalidArgumentError(
                f"A job named {spec.name!r} was already submitted "
                "(names key flight files and metric labels).")
        if self._draining:
            raise InvalidArgumentError(
                "The scheduler is draining — no new admissions.")
        job = Job(spec, self._n_submitted)
        job.trace = trace
        self._n_submitted += 1
        job.submitted_t = time.time()
        job.last_end_t = time.monotonic()
        self.jobs[spec.name] = job
        self._order.append(job)
        hooks.note_job_transition("submitted")
        self._update_queue_gauges()
        # NB "run" is the flight recorder's reserved run-id key — the
        # spec payload must travel under its own name
        self._log("job_submitted", job=spec.name, nt=int(spec.nt),
                  priority=int(spec.priority),
                  deadline_s=spec.deadline_s, grid=dict(spec.grid),
                  run_spec=spec.run.to_json())
        return job

    # -- queries -----------------------------------------------------------

    def job(self, name: str) -> Job:
        if name not in self.jobs:
            raise InvalidArgumentError(
                f"No job named {name!r} (have "
                f"{[j.name for j in self._order]}).")
        return self.jobs[name]

    def runnable(self) -> list:
        """Jobs that can take a slice right now, in submission order."""
        return [j for j in self._order if not j.finished]

    def results(self) -> dict:
        """``name -> final state dict`` of every DONE job."""
        return {j.name: j.result for j in self._order
                if j.state == JobState.DONE}

    def status(self) -> dict:
        """JSON-able service snapshot (queue depths + per-job records)."""
        states: dict = {}
        for j in self._order:
            states[j.state] = states.get(j.state, 0) + 1
        return {"policy": self.policy.name, "slices": self.slices,
                "jobs": [j.status() for j in self._order],
                "states": states,
                "metrics_port": None if self._server is None
                else self._server.port}

    # -- lifecycle ---------------------------------------------------------

    def cancel(self, name: str) -> Job:
        """Cancel a job: immediately when QUEUED; at its next slice
        boundary when RUNNING (the current chunk, if one is mid-flight in
        another caller's slice, completes — preemption stays
        chunk-granular)."""
        self._check_open()
        job = self.job(name)
        if job.finished:
            raise InvalidArgumentError(
                f"Job {name!r} already finished ({job.state}).")
        if job.state == JobState.QUEUED:
            self._finalize(job, JobState.CANCELLED)
        else:
            job.cancel_requested = True
        return job

    def resize(self, name: str, new_dims, *, via: str = "auto") -> Job:
        """Request an elastic resize of one job: at its NEXT slice
        boundary the scheduler re-blocks the job's state onto
        ``new_dims`` (`runtime.ResilientRun.resize` — the on-device
        HBM-to-HBM collective program, falling back to the
        checkpoint-based elastic restore), swaps the job's grid epoch,
        and journals ``job_resized``. The resize consumes that slice;
        preemption stays chunk-granular and the job's trajectory is
        bit-identical to its unresized run (the redistribution is
        exact). This is the SCHEDULER-decision form of the autoscaling
        primitive: shrink a tenant under load, grow it when the mesh
        frees up — ``tools jobs resize`` files the same request from the
        CLI."""
        self._check_open()
        job = self.job(name)
        if job.finished:
            raise InvalidArgumentError(
                f"Job {name!r} already finished ({job.state}).")
        new_dims = tuple(int(d) for d in new_dims)
        if len(new_dims) != 3 or any(d < 1 for d in new_dims):
            raise InvalidArgumentError(
                f"resize: new_dims must be 3 positive ints; got "
                f"{new_dims}.")
        if via not in ("auto", "device", "checkpoint"):
            raise InvalidArgumentError(
                f"resize: via must be auto|device|checkpoint; got "
                f"{via!r}.")
        job.resize_requested = (new_dims, via)
        self._log("resize_requested", job=name, new_dims=list(new_dims),
                  via=via)
        return job

    def drain(self) -> None:
        """Stop admitting: cancel every still-QUEUED job, let RUNNING jobs
        finish. (`run()` afterwards completes the running set.)"""
        self._check_open()
        self._draining = True
        self._log("drain")
        for j in list(self._order):
            if j.state == JobState.QUEUED:
                self._finalize(j, JobState.CANCELLED)

    def close(self) -> None:
        """Release everything: running jobs' resources (their runs are
        closed, NOT completed — submitted snapshots drain, checkpoints
        stay restorable), the per-job metric scopes, the scheduler
        heartbeat, the journal, and the metrics endpoint. Idempotent."""
        if self._closed:
            return
        self._closed = True
        for j in self._order:
            if not j.finished:
                self._finalize(j, JobState.CANCELLED)
        self._log("scheduler_stop", slices=self.slices,
                  jobs=len(self._order))
        # the per-job labeled series die WITH the service (during its
        # lifetime a finished job's final step/latencies stay scrapeable)
        for j in self._order:
            if j.scope is not None:
                j.scope.remove_scope()
        hooks.clear_scheduler_heartbeat()
        if self._journal is not None:
            self._journal.close()
        if self._server is not None:
            from ..telemetry.server import stop_metrics_server

            stop_metrics_server()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _check_open(self) -> None:
        if self._closed:
            raise InvalidArgumentError("The scheduler is closed.")

    # -- the scheduling loop ----------------------------------------------

    def step(self) -> bool:
        """One scheduling decision: poll control requests, pick a job
        under the policy, grant it ONE chunk-boundary slice. Returns True
        when a slice was granted (False = nothing runnable — the queue is
        drained)."""
        self._check_open()
        self._poll_control()
        self._poll_queue()
        self._update_backlog_gauges()
        cands = self.runnable()
        for j in [j for j in cands if j.cancel_requested]:
            self._finalize(j, JobState.CANCELLED)
        cands = self.runnable()
        if not cands:
            hooks.note_scheduler_heartbeat()
            return False
        job = self.policy.pick(cands)
        self._slice(job)
        if self.alert_engine is not None:
            # the slice boundary IS the alert-evaluation cadence:
            # signals only change when a slice ran, and a sink's control
            # file lands before the very next _poll_control
            self.alert_engine.evaluate(self._live_signals())
        if self.autoscaler is not None:
            # after the alert engine: a hard alert action (cancel) filed
            # this boundary lands in _poll_control before any autoscale
            # move of the SAME job can waste a slice on it
            self.autoscaler.evaluate(self._live_signals())
        hooks.note_scheduler_heartbeat(granted=True)
        return True

    def run(self, max_slices: int | None = None) -> "MeshScheduler":
        """Drain the queue: grant slices until nothing is runnable (or
        ``max_slices`` was granted). Returns self."""
        granted = 0
        while max_slices is None or granted < max_slices:
            if not self.step():
                break
            granted += 1
        return self

    # -- internals ---------------------------------------------------------

    def _update_queue_gauges(self) -> None:
        hooks.note_queue_depth(
            sum(1 for j in self._order if j.state == JobState.QUEUED),
            sum(1 for j in self._order if j.state == JobState.RUNNING))

    def _update_backlog_gauges(self) -> None:
        """Queue-pressure pair from the backend: unclaimed records +
        oldest-record age (upstream of the admitted-jobs gauges)."""
        if self.queue is None:
            return
        hooks.note_queue_backlog(self.queue.pending_count(),
                                 self.queue.oldest_age_s())

    def _live_signals(self) -> dict:
        """The scheduler-side live snapshot the in-process alert engine
        evaluates against — same shape (``jobs`` / ``procs`` / ``queue``
        / ``scheduler`` keys, same signal names) as
        `telemetry.LiveAggregate.snapshot`, built from direct state
        instead of tailed files. ``procs`` is empty here (barrier
        spreads need the multi-process tail view); the straggler rule
        simply stays silent in-process."""
        jobs = {}
        for j in self._order:
            run, st = j.run, j.status()
            watch = None if run is None else getattr(run, "watch", None)
            jobs[j.name] = {
                "state": st["state"], "step": st["step"],
                "nt": st["nt"], "slices": st["slices"],
                "guard_trips": st["guard_trips"],
                "deadline_slack_s": None if run is None
                else getattr(run, "deadline_slack_s", None),
                "deadline_missed": bool(
                    run is not None
                    and getattr(run, "deadline_missed", False)),
                "perf_regressions": 0 if watch is None
                else getattr(watch, "regressions", 0),
                "priority": int(j.spec.priority),
                "devices": None if j.gg is None
                else int(j.gg.dims[0]) * int(j.gg.dims[1])
                * int(j.gg.dims[2]),
            }
        queue = {
            "queued": sum(1 for j in self._order
                          if j.state == JobState.QUEUED),
            "running": sum(1 for j in self._order
                           if j.state == JobState.RUNNING),
        }
        if self.queue is not None:
            queue["pending"] = self.queue.pending_count()
            queue["oldest_age_s"] = self.queue.oldest_age_s()
        return {"t": time.time(), "jobs": jobs, "procs": {},
                "queue": queue,
                "scheduler": {"slices": self.slices,
                              "draining": self._draining}}

    def _poll_control(self) -> None:
        """Control channel: `tools jobs cancel|drain|resize` and the
        HTTP API file request files through the queue backend; a live
        scheduler consumes them at slice boundaries."""
        if self.queue is None:
            return
        for req in self.queue.poll_control():
            kind = req["request"]
            if kind == "drain":
                self._log("control", request="drain")
                self.drain()
            elif kind == "cancel":
                name, payload = req["job"], req.get("payload")
                # a cancel filed WITH a trace (the HTTP API's request
                # span, or the alert span a ControlFileSink acted on)
                # parents the control event — "why was my job
                # cancelled" is one trace walk back to the decider
                ctx = self._parse_traceparent(payload)
                self._log("control", request="cancel", job=name,
                          **(ctx.fields() if ctx is not None else {}))
                job = self.jobs.get(name)
                if job is not None and not job.finished:
                    self.cancel(name)
            elif kind == "resize":
                name, payload = req["job"], req.get("payload")
                ctx = self._parse_traceparent(payload)
                if isinstance(payload, dict):
                    payload = {k: v for k, v in payload.items()
                               if k != "traceparent"}
                self._log("control", request="resize", job=name,
                          payload=payload,
                          **(ctx.fields() if ctx is not None else {}))
                job = self.jobs.get(name)
                if job is None or job.finished \
                        or not isinstance(payload, dict):
                    # never drop an operator request silently
                    self._log("resize_rejected", job=name,
                              error=("malformed control payload"
                                     if not isinstance(payload, dict) else
                                     "unknown or finished job"))
                    continue
                try:
                    self.resize(name, payload.get("new_dims", ()),
                                via=payload.get("via", "auto"))
                except (InvalidArgumentError, ValueError, TypeError) as e:
                    # ValueError/TypeError: non-integer new_dims in a
                    # hand-written control file — an operator typo must
                    # not take the scheduler (and every tenant) down
                    self._log("resize_rejected", job=name, error=str(e))

    def _alert_trace(self, transition: dict) -> dict:
        """`AlertEngine.tracer` hook: the transition as a child span of
        the affected job's trace (empty for untraced/unattributed)."""
        job = self.jobs.get(transition.get("job"))
        tr = getattr(job, "trace", None)
        return tr.child().fields() if tr is not None else {}

    @staticmethod
    def _parse_traceparent(rec) -> TraceContext | None:
        """A queue record's / control payload's ``traceparent`` as a
        fresh CHILD context of the requester's span; None when absent or
        malformed (a bad header degrades to an untraced job — it never
        rejects work)."""
        tp = rec.get("traceparent") if isinstance(rec, dict) else None
        if not tp:
            return None
        try:
            return TraceContext.parse(str(tp)).child()
        except InvalidArgumentError:
            return None

    def _poll_queue(self) -> None:
        """Claim at most ONE pending record from the queue backend per
        scheduling decision — claims interleave with slices, so N
        schedulers sharing a backend each take work at the rate they
        can serve it (and the atomic-rename claim guarantees every
        record is admitted by exactly one of them)."""
        if self.queue is None or self._draining:
            return
        claimed = self.queue.claim()
        if claimed is None:
            return
        name = claimed["name"]
        if claimed.get("record") is None:
            self._log("submit_rejected", job=name,
                      error=claimed.get("error") or "unreadable record")
            return
        # the record's traceparent (the API's submit span) becomes the
        # job's ROOT context: job_claimed IS the root span, its parent
        # the HTTP submit — one connected tree from request to slices
        trace = self._parse_traceparent(claimed["record"])
        self._log("job_claimed", job=name,
                  owner=getattr(self.queue, "owner", None),
                  **(trace.fields() if trace is not None else {}))
        try:
            spec = jobspec_from_json(claimed["record"],
                                     where=f"queue record {name!r}")
            if spec.name != name:
                raise InvalidArgumentError(
                    f"queue record {name!r} names job {spec.name!r} — "
                    "the record key and its 'name' must agree.")
            self.submit(spec, trace=trace)
        except InvalidArgumentError as e:
            # a malformed record must not take the scheduler (and every
            # tenant) down — journal the rejection and keep serving
            self._log("submit_rejected", job=name, error=str(e))

    def _admit(self, job: Job) -> None:
        """First slice grant: build the job's grid over the shared device
        pool, run its setup under that grid, construct its `ResilientRun`.
        All of it streams into the job's own flight recorder; the cost is
        journaled as ``admit_s`` (the admission analog of a cold chunk).

        A tuned job (``RunSpec.tuned`` — `telemetry.tune_config` output)
        is LOADED-AND-APPLIED here: the config's trace-time knobs
        (``IGG_COMM_EVERY`` / wire dtype / coalescing) scope the setup —
        so a setup that consults the environment (the builtin model
        inits do) builds the tuned step — a tuned ``ensemble`` fills an
        unset ``RunSpec.ensemble`` (the guard then trips per member),
        and the applied knob set is journaled as ``job_tuned``. The
        `ResilientRun` keeps scoping the same knobs around every slice's
        chunk compiles."""
        import contextlib
        import dataclasses

        from ..parallel.grid import init_global_grid
        from ..telemetry.tune import _scoped_env, resolve_tuned

        t0 = time.monotonic()
        # the gauge scope first: it cannot fail, and the failure path
        # below accounts the slice through it (a raising recorder/grid/
        # setup must fail THIS job, never crash the scheduler)
        job.scope = hooks.job_gauges(None, job.name)
        if self.flight_dir is not None:
            job.recorder = FlightRecorder(
                os.path.join(self.flight_dir, f"job_{job.name}.jsonl"),
                run_id=job.name)
            # every driver event of this job (run/chunk/guard_trip/
            # resize) joins the job's trace as a child of its root span
            job.recorder.trace = job.trace
        run_spec = job.spec.run
        tuned = resolve_tuned(run_spec.tuned)
        if tuned is not None and run_spec.ensemble is None \
                and tuned.ensemble is not None:
            run_spec = dataclasses.replace(run_spec,
                                           ensemble=int(tuned.ensemble))
        knob_scope = (_scoped_env(tuned.env()) if tuned is not None
                      else contextlib.nullcontext())
        prev = top.swap_global_grid(None)
        try:
            init_global_grid(**{"quiet": True, **job.spec.grid})
            job.gg = top.global_grid()
            top.retain_epoch(job.gg.epoch)
            with use_flight_recorder(job.recorder), knob_scope:
                step_local, state = job.spec.setup()
                unit_price_s = self._price_admission(job, run_spec,
                                                     tuned, state)
                if unit_price_s is not None \
                        and run_spec.perf_model is None:
                    # hand the admission price to the driver as its
                    # perf model: the deadline-slack gauge then prices
                    # remaining work from the first boundary instead of
                    # waiting for a warm measured baseline
                    run_spec = dataclasses.replace(
                        run_spec, perf_model=float(unit_price_s))
                if job.spec.deadline_s is not None \
                        and run_spec.deadline_s is None:
                    # hand the REMAINING budget to the runtime surface:
                    # the driver fires deadline_missed (event + counter)
                    # when an admitted job crosses it anyway
                    left = float(job.spec.deadline_s) - max(
                        0.0, time.time() - (job.submitted_t
                                            or time.time()))
                    run_spec = dataclasses.replace(
                        run_spec, deadline_s=max(1e-9, left))
                job.run = ResilientRun(step_local, state,
                                       int(job.spec.nt), run_spec)
        except BaseException:
            if job.gg is not None:
                top.release_epoch(job.gg.epoch)
                _evict_epoch_caches(job.gg.epoch)
                job.gg = None
            raise
        finally:
            top.swap_global_grid(prev)
        job.state = JobState.RUNNING
        job.started_t = time.time()
        job.admit_s = time.monotonic() - t0
        self._update_queue_gauges()
        if tuned is not None:
            self._log("job_tuned", job=job.name, model=tuned.model,
                      **tuned.knobs(), speedup=tuned.speedup)
        self._log("job_admitted", job=job.name, admit_s=job.admit_s,
                  epoch=int(job.gg.epoch))
        hooks.note_job_target_devices(
            job.scope, int(job.gg.dims[0]) * int(job.gg.dims[1])
            * int(job.gg.dims[2]))

    def _price_admission(self, job: Job, run_spec, tuned, state):
        """Deadline-aware admission (runs under the job's grid, state
        built): price the job's expected mesh-seconds with the PR-6
        cost model — ``predict_step`` on the job's OWN field shapes,
        honoring its tuned knob set and ensemble width — and refuse a
        job whose priced completion provably busts what is left of its
        ``deadline_s`` budget. Every verdict (admit AND reject) is
        journaled as ``admission_priced`` with the full pricing inputs,
        so `service_report` can defend it post-hoc. Unpriceable jobs
        (no ``model``, a non-workload model, a cost-model refusal)
        always admit — admission only rejects what it can PROVE.

        Returns the priced per-nt-unit step cost (seconds) on a priced
        admit, None otherwise — `_admit` hands it to the driver as the
        run's perf model when the spec left one unset."""
        spec = job.spec
        if spec.deadline_s is None:
            return None
        from ..telemetry.perfmodel import (
            STEP_WORKLOADS, default_machine_profile, predict_step,
        )

        waited_s = max(0.0, time.time() - (job.submitted_t
                                           or time.time()))
        budget_s = float(spec.deadline_s) - waited_s
        if spec.model not in STEP_WORKLOADS:
            self._log("admission_priced", job=job.name, verdict="admit",
                      priced_by="unpriceable", model=spec.model,
                      deadline_s=float(spec.deadline_s),
                      waited_s=waited_s, budget_s=budget_s)
            return None
        from ..models.common import resolve_comm_every

        E = run_spec.ensemble
        # per-member stacked shapes in canonical state order (the
        # builtin setups build the dict in exactly that order); an
        # ensemble state carries members on a leading axis predict_step
        # must not read as geometry
        import jax

        fields = tuple(
            jax.ShapeDtypeStruct(v.shape[1:] if E else v.shape, v.dtype)
            for v in state.values())
        knobs = dict(comm_every=1, overlap=False, coalesce=None,
                     wire_dtype=None, wire_stage=None)
        if tuned is not None:
            knobs = dict(comm_every=tuned.comm_every,
                         overlap=bool(tuned.overlap),
                         coalesce=tuned.coalesce,
                         wire_dtype=tuned.wire_dtype,
                         wire_stage=tuned.wire_stage)
        try:
            pred = predict_step(spec.model, fields,
                                profile=default_machine_profile(),
                                ensemble=E, **knobs)
        except Exception as e:
            # the cost model refusing a geometry is not a admission
            # failure — an unpriceable job admits (and says why)
            self._log("admission_priced", job=job.name, verdict="admit",
                      priced_by="unpriceable", model=spec.model,
                      error=f"{type(e).__name__}: {e}",
                      deadline_s=float(spec.deadline_s),
                      waited_s=waited_s, budget_s=budget_s)
            return None
        cadence = resolve_comm_every(knobs["comm_every"])
        # a deep cadence makes the job's step the SUPER-STEP (the
        # builtin setups' rule): one nt unit = cadence.cycle physical
        # steps, each priced at step_s
        steps_per_unit = cadence.cycle if cadence.deep else 1
        price_s = pred["step_s"] * steps_per_unit * int(spec.nt)
        verdict = "admit" if price_s <= budget_s else "reject"
        rec = dict(job=job.name, verdict=verdict,
                   admit_price_s=price_s, step_price_s=pred["step_s"],
                   nt=int(spec.nt), steps_per_unit=steps_per_unit,
                   deadline_s=float(spec.deadline_s), waited_s=waited_s,
                   budget_s=budget_s, bound=pred.get("bound"),
                   profile_source=pred.get("profile_source"),
                   model=spec.model, ensemble=E,
                   priced_by="predict_step")
        self._log("admission_priced", **rec)
        if verdict == "reject":
            raise _DeadlineRejected(rec)
        return pred["step_s"] * steps_per_unit

    def _retune(self, job: Job, reason) -> bool:
        """Boundary re-tune (the autoscale loop's closing rung): re-RUN
        `telemetry.tune_config` against the job's LIVE geometry —
        model-only (``measure=False``; a measured calibration run would
        stall every tenant) — and apply the winner to the running job
        (`ResilientRun.apply_tuned`). Structural knobs are FROZEN at
        their live values: ``comm_every`` is baked into the compiled
        step body at setup, ``overlap`` schedules that body, and
        ``ensemble`` shapes the state — only re-admission could change
        them. ``wire_dtype`` is frozen too: a re-tune must never switch
        a tenant onto a lossy wire mid-run (trajectories stay
        bit-identical to the solo reference). What IS searched are the
        bit-exact transport knobs — halo coalescing and the
        topology-staged wire. Journals ``job_retuned`` (or
        ``job_retune_failed``) and re-prices the driver so deadline
        slack tracks the tuned geometry. Returns True when a config was
        applied."""
        from ..models.common import resolve_comm_every
        from ..telemetry.tune import _MODEL_STAGGER, tune_config

        model = job.spec.model
        if model not in _MODEL_STAGGER or job.run is None \
                or job.gg is None:
            return False
        t0 = time.monotonic()
        gg = job.gg
        run = job.run
        tuned = run.tuned
        cur = dict(comm_every=1, overlap=False, coalesce=True,
                   wire_dtype=None, wire_stage=None)
        if tuned is not None:
            cur = dict(comm_every=tuned.comm_every,
                       overlap=bool(tuned.overlap),
                       coalesce=tuned.coalesce,
                       wire_dtype=tuned.wire_dtype,
                       wire_stage=tuned.wire_stage)
        n = tuple(int(v) for v in gg.nxyz)
        grid = dict(nx=n[0], ny=n[1], nz=n[2],
                    dimx=int(gg.dims[0]), dimy=int(gg.dims[1]),
                    dimz=int(gg.dims[2]),
                    periodx=int(gg.periods[0]),
                    periody=int(gg.periods[1]),
                    periodz=int(gg.periods[2]),
                    overlaps=tuple(int(o) for o in gg.overlaps),
                    halowidths=tuple(int(h) for h in gg.halowidths))
        dtype = str(next(iter(run.state.values())).dtype)
        try:
            cfg = tune_config(
                model, grid, dtype=dtype,
                comm_every_options=(cur["comm_every"],),
                wire_dtype_options=(cur["wire_dtype"],),
                wire_stage_options=tuple(dict.fromkeys(
                    [cur["wire_stage"], None, "z:staged"])),
                coalesce_options=tuple(dict.fromkeys(
                    [cur["coalesce"], True, False])),
                overlap_options=(cur["overlap"],),
                ensemble_options=(run.ensemble,),
                measure=False)
            run.apply_tuned(cfg)
        except Exception as e:
            self._log("job_retune_failed", job=job.name, model=model,
                      reason=reason, error=f"{type(e).__name__}: {e}")
            return False
        search_s = time.monotonic() - t0
        self._log("job_retuned", job=job.name, model=model,
                  reason=reason, **cfg.knobs(),
                  predicted_step_s=cfg.predicted_step_s,
                  search_s=search_s)
        if cfg.predicted_step_s:
            cadence = resolve_comm_every(cfg.comm_every)
            spu = cadence.cycle if cadence.deep else 1
            try:
                run.reprice(float(cfg.predicted_step_s) * spu,
                            source="autoscale_retune")
            except InvalidArgumentError:
                pass
        return True

    def _slice(self, job: Job) -> None:
        """Grant ``job`` one chunk-boundary slice (admitting it first if
        this is its first grant). A raising slice FAILS the job alone."""
        t_pick = time.monotonic()
        wait_s = max(0.0, t_pick - (job.last_end_t or t_pick))
        chunks0 = 0 if job.run is None else len(job.run.reports)
        resized = False
        try:
            if job.state == JobState.QUEUED:
                self._admit(job)
            resize_req, job.resize_requested = job.resize_requested, None
            prev = top.swap_global_grid(job.gg)
            try:
                with use_flight_recorder(job.recorder):
                    if resize_req is not None:
                        # the resize consumes this slice: one epoch-
                        # swapping re-block at the chunk boundary, then
                        # the job resumes its schedule next grant. A
                        # FAILED resize rejects the request and keeps
                        # the tenant running — one fat-fingered dims
                        # request must not kill a long-lived job (the
                        # driver restores its grid on device-path
                        # failures and the checkpoint fallback is
                        # non-destructive)
                        new_dims, via = resize_req
                        try:
                            rec = job.run.resize(new_dims, via=via)
                        except Exception as e:
                            self._log("resize_rejected", job=job.name,
                                      new_dims=list(new_dims), via=via,
                                      error=f"{type(e).__name__}: {e}")
                            more = not job.run.done
                            if self.autoscaler is not None:
                                self.autoscaler.on_resize_rejected(job)
                        else:
                            resized = True
                            more = not job.run.done
                            self._log("job_resized", job=job.name,
                                      new_dims=list(new_dims),
                                      via=rec.get("via"),
                                      dur_s=rec.get("seconds"),
                                      rounds=rec.get("rounds"),
                                      wire_bytes=rec.get("wire_bytes"),
                                      step=job.step)
                            if self.autoscaler is not None:
                                # the policy repriced this geometry when
                                # it filed the move: hand the driver the
                                # priced unit cost so slack converges
                                self.autoscaler.on_resized(job, new_dims)
                    else:
                        more = job.run.advance()
                # a resize or elastic restart inside the slice re-inits
                # the grid: track the NEW one (retire the dead epoch)
                cur = top._global_grid
                if cur is not job.gg and cur is not None:
                    old = job.gg
                    job.gg = cur
                    top.retain_epoch(cur.epoch)
                    top.release_epoch(old.epoch)
                    _evict_epoch_caches(old.epoch)
                    if job.scope is not None:
                        hooks.note_job_target_devices(
                            job.scope,
                            int(cur.dims[0]) * int(cur.dims[1])
                            * int(cur.dims[2]))
            finally:
                top.swap_global_grid(prev)
        except _DeadlineRejected as e:
            # an admission verdict, not a failure: the job never ran
            job.error = str(e)
            self._account_slice(job, t_pick, wait_s, chunks0)
            self._finalize(job, JobState.REJECTED)
            return
        except Exception as e:
            job.error = f"{type(e).__name__}: {e}"
            self._account_slice(job, t_pick, wait_s, chunks0)
            self._finalize(job, JobState.FAILED)
            return
        self._account_slice(job, t_pick, wait_s, chunks0)
        # a running job crossing its deadline (the driver flagged it at
        # a chunk boundary): journal it ONCE — the admission verdict
        # said yes, the operator deserves to see where it went wrong
        if job.run is not None \
                and getattr(job.run, "deadline_missed", False) \
                and not job.deadline_logged:
            job.deadline_logged = True
            # the budget the driver actually watched (run-level, which
            # _admit derives from the job deadline when unset)
            self._log("deadline_missed", job=job.name, step=job.step,
                      deadline_s=job.run.deadline_s)
        # re-tune trigger (ROADMAP tuner rung c): a resize or PerfWatch
        # drift marked the applied TunedConfig stale. With the
        # autoscaler's closed loop on (policy.retune), the scheduler
        # re-RUNS the tuner against the live geometry right here at the
        # boundary — model-only, trace-time knobs — and applies the
        # winner; otherwise (or when the re-tune itself fails) it falls
        # back to clearing the stale config (journaled; the operator
        # re-runs `tools tune`). A resize of a never-tuned job re-tunes
        # too: the new geometry deserves a knob search either way.
        retune_on = self.autoscaler is not None \
            and self.autoscaler.policy.retune and not job.finished
        if job.run is not None and getattr(job.run, "tuned_stale", False):
            reason = job.run.tuned_stale_reason
            if not (retune_on and self._retune(job, reason)):
                job.run.clear_tuned()
                self._log("job_tuned_cleared", job=job.name,
                          reason=reason)
        elif resized and retune_on and job.run is not None \
                and not job.run.done:
            self._retune(job, "resize")
        if not more:
            self._finalize(job, JobState.DONE)

    def _account_slice(self, job: Job, t_pick: float, wait_s: float,
                       chunks0: int) -> None:
        t_end = time.monotonic()
        slice_s = t_end - t_pick
        job.slices += 1
        job.slice_s_total += slice_s
        job.wait_s_total += wait_s
        job.last_end_t = t_end
        self.slices += 1
        self.policy.granted(job, slice_s)
        # mirror the perf oracle's process-wide gauges (they flap between
        # tenants under multiplexing) into this job's labeled copies —
        # only when THIS slice actually ran a chunk (a fault-boundary or
        # elastic-restart iteration dispatches none, and the global gauge
        # still holds the PREVIOUS tenant's value) — and attribute audit
        # findings by diffing the global family against the scheduler's
        # baseline (slices are serialized, so the growth is this job's)
        ran_chunk = job.run is not None and len(job.run.reports) > chunks0
        reg = hooks.metrics_registry()
        perf_step_s = perf_ratio = None
        if ran_chunk and job.run.watch is not None:
            fam = reg.get(hooks.PERF_STEP_S)
            if fam is not None:
                samples = fam.samples()
                if samples:
                    perf_step_s = samples[0][1]
            if job.run.watch.model_step_s:
                fam = reg.get(hooks.PERF_RATIO)
                if fam is not None:
                    samples = fam.samples()
                    if samples:
                        perf_ratio = samples[0][1]
        total = self._audit_total()
        findings = total - self._audit_seen
        self._audit_seen = total
        slack_s = None if job.run is None \
            else getattr(job.run, "deadline_slack_s", None)
        hooks.observe_job_slice(
            job.scope, step=job.step, slice_s=slice_s, wait_s=wait_s,
            perf_step_s=perf_step_s, perf_ratio=perf_ratio,
            audit_findings=max(0.0, findings), slack_s=slack_s)
        # batched (ensemble) jobs: mirror the LAST chunk's per-member
        # guard verdicts into this job's scoped registry — the global
        # igg_member_* series flap between tenants exactly like the perf
        # gauges; the job-labeled copies are the per-scenario surface an
        # operator watches
        # the RUN's member count (a tuned config may have filled an
        # unset RunSpec.ensemble at admission — the spec alone is stale)
        E = None if job.run is None else job.run.ensemble
        if ran_chunk and E:
            members = job.run.reports[-int(E):]
            if len(members) == int(E) and all(
                    r.member is not None for r in members):
                hooks.observe_member_health(members, scope=job.scope)
        self._log("slice", job=job.name, slice=self.slices - 1,
                  step=job.step, dur_s=slice_s, wait_s=wait_s,
                  policy=self.policy.name, slack_s=slack_s)

    def _finalize(self, job: Job, state: str) -> None:
        """Move a job to a terminal state and release its resources (run
        close → snapshot drain; epoch release → cache eviction; recorder
        close). The job's labeled metric series survive until the
        SCHEDULER closes — a finished tenant's final step/latencies stay
        scrapeable across job lifetimes."""
        if job.finished:
            return
        if job.resize_requested is not None:
            # never drop an operator request silently: a job reaching a
            # terminal state with a resize still pending journals the
            # rejection (the control-poll path's rule)
            new_dims, via = job.resize_requested
            job.resize_requested = None
            self._log("resize_rejected", job=job.name,
                      new_dims=list(new_dims), via=via,
                      error=f"job reached terminal state {state} before "
                            "the resize slice")
            if self.autoscaler is not None:
                self.autoscaler.on_resize_rejected(job)
        if job.run is not None:
            if state == JobState.DONE:
                from ..utils.timing import sync

                prev = top.swap_global_grid(job.gg)
                try:
                    job.result = sync(job.run.state)
                finally:
                    top.swap_global_grid(prev)
            job.reports = job.run.reports
            with use_flight_recorder(job.recorder):
                job.run.close()
        job.state = state
        job.finished_t = time.time()
        if job.recorder is not None:
            job.recorder.close()
        if job.gg is not None:
            top.release_epoch(job.gg.epoch)
            _evict_epoch_caches(job.gg.epoch)
        hooks.note_job_transition(state)
        self._update_queue_gauges()
        self._log("job_" + state, job=job.name, step=job.step,
                  slices=job.slices, slice_s_total=job.slice_s_total,
                  wait_s_total=job.wait_s_total, error=job.error)
