"""Jobs: what the persistent-mesh scheduler admits and multiplexes.

A job is a complete supervised run waiting to happen: a grid geometry
(its own `init_global_grid` arguments — jobs with DIFFERENT models and
grid sizes share one device pool), a setup callable that builds the step
function and state UNDER that grid, a step budget, the full
`runtime.RunSpec` knob set (checkpoints, snapshots, reducers, perf
watch, audit — every subsystem of PRs 2-7 becomes per-tenant), and
scheduling metadata (priority weight, optional deadline).

`JobSpec` is the immutable submission; `Job` is the scheduler's live
record of it (state machine QUEUED → RUNNING → DONE/FAILED/CANCELLED,
slice accounting, the underlying `ResilientRun`). `builtin_setup` maps
the model names the CLI accepts (``diffusion3d`` …) to setup callables so
a job queue can be described in plain JSON (`tools jobs submit`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..runtime.spec import RunSpec
from ..utils.exceptions import InvalidArgumentError

__all__ = ["JobSpec", "Job", "JobState", "builtin_setup", "BUILTIN_MODELS",
           "jobspec_from_json"]


class JobState:
    """Job lifecycle states (plain strings — they travel through JSON
    journals and Prometheus labels)."""

    QUEUED = "queued"        # submitted, not yet granted a slice
    RUNNING = "running"      # admitted: grid + state live, being sliced
    DONE = "done"            # completed all nt steps; result available
    FAILED = "failed"        # raised (retry budget, fatal guard, setup)
    CANCELLED = "cancelled"  # cancelled before completion
    REJECTED = "rejected"    # refused at admission (deadline pricing)

    TERMINAL = (DONE, FAILED, CANCELLED, REJECTED)


@dataclass(frozen=True)
class JobSpec:
    """One queued simulation.

    ``name`` must be unique within a scheduler (it keys the flight JSONL,
    the journal, and every per-job metric label). ``setup`` is called
    ONCE, at admission, with the job's grid current — it returns
    ``(step_local, state)`` exactly as `run_resilient` takes them.
    ``grid`` holds `init_global_grid` keyword arguments (``quiet=True``
    is applied unless overridden); the scheduler builds a SEPARATE grid
    per job over the same device pool and context-switches between them.
    ``run`` is the embedded `runtime.RunSpec` (all ~20 supervised-run
    knobs — not re-declared here). ``priority`` is the weight the
    ``fair`` policy shares mesh time by (higher = more slices; must be
    >= 1).

    ``deadline_s`` is a wall-clock budget measured from submission.
    Two mechanisms enforce it: admission pricing — when ``model`` names
    a `telemetry.predict_step` workload (``diffusion3d`` …, what
    `jobspec_from_json` fills for built-in jobs), the scheduler prices
    the job's expected mesh-seconds at ``_admit`` time and REJECTS a
    job whose priced completion provably busts the remaining budget
    (journaled ``admission_priced`` verdict; `JobState.REJECTED`) —
    and the runtime ``deadline_missed`` flight event + counter when a
    running job crosses it anyway. ``model=None`` (a custom setup) is
    unpriceable: such jobs always admit; only the runtime surface
    fires."""

    name: str
    setup: Callable[[], tuple]
    nt: int
    grid: dict = field(default_factory=dict)
    run: RunSpec = field(default_factory=RunSpec)
    priority: int = 1
    deadline_s: float | None = None
    model: str | None = None

    def __post_init__(self):
        if not self.name or "/" in str(self.name):
            raise InvalidArgumentError(
                f"JobSpec.name must be a non-empty, slash-free string "
                f"(it names files); got {self.name!r}.")
        if not callable(self.setup):
            raise InvalidArgumentError(
                "JobSpec.setup must be callable () -> (step_local, state).")
        if int(self.nt) <= 0:
            raise InvalidArgumentError(
                f"JobSpec.nt must be positive; got {self.nt}.")
        if not isinstance(self.run, RunSpec):
            raise InvalidArgumentError(
                "JobSpec.run must be a runtime.RunSpec (it embeds the "
                "supervised-run knob set instead of re-declaring it).")
        if int(self.priority) < 1:
            raise InvalidArgumentError(
                f"JobSpec.priority is a fair-share weight >= 1; got "
                f"{self.priority}.")
        if self.deadline_s is not None and not float(self.deadline_s) > 0:
            raise InvalidArgumentError(
                f"JobSpec.deadline_s is a wall-clock budget in seconds "
                f"(> 0) measured from submission; got {self.deadline_s}.")


class Job:
    """The scheduler's live record of one submitted `JobSpec`."""

    def __init__(self, spec: JobSpec, index: int):
        self.spec = spec
        self.index = index              # submission order (fifo key)
        self.state = JobState.QUEUED
        self.gg = None                  # this job's GlobalGrid, once admitted
        self.run = None                 # the ResilientRun machine
        self.recorder = None            # per-job FlightRecorder (or None)
        self.scope = None               # per-job ScopedRegistry gauges
        self.error: str | None = None
        self.result = None              # final state dict (DONE only)
        self.reports = None
        self.submitted_t: float | None = None
        self.started_t: float | None = None
        self.finished_t: float | None = None
        self.admit_s: float = 0.0       # grid init + user setup cost
        self.slices = 0
        self.slice_s_total = 0.0
        self.wait_s_total = 0.0
        self.cancel_requested = False
        self.resize_requested = None    # (dims tuple, via); applied at a slice
        self.last_end_t: float | None = None
        self.deadline_logged = False    # deadline_missed journaled once
        self.trace = None               # job-root TraceContext (or None)

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def step(self) -> int:
        return 0 if self.run is None else int(self.run.step)

    @property
    def finished(self) -> bool:
        return self.state in JobState.TERMINAL

    def status(self) -> dict:
        """JSON-able snapshot (the `tools jobs status` record)."""
        trips = 0 if self.reports is None and self.run is None else sum(
            1 for r in (self.reports if self.reports is not None
                        else self.run.reports) if not r.ok)
        return {
            "name": self.name, "state": self.state, "nt": int(self.spec.nt),
            "step": self.step, "priority": int(self.spec.priority),
            "deadline_s": self.spec.deadline_s,
            "slices": self.slices,
            "slice_s_total": self.slice_s_total,
            "wait_s_total": self.wait_s_total,
            "admit_s": self.admit_s,
            "guard_trips": trips,
            "submitted_t": self.submitted_t, "started_t": self.started_t,
            "finished_t": self.finished_t, "error": self.error,
        }


# ---------------------------------------------------------------------------
# Built-in model setups (the CLI's JSON-describable jobs)
# ---------------------------------------------------------------------------

def _tuned_knobs(cfg) -> dict:
    """(comm_every, overlap) init keywords from a tuned config (or the
    defaults)."""
    if cfg is None:
        return {"comm_every": 1, "overlap": False}
    return {"comm_every": cfg.comm_every, "overlap": bool(cfg.overlap)}


def _dict_step(names, tuple_step):
    """Adapt a tuple-state local step to the driver's dict-state form."""
    def step(s):
        out = tuple_step(tuple(s[n] for n in names))
        return dict(zip(names, out))
    return step


def _setup_diffusion3d(dtype, cfg=None):
    from ..models import diffusion_step_local, init_diffusion3d
    from ..models import diffusion as D
    from ..models.common import resolve_comm_every

    T, Cp, p = init_diffusion3d(dtype=dtype, **_tuned_knobs(cfg))
    if resolve_comm_every(p.comm_every).deep:
        # the tuned deep cadence: the job's step is the SUPER-STEP
        # (lcm(k_d) physical steps + due-axis exchanges per call) — the
        # JobSpec's nt then counts super-steps
        sstep, _ = D.deep_step(p)
        return _dict_step(("T", "Cp"), sstep), {"T": T, "Cp": Cp}

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


def _setup_diffusion2d(dtype, cfg=None):
    from ..models import diffusion_step_local, init_diffusion2d
    from ..models.common import resolve_comm_every

    if cfg is not None and resolve_comm_every(cfg.comm_every).deep:
        raise InvalidArgumentError(
            "diffusion2d jobs do not support a tuned deep comm_every "
            "cadence (the 2-D builtin runs the per-step path).")
    T, Cp, p = init_diffusion2d(dtype=dtype)

    def step(s):
        return {"T": diffusion_step_local(s["T"], s["Cp"], p, "xla"),
                "Cp": s["Cp"]}

    return step, {"T": T, "Cp": Cp}


def _setup_acoustic3d(dtype, cfg=None):
    from ..models import acoustic_step_local, init_acoustic3d
    from ..models import acoustic as A
    from ..models.common import resolve_comm_every

    state, p = init_acoustic3d(dtype=dtype, **_tuned_knobs(cfg))
    names = ("P", "Vx", "Vy", "Vz")
    if resolve_comm_every(p.comm_every).deep:
        sstep, _ = A.deep_step(p)
        return _dict_step(names, sstep), dict(zip(names, state))

    def step(s):
        out = acoustic_step_local(tuple(s[n] for n in names), p, "xla")
        return dict(zip(names, out))

    return step, dict(zip(names, state))


def _setup_stokes3d(dtype, cfg=None):
    from ..models import init_stokes3d, stokes_step_local
    from ..models import stokes as S
    from ..models.common import resolve_comm_every

    state, p = init_stokes3d(dtype=dtype, **_tuned_knobs(cfg))
    names = ("P", "Vx", "Vy", "Vz", "dVx", "dVy", "dVz", "rhog")
    if resolve_comm_every(p.comm_every).deep:
        sstep, _ = S.deep_step(p)
        return _dict_step(names, sstep), dict(zip(names, state))

    def step(s):
        out = stokes_step_local(tuple(s[n] for n in names), p, "xla")
        return dict(zip(names, out))

    return step, dict(zip(names, state))


BUILTIN_MODELS = {
    "diffusion3d": _setup_diffusion3d,
    "diffusion2d": _setup_diffusion2d,
    "acoustic3d": _setup_acoustic3d,
    "stokes3d": _setup_stokes3d,
}


def builtin_setup(model: str, dtype: str = "float32",
                  ensemble: int | None = None, perturb: float = 0.0,
                  tuned=None):
    """A `JobSpec.setup` callable for a built-in model family — what
    `tools jobs submit` builds from a JSON job description. The callable
    runs at ADMISSION, under the job's own grid.

    ``ensemble=E`` makes the job a BATCHED one (ISSUE 12): the state is
    stacked E members deep along a new leading axis
    (`models.common.ensemble_state`; ``perturb`` ramps member m's initial
    state by ``1 + perturb·m`` — E parameter variants of one scenario),
    and the step function stays the per-member local step — pair it with
    ``RunSpec(ensemble=E)`` so the scheduler's `ResilientRun` vmaps the
    chunk and trips the guard per member. One admitted job then serves E
    scenario users through one set of collectives, with per-member gauges
    in the job's scoped registry (`hooks.observe_member_health`).

    ``tuned`` (a `telemetry.TunedConfig` / dict / path — pair it with
    ``RunSpec(tuned=...)`` so the driver scopes the wire knobs too)
    applies the auto-tuner's STRUCTURAL knobs at setup: the model is
    built with the tuned ``overlap`` and ``comm_every``; a deep cadence
    makes the job's step the deep-halo SUPER-STEP (one call = the
    cadence cycle of physical steps — size ``nt`` in super-steps and
    init the job's grid with the cadence's ``halowidths[d] =
    depth*k_d`` / ``overlaps[d] = 2*depth*k_d``; the tuned config's
    ``grid.winner`` records exactly that geometry). An unset
    ``ensemble`` argument inherits the tuned one. A tuned config for a
    DIFFERENT model raises — silently applying another family's knobs
    would be a misconfiguration, not a tuning."""
    if model not in BUILTIN_MODELS:
        raise InvalidArgumentError(
            f"Unknown model {model!r}; available: "
            f"{sorted(BUILTIN_MODELS)}.")
    from ..telemetry.tune import resolve_tuned

    cfg = resolve_tuned(tuned)
    if cfg is not None and cfg.model != model:
        raise InvalidArgumentError(
            f"builtin_setup: tuned config is for model {cfg.model!r}, "
            f"job runs {model!r} — refusing to apply another family's "
            "knobs.")
    if ensemble is None and cfg is not None:
        ensemble = cfg.ensemble
    if ensemble is not None and int(ensemble) < 1:
        raise InvalidArgumentError(
            f"builtin_setup: ensemble must be >= 1; got {ensemble}.")
    import numpy as np

    dt = np.dtype(dtype).type

    def setup():
        step, state = BUILTIN_MODELS[model](dt, cfg)
        if ensemble is not None:
            from ..models.common import ensemble_state

            state = ensemble_state(state, int(ensemble), perturb=perturb)
        return step, state

    setup.__qualname__ = (
        f"builtin_setup({model!r}, {dtype!r}"
        + (f", ensemble={int(ensemble)}" if ensemble is not None else "")
        + (f", tuned={cfg.comm_every}/{cfg.wire_dtype}"
           if cfg is not None else "")
        + ")")
    return setup


def jobspec_from_json(rec: dict, *, where: str = "job record") -> JobSpec:
    """Build a `JobSpec` from one queue-JSON job record — THE schema of
    ``tools jobs submit`` and ``POST /v1/jobs`` (one code path, so the
    CLI and the HTTP API can never diverge):

        {"name": ..., "model": ..., "nt": ...,         # required
         "grid": {...}, "dtype": "float32",            # optional
         "priority": 1, "deadline_s": ..., "perturb": 0.0,
         "run": {... RunSpec knobs, incl. "tuned"/"ensemble" ...}}

    ``where`` labels errors (a file path, an HTTP request id). Unknown
    top-level keys and unknown ``run`` knobs raise `InvalidArgumentError`
    loudly — a typo'd knob must fail, not silently default."""
    if not isinstance(rec, dict):
        raise InvalidArgumentError(
            f"{where}: a job record must be a JSON object; got "
            f"{type(rec).__name__}.")
    rec = dict(rec)
    # transport envelope, not a job knob: the submit span's W3C header
    # the API stamped into the record (the claiming scheduler reads it
    # off the RAW record; the spec itself stays trace-free)
    rec.pop("traceparent", None)
    missing = [k for k in ("name", "model", "nt") if k not in rec]
    if missing:
        raise InvalidArgumentError(
            f"{where}: missing required key(s) {missing}.")
    run = dict(rec.pop("run", {}) or {})
    # runner caching across chunks needs a key; the job name is the
    # natural one
    run.setdefault("key", ("jobs_cli", rec.get("name")))
    model = rec.pop("model")
    try:
        # a batched job is JSON-describable end-to-end: the RunSpec's
        # ensemble knob also drives the setup's member stacking
        # ("perturb" ramps the members into parameter variants), and a
        # "tuned" path applies the auto-tuner's knob set on both sides —
        # the setup (structural: comm_every/overlap/ensemble) and the
        # driver (trace-time: wire/coalesce env)
        spec = JobSpec(
            name=rec.pop("name"),
            setup=builtin_setup(model,
                                rec.pop("dtype", "float32"),
                                ensemble=run.get("ensemble"),
                                perturb=rec.pop("perturb", 0.0),
                                tuned=run.get("tuned")),
            nt=rec.pop("nt"),
            grid=dict(rec.pop("grid", {}) or {}),
            run=RunSpec(**run),
            priority=rec.pop("priority", 1),
            deadline_s=rec.pop("deadline_s", None),
            model=model)
    except TypeError as e:
        # RunSpec(**run) with an unknown knob — surface it as the typed
        # validation error every caller (CLI exit, HTTP 400) handles
        raise InvalidArgumentError(
            f"{where}: bad 'run' knob set ({e}).") from e
    if rec:  # a typo'd knob must fail, not silently default
        raise InvalidArgumentError(
            f"{where}: job {spec.name!r} has unknown key(s) "
            f"{sorted(rec)} (supervised-run knobs belong inside 'run').")
    return spec
