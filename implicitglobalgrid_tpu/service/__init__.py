"""Multi-run scheduler: the mesh as a persistent simulation service
(ISSUE 8 tentpole; no reference analog — the reference is one script,
one run, one exit).

`MeshScheduler` owns the device mesh and multiplexes QUEUED jobs through
it in chunk-granular time slices over the existing runner cache: every
job gets its own grid (different models/grid sizes share one device
pool), its own `runtime.ResilientRun` (checkpoints, snapshots, reducers,
perf watch, audit — the PR 2-7 surface, per tenant), and its own flight
JSONL; the scheduler owns the long-lived /metrics + /healthz endpoint
with per-job labeled gauges. `service_report`/`export_service_trace`
reconstruct the interleaved schedule post-hoc (one Perfetto track per
job); `tools jobs submit|list|status|cancel|drain` is the operator CLI.

Producers outside the scheduler process reach it through a
`QueueBackend` (`DirectoryBackend` = queue-JSON records + the control-
file protocol under one directory, atomic-rename claims so N schedulers
partition jobs without double-admission); `jobspec_from_json` is the one
record-to-`JobSpec` code path the CLI (`tools jobs submit`) and the HTTP
front door (`serve.JobApiServer`) share. Jobs with a ``deadline_s`` are
priced at admission (`telemetry.predict_step`) and REJECTED when their
completion provably busts the budget.

The CLOSED LOOP (ISSUE 19): `MeshScheduler(autoscale=AutoscalePolicy(...))`
runs an `Autoscaler` at every slice boundary — it reads the live signals
(deadline slack, queue pressure), generates candidate ``dims`` moves
inside per-job `ScaleBounds`, PRICES each with `telemetry.predict_step`
+ `predict_reshard` (a move files only when its amortized break-even
lands inside the job's remaining horizon), damps bounced signals with
hysteresis + cooldown, actuates through the control-file path, re-tunes
the resized job at the boundary, and journals every decision —
rejections included — as ``autoscale_decision`` records that
`service_report` and ``tools autoscale explain`` reconstruct.
"""

from .autoscale import Autoscaler, AutoscalePolicy, ScaleBounds
from .backend import DirectoryBackend, QueueBackend
from .job import (
    BUILTIN_MODELS, Job, JobSpec, JobState, builtin_setup,
    jobspec_from_json,
)
from .policies import (
    FairSharePolicy, FifoPolicy, POLICIES, RoundRobinPolicy,
    SchedulingPolicy, resolve_policy,
)
from .report import (
    explain_autoscale, export_service_trace, is_service_dir,
    service_report,
)
from .scheduler import MeshScheduler

__all__ = [
    "MeshScheduler",
    "JobSpec", "Job", "JobState", "builtin_setup", "BUILTIN_MODELS",
    "jobspec_from_json",
    "QueueBackend", "DirectoryBackend",
    "SchedulingPolicy", "FifoPolicy", "RoundRobinPolicy",
    "FairSharePolicy", "POLICIES", "resolve_policy",
    "service_report", "export_service_trace", "is_service_dir",
    "Autoscaler", "AutoscalePolicy", "ScaleBounds", "explain_autoscale",
]
