"""Closed-loop autoscaler: the mesh resizes itself (ISSUE 19 tentpole).

Every ingredient existed — on-device `resize` (PR 14), `predict_step` /
`predict_reshard` pricing (PR 6/14), `tuned_stale` re-tune triggers
(PR 14), the live plane's queue-pressure / deadline-slack signals
(PR 18), control-file actuation — and no policy connected them. This
module is the POLICY: an `Autoscaler` runs inside `MeshScheduler` at
slice boundaries (``MeshScheduler(autoscale=AutoscalePolicy(...))``),
reads the scheduler's live signal snapshot, and drives elastic resizes
through the EXISTING control path with priced, journaled, explainable
decisions:

1. **Signals.** Per-job deadline slack (the driver's live
   ``deadline_slack_s``), queue pressure (backend backlog + queued
   jobs), per-job perf regressions / guard trips, and mesh utilization
   ride in every decision record; the policy acts on slack and
   pressure.
2. **Candidates.** A starved job (slack below ``grow_slack_s``, under
   its `ScaleBounds` max) wants to GROW; when the mesh is contended
   (some tenant starved, or the queue backlog at/above
   ``shrink_queue_pending``) an unpressured job above its min wants to
   SHRINK. Candidate ``dims`` double or halve one mesh axis, keeping
   the IMPLICIT GLOBAL GRID fixed — only even re-blockings within the
   device pool and the job's bounds survive.
3. **Pricing.** Before acting, every candidate geometry is priced with
   `predict_step` on its OWN grid (swapped in host-side, exactly like
   `tune_config` phase 1 — nothing allocates) and the winning move's
   transfer is priced with `predict_reshard`; the shared
   `ReshardPrediction.amortized_break_even_steps` verdict gates it: a
   grow files only when the break-even lands inside the job's
   remaining ``nt`` horizon, a shrink only when the job can afford the
   priced slowdown inside its deadline slack.
4. **Hysteresis + cooldown.** An action must be wanted for
   ``hysteresis_slices`` CONSECUTIVE boundaries before it is priced,
   and a job that just moved (or was just priced out) is frozen for
   ``cooldown_slices`` boundaries — a bounced signal cannot thrash the
   mesh (proven in tests/test_autoscale.py).
5. **Actuation.** The winning move files through the queue backend's
   control files (``control("resize", ...)``) — the same journal chain
   an operator's ``tools jobs resize`` produces (``autoscale_decision``
   -> ``control`` -> ``resize_requested`` -> ``job_resized``) — so the
   autoscaler has no private path into the mesh.
6. **Re-tune + reprice.** After the resize applies, the scheduler
   re-RUNS `tune_config` (model-only, trace-time knobs — the step
   function is already built) against the NEW geometry and applies the
   winner (`ResilientRun.apply_tuned`), closing the tuner rung that
   previously only cleared the stale config; the driver's perf-model
   unit price is re-priced (`ResilientRun.reprice`) so deadline slack
   tracks the new geometry and the loop converges.
7. **Explainability.** EVERY decision — rejections included — journals
   as an ``autoscale_decision`` record carrying the signal snapshot and
   the full pricing breakdown (a repeated identical rejection collapses
   to its first record; the ``igg_autoscale_*`` counters still count
   each). `service_report` folds them into an ``autoscale`` section and
   ``tools autoscale explain`` reconstructs the WHY of each move from
   the journal alone.

The steady-state cost is dict arithmetic: grid swaps and pricing only
run once a streak matures past hysteresis, so the per-boundary decision
cost stays far under the 2%-of-slice gate (bench_autoscale.py).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field as dc_field

from ..telemetry import hooks
from ..utils.exceptions import (
    IncoherentArgumentError, InvalidArgumentError,
)

__all__ = ["ScaleBounds", "AutoscalePolicy", "Autoscaler"]


@dataclass(frozen=True)
class ScaleBounds:
    """Per-job device-count bounds the policy must respect.
    ``max_devices=None`` = the device pool is the ceiling."""

    min_devices: int = 1
    max_devices: int | None = None

    def __post_init__(self):
        if int(self.min_devices) < 1:
            raise InvalidArgumentError(
                f"ScaleBounds.min_devices must be >= 1; got "
                f"{self.min_devices!r}.")
        if self.max_devices is not None \
                and int(self.max_devices) < int(self.min_devices):
            raise InvalidArgumentError(
                f"ScaleBounds: max_devices ({self.max_devices!r}) < "
                f"min_devices ({self.min_devices!r}).")

    def to_json(self) -> dict:
        return {"min_devices": int(self.min_devices),
                "max_devices": (None if self.max_devices is None
                                else int(self.max_devices))}


@dataclass(frozen=True)
class AutoscalePolicy:
    """The declarative knob set of the closed loop (module docstring).

    ``grow_slack_s``: a RUNNING job whose live deadline slack drops
    below this wants to grow (default 0.0 = only provable busts).
    ``shrink_queue_pending``: queue backlog (unclaimed + queued) at or
    above this marks the mesh contended even with no starved tenant.
    ``hysteresis_slices``: consecutive boundary votes an action needs
    before it is priced. ``cooldown_slices``: boundaries a job is
    frozen after a filed (or priced-out) move. ``max_moves_per_eval``:
    moves filed per boundary (the rest keep their streak and file on
    later boundaries). ``via``: the resize path handed to the driver
    (``auto`` | ``device`` | ``checkpoint``). ``retune``: re-run
    `tune_config` against the new geometry once a resize applies.
    ``bounds``: per-job-name `ScaleBounds` overrides over
    ``default_bounds``."""

    grow_slack_s: float = 0.0
    shrink_queue_pending: int = 1
    hysteresis_slices: int = 2
    cooldown_slices: int = 4
    max_moves_per_eval: int = 1
    via: str = "auto"
    retune: bool = True
    default_bounds: ScaleBounds = ScaleBounds()
    bounds: dict = dc_field(default_factory=dict)

    def __post_init__(self):
        if self.via not in ("auto", "device", "checkpoint"):
            raise InvalidArgumentError(
                f"AutoscalePolicy.via must be auto|device|checkpoint; "
                f"got {self.via!r}.")
        if int(self.hysteresis_slices) < 1:
            raise InvalidArgumentError(
                "AutoscalePolicy.hysteresis_slices must be >= 1 (1 = "
                f"act on the first vote); got {self.hysteresis_slices!r}.")
        if int(self.cooldown_slices) < 0:
            raise InvalidArgumentError(
                "AutoscalePolicy.cooldown_slices must be >= 0; got "
                f"{self.cooldown_slices!r}.")
        for name, b in dict(self.bounds).items():
            if not isinstance(b, ScaleBounds):
                raise InvalidArgumentError(
                    f"AutoscalePolicy.bounds[{name!r}] must be a "
                    f"ScaleBounds; got {type(b).__name__}.")

    def bounds_for(self, name: str) -> ScaleBounds:
        return self.bounds.get(name, self.default_bounds)

    def describe(self) -> dict:
        """JSON-able policy echo (``scheduler_start`` journal +
        ``/v1/observe``)."""
        return {"grow_slack_s": float(self.grow_slack_s),
                "shrink_queue_pending": int(self.shrink_queue_pending),
                "hysteresis_slices": int(self.hysteresis_slices),
                "cooldown_slices": int(self.cooldown_slices),
                "max_moves_per_eval": int(self.max_moves_per_eval),
                "via": self.via, "retune": bool(self.retune),
                "default_bounds": self.default_bounds.to_json(),
                "bounds": {k: v.to_json()
                           for k, v in self.bounds.items()}}


class Autoscaler:
    """The policy engine (module docstring). Constructed standalone
    (``Autoscaler(policy)``) and attached by the scheduler
    (``MeshScheduler(autoscale=...)`` calls `attach`), or fed synthetic
    signal snapshots directly through `evaluate` (how the thrash test
    proves hysteresis). ``evaluations`` / ``moves_filed`` /
    ``last_decision_s`` / ``decision_s_total`` are the bench
    accounting surface."""

    def __init__(self, policy: AutoscalePolicy | None = None, *,
                 scheduler=None):
        if policy is None:
            policy = AutoscalePolicy()
        elif isinstance(policy, dict):
            policy = AutoscalePolicy(**policy)
        if not isinstance(policy, AutoscalePolicy):
            raise InvalidArgumentError(
                f"Autoscaler takes an AutoscalePolicy (or its kwargs "
                f"dict); got {type(policy).__name__}.")
        self.policy = policy
        self.sched = None
        self._streak: dict = {}      # (job, action) -> consecutive votes
        self._cooldown: dict = {}    # job -> boundaries left frozen
        self._last_verdict: dict = {}  # (job, action) -> (verdict, reason)
        self.evaluations = 0
        self.moves_filed = 0
        self.last_decision_s = 0.0
        self.decision_s_total = 0.0
        # recent per-boundary costs (bench surface): the median is the
        # steady-state dict-arithmetic cost; the max shows the rare
        # boundary that actually priced a matured streak
        from collections import deque

        self.decision_s_recent: deque = deque(maxlen=256)
        if scheduler is not None:
            self.attach(scheduler)

    def attach(self, scheduler) -> None:
        """Bind to the scheduler whose jobs this policy moves (journal,
        job table, queue backend)."""
        self.sched = scheduler

    # -- the boundary evaluation -------------------------------------------

    def evaluate(self, signals: dict) -> list:
        """One policy pass over a live-signal snapshot
        (`MeshScheduler._live_signals` shape). Returns the decision
        records of this boundary (journaled as ``autoscale_decision``);
        files at most ``max_moves_per_eval`` resizes through the
        control path."""
        t0 = time.perf_counter()
        try:
            decisions = self._evaluate(signals)
        finally:
            dt = time.perf_counter() - t0
            self.last_decision_s = dt
            self.decision_s_total += dt
            self.decision_s_recent.append(dt)
            self.evaluations += 1
        return decisions

    def _evaluate(self, signals: dict) -> list:
        pol = self.policy
        jobs = signals.get("jobs", {}) or {}
        queue = signals.get("queue", {}) or {}
        for name in list(self._cooldown):
            self._cooldown[name] -= 1
            if self._cooldown[name] <= 0:
                del self._cooldown[name]
        running = {n: s for n, s in jobs.items()
                   if s.get("state") == "running"}
        starved = {
            n for n, s in running.items()
            if s.get("deadline_slack_s") is not None
            and float(s["deadline_slack_s"]) < float(pol.grow_slack_s)}
        pending = int(queue.get("pending") or 0) \
            + int(queue.get("queued") or 0)
        contended = bool(starved) \
            or pending >= int(pol.shrink_queue_pending)
        util = self._mesh_utilization(running)
        desired = {}
        for name, sig in running.items():
            devices = self._devices(name, sig)
            lo, hi = self._bounds(name, devices)
            if name in starved:
                if devices is None or hi is None or devices < hi:
                    desired[name] = "grow"
            elif contended and devices is not None and devices > lo:
                desired[name] = "shrink"
        # a vote that did not repeat resets its streak — the hysteresis
        # contract is CONSECUTIVE boundaries
        for key in list(self._streak):
            if desired.get(key[0]) != key[1]:
                del self._streak[key]
        ctx = {"queue": {"pending": queue.get("pending"),
                         "queued": queue.get("queued"),
                         "oldest_age_s": queue.get("oldest_age_s")},
               "starved": sorted(starved),
               "mesh_utilization": util}
        decisions = []
        moves = 0
        # grows first (highest priority first), then shrinks
        order = sorted(
            desired.items(),
            key=lambda kv: (kv[1] != "grow",
                            -int(running[kv[0]].get("priority") or 1)))
        for name, action in order:
            key = (name, action)
            self._streak[key] = streak = self._streak.get(key, 0) + 1
            base = dict(job=name, action=action, streak=streak,
                        signals=dict(running[name], **ctx))
            if streak < int(pol.hysteresis_slices):
                decisions.append(self._decide(
                    **base, verdict="rejected", reason="hysteresis"))
                continue
            if name in self._cooldown:
                decisions.append(self._decide(
                    **base, verdict="rejected", reason="cooldown",
                    cooldown_left=self._cooldown[name]))
                continue
            if moves >= int(pol.max_moves_per_eval):
                # keep the streak: the move files at a later boundary
                decisions.append(self._decide(
                    **base, verdict="rejected", reason="move_budget"))
                continue
            rec = self._plan_move(base)
            decisions.append(rec)
            if rec["verdict"] == "filed":
                moves += 1
                self.moves_filed += 1
                self._streak.pop(key, None)
            if rec.get("priced"):
                # pricing ran (grid swaps + cost model): freeze the job
                # whether or not the move filed, so a persistently
                # priced-out signal cannot re-price every boundary
                self._cooldown[name] = max(1, int(pol.cooldown_slices))
        return decisions

    # -- decision bookkeeping ----------------------------------------------

    def _decide(self, *, job: str, action: str, verdict: str,
                reason: str | None = None, **fields) -> dict:
        """Count + journal one decision. Rejections journal on every
        (verdict, reason) CHANGE per (job, action) — the counters count
        every one; the journal stays readable. Filed moves always
        journal."""
        rec = dict(job=job, action=action, verdict=verdict,
                   reason=reason, **fields)
        hooks.note_autoscale_decision(action, verdict, reason)
        key = (job, action)
        if verdict == "filed" \
                or self._last_verdict.get(key) != (verdict, reason):
            self._log("autoscale_decision", **rec)
        self._last_verdict[key] = (verdict, reason)
        return rec

    def _log(self, kind: str, **fields) -> None:
        if self.sched is not None:
            self.sched._log(kind, **fields)

    def _job(self, name: str):
        return None if self.sched is None else self.sched.jobs.get(name)

    def _devices(self, name: str, sig: dict):
        d = sig.get("devices")
        if d:
            return int(d)
        job = self._job(name)
        if job is not None and job.gg is not None:
            dims = job.gg.dims
            return int(dims[0]) * int(dims[1]) * int(dims[2])
        return None

    def _bounds(self, name: str, devices) -> tuple:
        b = self.policy.bounds_for(name)
        return int(b.min_devices), \
            (None if b.max_devices is None else int(b.max_devices))

    def _mesh_utilization(self, running: dict):
        """Sum of running jobs' targeted devices over the pool (> 1 is
        normal — tenants share the pool; it rides in every record as
        context)."""
        total = 0
        for name, sig in running.items():
            d = self._devices(name, sig)
            if d is None:
                return None
            total += d
        try:
            import jax

            return total / max(1, jax.device_count())
        except Exception:
            return None

    # -- candidate generation + pricing -------------------------------------

    def _plan_move(self, base: dict) -> dict:
        """Generate candidate ``dims``, price them, verdict the best,
        file it. Only runs once hysteresis + cooldown pass."""
        name, action = base["job"], base["action"]
        job = self._job(name)
        if job is None or job.run is None or job.gg is None \
                or job.run.done:
            return self._decide(**base, verdict="rejected",
                                reason="no_live_job")
        if job.resize_requested is not None \
                or getattr(job, "_autoscale_filed", None):
            # a filed move is still in flight (applies at the job's next
            # granted slice) — re-filing would stack duplicate controls
            return self._decide(**base, verdict="rejected",
                                reason="resize_pending")
        from ..telemetry.tune import _MODEL_STAGGER

        model = job.spec.model
        if model not in _MODEL_STAGGER:
            return self._decide(**base, verdict="rejected",
                                reason="unpriceable",
                                detail=f"model {model!r} has no priced "
                                       "workload")
        cands = self._candidate_dims(job, action)
        cur_dims = tuple(int(d) for d in job.gg.dims)
        if not cands:
            return self._decide(**base, verdict="rejected",
                                reason="no_feasible_dims",
                                dims=list(cur_dims))
        try:
            pricing = self._price_move(job, cur_dims, cands)
        except Exception as e:
            return self._decide(**base, verdict="rejected",
                                reason="plan_error", priced=True,
                                dims=list(cur_dims),
                                error=f"{type(e).__name__}: {e}")
        be = pricing["break_even"]
        if action == "grow":
            ok = bool(be["within_horizon"])
            reason = None if ok else "priced_out"
        else:
            # a shrink is a priced slowdown: the job must afford it
            # inside its live slack (jobs without a deadline always can)
            slack = base["signals"].get("deadline_slack_s")
            ok = slack is None or float(slack) + be["net_gain_s"] >= 0.0
            reason = None if ok else "priced_out"
        if not ok:
            return self._decide(**base, verdict="rejected", reason=reason,
                                priced=True, dims=list(cur_dims),
                                new_dims=list(pricing["new_dims"]),
                                pricing=pricing)
        try:
            self._file(job, pricing["new_dims"], pricing["new_unit_s"])
        except Exception as e:
            return self._decide(**base, verdict="rejected",
                                reason="file_error", priced=True,
                                dims=list(cur_dims),
                                new_dims=list(pricing["new_dims"]),
                                error=f"{type(e).__name__}: {e}")
        return self._decide(**base, verdict="filed", priced=True,
                            dims=list(cur_dims),
                            new_dims=list(pricing["new_dims"]),
                            via=self.policy.via, pricing=pricing)

    def _candidate_dims(self, job, action: str) -> list:
        """Feasible one-axis doubles (grow) / halves (shrink) of the
        job's dims: even re-blocking of the SAME implicit global grid,
        inside the device pool and the job's `ScaleBounds`."""
        from ..reshard.plan import device_pool
        from ..telemetry.tune import _grid_ok

        gg = job.gg
        dims = tuple(int(d) for d in gg.dims)
        n = tuple(int(v) for v in gg.nxyz)
        ol = tuple(int(o) for o in gg.overlaps)
        hw = tuple(int(h) for h in gg.halowidths)
        periods = tuple(int(p) for p in gg.periods)
        glob = tuple(dims[d] * (n[d] - ol[d]) + ol[d] for d in range(3))
        pool = len(device_pool(gg))
        b = self.policy.bounds_for(job.name)
        lo = int(b.min_devices)
        hi = pool if b.max_devices is None else min(
            pool, int(b.max_devices))
        out = []
        for d in range(3):
            c = list(dims)
            if action == "grow":
                c[d] *= 2
            elif dims[d] % 2 == 0:
                c[d] //= 2
            else:
                continue
            ndev = c[0] * c[1] * c[2]
            if not lo <= ndev <= hi or tuple(c) == dims:
                continue
            cand_n = []
            for e in range(3):
                span = glob[e] - ol[e]
                if span % c[e]:
                    cand_n = None
                    break
                cand_n.append(span // c[e] + ol[e])
            if cand_n is None:
                continue
            kw = dict(nx=cand_n[0], ny=cand_n[1], nz=cand_n[2],
                      dimx=c[0], dimy=c[1], dimz=c[2],
                      periodx=periods[0], periody=periods[1],
                      periodz=periods[2], overlaps=ol, halowidths=hw,
                      quiet=True)
            if not _grid_ok(kw):
                continue
            out.append((tuple(c), kw))
        return out

    def _price_move(self, job, cur_dims: tuple, cands: list) -> dict:
        """Price the current geometry and every candidate with
        `predict_step` (each on its OWN host-side grid — the
        `tune_config` phase-1 idiom; model-vs-model so the gain ratio is
        honest), pick the fastest candidate, price its transfer with
        `predict_reshard`, and return the full breakdown including the
        shared break-even verdict."""
        from ..models.common import resolve_comm_every
        from ..parallel import topology as top
        from ..parallel.grid import finalize_global_grid, init_global_grid
        from ..reshard.plan import (
            build_reshard_plan, fields_of_state, live_topology,
        )
        from ..telemetry.perfmodel import (
            default_machine_profile, predict_reshard, predict_step,
        )
        from ..telemetry.tune import _model_fields

        model = job.spec.model
        run = job.run
        E = run.ensemble
        dtype = next(iter(run.state.values())).dtype
        tuned = run.tuned
        knobs = dict(comm_every=1, overlap=False, coalesce=None,
                     wire_dtype=None, wire_stage=None)
        if tuned is not None:
            knobs = dict(comm_every=tuned.comm_every,
                         overlap=bool(tuned.overlap),
                         coalesce=tuned.coalesce,
                         wire_dtype=tuned.wire_dtype,
                         wire_stage=tuned.wire_stage)
        # the boundary has NO current grid — resolve the profile from the
        # job's own grid instead of the (uninitialized) global one
        dt = getattr(job.gg, "device_type", None)
        profile = default_machine_profile(
            dt if dt and dt != "none" else "cpu")
        cadence = resolve_comm_every(knobs["comm_every"])
        spu = cadence.cycle if cadence.deep else 1
        src_topo = live_topology(job.gg)

        def price(kw) -> float:
            init_global_grid(**kw)
            try:
                cgg = top.global_grid()
                hw = tuple(int(h) for h in cgg.halowidths)
                fields = _model_fields(model, cgg, hw, dtype)
                pred = predict_step(model, fields, profile=profile,
                                    ensemble=E, **knobs)
            finally:
                finalize_global_grid()
            return float(pred["step_s"]) * spu

        n = tuple(int(v) for v in src_topo["nxyz"])
        cur_kw = dict(
            nx=n[0], ny=n[1], nz=n[2],
            dimx=cur_dims[0], dimy=cur_dims[1], dimz=cur_dims[2],
            periodx=int(src_topo["periods"][0]),
            periody=int(src_topo["periods"][1]),
            periodz=int(src_topo["periods"][2]),
            overlaps=tuple(int(o) for o in src_topo["overlaps"]),
            halowidths=tuple(int(h) for h in src_topo["halowidths"]),
            quiet=True)
        prev = top.swap_global_grid(None)
        if prev is not None:
            top.retain_epoch(prev.epoch)
        try:
            old_unit_s = price(cur_kw)
            priced = []
            for dims_c, kw in cands:
                try:
                    priced.append((price(kw), dims_c))
                except (InvalidArgumentError,
                        IncoherentArgumentError):
                    continue
            if not priced:
                raise InvalidArgumentError(
                    "every candidate geometry refused pricing")
            priced.sort(key=lambda t: t[0])
            new_unit_s, new_dims = priced[0]
        finally:
            if prev is not None:
                top.swap_global_grid(prev)
                top.release_epoch(prev.epoch)
        plan = build_reshard_plan(src_topo, new_dims,
                                  fields_of_state(run.state))
        rp = predict_reshard(plan, profile=profile)
        nt_remaining = max(0, int(job.spec.nt) - int(job.step))
        be = rp.amortized_break_even_steps(nt_remaining, old_unit_s,
                                           new_unit_s)
        return {"new_dims": list(new_dims),
                "old_unit_s": old_unit_s, "new_unit_s": new_unit_s,
                "steps_per_unit": spu,
                "candidates": [{"dims": list(d), "unit_s": s}
                               for s, d in priced],
                "reshard": {k: rp[k] for k in
                            ("rounds", "wire_bytes", "seconds",
                             "profile_source")},
                "break_even": be}

    # -- actuation -----------------------------------------------------------

    def _file(self, job, new_dims, new_unit_s: float) -> None:
        """File the move through the EXISTING control path (the queue
        backend's control files — the same chain ``tools jobs resize``
        produces), falling back to the scheduler's direct `resize` when
        no backend exists. Stashes the priced new-geometry unit cost on
        the job so the scheduler re-prices the driver once the resize
        actually APPLIES (`MeshScheduler._slice` ->
        `Autoscaler.on_resized`)."""
        dims = [int(d) for d in new_dims]
        job._autoscale_filed = (tuple(dims), float(new_unit_s))
        q = None if self.sched is None else self.sched.queue
        if q is not None:
            q.control("resize", job.name,
                      {"new_dims": dims, "via": self.policy.via})
        elif self.sched is not None:
            self.sched.resize(job.name, dims, via=self.policy.via)
        else:
            raise InvalidArgumentError(
                "Autoscaler is not attached to a scheduler — nothing "
                "can actuate the move.")

    def on_resized(self, job, new_dims) -> None:
        """Scheduler callback once a resize APPLIED: when it matches the
        move this policy filed, hand the priced new-geometry unit cost
        to the driver (`ResilientRun.reprice`) so deadline slack tracks
        the new geometry — the convergence half of the loop (the re-tune
        then refines the price further)."""
        filed = getattr(job, "_autoscale_filed", None)
        if filed is None:
            return
        dims, unit_s = filed
        job._autoscale_filed = None  # any applied resize supersedes ours
        if tuple(int(d) for d in new_dims) != dims:
            return  # an operator raced us — their resize, their price
        if job.run is not None and unit_s and unit_s > 0:
            job.run.reprice(unit_s, source="autoscale")

    def on_resize_rejected(self, job) -> None:
        """Scheduler callback when a pending resize was REJECTED: clear
        the in-flight stash so the policy is free to vote again (the
        rejection is already journaled as ``resize_rejected``)."""
        job._autoscale_filed = None
