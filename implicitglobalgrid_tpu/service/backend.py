"""Pluggable queue backends: where job submissions and control
requests live OUTSIDE the scheduler process.

`MeshScheduler.submit` is an in-process call; everything else — the
`tools jobs` CLI, the `serve.JobApiServer` HTTP front door, a second
scheduler sharing the load — talks to the scheduler through a
`QueueBackend`. The backend owns two channels under one root:

``queue/``
    One JSON record per pending job (the `tools jobs submit` queue-JSON
    job schema — see `service.job.jobspec_from_json`). Producers write
    with the atomic ``.tmp`` + ``os.replace`` idiom; consumers CLAIM a
    record with a single atomic ``os.rename`` to an owner-stamped name,
    so N schedulers over one backend partition jobs with zero
    double-admissions: exactly one rename wins, every loser gets
    ``FileNotFoundError`` and moves on.

``control/``
    The PR-8 control-file protocol, verbatim: ``drain`` (empty file),
    ``cancel_<name>`` (empty file; may carry an optional JSON payload —
    requester trace context), ``resize_<name>`` (JSON payload
    ``{"new_dims": [...], "via": ...}``). ``.tmp`` staging files are
    skipped; consuming a request removes the file.

`DirectoryBackend` is the reference implementation and exactly the
behavior `MeshScheduler._poll_control` shipped with — the scheduler now
routes through it, so the CLI, the HTTP API, and any future backend
(a real message queue) can never diverge from each other.
"""

from __future__ import annotations

import itertools
import json
import os

from ..utils.exceptions import InvalidArgumentError

__all__ = ["QueueBackend", "DirectoryBackend"]

_owner_seq = itertools.count()


class QueueBackend:
    """Interface between job producers (CLI, HTTP API) and job
    consumers (schedulers). All methods are synchronous and must be
    safe to call from multiple processes against the same backing
    store; `claim` must be ATOMIC (at most one caller wins each
    record)."""

    # -- producer side -----------------------------------------------------

    def submit(self, record: dict) -> str:
        """Enqueue one job record (queue-JSON job schema). Returns the
        job name. Raises `InvalidArgumentError` on a malformed record or
        a duplicate pending name."""
        raise NotImplementedError

    def control(self, request: str, job: str | None = None,
                payload: dict | None = None) -> None:
        """File one control request: ``drain`` | ``cancel`` (needs
        ``job``; ``payload`` optional — e.g. the requester's trace
        context) | ``resize`` (needs ``job`` + ``payload``)."""
        raise NotImplementedError

    # -- consumer side -----------------------------------------------------

    def pending(self) -> list:
        """Names of unclaimed records, in claim order."""
        raise NotImplementedError

    def pending_count(self) -> int:
        """Number of unclaimed records — the queue-pressure gauge
        (``igg_queue_pending``). Backends override when they can count
        cheaper than listing; the default is ``len(self.pending())``."""
        return len(self.pending())

    def oldest_age_s(self) -> float | None:
        """Age in seconds of the OLDEST unclaimed record (None when the
        queue is empty or the backend cannot tell) — the starvation
        signal (``igg_queue_oldest_age_seconds``) next to the count.
        Purely observational: never claims, never mutates."""
        return None

    def claim(self) -> dict | None:
        """Atomically claim the next pending record. Returns ``None``
        when the queue is empty, else ``{"name", "record", "error"}``
        — ``record`` is the parsed JSON (None when unreadable, with
        ``error`` set). A claimed record is this consumer's alone."""
        raise NotImplementedError

    def discard(self, name: str) -> bool:
        """Atomically remove a still-PENDING record (a cancel that
        beat every scheduler to it). True when this caller won the
        removal; False when the record was already claimed or gone."""
        raise NotImplementedError

    def poll_control(self) -> list:
        """Consume every complete control request, in filing order.
        Returns dicts: ``{"request": "drain"}``,
        ``{"request": "cancel", "job": name, "payload": dict|None}``,
        ``{"request": "resize", "job": name, "payload": dict|None}``
        (payload None = unreadable file — the scheduler journals the
        rejection; never drop an operator request silently)."""
        raise NotImplementedError


class DirectoryBackend(QueueBackend):
    """Filesystem queue under ``root`` (``queue/`` + ``control/``
    subdirectories — `MeshScheduler` points it at its ``flight_dir`` so
    the journal, the queue, and the control channel share one
    directory). ``owner`` stamps claimed records
    (``<name>.json.claimed-<owner>``) for the journal/report to
    attribute; it defaults to a per-process unique tag."""

    def __init__(self, root, *, owner: str | None = None):
        self.root = str(root)
        self.owner = (str(owner) if owner is not None
                      else f"pid{os.getpid()}-{next(_owner_seq)}")
        if "/" in self.owner:
            raise InvalidArgumentError(
                f"DirectoryBackend.owner must be slash-free (it lands "
                f"in filenames); got {self.owner!r}.")
        self.queue_dir = os.path.join(self.root, "queue")
        self.control_dir = os.path.join(self.root, "control")
        os.makedirs(self.queue_dir, exist_ok=True)
        os.makedirs(self.control_dir, exist_ok=True)

    # -- producer side -----------------------------------------------------

    def submit(self, record: dict) -> str:
        if not isinstance(record, dict) or not record.get("name"):
            raise InvalidArgumentError(
                "QueueBackend.submit takes one queue-JSON job record "
                "(a dict with at least a 'name').")
        name = str(record["name"])
        if "/" in name or name.startswith("."):
            raise InvalidArgumentError(
                f"job name must be a slash-free, non-dot-leading string "
                f"(it names queue files); got {name!r}.")
        final = os.path.join(self.queue_dir, name + ".json")
        taken = [f for f in os.listdir(self.queue_dir)
                 if f == name + ".json"
                 or f.startswith(name + ".json.claimed-")]
        if taken:
            raise InvalidArgumentError(
                f"A job named {name!r} is already enqueued "
                f"({taken[0]}) — names key queue records.")
        tmp = final + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(record, f)
        os.replace(tmp, final)
        return name

    def control(self, request: str, job: str | None = None,
                payload: dict | None = None) -> None:
        if request == "drain":
            path = os.path.join(self.control_dir, "drain")
            with open(path, "w", encoding="utf-8"):
                pass
            return
        if job is None or "/" in str(job):
            raise InvalidArgumentError(
                f"control({request!r}) needs a slash-free job name; "
                f"got {job!r}.")
        if request == "cancel":
            # the PR-8 protocol's empty file stays valid; an optional
            # JSON payload (e.g. the requesting span's traceparent, or
            # the alert that decided the cancel) rides in the body and
            # old consumers that ignore content are unaffected
            path = os.path.join(self.control_dir, f"cancel_{job}")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                if payload is not None:
                    json.dump(payload, f)
            os.replace(tmp, path)
        elif request == "resize":
            if not isinstance(payload, dict):
                raise InvalidArgumentError(
                    "control('resize') needs a JSON payload dict "
                    "({'new_dims': [...], 'via': ...}).")
            path = os.path.join(self.control_dir, f"resize_{job}")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        else:
            raise InvalidArgumentError(
                f"Unknown control request {request!r} "
                "(drain | cancel | resize).")

    # -- consumer side -----------------------------------------------------

    def pending(self) -> list:
        try:
            names = sorted(os.listdir(self.queue_dir))
        except FileNotFoundError:
            return []
        return [f[:-len(".json")] for f in names
                if f.endswith(".json") and not f.startswith(".")]

    def pending_count(self) -> int:
        # one listdir, no stat calls — cheap enough to stamp per
        # scheduling decision
        try:
            names = os.listdir(self.queue_dir)
        except FileNotFoundError:
            return 0
        return sum(1 for f in names
                   if f.endswith(".json") and not f.startswith("."))

    def oldest_age_s(self) -> float | None:
        import time

        oldest = None
        for name in self.pending():
            path = os.path.join(self.queue_dir, name + ".json")
            try:
                m = os.stat(path).st_mtime
            except FileNotFoundError:
                continue  # claimed between the listing and the stat
            oldest = m if oldest is None else min(oldest, m)
        return None if oldest is None else max(0.0, time.time() - oldest)

    def claim(self) -> dict | None:
        for name in self.pending():
            path = os.path.join(self.queue_dir, name + ".json")
            claimed = path + ".claimed-" + self.owner
            try:
                os.rename(path, claimed)
            except FileNotFoundError:
                continue  # another consumer won this record — move on
            try:
                with open(claimed, encoding="utf-8") as f:
                    record = json.load(f)
                error = None
            except Exception as e:
                record, error = None, f"{type(e).__name__}: {e}"
            return {"name": name, "record": record, "error": error,
                    "path": claimed}
        return None

    def discard(self, name: str) -> bool:
        path = os.path.join(self.queue_dir, str(name) + ".json")
        try:
            os.rename(path, path + ".cancelled")
        except FileNotFoundError:
            return False
        os.remove(path + ".cancelled")
        return True

    def poll_control(self) -> list:
        out = []
        if not os.path.isdir(self.control_dir):
            return out
        for fname in sorted(os.listdir(self.control_dir)):
            path = os.path.join(self.control_dir, fname)
            if fname.endswith(".tmp"):
                continue  # a request still being written (CLI staging)
            if fname == "drain":
                os.remove(path)
                out.append({"request": "drain"})
            elif fname.startswith("cancel_"):
                try:
                    with open(path, encoding="utf-8") as f:
                        raw = f.read().strip()
                    req = json.loads(raw) if raw else None
                except Exception:
                    req = None  # empty/foreign body = legacy cancel
                os.remove(path)
                rec = {"request": "cancel",
                       "job": fname[len("cancel_"):]}
                if isinstance(req, dict):
                    # only a filed JSON body surfaces — a legacy empty
                    # cancel keeps its exact PR-8 wire shape
                    rec["payload"] = req
                out.append(rec)
            elif fname.startswith("resize_"):
                try:
                    with open(path, encoding="utf-8") as f:
                        req = json.load(f)
                except Exception:
                    req = None
                os.remove(path)
                out.append({"request": "resize",
                            "job": fname[len("resize_"):],
                            "payload": req})
        return out
