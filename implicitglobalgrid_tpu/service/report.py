"""Post-hoc service view: the interleaved schedule, reconstructed.

A scheduler run leaves one directory of JSONLs behind: the scheduler's
own journal (``scheduler.jsonl`` — submissions, admissions, every granted
slice, terminal transitions) plus one flight stream per job
(``job_<name>.jsonl`` — the same driver lifecycle a solo `run_resilient`
records). Everything here is reconstructed from those files ALONE, hours
after the process died:

- `service_report(dir)` — the ``"service"`` record: per-tenant accounting
  (slices, mesh-time share, wait fractions, cold compiles, terminal
  state), the interleaved slice schedule, queue-depth trajectory, and a
  per-tenant straggler summary; each job's full `telemetry.run_report`
  rides along under ``jobs.<name>.report``. `igg.run_report(dir)`
  delegates here when it sees a scheduler journal.
- `export_service_trace(dir)` — Chrome/Perfetto trace JSON with ONE TRACK
  PER JOB (each job's chunk/checkpoint/snapshot spans and guard markers,
  exactly as `telemetry.export_chrome_trace` draws a process) plus a
  scheduler track whose slice spans show who owned the mesh when — the
  interleaving is visible as non-overlapping chunk spans across job
  tracks. All streams share one process's monotonic clock, so no
  cross-clock alignment is needed (unlike the multi-process aggregate).
"""

from __future__ import annotations

import json
import os

from ..telemetry.recorder import read_flight_events
from ..utils.exceptions import InvalidArgumentError

__all__ = ["service_report", "export_service_trace", "read_journal",
           "explain_autoscale"]

_JOURNAL = "scheduler.jsonl"
_TERMINAL_KINDS = {"job_done": "done", "job_failed": "failed",
                   "job_cancelled": "cancelled",
                   "job_rejected": "rejected"}


def journal_path(flight_dir) -> str:
    return os.path.join(os.fspath(flight_dir), _JOURNAL)


def is_service_dir(path) -> bool:
    """True when ``path`` is a scheduler flight directory (has a
    journal) — how `run_report` decides to delegate here."""
    try:
        return os.path.isfile(journal_path(path))
    except (TypeError, ValueError):
        return False


def read_journal(source) -> list:
    """Journal events from a flight directory or a journal file path."""
    src = os.fspath(source)
    if os.path.isdir(src):
        src = journal_path(src)
    if not os.path.isfile(src):
        raise InvalidArgumentError(
            f"No scheduler journal at {src} (expected a MeshScheduler "
            "flight_dir or its scheduler.jsonl).")
    return read_flight_events(src)


def _job_file(flight_dir, name: str) -> str | None:
    p = os.path.join(os.fspath(flight_dir), f"job_{name}.jsonl")
    return p if os.path.isfile(p) else None


def _autoscale_moves(events: list) -> list:
    """Reconstruct every autoscaler-FILED move's actuation chain from
    the journal alone: ``autoscale_decision`` (verdict filed) ->
    ``control`` -> ``resize_requested`` -> ``job_resized`` ->
    ``job_retuned`` — the proof each resize went through the same
    control path an operator's would, with its pricing attached."""
    moves: list = []
    pending: dict = {}
    for e in events:
        k = e.get("kind")
        name = e.get("job")
        if k == "autoscale_decision" and e.get("verdict") == "filed":
            rec = {"job": name, "action": e.get("action"),
                   "dims": e.get("dims"), "new_dims": e.get("new_dims"),
                   "t_decided": e.get("t"), "via": e.get("via"),
                   "streak": e.get("streak"),
                   "signals": e.get("signals"),
                   "pricing": e.get("pricing"),
                   "applied": False, "chain": ["autoscale_decision"]}
            pending[name] = rec
            moves.append(rec)
            continue
        rec = pending.get(name)
        if rec is None:
            continue
        if k == "control" and e.get("request") == "resize" \
                and not rec["applied"]:
            rec["chain"].append("control")
        elif k == "resize_requested" and not rec["applied"]:
            rec["chain"].append("resize_requested")
        elif k == "job_resized":
            if list(e.get("new_dims") or ()) \
                    == list(rec["new_dims"] or ()):
                rec.update(applied=True, t_applied=e.get("t"),
                           resize_s=e.get("dur_s"), step=e.get("step"))
                rec["chain"].append("job_resized")
        elif k == "resize_rejected" and not rec["applied"]:
            rec["rejected"] = e.get("error")
            rec["chain"].append("resize_rejected")
            del pending[name]
        elif k == "job_retuned" and rec["applied"]:
            rec["retuned"] = {key: v for key, v in e.items()
                              if key not in ("kind", "t", "run", "job")}
            rec["chain"].append("job_retuned")
            del pending[name]
        elif k == "job_retune_failed" and rec["applied"]:
            rec["retune_failed"] = e.get("error")
            rec["chain"].append("job_retune_failed")
            del pending[name]
    return moves


def _autoscale_section(events: list):
    """The ``"autoscale"`` report section (None when the run had no
    autoscaler and no decisions): policy echo, verdict counts,
    rejection histogram, and the reconstructed move chains."""
    start = next((e for e in events
                  if e.get("kind") == "scheduler_start"), None)
    decisions = [e for e in events
                 if e.get("kind") == "autoscale_decision"]
    policy = (start or {}).get("autoscale")
    if not decisions and policy is None:
        return None
    reasons: dict = {}
    filed = rejected = 0
    for e in decisions:
        if e.get("verdict") == "filed":
            filed += 1
        elif e.get("verdict") == "rejected":
            rejected += 1
            r = str(e.get("reason"))
            reasons[r] = reasons.get(r, 0) + 1
    return {"policy": policy, "decisions": len(decisions),
            "filed": filed, "rejected": rejected,
            "rejected_by_reason": reasons,
            "moves": _autoscale_moves(events)}


def explain_autoscale(source) -> dict:
    """WHY did the mesh resize itself — reconstructed from the journal
    ALONE (the ``tools autoscale explain`` engine). The
    `_autoscale_section` record (policy echo, verdict counts, every
    filed move's full actuation chain with its pricing breakdown) plus
    ``jobs``: each job's complete decision history — every journaled
    verdict with its signal snapshot, so a rejection ("hysteresis",
    "cooldown", "priced_out", ...) is as explainable as a move."""
    events = read_journal(source)
    sec = _autoscale_section(events) or {
        "policy": None, "decisions": 0, "filed": 0, "rejected": 0,
        "rejected_by_reason": {}, "moves": []}
    per_job: dict = {}
    for e in events:
        if e.get("kind") != "autoscale_decision":
            continue
        per_job.setdefault(str(e.get("job")), []).append(
            {k: v for k, v in e.items()
             if k not in ("kind", "run")})
    sec["jobs"] = per_job
    return sec


def service_report(source, *, include_jobs: bool = True) -> dict:
    """The unified service record for one scheduler run (see module
    docstring). ``source`` is the scheduler ``flight_dir`` (or its
    journal file — then per-job reports are attached only if the job
    files sit next to it). ``include_jobs=False`` skips the per-job
    `run_report` attachments (the journal-derived accounting remains)."""
    src = os.fspath(source)
    flight_dir = src if os.path.isdir(src) else os.path.dirname(src)
    events = read_journal(src)

    start = next((e for e in events if e.get("kind") == "scheduler_start"),
                 None)
    stop = next((e for e in events if e.get("kind") == "scheduler_stop"),
                None)
    jobs: dict = {}
    order: list = []

    def rec(name):
        if name not in jobs:
            jobs[name] = {"name": name, "state": "queued", "slices": 0,
                          "slice_s_total": 0.0, "wait_s_total": 0.0,
                          "admit_s": None, "step": None, "error": None}
            order.append(name)
        return jobs[name]

    schedule: list = []
    submit_rejected: list = []
    alerts: list = []
    switches = 0
    prev_job = None
    queued = running = 0
    max_queued = 0
    for e in events:
        k = e.get("kind")
        if k == "job_submitted":
            r = rec(e["job"])
            r.update(nt=e.get("nt"), priority=e.get("priority"),
                     deadline_s=e.get("deadline_s"), grid=e.get("grid"),
                     run_spec=e.get("run_spec"), submitted_t=e.get("t"))
            queued += 1
            max_queued = max(max_queued, queued)
        elif k == "job_admitted":
            r = rec(e["job"])
            r["admit_s"] = e.get("admit_s")
            r["state"] = "running"
            queued -= 1
            running += 1
        elif k == "job_claimed":
            # the record arrived through a queue backend (CLI drop /
            # HTTP POST) — who claimed it, for multi-scheduler forensics
            r = rec(e["job"])
            r["claimed_by"] = e.get("owner")
            if e.get("trace_id") is not None:
                # the distributed-trace identity the submitter's
                # traceparent seeded — the handle for export_otlp
                # --trace-id / any collector query
                r["trace_id"] = e.get("trace_id")
        elif k == "admission_priced":
            # the deadline-admission verdict WITH its pricing inputs —
            # the journal defends every reject (and every admit)
            rec(e["job"])["admission"] = {
                key: v for key, v in e.items()
                if key not in ("kind", "t", "run", "job")}
        elif k == "deadline_missed":
            r = rec(e["job"])
            r["deadline_missed"] = {"step": e.get("step"),
                                    "deadline_s": e.get("deadline_s")}
        elif k == "submit_rejected":
            submit_rejected.append({"job": e.get("job"),
                                    "error": e.get("error")})
        elif k == "alert":
            alerts.append(e)
            if e.get("job"):
                rec(e["job"]).setdefault("alerts", []).append(
                    {"rule": e.get("rule"), "state": e.get("state"),
                     "severity": e.get("severity"), "t": e.get("t")})
        elif k == "job_resized":
            r = rec(e["job"])
            r["resizes"] = r.get("resizes", 0) + 1
            r["dims"] = e.get("new_dims")
        elif k == "job_retuned":
            r = rec(e["job"])
            r["retunes"] = r.get("retunes", 0) + 1
        elif k == "slice":
            r = rec(e["job"])
            r["slices"] += 1
            r["slice_s_total"] += float(e.get("dur_s", 0.0) or 0.0)
            r["wait_s_total"] += float(e.get("wait_s", 0.0) or 0.0)
            r["step"] = e.get("step")
            if e.get("slack_s") is not None:
                r["slack_s_last"] = e["slack_s"]
            schedule.append({"t": e.get("t"), "job": e["job"],
                             "slice": e.get("slice"), "step": e.get("step"),
                             "dur_s": e.get("dur_s"),
                             "wait_s": e.get("wait_s")})
            if prev_job is not None and e["job"] != prev_job:
                switches += 1
            prev_job = e["job"]
        elif k in _TERMINAL_KINDS:
            r = rec(e["job"])
            was = r["state"]
            r["state"] = _TERMINAL_KINDS[k]
            r["step"] = e.get("step", r["step"])
            r["error"] = e.get("error")
            if was == "running":
                running -= 1
            elif was == "queued":
                queued -= 1

    mesh_s = sum(r["slice_s_total"] for r in jobs.values())
    for r in jobs.values():
        r["mesh_share"] = (r["slice_s_total"] / mesh_s) if mesh_s else 0.0
        busy = r["slice_s_total"] + r["wait_s_total"]
        r["wait_frac"] = (r["wait_s_total"] / busy) if busy else 0.0
    # per-tenant straggler attribution: who holds the mesh longest per
    # granted slice (the single-process analog of the cross-process
    # barrier-spread report — a tenant with outsized slices delays every
    # other tenant's next grant)
    slowest = None
    for r in jobs.values():
        if r["slices"]:
            mean = r["slice_s_total"] / r["slices"]
            if slowest is None or mean > slowest[1]:
                slowest = (r["name"], mean)
    ts = [e["t"] for e in events if "t" in e]

    report = {
        "policy": (start or {}).get("policy"),
        "jobs_submitted": len(jobs),
        "states": {s: sum(1 for r in jobs.values() if r["state"] == s)
                   for s in sorted({r["state"] for r in jobs.values()})},
        "slices": len(schedule),
        "switches": switches,
        "mesh_busy_s": mesh_s,
        "makespan_s": (max(ts) - min(ts)) if ts else None,
        "max_queue_depth": max_queued,
        "slowest_tenant": None if slowest is None
        else {"job": slowest[0], "mean_slice_s": slowest[1]},
        "jobs": {name: jobs[name] for name in order},
        "schedule": schedule,
    }
    from ..telemetry.report import _alerts_section

    report["alerts"] = _alerts_section(alerts)
    autoscale = _autoscale_section(events)
    if autoscale is not None:
        report["autoscale"] = autoscale
    if submit_rejected:
        report["submit_rejected"] = submit_rejected
    if stop is not None:
        report["closed"] = True
    if include_jobs:
        from ..telemetry.report import run_report

        for name in order:
            path = _job_file(flight_dir, name)
            if path is not None:
                jobs[name]["report"] = run_report(
                    path, include_metrics=False)
    return report


def export_service_trace(source, out=None):
    """Chrome/Perfetto trace of one scheduler run: track 0 is the
    SCHEDULER (each granted slice as a span named by its job — mesh
    ownership over time), and every job gets ITS OWN track carrying the
    full per-run rendering (chunk spans with build/exec nesting,
    checkpoint/snapshot spans, guard-trip/rollback/fault instant
    markers, counter tracks). With ``out``, writes the JSON and returns
    the path; otherwise returns the trace dict. Open at
    https://ui.perfetto.dev."""
    from ..telemetry.trace_export import (
        _emit_event, _span_start, _track_meta,
    )

    src = os.fspath(source)
    flight_dir = src if os.path.isdir(src) else os.path.dirname(src)
    journal = read_journal(src)
    names: list = []
    for e in journal:
        if e.get("kind") == "job_submitted" and e["job"] not in names:
            names.append(e["job"])
    streams = {}
    for name in names:
        path = _job_file(flight_dir, name)
        if path is not None:
            streams[name] = read_flight_events(path)

    starts = [s for s in map(_span_start, journal) if s is not None]
    for evs in streams.values():
        starts.extend(s for s in map(_span_start, evs) if s is not None)
    if not starts:
        raise InvalidArgumentError(
            "export_service_trace: no timestamped events.")
    t0 = min(starts)

    def us(t: float) -> float:
        return (float(t) - t0) * 1e6

    trace: list = []
    trace.append({"ph": "M", "pid": 0, "name": "process_name",
                  "args": {"name": "scheduler"}})
    trace.append({"ph": "M", "pid": 0, "tid": 0, "name": "thread_name",
                  "args": {"name": "slices"}})
    queued = 0
    admitted: set = set()
    for e in journal:
        k = e.get("kind")
        if "t" not in e:
            continue
        t = float(e["t"])
        if k == "slice":
            dur = float(e.get("dur_s", 0.0) or 0.0)
            trace.append({"ph": "X", "pid": 0, "tid": 0, "cat": "slice",
                          "name": e.get("job"), "ts": us(t - dur),
                          "dur": dur * 1e6,
                          "args": {"job": e.get("job"),
                                   "step": e.get("step"),
                                   "wait_s": e.get("wait_s")}})
        elif k == "job_submitted":
            queued += 1
            trace.append({"ph": "C", "pid": 0, "name": "igg_jobs_queued",
                          "ts": us(t), "args": {"jobs": queued}})
        elif k == "job_admitted":
            admitted.add(e.get("job"))
            queued -= 1
            trace.append({"ph": "C", "pid": 0, "name": "igg_jobs_queued",
                          "ts": us(t), "args": {"jobs": queued}})
        elif k == "alert":
            trace.append({"ph": "i", "pid": 0, "tid": 0, "cat": "alert",
                          "name": (f"alert {e.get('rule')} "
                                   f"{e.get('state')}"),
                          "ts": us(t), "s": "p",
                          "args": {"rule": e.get("rule"),
                                   "severity": e.get("severity"),
                                   "state": e.get("state"),
                                   "job": e.get("job"),
                                   "value": e.get("value")}})
        elif k in ("job_done", "job_failed", "job_cancelled",
                   "job_rejected", "deadline_missed", "drain",
                   "scheduler_start", "scheduler_stop", "control"):
            if k in _TERMINAL_KINDS and e.get("job") not in admitted:
                # cancelled (or admission-failed) while still QUEUED:
                # it leaves the queue here, not at an admission
                queued -= 1
                trace.append({"ph": "C", "pid": 0,
                              "name": "igg_jobs_queued", "ts": us(t),
                              "args": {"jobs": queued}})
            trace.append({"ph": "i", "pid": 0, "tid": 0, "cat": "event",
                          "name": (f"{k} {e.get('job')}" if e.get("job")
                                   else k),
                          "ts": us(t), "s": "p"})

    for i, name in enumerate(names):
        pid = i + 1
        _track_meta(trace, pid, f"job {name}")
        wire_cum = {pid: 0}
        for e in streams.get(name, ()):
            if "t" not in e or e.get("kind") is None:
                continue
            _emit_event(trace, e, pid, us, wire_cum)

    doc = {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "implicitglobalgrid_tpu multi-run scheduler",
            "jobs": names,
        },
    }
    if out is None:
        return doc
    out = os.fspath(out)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return out
