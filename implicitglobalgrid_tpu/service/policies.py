"""Scheduling policies: who gets the next chunk-granular slice.

One tiny interface — ``pick(candidates)`` chooses the job the mesh serves
next — behind which three shipped disciplines live:

- ``fifo``: strict submission order; a job runs to completion before the
  next starts (the batch queue — zero context switches, zero overhead,
  no fairness).
- ``round_robin``: cycle through runnable jobs, one slice each (equal
  slice COUNTS; ignores priorities and slice durations).
- ``fair``: weighted max-min over mesh TIME — pick the job with the
  smallest ``granted_time / priority`` (stride scheduling over measured
  slice seconds, so a job with heavy chunks does not crowd out light
  ones, and ``priority=2`` earns 2x the mesh time of ``priority=1``).

Preemption is only ever at chunk boundaries (the scheduler grants one
`ResilientRun.advance()` per pick), so the policy choice affects latency
and fairness, never results: every job's trajectory is bit-identical
under every policy (asserted in tests/test_service.py).
"""

from __future__ import annotations

from ..utils.exceptions import InvalidArgumentError
from .job import Job

__all__ = ["SchedulingPolicy", "FifoPolicy", "RoundRobinPolicy",
           "FairSharePolicy", "POLICIES", "resolve_policy"]


class SchedulingPolicy:
    """Pick the next job to slice. ``candidates`` is the non-empty list of
    runnable jobs (admitted or queued, not finished), in submission
    order. Implementations must be deterministic — the schedule is part
    of the service's reproducibility story."""

    name = "base"

    def pick(self, candidates: list) -> Job:
        raise NotImplementedError

    def granted(self, job: Job, slice_s: float) -> None:
        """Feedback after a slice (default: ignored)."""


class FifoPolicy(SchedulingPolicy):
    """Strict submission order: the oldest unfinished job owns the mesh
    until it finishes."""

    name = "fifo"

    def pick(self, candidates: list) -> Job:
        return min(candidates, key=lambda j: j.index)


class RoundRobinPolicy(SchedulingPolicy):
    """Cycle through runnable jobs, one slice each."""

    name = "round_robin"

    def __init__(self):
        self._last = -1

    def pick(self, candidates: list) -> Job:
        after = [j for j in candidates if j.index > self._last]
        job = min(after or candidates, key=lambda j: j.index)
        self._last = job.index
        return job


class FairSharePolicy(SchedulingPolicy):
    """Weighted fair share of mesh TIME: pick the minimal
    ``granted_s / priority`` (ties: submission order). New jobs start at
    the current minimum share rather than zero, so a late submission
    catches up without starving everyone else of the mesh for its whole
    backlog.

    Deadline softening (ISSUE 19): before the alert engine's HARD
    ``deadline_missed``/cancel path ever fires, a job whose live
    ``deadline_slack_s`` has dropped below ``low_slack_s`` gets its
    effective share divided by up to ``1 + slack_boost`` — a stride
    boost that GROWS as slack sinks through ``slack_horizon_s``, so the
    scheduler spends mesh time where the deadline pressure is, smoothly
    and reversibly. The boost reads the driver's live gauge only at
    pick time; ``granted`` accounting is untouched, so a job whose
    slack recovers pays its fair share back. Jobs without a deadline
    (slack None) never boost — the policy is byte-identical to plain
    fair share for them."""

    name = "fair"

    def __init__(self, *, low_slack_s: float = 0.0,
                 slack_boost: float = 4.0,
                 slack_horizon_s: float = 30.0):
        if slack_boost < 0:
            raise InvalidArgumentError(
                f"FairSharePolicy: slack_boost must be >= 0; got "
                f"{slack_boost!r}.")
        if not slack_horizon_s > 0:
            raise InvalidArgumentError(
                f"FairSharePolicy: slack_horizon_s must be > 0; got "
                f"{slack_horizon_s!r}.")
        self.low_slack_s = float(low_slack_s)
        self.slack_boost = float(slack_boost)
        self.slack_horizon_s = float(slack_horizon_s)
        self._share: dict = {}  # job index -> granted_s / weight

    def _boost(self, job: Job) -> float:
        """> 1 when the job's live deadline slack is below
        ``low_slack_s``, saturating at ``1 + slack_boost`` once the
        deficit spans ``slack_horizon_s``."""
        if self.slack_boost == 0 or job.run is None:
            return 1.0
        slack = getattr(job.run, "deadline_slack_s", None)
        if slack is None or slack >= self.low_slack_s:
            return 1.0
        deficit = min(1.0, (self.low_slack_s - float(slack))
                      / self.slack_horizon_s)
        return 1.0 + self.slack_boost * deficit

    def pick(self, candidates: list) -> Job:
        # the floor is the RUNNABLE minimum: a finished job's frozen
        # (small) share must not drag it down, or a late arrival seeded
        # from it would monopolize the mesh until it "caught up" with a
        # tenant that no longer exists
        known = [self._share[j.index] for j in candidates
                 if j.index in self._share]
        floor = min(known) if known else 0.0
        for j in candidates:
            if j.index not in self._share:
                self._share[j.index] = floor
        return min(candidates,
                   key=lambda j: (self._share[j.index] / self._boost(j),
                                  j.index))

    def granted(self, job: Job, slice_s: float) -> None:
        w = max(1, int(job.spec.priority))
        self._share[job.index] = self._share.get(job.index, 0.0) \
            + max(0.0, float(slice_s)) / w


POLICIES = {
    "fifo": FifoPolicy,
    "round_robin": RoundRobinPolicy,
    "fair": FairSharePolicy,
}


def resolve_policy(policy) -> SchedulingPolicy:
    """A policy instance from a name, class, or instance."""
    if isinstance(policy, SchedulingPolicy):
        return policy
    if isinstance(policy, type) and issubclass(policy, SchedulingPolicy):
        return policy()
    if isinstance(policy, str):
        if policy not in POLICIES:
            raise InvalidArgumentError(
                f"Unknown scheduling policy {policy!r}; available: "
                f"{sorted(POLICIES)}.")
        return POLICIES[policy]()
    raise InvalidArgumentError(
        f"policy must be a name, SchedulingPolicy class, or instance; "
        f"got {policy!r}.")
