"""implicitglobalgrid_tpu — a TPU-native implicit-global-grid framework.

A from-scratch re-design of the capabilities of ImplicitGlobalGrid.jl
(reference mounted at `/root/reference`; structural analysis in `SURVEY.md`)
for TPUs on JAX/XLA: distributed stencil computations on an implicit global
grid by Cartesian domain decomposition over a `jax.sharding.Mesh`, with halo
exchange lowered to per-axis `lax.ppermute` collectives riding the ICI mesh.

Public API — the reference's 13 exported symbols
(`/root/reference/src/ImplicitGlobalGrid.jl:10-22`), Python-style (functional,
no `!`):

    init_global_grid, finalize_global_grid, update_halo, gather,
    select_device, nx_g, ny_g, nz_g, x_g, y_g, z_g, tic, toc

plus TPU-native extensions: `local_update_halo` (the local-view exchange for
use inside your own `shard_map`), `zeros_g`/`ones_g`/`full_g`/`device_put_g`
(sharded allocation), `coords_g`/`x_g_vec` (vectorized coordinates for ICs),
`gather_interior`, `barrier`, stencil helpers (`d_xa` … `inn`), and the
`Field` wrapper for per-field halowidths.

Usage (compare reference `examples/diffusion3D_multicpu_novis.jl`)::

    import implicitglobalgrid_tpu as igg
    me, dims, nprocs, coords, mesh = igg.init_global_grid(nx, ny, nz)
    dx = lx / (igg.nx_g() - 1)
    T = igg.zeros_g()                     # one sharded array; shards = local blocks
    ...
    T = igg.update_halo(T)                # one ppermute pair per axis, jitted
    igg.finalize_global_grid()
"""

from .parallel.grid import (
    init_global_grid, finalize_global_grid, select_device,
)
from .parallel.topology import (
    AXIS_NAMES, NDIMS, PROC_NULL, GlobalGrid,
    global_grid, get_global_grid, grid_is_initialized, check_initialized,
    neighbors_table, ol, dims_create,
)
from .ops.halo import (
    update_halo, local_update_halo, halo_comm_plan, DEFAULT_DIMS_ORDER,
)
from .ops.overlap import hide_communication
from .ops.gather import gather, gather_interior, gather_sub
from .ops.alloc import zeros_g, ones_g, full_g, device_put_g, sharding_of
from .ops.fields import Field, wrap_field, extract, local_shape_of, stacked_shape
from .ops.stencil import d_xa, d_ya, d_za, d_xi, d_yi, d_zi, inn
from .ops.precision import stochastic_round_bf16
from .tools import (
    nx_g, ny_g, nz_g, x_g, y_g, z_g, x_g_vec, y_g_vec, z_g_vec, coords_g,
)
from .utils.timing import tic, toc, barrier, sync
from .utils.profiling import (
    trace, annotate, overlap_stats, op_breakdown,
)
from .utils.checkpoint import (
    save_checkpoint, restore_checkpoint, load_checkpoint,
    save_checkpoint_sharded, restore_checkpoint_sharded,
    restore_checkpoint_elastic, saved_topology, elastic_local_size,
)
from .runtime import (
    run_resilient, ResilientRun, RunSpec, GuardConfig, HealthReport,
    RecoveryPolicy,
    NaNPoke, CheckpointCorruption, ProcessLoss,
    poke_nan, corrupt_checkpoint, elastic_restart,
)
from . import reshard
from .reshard import (
    ReshardPlan, build_reshard_plan, reshard_contract, reshard_state,
)
from .telemetry import (
    MetricsRegistry, metrics_registry, reset_metrics, prometheus_snapshot,
    FlightRecorder, start_flight_recorder, stop_flight_recorder,
    flight_recorder, record_event, record_span, read_flight_events,
    run_report, aggregate_flight, aggregate_events, straggler_report,
    export_chrome_trace,
    MetricsServer, start_metrics_server, stop_metrics_server,
    metrics_server,
    FlightTail, LiveAggregate, AlertRule, AlertEngine, default_rule_pack,
    log_sink, ControlFileSink, WebhookSink,
    MachineProfile, StepWorkload, PerfWatch, default_machine_profile,
    load_machine_profile, save_machine_profile, predict_step,
    predict_reshard, calibrate_machine, perfdb_add, perfdb_check,
    TunedConfig, tune_config, save_tuned_config, load_tuned_config,
    TraceContext, export_otlp, OtlpSpanExporter,
)
from .models.common import ensemble_partition_spec, ensemble_state
from . import io
from .io import (
    SnapshotWriter, write_snapshot, open_snapshot, list_snapshots,
    Probe, AxisSlice, Stats,
)
from . import service
from .service import (
    MeshScheduler, JobSpec, JobState, service_report,
    export_service_trace,
)
from . import serve
from .serve import (
    BlockCache, CachedSnapshot, JobApiServer, ObservePlane, ObserveServer,
    SnapshotQueryServer,
)
from . import analysis
from .analysis import (
    AuditFinding, AuditReport, CollectiveContract, ProgramIR,
    audit_model, audit_program, check_contract, exchange_contract,
    model_contract, parse_program,
)
from .utils import exceptions

__version__ = "0.1.0"

__all__ = [
    # reference 13-symbol API (src/ImplicitGlobalGrid.jl:10-22)
    "init_global_grid", "finalize_global_grid", "update_halo", "gather",
    "select_device", "nx_g", "ny_g", "nz_g", "x_g", "y_g", "z_g", "tic", "toc",
    # TPU-native extensions
    "local_update_halo", "hide_communication", "gather_interior", "gather_sub", "barrier",
    "sync", "trace", "annotate", "overlap_stats", "op_breakdown",
    "zeros_g", "ones_g", "full_g", "device_put_g", "sharding_of",
    "Field", "wrap_field", "extract", "local_shape_of", "stacked_shape",
    "x_g_vec", "y_g_vec", "z_g_vec", "coords_g",
    "save_checkpoint", "restore_checkpoint", "load_checkpoint",
    "save_checkpoint_sharded", "restore_checkpoint_sharded",
    "restore_checkpoint_elastic", "saved_topology", "elastic_local_size",
    # ensemble axis (batch E scenario members through one mesh)
    "ensemble_state", "ensemble_partition_spec",
    # resilient runtime (supervised long runs)
    "run_resilient", "ResilientRun", "RunSpec",
    "GuardConfig", "HealthReport", "RecoveryPolicy",
    "NaNPoke", "CheckpointCorruption", "ProcessLoss",
    "poke_nan", "corrupt_checkpoint", "elastic_restart",
    # multi-run scheduler (the mesh as a persistent simulation service)
    "service", "MeshScheduler", "JobSpec", "JobState", "service_report",
    "export_service_trace",
    # serving tier (networked job API + read-side snapshot query service)
    "serve", "JobApiServer", "SnapshotQueryServer", "BlockCache",
    "CachedSnapshot",
    # live observability plane (incremental tailing, SLO/alert engine,
    # streaming ops endpoints)
    "FlightTail", "LiveAggregate", "AlertRule", "AlertEngine",
    "default_rule_pack", "log_sink", "ControlFileSink", "WebhookSink",
    "ObservePlane", "ObserveServer",
    # on-device elastic resharding (HBM-to-HBM re-blocking, no disk)
    "reshard", "ReshardPlan", "build_reshard_plan", "reshard_contract",
    "reshard_state",
    # telemetry (metrics registry, flight recorder, exporters, run report)
    "MetricsRegistry", "metrics_registry", "reset_metrics",
    "prometheus_snapshot", "FlightRecorder", "start_flight_recorder",
    "stop_flight_recorder", "flight_recorder", "record_event",
    "record_span", "read_flight_events", "run_report", "halo_comm_plan",
    # mesh-wide observability (cross-process aggregation, Perfetto export,
    # straggler analysis, live metrics endpoint)
    "aggregate_flight", "aggregate_events", "straggler_report",
    "export_chrome_trace",
    # distributed tracing (W3C trace context propagation + OTLP export)
    "TraceContext", "export_otlp", "OtlpSpanExporter",
    "MetricsServer", "start_metrics_server", "stop_metrics_server",
    "metrics_server",
    # performance oracle (analytical cost model, calibration, drift
    # detection, perf-history gate)
    "MachineProfile", "StepWorkload", "PerfWatch",
    "default_machine_profile", "load_machine_profile",
    "save_machine_profile", "predict_step", "predict_reshard",
    "calibrate_machine", "perfdb_add", "perfdb_check",
    # closed-loop auto-tuner (search the oracle, validate with measured
    # runs, persist, apply per job)
    "TunedConfig", "tune_config", "save_tuned_config",
    "load_tuned_config",
    # io (sharded snapshot & in-situ analysis pipeline)
    "io", "SnapshotWriter", "write_snapshot", "open_snapshot",
    "list_snapshots", "Probe", "AxisSlice", "Stats",
    # static analysis (compiled-program parser, collective contracts,
    # implicit-grid lints, audit entry points)
    "analysis", "ProgramIR", "parse_program", "AuditFinding",
    "AuditReport", "CollectiveContract", "exchange_contract",
    "model_contract", "check_contract", "audit_program", "audit_model",
    "d_xa", "d_ya", "d_za", "d_xi", "d_yi", "d_zi", "inn",
    "stochastic_round_bf16",
    # state/introspection
    "AXIS_NAMES", "NDIMS", "PROC_NULL", "GlobalGrid", "global_grid",
    "get_global_grid", "grid_is_initialized", "check_initialized",
    "neighbors_table", "ol", "dims_create", "DEFAULT_DIMS_ORDER",
    "exceptions",
]
