"""Implicit-global-grid lint rules over a parsed program.

Where `contracts` checks the *planned* collective shape, these rules hunt
for the hazards no plan mentions — things a compiled hot-path program of
this framework must never contain:

- ``global-materialization`` — an op shaped like the IMPLICIT GLOBAL grid
  (``nxyz_g``): the array whose whole point is to never exist. Post-SPMD
  programs only hold per-shard blocks, so any global-shaped buffer is a
  partitioning failure (optimized-HLO dialect only: the pre-partitioning
  StableHLO module legitimately carries stacked arrays at the jit
  boundary).
- ``wire-downcast-missing`` — a reduced-precision wire format was
  requested but some float permute payload still crosses the link wider
  than its axis allows (the narrowing silently didn't happen). Per-axis
  aware: under ``"z:int8,x:f32"`` a full-width payload on the exact
  x-axis is legal while a stale f32 payload on the quantized z-axis
  flags; integer (quantized s8) payloads are never stale. Float casts
  are meaningful on the LOWERED module for CPU runs — the XLA:CPU
  float-normalization pass rewrites bf16 payloads back to f32 in
  backend-optimized text; TPU keeps them native (quantized int8 payloads
  survive both dialects).
- ``donation-unaliased`` — fewer input-output aliases in the module
  header than donated inputs: each missing alias is a hidden full-block
  copy per dispatch.
- ``host-transfer`` — infeed/outfeed/send/recv/host callbacks inside the
  program: a device<->host round-trip in the chunk body serializes the
  step loop.
- ``custom-call`` — opaque custom-calls (partitioner markers and other
  benign targets excluded): the scheduler can't reason about them.
- ``f64-leakage`` — f64 buffers in a program whose state dtypes don't
  include f64 (on TPU every f64 op runs through emulation several-x
  slower; an unintended promotion is a silent perf cliff).
- ``copy-feeds-collective`` — a ``copy`` op feeding a collective operand:
  the slab slicing failed to fuse and the wire payload is staged through
  an extra buffer.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.exceptions import InvalidArgumentError
from .contracts import AuditFinding, SEV_ERROR, SEV_WARNING, sort_findings
from .hlo import ProgramIR, Shape

__all__ = ["LintConfig", "default_lint_config", "run_lints", "LINT_RULES",
           "DEFAULT_LINTS"]

# custom-call targets that are partitioning/sharding machinery, not opaque
# compute — present in every shard_map program by construction
_BENIGN_CUSTOM_CALLS = {
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
    "MoveToHost", "MoveToDevice", "AllocateBuffer", "xla.sdy.FuncResultSharding",
}
# host-callback custom-call targets: a device->host round trip per call
_HOST_CALLBACK_TARGETS = {
    "xla_python_cpu_callback", "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback", "xla_ffi_python_gpu_callback",
    "xla_ffi_partitioned_python_cpu_callback", "tpu_host_callback",
}
_HOST_TRANSFER_OPS = {"infeed", "outfeed", "send", "recv", "send-done",
                      "recv-done"}


@dataclass(frozen=True)
class LintConfig:
    """What the linter should consider legitimate for this program.

    ``global_shape``/``local_shape`` come from the live grid (see
    `default_lint_config`); ``state_dtypes`` are the dtypes the program's
    state legitimately holds (f64 presence beyond these flags);
    ``wire_dtype`` is the REQUESTED reduced-precision wire format (HLO
    spelling, e.g. ``"bf16"``) whose absence from the wire should flag.
    Under a PER-AXIS wire policy, ``wire_axes`` maps mesh axis names to
    the HLO spelling of that axis's on-wire dtype (axes missing from the
    map are exact — any payload width legal there) and ``routes`` (the
    `contracts.axis_routes` table) attributes each permute to its axis,
    so an exact-by-policy axis's full-width payload no longer false-flags.
    With ``wire_axes`` set, a permute that cannot be attributed (missing
    routes, unknown pair set) is never flagged and ``wire_dtype`` is NOT
    consulted — it only feeds the finding message (see
    `_allowed_wire_width`). ``expect_donation`` is the number of donated
    inputs that must appear as input-output aliases."""

    global_shape: tuple | None = None
    local_shape: tuple | None = None
    state_dtypes: tuple = ()
    wire_dtype: str | None = None
    wire_axes: dict | None = None
    routes: dict | None = None
    expect_donation: int | None = None


_WIRE_NAMES = {"bfloat16": "bf16", "float16": "f16", "float32": "f32",
               "float64": "f64",
               # quantized payloads (incl. bit-packed int4) ship as s8
               "int8": "s8", "int4": "s8"}


def default_lint_config(grid=None, *, state_dtypes=(), wire_dtype=None,
                        expect_donation=None) -> LintConfig:
    """Build a config from the LIVE grid: the forbidden global shape is
    ``nxyz_g``, the legitimate block shape ``nxyz``. ``wire_dtype``
    accepts numpy/jax spellings (``bfloat16``), HLO ones (``bf16``),
    quantized formats (``int8``/``int4``), a per-axis policy spec
    (``"z:int8,x:f32"``), or a resolved `ops.precision.WirePolicy` — a
    per-axis policy additionally fills ``wire_axes``/``routes`` from the
    live grid so the wire-downcast lint judges each permute against ITS
    axis's width."""
    from ..parallel.topology import global_grid, grid_is_initialized

    gshape = lshape = None
    if grid is not None or grid_is_initialized():
        gg = grid if grid is not None else global_grid()
        gshape = tuple(int(n) for n in gg.nxyz_g)
        lshape = tuple(int(n) for n in gg.nxyz)
    wd, wire_axes, routes = None, None, None
    if wire_dtype is not None:
        policy = _maybe_policy(wire_dtype)
        if policy is not None and policy.uniform is None:
            # per-axis policy: widths judged per attributed axis only
            # (unattributable permutes are never flagged — see
            # `_allowed_wire_width`); `wire_dtype` records the WIDEST
            # requested format purely for display in messages
            from ..parallel.topology import AXIS_NAMES

            wire_axes = {}
            widest = None
            for d, axis in enumerate(AXIS_NAMES):
                fmt = policy.for_dim(d)
                if fmt is None:
                    continue
                name = _WIRE_NAMES.get(str(fmt), str(fmt))
                wire_axes[axis] = name
                w = Shape(name, ()).itemsize
                if widest is None or w > widest[0]:
                    widest = (w, name)
            wd = widest[1] if widest else None
            if grid is not None or grid_is_initialized():
                from .contracts import axis_routes

                routes = axis_routes(grid if grid is not None
                                     else global_grid())
        elif policy is not None:
            wd = _WIRE_NAMES.get(str(policy.uniform), str(policy.uniform))
        else:
            wd = str(wire_dtype)
            wd = _WIRE_NAMES.get(wd, wd)
    return LintConfig(
        global_shape=gshape, local_shape=lshape,
        state_dtypes=tuple(_WIRE_NAMES.get(str(d), str(d))
                           for d in state_dtypes),
        wire_dtype=wd, wire_axes=wire_axes, routes=routes,
        expect_donation=expect_donation)


def _maybe_policy(wire_dtype):
    """`ops.precision.WirePolicy` for the argument when it parses as one.
    Callers also pass raw HLO spellings the policy parser doesn't know
    (``"f64"``) — ONLY those recognized dtype spellings keep the legacy
    string path; anything else that fails to parse (a typo'd axis
    ``"w:int8"``, a bad format ``"int3"``) re-raises, because silently
    falling through would hand `_lint_wire_downcast` a width-4 fallback
    that never flags anything — a disabled lint disguised as a clean
    audit."""
    from ..ops.precision import WirePolicy, resolve_wire_dtype

    if isinstance(wire_dtype, WirePolicy):
        return wire_dtype
    try:
        return resolve_wire_dtype(wire_dtype)
    except InvalidArgumentError:
        from .hlo import _ITEMSIZE

        s = str(wire_dtype)
        if s in _WIRE_NAMES or s in _ITEMSIZE:
            return None
        raise


# ---------------------------------------------------------------------------
# rules: each fn(ir, cfg) -> list[AuditFinding]

def _lint_global_materialization(ir: ProgramIR, cfg: LintConfig) -> list:
    if ir.dialect != "hlo" or cfg.global_shape is None:
        return []  # pre-SPMD modules legitimately hold stacked arrays
    if cfg.global_shape == cfg.local_shape:
        return []  # single-shard grid: the block IS the global array
    out = []
    for op in ir.ops:
        for s in op.shapes:
            if s.dims == cfg.global_shape:
                out.append(AuditFinding(
                    "global-materialization", SEV_ERROR,
                    f"op materializes the implicit GLOBAL grid shape {s} "
                    "— the array this framework exists to never build.",
                    op=op.name, computation=op.computation,
                    details={"shape": str(s)}))
                break
    return out


def _is_float_payload(pay) -> bool:
    return pay is not None and pay.dtype.lstrip("b").startswith("f") \
        and not pay.dtype.startswith("f8")


def _allowed_wire_width(cfg: LintConfig, op) -> int | None:
    """The widest payload the policy allows for this permute: its
    attributed axis's configured width under a per-axis policy (an axis
    the policy leaves exact allows any width -> None), else the global
    wire width. Under a PER-AXIS policy a permute that cannot be
    attributed (no routes table — e.g. a host-only dump audit — or a
    pair set matching no axis) is NEVER flagged: without attribution a
    full-width payload may legally belong to an exact-by-policy axis,
    and unplanned routes are the contract checker's `permute-route`
    finding, not this lint's. Integer payloads never reach this (the
    quantized s8 buffer IS the narrowing — only float payloads can be
    stale)."""
    # truthiness, matching `_lint_wire_downcast`'s guard: an EMPTY
    # wire_axes map must fall through to the global width, not disable
    # the lint
    if cfg.wire_axes:
        if cfg.routes is not None:
            from .contracts import attribute_axis

            pairs = op.attrs.get("source_target_pairs") or ()
            axis = attribute_axis(cfg.routes, pairs) if pairs else None
            if axis is not None:
                name = cfg.wire_axes.get(axis)
                return None if name is None else Shape(name, ()).itemsize
        return None
    if cfg.wire_dtype is None:
        return None
    return Shape(cfg.wire_dtype, ()).itemsize


def _lint_wire_downcast(ir: ProgramIR, cfg: LintConfig) -> list:
    if cfg.wire_dtype is None and not cfg.wire_axes:
        return []
    permutes = ir.permutes
    if not permutes:
        return []
    # EVERY float payload must be at or below ITS AXIS's wire width — a
    # partial regression (one axis narrowed, the others still full
    # precision) is as real a bandwidth loss as a total one, while a
    # full-width payload on an axis the per-axis policy leaves exact is
    # legal (the pre-policy global check flagged those). Width, not
    # equality: an f16 field under bf16 wire legitimately ships as f16,
    # and integer (quantized) payloads are always at or below any
    # configured width (`wire_format_for` never widens a payload).
    stale = []
    for p in permutes:
        pay = ir.payload_of(p)
        if not _is_float_payload(pay):
            continue
        allowed = _allowed_wire_width(cfg, p)
        if allowed is not None and pay.itemsize > allowed:
            stale.append(p)
    if not stale:
        return []
    n_float = sum(1 for p in permutes if _is_float_payload(ir.payload_of(p)))
    got = sorted({str(ir.payload_of(p)) for p in stale})
    wire_desc = (",".join(f"{a}:{d}" for a, d in sorted(cfg.wire_axes.items()))
                 if cfg.wire_axes else cfg.wire_dtype)
    return [AuditFinding(
        "wire-downcast-missing", SEV_ERROR,
        f"wire dtype {wire_desc!r} requested but {len(stale)} of "
        f"{n_float} float collective-permute payload(s) still cross the "
        f"link wider than it allows (stale payloads: {got}) — the "
        "narrowing did not reach (all of) the wire. (Audit the LOWERED "
        "module on CPU: its backend normalizes bf16 payloads back to "
        "f32.)",
        details={"wire_dtype": wire_desc, "payloads": got,
                 "stale": len(stale), "float_permutes": n_float})]


def _lint_donation(ir: ProgramIR, cfg: LintConfig) -> list:
    if cfg.expect_donation is None or ir.dialect != "hlo":
        return []
    n = int(ir.attrs.get("n_aliases", 0))
    if n >= int(cfg.expect_donation):
        return []
    return [AuditFinding(
        "donation-unaliased", SEV_WARNING,
        f"{cfg.expect_donation} donated input(s) but only {n} input-"
        "output alias(es) in the module header: each missing alias is a "
        "hidden full-block copy per dispatch.",
        details={"expected": int(cfg.expect_donation), "aliased": n})]


def _lint_host_transfer(ir: ProgramIR, cfg: LintConfig) -> list:
    out = []
    for op in ir.ops:
        hostile = op.op in _HOST_TRANSFER_OPS \
            or op.attrs.get("is_host_transfer") \
            or (op.op == "custom-call"
                and op.attrs.get("custom_call_target")
                in _HOST_CALLBACK_TARGETS)
        if hostile:
            out.append(AuditFinding(
                "host-transfer", SEV_ERROR,
                f"{op.op} inside the compiled body "
                f"({op.attrs.get('custom_call_target') or op.name}): a "
                "host round-trip serializes the step loop.",
                op=op.name, computation=op.computation))
    return out


def _lint_custom_call(ir: ProgramIR, cfg: LintConfig) -> list:
    out = []
    for op in ir.ops:
        if op.op != "custom-call":
            continue
        target = op.attrs.get("custom_call_target")
        if target in _BENIGN_CUSTOM_CALLS \
                or target in _HOST_CALLBACK_TARGETS:
            continue  # host callbacks are the host-transfer rule's job
        out.append(AuditFinding(
            "custom-call", SEV_WARNING,
            f"opaque custom-call {target!r}: the compiler cannot fuse or "
            "reason across it.",
            op=op.name, computation=op.computation,
            details={"target": target}))
    return out


def _lint_f64(ir: ProgramIR, cfg: LintConfig) -> list:
    if "f64" in cfg.state_dtypes:
        return []
    leaks = [op for op in ir.ops if op.has_shape("f64")]
    if not leaks:
        return []
    return [AuditFinding(
        "f64-leakage", SEV_WARNING,
        f"{len(leaks)} op(s) carry f64 buffers in a program whose state "
        "dtypes are "
        f"{sorted(cfg.state_dtypes) or '(unspecified)'} — on TPU every "
        "f64 op runs through emulation; an unintended promotion is a "
        "silent perf cliff.",
        op=leaks[0].name, computation=leaks[0].computation,
        details={"ops": len(leaks), "first": leaks[0].op})]


def _lint_copy_feeds_collective(ir: ProgramIR, cfg: LintConfig) -> list:
    out = []
    for op in ir.collectives():
        for name in op.operands:
            prod = ir.resolve(op.computation, name)
            if prod is not None and prod.op == "copy":
                out.append(AuditFinding(
                    "copy-feeds-collective", SEV_WARNING,
                    f"{op.op} payload is staged through a copy "
                    f"({prod.shapes[0] if prod.shapes else '?'}): the "
                    "slab slicing failed to fuse into the collective.",
                    op=op.name, computation=op.computation,
                    details={"copy": prod.name}))
    return out


LINT_RULES = {
    "global-materialization": _lint_global_materialization,
    "wire-downcast-missing": _lint_wire_downcast,
    "donation-unaliased": _lint_donation,
    "host-transfer": _lint_host_transfer,
    "custom-call": _lint_custom_call,
    "f64-leakage": _lint_f64,
    "copy-feeds-collective": _lint_copy_feeds_collective,
}
DEFAULT_LINTS = tuple(LINT_RULES)


def run_lints(ir: ProgramIR, *, config: LintConfig | None = None,
              rules=None) -> list:
    """Run the lint ``rules`` (names from `LINT_RULES`; default all) over
    a parsed program. Returns findings sorted most-severe first."""
    if not isinstance(ir, ProgramIR):
        raise InvalidArgumentError(
            "run_lints expects a ProgramIR (use parse_program).")
    config = config if config is not None else default_lint_config()
    out: list = []
    for name in (rules if rules is not None else DEFAULT_LINTS):
        fn = LINT_RULES.get(name)
        if fn is None:
            raise InvalidArgumentError(
                f"unknown lint rule {name!r} (have {sorted(LINT_RULES)}).")
        out.extend(fn(ir, config))
    return sort_findings(out)
