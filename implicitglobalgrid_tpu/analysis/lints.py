"""Implicit-global-grid lint rules over a parsed program.

Where `contracts` checks the *planned* collective shape, these rules hunt
for the hazards no plan mentions — things a compiled hot-path program of
this framework must never contain:

- ``global-materialization`` — an op shaped like the IMPLICIT GLOBAL grid
  (``nxyz_g``): the array whose whole point is to never exist. Post-SPMD
  programs only hold per-shard blocks, so any global-shaped buffer is a
  partitioning failure (optimized-HLO dialect only: the pre-partitioning
  StableHLO module legitimately carries stacked arrays at the jit
  boundary).
- ``wire-downcast-missing`` — a reduced-precision wire dtype was
  requested but no permute payload carries it (the narrowing silently
  didn't happen). Meaningful on the LOWERED module for CPU runs — the
  XLA:CPU float-normalization pass rewrites bf16 payloads back to f32 in
  backend-optimized text; TPU keeps them native.
- ``donation-unaliased`` — fewer input-output aliases in the module
  header than donated inputs: each missing alias is a hidden full-block
  copy per dispatch.
- ``host-transfer`` — infeed/outfeed/send/recv/host callbacks inside the
  program: a device<->host round-trip in the chunk body serializes the
  step loop.
- ``custom-call`` — opaque custom-calls (partitioner markers and other
  benign targets excluded): the scheduler can't reason about them.
- ``f64-leakage`` — f64 buffers in a program whose state dtypes don't
  include f64 (on TPU every f64 op runs through emulation several-x
  slower; an unintended promotion is a silent perf cliff).
- ``copy-feeds-collective`` — a ``copy`` op feeding a collective operand:
  the slab slicing failed to fuse and the wire payload is staged through
  an extra buffer.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.exceptions import InvalidArgumentError
from .contracts import AuditFinding, SEV_ERROR, SEV_WARNING, sort_findings
from .hlo import ProgramIR, Shape

__all__ = ["LintConfig", "default_lint_config", "run_lints", "LINT_RULES",
           "DEFAULT_LINTS"]

# custom-call targets that are partitioning/sharding machinery, not opaque
# compute — present in every shard_map program by construction
_BENIGN_CUSTOM_CALLS = {
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
    "MoveToHost", "MoveToDevice", "AllocateBuffer", "xla.sdy.FuncResultSharding",
}
# host-callback custom-call targets: a device->host round trip per call
_HOST_CALLBACK_TARGETS = {
    "xla_python_cpu_callback", "xla_python_gpu_callback",
    "xla_ffi_python_cpu_callback", "xla_ffi_python_gpu_callback",
    "xla_ffi_partitioned_python_cpu_callback", "tpu_host_callback",
}
_HOST_TRANSFER_OPS = {"infeed", "outfeed", "send", "recv", "send-done",
                      "recv-done"}


@dataclass(frozen=True)
class LintConfig:
    """What the linter should consider legitimate for this program.

    ``global_shape``/``local_shape`` come from the live grid (see
    `default_lint_config`); ``state_dtypes`` are the dtypes the program's
    state legitimately holds (f64 presence beyond these flags);
    ``wire_dtype`` is the REQUESTED reduced-precision wire format (HLO
    spelling, e.g. ``"bf16"``) whose absence from the wire should flag;
    ``expect_donation`` is the number of donated inputs that must appear
    as input-output aliases."""

    global_shape: tuple | None = None
    local_shape: tuple | None = None
    state_dtypes: tuple = ()
    wire_dtype: str | None = None
    expect_donation: int | None = None


_WIRE_NAMES = {"bfloat16": "bf16", "float16": "f16", "float32": "f32",
               "float64": "f64"}


def default_lint_config(grid=None, *, state_dtypes=(), wire_dtype=None,
                        expect_donation=None) -> LintConfig:
    """Build a config from the LIVE grid: the forbidden global shape is
    ``nxyz_g``, the legitimate block shape ``nxyz``. ``wire_dtype``
    accepts numpy/jax spellings (``bfloat16``) or HLO ones (``bf16``)."""
    from ..parallel.topology import global_grid, grid_is_initialized

    gshape = lshape = None
    if grid is not None or grid_is_initialized():
        gg = grid if grid is not None else global_grid()
        gshape = tuple(int(n) for n in gg.nxyz_g)
        lshape = tuple(int(n) for n in gg.nxyz)
    wd = None
    if wire_dtype is not None:
        wd = str(wire_dtype)
        wd = _WIRE_NAMES.get(wd, wd)
    return LintConfig(
        global_shape=gshape, local_shape=lshape,
        state_dtypes=tuple(_WIRE_NAMES.get(str(d), str(d))
                           for d in state_dtypes),
        wire_dtype=wd, expect_donation=expect_donation)


# ---------------------------------------------------------------------------
# rules: each fn(ir, cfg) -> list[AuditFinding]

def _lint_global_materialization(ir: ProgramIR, cfg: LintConfig) -> list:
    if ir.dialect != "hlo" or cfg.global_shape is None:
        return []  # pre-SPMD modules legitimately hold stacked arrays
    if cfg.global_shape == cfg.local_shape:
        return []  # single-shard grid: the block IS the global array
    out = []
    for op in ir.ops:
        for s in op.shapes:
            if s.dims == cfg.global_shape:
                out.append(AuditFinding(
                    "global-materialization", SEV_ERROR,
                    f"op materializes the implicit GLOBAL grid shape {s} "
                    "— the array this framework exists to never build.",
                    op=op.name, computation=op.computation,
                    details={"shape": str(s)}))
                break
    return out


def _lint_wire_downcast(ir: ProgramIR, cfg: LintConfig) -> list:
    if cfg.wire_dtype is None:
        return []
    permutes = ir.permutes
    if not permutes:
        return []
    # EVERY float payload must be at or below the wire width — a partial
    # regression (one axis narrowed, the others still full precision) is
    # as real a bandwidth loss as a total one. Width, not equality: an
    # f16 field under bf16 wire legitimately ships as f16
    # (`wire_dtype_for` never widens a payload).
    wire_width = Shape(cfg.wire_dtype, ()).itemsize
    stale = [p for p in permutes
             if (pay := ir.payload_of(p)) is not None
             and pay.dtype.lstrip("b").startswith("f")
             and not pay.dtype.startswith("f8")
             and pay.itemsize > wire_width]
    if not stale:
        return []
    n_float = sum(1 for p in permutes
                  if (pay := ir.payload_of(p)) is not None
                  and pay.dtype.lstrip("b").startswith("f"))
    got = sorted({str(ir.payload_of(p)) for p in stale})
    return [AuditFinding(
        "wire-downcast-missing", SEV_ERROR,
        f"wire dtype {cfg.wire_dtype!r} requested but {len(stale)} of "
        f"{n_float} float collective-permute payload(s) still cross the "
        f"link wider than it (stale payloads: {got}) — the narrowing "
        "did not reach (all of) the wire. (Audit the LOWERED module on "
        "CPU: its backend normalizes bf16 payloads back to f32.)",
        details={"wire_dtype": cfg.wire_dtype, "payloads": got,
                 "stale": len(stale), "float_permutes": n_float})]


def _lint_donation(ir: ProgramIR, cfg: LintConfig) -> list:
    if cfg.expect_donation is None or ir.dialect != "hlo":
        return []
    n = int(ir.attrs.get("n_aliases", 0))
    if n >= int(cfg.expect_donation):
        return []
    return [AuditFinding(
        "donation-unaliased", SEV_WARNING,
        f"{cfg.expect_donation} donated input(s) but only {n} input-"
        "output alias(es) in the module header: each missing alias is a "
        "hidden full-block copy per dispatch.",
        details={"expected": int(cfg.expect_donation), "aliased": n})]


def _lint_host_transfer(ir: ProgramIR, cfg: LintConfig) -> list:
    out = []
    for op in ir.ops:
        hostile = op.op in _HOST_TRANSFER_OPS \
            or op.attrs.get("is_host_transfer") \
            or (op.op == "custom-call"
                and op.attrs.get("custom_call_target")
                in _HOST_CALLBACK_TARGETS)
        if hostile:
            out.append(AuditFinding(
                "host-transfer", SEV_ERROR,
                f"{op.op} inside the compiled body "
                f"({op.attrs.get('custom_call_target') or op.name}): a "
                "host round-trip serializes the step loop.",
                op=op.name, computation=op.computation))
    return out


def _lint_custom_call(ir: ProgramIR, cfg: LintConfig) -> list:
    out = []
    for op in ir.ops:
        if op.op != "custom-call":
            continue
        target = op.attrs.get("custom_call_target")
        if target in _BENIGN_CUSTOM_CALLS \
                or target in _HOST_CALLBACK_TARGETS:
            continue  # host callbacks are the host-transfer rule's job
        out.append(AuditFinding(
            "custom-call", SEV_WARNING,
            f"opaque custom-call {target!r}: the compiler cannot fuse or "
            "reason across it.",
            op=op.name, computation=op.computation,
            details={"target": target}))
    return out


def _lint_f64(ir: ProgramIR, cfg: LintConfig) -> list:
    if "f64" in cfg.state_dtypes:
        return []
    leaks = [op for op in ir.ops if op.has_shape("f64")]
    if not leaks:
        return []
    return [AuditFinding(
        "f64-leakage", SEV_WARNING,
        f"{len(leaks)} op(s) carry f64 buffers in a program whose state "
        "dtypes are "
        f"{sorted(cfg.state_dtypes) or '(unspecified)'} — on TPU every "
        "f64 op runs through emulation; an unintended promotion is a "
        "silent perf cliff.",
        op=leaks[0].name, computation=leaks[0].computation,
        details={"ops": len(leaks), "first": leaks[0].op})]


def _lint_copy_feeds_collective(ir: ProgramIR, cfg: LintConfig) -> list:
    out = []
    for op in ir.collectives():
        for name in op.operands:
            prod = ir.resolve(op.computation, name)
            if prod is not None and prod.op == "copy":
                out.append(AuditFinding(
                    "copy-feeds-collective", SEV_WARNING,
                    f"{op.op} payload is staged through a copy "
                    f"({prod.shapes[0] if prod.shapes else '?'}): the "
                    "slab slicing failed to fuse into the collective.",
                    op=op.name, computation=op.computation,
                    details={"copy": prod.name}))
    return out


LINT_RULES = {
    "global-materialization": _lint_global_materialization,
    "wire-downcast-missing": _lint_wire_downcast,
    "donation-unaliased": _lint_donation,
    "host-transfer": _lint_host_transfer,
    "custom-call": _lint_custom_call,
    "f64-leakage": _lint_f64,
    "copy-feeds-collective": _lint_copy_feeds_collective,
}
DEFAULT_LINTS = tuple(LINT_RULES)


def run_lints(ir: ProgramIR, *, config: LintConfig | None = None,
              rules=None) -> list:
    """Run the lint ``rules`` (names from `LINT_RULES`; default all) over
    a parsed program. Returns findings sorted most-severe first."""
    if not isinstance(ir, ProgramIR):
        raise InvalidArgumentError(
            "run_lints expects a ProgramIR (use parse_program).")
    config = config if config is not None else default_lint_config()
    out: list = []
    for name in (rules if rules is not None else DEFAULT_LINTS):
        fn = LINT_RULES.get(name)
        if fn is None:
            raise InvalidArgumentError(
                f"unknown lint rule {name!r} (have {sorted(LINT_RULES)}).")
        out.extend(fn(ir, config))
    return sort_findings(out)
