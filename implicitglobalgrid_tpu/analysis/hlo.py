"""Structured parsing of compiled XLA programs: HLO / StableHLO text -> IR.

The framework's wire-level guarantees ("one `collective-permute` pair per
exchanging mesh axis", "exactly one tiny guard psum per chunk") were
historically enforced by per-test regexes over `as_text()` dumps — regexes
that silently went stale across XLA versions (the old
`_assert_slab_sized_permutes` only recognised ``f32[...]`` shapes, so bf16
wire payloads and f64 fields were invisible to the slab audit). This module
replaces them with a real parser: `parse_text` / `parse_program` turn an
optimized-HLO dump (or a lowered StableHLO module) into a `ProgramIR` — a
full op inventory where every collective carries its operand/result shapes,
dtype, bytes-on-wire, and source-target/replica-group metadata, plus the
def-use graph (`ProgramIR.closure`) the structural-overlap audit needs.

Two dialects, one IR:

- **optimized HLO** (``fn.lower(...).compile().as_text()``) — the program
  the backend actually runs, post-SPMD: parameters are per-shard blocks,
  collectives name their ``source_target_pairs`` over linearized mesh
  positions. The deep audit (contracts, global-materialization lint) runs
  here.
- **StableHLO** (``fn.lower(...).as_text()``) — the pre-backend module.
  Reduced-precision wire payloads are still visible here (the XLA:CPU
  float-normalization pass rewrites bf16 back to f32 in the optimized
  text; TPU keeps them native), and tracing+lowering costs no backend
  compile — which is why `run_resilient(audit=True)` audits this form.

Opcode names are canonicalised to HLO spelling (underscores -> dashes,
dialect prefixes stripped): ``stablehlo.collective_permute`` and
``collective-permute-start`` both answer to ``"collective-permute"`` in
`ProgramIR.permutes`. Everything here is stdlib + numpy: no jax import,
so golden HLO fixtures parse host-only (tests/data/hlo/).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field

from ..utils.exceptions import InvalidArgumentError

__all__ = ["Shape", "HloOp", "ProgramIR", "parse_text", "parse_program"]


_ITEMSIZE = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "f16": 2, "bf16": 2, "f32": 4, "f64": 8, "c64": 8, "c128": 16,
    "token": 0, "opaque": 0,
}


@dataclass(frozen=True)
class Shape:
    """One array shape in a program: element dtype (HLO spelling) + dims."""

    dtype: str
    dims: tuple

    @property
    def cells(self) -> int:
        n = 1
        for d in self.dims:
            n *= int(d)
        return n

    @property
    def itemsize(self) -> int:
        if self.dtype in _ITEMSIZE:
            return _ITEMSIZE[self.dtype]
        return 1 if self.dtype.startswith("f8") else 4

    @property
    def nbytes(self) -> int:
        return self.cells * self.itemsize

    def __str__(self) -> str:
        return f"{self.dtype}[{','.join(str(d) for d in self.dims)}]"


@dataclass(frozen=True, eq=False)  # identity hash: ops are graph nodes
class HloOp:
    """One parsed instruction (either dialect, canonical opcode spelling)."""

    name: str                 # SSA name without the leading %
    op: str                   # canonical opcode, e.g. "collective-permute"
    computation: str          # owning computation / func name
    shapes: tuple             # result Shape(s)
    operands: tuple           # operand SSA names (computation-scoped)
    operand_shapes: tuple     # operand Shape(s) when the text carries them
    attrs: dict = dc_field(default_factory=dict)
    metadata: dict = dc_field(default_factory=dict)
    line_no: int = 0
    line: str = ""

    @property
    def uid(self) -> str:
        """Module-unique id (StableHLO reuses %0.. per func)."""
        return f"{self.computation}:{self.name}"

    def has_shape(self, dtype: str, dims=None) -> bool:
        """Whether any result/operand shape matches (dims=None: dtype only)."""
        for s in self.shapes + self.operand_shapes:
            if s.dtype == dtype and (dims is None or s.dims == tuple(dims)):
                return True
        return False


# ---------------------------------------------------------------------------
# shared text helpers

_HLO_SHAPE = re.compile(
    r"\b(pred|token|opaque|bf16|f16|f32|f64|f8e\w+|[suc]\d+)"
    r"\[([0-9,]*)\](?:\{[^}]*\})?")
_TENSOR = re.compile(r"tensor<((?:\d+x)*)([A-Za-z]\w*)>")
_MLIR_DTYPE = {"i1": "pred"}


def _mlir_dtype(dt: str) -> str:
    if dt in _MLIR_DTYPE:
        return _MLIR_DTYPE[dt]
    if dt.startswith("ui"):
        return "u" + dt[2:]
    if dt.startswith("i") and dt[1:].isdigit():
        return "s" + dt[1:]
    return dt


def _hlo_shapes(s: str) -> tuple:
    return tuple(Shape(m.group(1),
                       tuple(int(x) for x in m.group(2).split(",") if x))
                 for m in _HLO_SHAPE.finditer(s))


def _tensor_shapes(s: str) -> tuple:
    return tuple(Shape(_mlir_dtype(m.group(2)),
                       tuple(int(x) for x in m.group(1).split("x") if x))
                 for m in _TENSOR.finditer(s))


def _match_paren(s: str, i: int) -> int:
    """Index of the paren closing the one at ``i`` (quote-aware)."""
    depth, in_str = 0, False
    for j in range(i, len(s)):
        c = s[j]
        if in_str:
            if c == '"' and s[j - 1] != "\\":
                in_str = False
            continue
        if c == '"':
            in_str = True
        elif c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return j
    raise InvalidArgumentError(f"unbalanced parentheses in HLO line: {s!r}")


def _split_top(s: str) -> list:
    """Split on top-level commas (outside (), {}, [], "")."""
    out, depth, in_str, start = [], 0, False, 0
    for j, c in enumerate(s):
        if in_str:
            if c == '"' and s[j - 1] != "\\":
                in_str = False
            continue
        if c == '"':
            in_str = True
        elif c in "({[":
            depth += 1
        elif c in ")}]":
            depth -= 1
        elif c == "," and depth == 0:
            out.append(s[start:j])
            start = j + 1
    out.append(s[start:])
    return [p.strip() for p in out if p.strip()]


_PAIRS = re.compile(r"\{(\d+),(\d+)\}")
_GROUP = re.compile(r"\{([0-9, ]*)\}")


def _parse_hlo_attrs(rest: str) -> dict:
    attrs: dict = {}
    for part in _split_top(rest):
        if "=" not in part:
            continue
        key, val = part.split("=", 1)
        key, val = key.strip(), val.strip()
        if key == "channel_id":
            attrs[key] = int(val)
        elif key == "source_target_pairs":
            attrs[key] = tuple((int(a), int(b))
                               for a, b in _PAIRS.findall(val))
        elif key == "replica_groups":
            attrs["replica_groups_raw"] = val
            if val.startswith("{"):
                attrs[key] = tuple(
                    tuple(int(x) for x in g.split(",") if x.strip())
                    for g in _GROUP.findall(val[1:-1]))
        elif key == "custom_call_target":
            attrs[key] = val.strip('"')
        elif key == "is_host_transfer":
            attrs[key] = val == "true"
        elif key in ("calls", "to_apply", "body", "condition"):
            attrs[key] = val.lstrip("%")
        elif key == "metadata":
            md = {}
            for mk in ("op_name", "source_file"):
                m = re.search(mk + r'="([^"]*)"', val)
                if m:
                    md[mk] = m.group(1)
            m = re.search(r"source_line=(\d+)", val)
            if m:
                md["source_line"] = int(m.group(1))
            attrs[key] = md
        else:
            attrs.setdefault("raw", {})[key] = val
    return attrs


# ---------------------------------------------------------------------------
# optimized-HLO dialect

_HLO_COMP = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->.*\{\s*$")
_HLO_INSTR = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.+)$")
_HLO_TYPE_ONE = re.compile(
    r"(pred|token|opaque|bf16|f16|f32|f64|f8e\w+|[suc]\d+)"
    r"\[[0-9,]*\](?:\{[^}]*\})?")
_HLO_OPCODE = re.compile(r"([\w\-]+)\(")


def _parse_hlo(text: str) -> "ProgramIR":
    ops, computations, entry = [], {}, None
    module, module_attrs = "", {}
    comp = None
    for ln, line in enumerate(text.splitlines(), 1):
        if line.startswith("HloModule"):
            head = line.split(None, 1)[1] if " " in line else ""
            parts = _split_top(head)
            module = parts[0].rstrip(",") if parts else ""
            for p in parts[1:]:
                if "=" in p:
                    k, v = p.split("=", 1)
                    module_attrs[k.strip()] = v.strip()
            alias = module_attrs.get("input_output_alias", "")
            module_attrs["n_aliases"] = len(
                re.findall(r"\{[0-9, ]*\}\s*:", alias))
            continue
        m = _HLO_COMP.match(line.strip()) if line.rstrip().endswith("{") \
            else None
        if m:
            comp = m.group(2)
            computations[comp] = []
            if m.group(1):
                entry = comp
            continue
        if comp is None:
            continue
        m = _HLO_INSTR.match(line)
        if not m:
            continue
        name, rhs = m.group(1), m.group(2)
        # result type: a tuple "(...)" or one shape token
        if rhs.startswith("("):
            close = _match_paren(rhs, 0)
            type_str, rest = rhs[:close + 1], rhs[close + 1:].lstrip()
        else:
            tm = _HLO_TYPE_ONE.match(rhs)
            if not tm:
                continue
            type_str, rest = tm.group(0), rhs[tm.end():].lstrip()
        om = _HLO_OPCODE.match(rest)
        if not om:
            continue
        opcode = om.group(1)
        close = _match_paren(rest, om.end() - 1)
        operand_str = rest[om.end():close]
        attr_str = rest[close + 1:].lstrip(", ")
        attrs = _parse_hlo_attrs(attr_str)
        op = HloOp(
            name=name, op=opcode, computation=comp,
            shapes=_hlo_shapes(type_str),
            operands=tuple(n.lstrip("%") for n in
                           re.findall(r"%[\w.\-]+", operand_str)),
            operand_shapes=_hlo_shapes(operand_str),
            attrs=attrs,
            metadata=attrs.get("metadata", {}),
            line_no=ln, line=line.strip())
        ops.append(op)
        computations[comp].append(op)
    return ProgramIR(dialect="hlo", module=module, ops=tuple(ops),
                     computations={k: tuple(v)
                                   for k, v in computations.items()},
                     entry=entry, attrs=module_attrs)


# ---------------------------------------------------------------------------
# StableHLO dialect

_SH_FUNC = re.compile(r"^\s*func\.func\s+(?:public\s+|private\s+)?@(\w+)")
_SH_INSTR = re.compile(r"^\s*%([\w]+)(?::\d+)?\s*=\s*(.+)$")
_SH_OPNAME = re.compile(r'^"?(?:[a-z]\w*\.)?([\w.]+?)"?[\s(<]')
_SH_DENSE_PAIRS = re.compile(r"\[\s*(\d+)\s*,\s*(\d+)\s*\]")


def _parse_stablehlo(text: str) -> "ProgramIR":
    ops, computations, entry = [], {}, None
    module, module_attrs = "", {}
    comp = None
    for ln, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if s.startswith("module"):
            m = re.match(r"module\s+@(\S+)", s)
            module = m.group(1) if m else ""
            m = re.search(r"mhlo\.num_partitions\s*=\s*(\d+)", s)
            if m:
                module_attrs["num_partitions"] = m.group(1)
            continue
        m = _SH_FUNC.match(line)
        if m:
            comp = m.group(1)
            computations[comp] = []
            if comp == "main":
                entry = comp
            continue
        if comp is None:
            continue
        m = _SH_INSTR.match(line)
        if not m:
            continue
        name, rhs = m.group(1), m.group(2)
        om = _SH_OPNAME.match(rhs)
        if om:
            opcode = om.group(1).replace("_", "-").replace(".", "-")
        elif rhs.startswith("call"):
            opcode = "call"
        else:
            continue
        if opcode.startswith("call"):
            opcode = "call"
        # final type signature: the last top-level " : " of the line
        before, sig = (rhs.rsplit(" : ", 1) + [""])[:2] \
            if " : " in rhs else (rhs, "")
        if "->" in sig:
            opnd_sig, res_sig = sig.rsplit("->", 1)
        else:
            opnd_sig, res_sig = "", sig
        attrs: dict = {}
        m = re.search(r"channel_handle<handle\s*=\s*(\d+)", rhs)
        if m:
            attrs["channel_id"] = int(m.group(1))
        m = re.search(r"source_target_pairs\s*=\s*dense<(.*?)>\s*:", rhs)
        if m:
            attrs["source_target_pairs"] = tuple(
                (int(a), int(b))
                for a, b in _SH_DENSE_PAIRS.findall(m.group(1)))
        m = re.search(r"replica_groups\s*=\s*dense<(.*?)>\s*:", rhs)
        if m:
            attrs["replica_groups_raw"] = m.group(1)
        if opcode == "custom-call":
            # dotted symbol names are real (@xla.sdy.FuncResultSharding)
            cm = re.search(r"@([\w.]+)", rhs)
            if cm:
                attrs["custom_call_target"] = cm.group(1).rstrip(".")
        if opcode == "call":
            cm = re.search(r"@([\w.]+)", rhs)
            if cm:
                attrs["calls"] = cm.group(1).rstrip(".")
        op = HloOp(
            name=name, op=opcode, computation=comp,
            shapes=_tensor_shapes(res_sig),
            operands=tuple(n.lstrip("%")
                           for n in re.findall(r"%[\w]+", before)),
            operand_shapes=_tensor_shapes(opnd_sig),
            attrs=attrs, line_no=ln, line=s)
        ops.append(op)
        computations[comp].append(op)
    return ProgramIR(dialect="stablehlo", module=module, ops=tuple(ops),
                     computations={k: tuple(v)
                                   for k, v in computations.items()},
                     entry=entry, attrs=module_attrs)


# ---------------------------------------------------------------------------
# the IR

_COLLECTIVE_BASES = ("collective-permute", "all-reduce", "all-gather",
                     "all-to-all", "reduce-scatter")


@dataclass(frozen=True)
class ProgramIR:
    """A parsed program: op inventory + def-use graph over all computations.

    Collective accessors follow the counting semantics the regex audits
    used: async pairs count by their ``-start`` op (``-done`` ignored);
    when no start form is present the synchronous ops count."""

    dialect: str              # "hlo" | "stablehlo"
    module: str
    ops: tuple
    computations: dict
    entry: str | None
    attrs: dict = dc_field(default_factory=dict)

    # -- inventory ----------------------------------------------------------
    def find(self, op: str | None = None, *, dtype: str | None = None,
             computation: str | None = None) -> list:
        out = []
        for o in self.ops:
            if op is not None and o.op != op:
                continue
            if computation is not None and o.computation != computation:
                continue
            if dtype is not None and not o.has_shape(dtype):
                continue
            out.append(o)
        return out

    def count(self, op: str) -> int:
        return sum(1 for o in self.ops if o.op == op)

    def inventory(self) -> dict:
        inv: dict = {}
        for o in self.ops:
            inv[o.op] = inv.get(o.op, 0) + 1
        return dict(sorted(inv.items()))

    def _kind(self, base: str) -> list:
        starts = [o for o in self.ops if o.op == base + "-start"]
        return starts if starts else [o for o in self.ops if o.op == base]

    @property
    def permutes(self) -> list:
        return self._kind("collective-permute")

    @property
    def all_reduces(self) -> list:
        return self._kind("all-reduce")

    @property
    def all_gathers(self) -> list:
        return self._kind("all-gather")

    @property
    def all_to_alls(self) -> list:
        return self._kind("all-to-all")

    def collectives(self) -> list:
        out = []
        for base in _COLLECTIVE_BASES:
            out.extend(self._kind(base))
        return sorted(out, key=lambda o: o.line_no)

    def parameters(self, computation: str | None = None) -> list:
        comp = computation or self.entry
        return [o for o in self.computations.get(comp, ())
                if o.op == "parameter"]

    # -- payloads -----------------------------------------------------------
    def resolve(self, computation: str, name: str) -> HloOp | None:
        for o in self.computations.get(computation, ()):
            if o.name == name:
                return o
        return None

    def payload_of(self, op: HloOp) -> Shape | None:
        """The on-wire payload shape of a collective: its first operand
        (resolved through the def-use graph when the text doesn't carry
        operand types inline, e.g. StableHLO region ops)."""
        if op.operand_shapes:
            return op.operand_shapes[0]
        for name in op.operands:
            prod = self.resolve(op.computation, name)
            if prod is not None and prod.shapes:
                return prod.shapes[0]
        if op.shapes:
            return op.shapes[0]
        return None

    def wire_bytes_of(self, op: HloOp) -> int:
        """Payload bytes summed over every directed link the op drives."""
        pay = self.payload_of(op)
        pairs = op.attrs.get("source_target_pairs") or ()
        return (pay.nbytes if pay else 0) * len(pairs)

    # -- def-use ------------------------------------------------------------
    def closure(self, seeds, direction: str = "up") -> set:
        """Transitive producers (``"up"``) or consumers (``"down"``) of the
        given ops, within their computations. Returns a set of `HloOp`."""
        if direction not in ("up", "down"):
            raise InvalidArgumentError(
                f"closure direction must be 'up' or 'down', got {direction!r}")
        by_comp: dict = {}
        for o in self.ops:
            by_comp.setdefault(o.computation, {})[o.name] = o
        rev: dict = {}
        if direction == "down":
            for o in self.ops:
                for name in o.operands:
                    rev.setdefault((o.computation, name), []).append(o)
        out: set = set()
        seen = {o.uid for o in seeds}
        stack = list(seeds)
        while stack:
            o = stack.pop()
            if direction == "up":
                nbrs = [by_comp.get(o.computation, {}).get(n)
                        for n in o.operands]
            else:
                nbrs = rev.get((o.computation, o.name), [])
            for nb in nbrs:
                if nb is not None and nb.uid not in seen:
                    seen.add(nb.uid)
                    out.add(nb)
                    stack.append(nb)
        return out


# ---------------------------------------------------------------------------
# entry points

def parse_text(text: str) -> ProgramIR:
    """Parse a program dump (optimized HLO or StableHLO, auto-detected)."""
    if not isinstance(text, str) or not text.strip():
        raise InvalidArgumentError("parse_text expects a non-empty program "
                                   "text.")
    head = text.lstrip()
    if head.startswith("HloModule"):
        return _parse_hlo(text)
    if head.startswith("module") or "stablehlo." in head[:4096]:
        return _parse_stablehlo(text)
    raise InvalidArgumentError(
        "parse_text: not recognizably HLO (expected a leading 'HloModule') "
        "or StableHLO (a leading 'module @...') dump.")


def parse_program(src, *args, optimized: bool = True) -> ProgramIR:
    """Parse ``src`` into a `ProgramIR`.

    ``src`` may be program text (either dialect), an already-parsed
    `ProgramIR` (returned as-is), a jax ``Lowered``/``Compiled`` object
    (anything with ``as_text``), or a jitted callable — which is lowered
    with ``*args`` and, when ``optimized`` (default), backend-compiled so
    the IR reflects the program the device actually runs. Pass
    ``optimized=False`` to parse the pre-backend StableHLO instead (no XLA
    compile — the cheap form `run_resilient(audit=True)` uses; also where
    reduced-precision wire payloads remain visible on backends whose
    float-normalization rewrites them)."""
    if isinstance(src, ProgramIR):
        return src
    if isinstance(src, str):
        return parse_text(src)
    if hasattr(src, "as_text"):
        return parse_text(src.as_text())
    if hasattr(src, "lower"):
        lowered = src.lower(*args)
        if optimized:
            return parse_text(lowered.compile().as_text())
        return parse_text(lowered.as_text())
    raise InvalidArgumentError(
        f"parse_program: cannot parse {type(src).__name__} (want text, a "
        "Lowered/Compiled object, or a jitted callable plus example args).")
