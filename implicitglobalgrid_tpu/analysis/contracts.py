"""Collective contracts: the static wire plan as a checkable declaration.

The framework's wire claims are *derivable*, not hand-counted: the halo
layer's `halo_comm_plan` already prices every exchange from shapes,
overlaps, and the wire dtype alone, and the perf oracle's `STEP_WORKLOADS`
records how each model's step actually groups its exchange rounds. This
module turns those same inputs into a `CollectiveContract` — per-axis
expected permute counts, on-wire dtypes, exact wire bytes, legal routes
(``source_target_pairs`` per mesh axis from the grid topology), payload
slab bounds, and the guard psum shape — and `check_contract` verifies a
parsed `ProgramIR` against it, yielding structured `AuditFinding`s instead
of regex assertion failures.

Because the contract and `telemetry.predict_step` price from the SAME
plan, `perfmodel_crosscheck` closes the loop: the oracle's priced
ppermute-pair and wire-byte counts must equal what the compiler actually
emitted — static-model drift becomes a caught finding, not a silent
mispricing.

Route attribution: JAX lowers ``lax.ppermute`` over a mesh axis to
``source_target_pairs`` in linearized mesh positions (row-major over
``gg.dims``), independent of the physical device assignment, so the legal
pair-sets per (axis, direction) are computed from ``_perm_pairs`` + the
dims alone (`axis_routes`). A permute whose pair set matches no axis is an
error finding by itself — an unplanned communication route.
"""

from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..utils.exceptions import InvalidArgumentError
from .hlo import ProgramIR

__all__ = ["AuditFinding", "CollectiveContract", "axis_routes",
           "measure_axes", "exchange_contract", "model_contract",
           "guard_contract", "check_contract", "perfmodel_crosscheck"]

SEV_ERROR, SEV_WARNING, SEV_INFO = "error", "warning", "info"
_SEV_ORDER = {SEV_ERROR: 0, SEV_WARNING: 1, SEV_INFO: 2}


@dataclass(frozen=True)
class AuditFinding:
    """One structured audit result (a broken rule, or a notable fact)."""

    rule: str
    severity: str             # "error" | "warning" | "info"
    message: str
    op: str | None = None     # SSA name of the op the finding anchors to
    computation: str | None = None
    details: dict = dc_field(default_factory=dict)

    def to_json(self) -> dict:
        out = {"rule": self.rule, "severity": self.severity,
               "message": self.message}
        if self.op is not None:
            out["op"] = self.op
        if self.computation is not None:
            out["computation"] = self.computation
        if self.details:
            out["details"] = self.details
        return out


def sort_findings(findings) -> list:
    return sorted(findings,
                  key=lambda f: (_SEV_ORDER.get(f.severity, 3), f.rule))


@dataclass(frozen=True)
class CollectiveContract:
    """Expected collective shape of one compiled program.

    ``axes`` maps mesh axis names to ``{"permutes", "wire_bytes",
    "dtypes"}`` — the exact number of collective-permute OPS (2 per pair
    per exchange group), the exact all-links bytes-on-wire, and the legal
    payload dtypes for that axis; ``axes=None`` skips the per-axis checks
    (counts/bytes/routes) while the structural ones (payload slab bound,
    guard psum, forbidden gathers) still run. ``routes`` holds the legal
    ``source_target_pairs`` sets per axis (`axis_routes`); ``None``
    disables attribution. ``allreduce_payload`` is ``(dtype, length)`` of
    the one permitted psum (the health guard's stats vector), checked on
    every all-reduce present. ``max_payload_cells`` bounds every permute
    payload strictly below the local block — dtype-generic (the old
    f32-only regex skipped bf16/f16/f64 payloads entirely)."""

    axes: dict | None = None
    routes: dict | None = None
    allreduces: int = 0
    allreduce_payload: tuple | None = None
    allow_all_gathers: bool = False
    allow_all_to_alls: bool = False
    max_payload_cells: int | None = None
    meta: dict = dc_field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "axes": self.axes,
            "routes": None if self.routes is None else {
                a: [sorted(list(p) for p in r) for r in routes]
                for a, routes in self.routes.items()},
            "allreduces": self.allreduces,
            "allreduce_payload": (list(self.allreduce_payload)
                                  if self.allreduce_payload else None),
            "allow_all_gathers": self.allow_all_gathers,
            "allow_all_to_alls": self.allow_all_to_alls,
            "max_payload_cells": self.max_payload_cells,
            "meta": self.meta,
        }

    @classmethod
    def from_json(cls, rec) -> "CollectiveContract":
        if isinstance(rec, (str, bytes)):
            rec = json.loads(rec)
        try:
            axes = rec.get("axes")
            if axes is not None:
                axes = {str(a): {"permutes": int(v["permutes"]),
                                 "wire_bytes": (None if v.get("wire_bytes")
                                                is None
                                                else int(v["wire_bytes"])),
                                 "dtypes": tuple(v.get("dtypes", ()))}
                        for a, v in axes.items()}
            routes = rec.get("routes")
            if routes is not None:
                routes = {str(a): tuple(
                    frozenset((int(s), int(t)) for s, t in route)
                    for route in rts) for a, rts in routes.items()}
            arp = rec.get("allreduce_payload")
            return cls(
                axes=axes, routes=routes,
                allreduces=int(rec.get("allreduces", 0)),
                allreduce_payload=(None if arp is None
                                   else (str(arp[0]), int(arp[1]))),
                allow_all_gathers=bool(rec.get("allow_all_gathers", False)),
                allow_all_to_alls=bool(rec.get("allow_all_to_alls", False)),
                max_payload_cells=(None if rec.get("max_payload_cells")
                                   is None
                                   else int(rec["max_payload_cells"])),
                meta=dict(rec.get("meta", {})))
        except (KeyError, TypeError, ValueError, IndexError) as e:
            raise InvalidArgumentError(
                f"CollectiveContract.from_json: malformed record ({e}).") \
                from e


# ---------------------------------------------------------------------------
# topology-derived route tables

def axis_routes(gg=None) -> dict:
    """Legal directed ``(source, target)`` pair-sets per mesh axis and
    exchange direction, in linearized mesh positions (row-major over
    ``gg.dims`` — the ids JAX emits in ``source_target_pairs``)."""
    from ..ops.halo import _perm_pairs
    from ..parallel.topology import AXIS_NAMES, global_grid

    gg = gg if gg is not None else global_grid()
    dims = [int(d) for d in gg.dims]
    table: dict = {}
    for d, axis in enumerate(AXIS_NAMES):
        D, periodic, disp = dims[d], bool(gg.periods[d]), int(gg.disp)
        perm_p, perm_m = _perm_pairs(D, periodic, disp)
        routes = []
        for perm in (perm_p, perm_m):
            pairs = set()
            spaces = [range(dims[k]) if k != d else (0,)
                      for k in range(len(dims))]
            for base in itertools.product(*spaces):
                for s, t in perm:
                    if s == t:  # periodic self-neighbor: local copy, no wire
                        continue
                    src, dst = list(base), list(base)
                    src[d], dst[d] = s, t
                    pairs.add((int(np.ravel_multi_index(src, dims)),
                               int(np.ravel_multi_index(dst, dims))))
            if pairs:
                routes.append(frozenset(pairs))
        if routes:
            table[axis] = tuple(routes)
    # topology-staged sub-routes: when the grid declares DCN granules
    # along an axis (`GlobalGrid.dcn_granules`), the staged wire's
    # gather / striped-DCN / scatter / intra hops ride pair-sets of
    # their own — appended under the staged axis so a staged program's
    # permutes attribute. A gather route that coincides with the gather
    # axis's flat route (every shard crosses, block=1) attributes to the
    # GATHER axis by first-match order — exactly the link its traffic
    # crosses, and the same order `_merged_plan` derives contracts with.
    from ..parallel.topology import staged_wire_layout

    for d, axis in enumerate(AXIS_NAMES):
        lay = staged_wire_layout(gg, d)
        if lay is None:
            continue
        have = {fs for rts in table.values() for fs in rts}
        extra = []
        for dr in lay.directions:
            for pl in (dr.gather_pairs, dr.dcn_pairs, dr.scatter_pairs,
                       dr.intra_pairs_lin):
                fs = frozenset((int(s), int(t)) for s, t in pl if s != t)
                if fs and fs not in have:
                    have.add(fs)
                    extra.append(fs)
        if extra:
            table[axis] = tuple(table.get(axis, ())) + tuple(extra)
    return table


def attribute_axis(routes: dict, pairs) -> str | None:
    """Mesh axis whose legal route matches the permute's pair set."""
    ps = frozenset((int(s), int(t)) for s, t in pairs)
    for axis, rts in routes.items():
        if ps in rts:
            return axis
    return None


def measure_axes(ir: ProgramIR, routes: dict) -> dict:
    """Per-axis totals of the parsed program's permutes: op count, directed
    pair count, all-links wire bytes, payload dtypes. Unattributable
    permutes land under the ``None`` key."""
    out: dict = {}
    for op in ir.permutes:
        pairs = op.attrs.get("source_target_pairs") or ()
        axis = attribute_axis(routes, pairs) if pairs else None
        rec = out.setdefault(axis, {"permutes": 0, "pairs": 0,
                                    "wire_bytes": 0, "dtypes": set()})
        rec["permutes"] += 1
        rec["pairs"] += len(pairs)
        rec["wire_bytes"] += ir.wire_bytes_of(op)
        pay = ir.payload_of(op)
        if pay is not None:
            rec["dtypes"].add(pay.dtype)
    return {a: {**r, "dtypes": tuple(sorted(r["dtypes"]))}
            for a, r in out.items()}


# ---------------------------------------------------------------------------
# contract derivation (from the SAME plan the telemetry layer prices)

_NP_TO_HLO = {
    "float64": "f64", "float32": "f32", "float16": "f16",
    "bfloat16": "bf16", "bool": "pred",
    "int8": "s8", "int16": "s16", "int32": "s32", "int64": "s64",
    "uint8": "u8", "uint16": "u16", "uint32": "u32", "uint64": "u64",
    "complex64": "c64", "complex128": "c128",
    # quantized wire payloads (bit-packed int4 included) ship as s8 bytes
    "int4": "s8",
}


def hlo_dtype(name) -> str:
    """Numpy/jax dtype spelling -> HLO spelling (``float32`` -> ``f32``);
    HLO spellings pass through unchanged."""
    return _NP_TO_HLO.get(str(name), str(name))


def _staged_stage_routes(layout) -> dict:
    """``{(direction, stage): pair tuple}`` of one `StagedWireLayout` —
    the route each stage-table entry's ppermutes ride."""
    out = {}
    for dr in layout.directions:
        out[(dr.name, "intra")] = dr.intra_pairs_lin
        out[(dr.name, "gather")] = dr.gather_pairs
        out[(dr.name, "dcn")] = dr.dcn_pairs
        out[(dr.name, "scatter")] = dr.scatter_pairs
    return out


def _merged_plan(fields, rounds, *, dims=None, coalesce=None,
                 wire_dtype=None, ensemble=None, comm_every=None,
                 wire_stage=None) -> dict:
    """Per-axis {ppermutes, wire_bytes, dtypes} merged over the exchange
    rounds exactly as `telemetry.predict_step` merges them: fields in one
    round coalesce, separate rounds pay separate permutes.

    ``wire_bytes`` here is the ALL-LINKS total the parser measures in a
    compiled program (`ProgramIR.wire_bytes_of` sums the payload over
    every ``source_target_pairs`` entry). `halo_comm_plan` prices one
    axis LINE — payload x directed pairs along a single line of shards —
    while the compiled permute's pair list enumerates every parallel
    line of the mesh, so each axis scales by the perpendicular line
    count (total shards / that axis's extent). Dtypes are converted to
    HLO spelling to match the parsed payloads.

    ``comm_every`` (a deep per-axis cadence — `ops.wire.CommCadence` /
    its spellings) switches the merge to the deep-halo SUPER-CYCLE: the
    compiled super-step advances ``lcm(k_d)`` physical steps, issuing
    each round only along the axes due at each sub-step
    (`CommCadence.due_dims` — the `models.*.deep_step` schedule), so the
    merged totals are per SUPER-STEP program: axis ``d`` carries
    ``cycle / k_d`` exchanges of its ``depth*k_d``-wide slabs.

    ``wire_stage`` merges the topology-staged program: a staged axis's
    plan record carries the hierarchical stage table (absolute ops /
    bytes — the per-line scaling does not apply), and each stage's ops
    are attributed through `attribute_axis` over the SAME route table
    `check_contract` measures with — so a gather pipeline whose route
    coincides with the gather axis's flat route counts under THAT axis,
    exactly as the parser will count it."""
    from ..ops.halo import halo_comm_plan
    from ..ops.wire import resolve_comm_every
    from ..parallel.topology import (
        AXIS_NAMES, global_grid, staged_wire_layout,
    )

    gg = global_grid()
    gdims = [int(d) for d in gg.dims]
    total = 1
    for d in gdims:
        total *= d
    axis_dim = {a: i for i, a in enumerate(AXIS_NAMES)}
    fields = tuple(fields)
    cad = resolve_comm_every(comm_every if comm_every is not None else 1)
    if cad.deep:
        # one (sub-step, due-axes) exchange event per cycle entry; the
        # caller's dims order is the within-event processing order
        events = [cad.due_dims(j) for j in range(cad.cycle)]
        events = [e for e in events if e]
    else:
        events = [dims]
    table = axis_routes(gg)
    stage_routes: dict = {}
    merged: dict = {}

    def rec_for(axis):
        return merged.setdefault(
            axis, {"permutes": 0, "wire_bytes": 0, "dtypes": set()})
    for ev_dims in events:
        for group in rounds:
            if any(i >= len(fields) for i in group):
                raise InvalidArgumentError(
                    f"exchange round {tuple(group)} indexes past the "
                    f"{len(fields)} given fields.")
            sub = halo_comm_plan(*(fields[i] for i in group), dims=ev_dims,
                                 coalesce=coalesce, wire_dtype=wire_dtype,
                                 ensemble=ensemble, wire_stage=wire_stage)
            for axis, rec in sub["axes"].items():
                dts = tuple(hlo_dtype(d) for d in rec["by_dtype"])
                if "staged" in rec:
                    # hierarchical stage table: absolute ops/bytes (no
                    # per-line scaling), each stage counted on the axis
                    # its ROUTE attributes to — byte-identical to what
                    # the parser measures on the compiled program
                    d = axis_dim[axis]
                    if d not in stage_routes:
                        stage_routes[d] = _staged_stage_routes(
                            staged_wire_layout(gg, d))
                    for st in rec["staged"]["stages"]:
                        pl = stage_routes[d][(st["direction"], st["stage"])]
                        ax = attribute_axis(table, pl)
                        dst = rec_for(ax if ax is not None else axis)
                        dst["permutes"] += int(st["ops"])
                        dst["wire_bytes"] += int(st["wire_bytes"])
                        dst["dtypes"].update(dts)
                    continue
                n_lines = total // gdims[axis_dim[axis]]
                dst = rec_for(axis)
                dst["permutes"] += int(rec["ppermutes"])
                dst["wire_bytes"] += int(rec["wire_bytes"]) * n_lines
                dst["dtypes"].update(dts)
    return merged


def _local_block_cells(fields) -> int:
    """Total per-shard block cells across the stacked fields — the slab
    bound: every permute payload must be strictly smaller. A coalesced
    payload legitimately aggregates N fields' slabs (N x slab can reach
    one field's block), so the structural bound is the whole group's
    block total; the per-axis ``wire_bytes`` equality pins the EXACT slab
    sizes whenever the contract carries axes."""
    from ..ops.halo import _normalized_fields, _stacked_sig
    from ..parallel.topology import global_grid

    gg = global_grid()
    sig = _stacked_sig(gg, _normalized_fields(fields))
    return sum(int(np.prod(shape)) for shape, _, _ in sig)


def exchange_contract(*fields, rounds=None, dims=None, coalesce=None,
                      wire_dtype=None, guard_floats: int | None = None,
                      ensemble: int | None = None, comm_every=None,
                      wire_stage=None, meta=None) -> CollectiveContract:
    """Derive the contract for an exchange (or a step program) over the
    CURRENT grid from the static wire plan alone.

    ``fields`` take the same forms as `halo_comm_plan` (arrays, `Field`,
    ``(A, hw)`` tuples, ``jax.ShapeDtypeStruct``). ``rounds`` lists the
    exchange rounds as tuples of field indices (default: one coalesced
    round of every field — `STEP_WORKLOADS[...].exchange_groups` for a
    model step). ``comm_every`` (a deep per-axis cadence) derives the
    DEEP-HALO SUPER-STEP program's contract: per-axis permute counts and
    byte-exact k_d-wide payloads merged over the cadence cycle's due
    schedule (`_merged_plan` — axis ``d`` carries ``lcm(k)/k_d``
    exchanges per compiled super-step). ``guard_floats`` adds the
    resilient runtime's psum
    expectation: exactly one f32 all-reduce of that many floats.
    ``ensemble=E`` is the E-member batched program's contract (fields
    stay the PHYSICAL per-member shapes): identical per-axis permute
    COUNTS with byte-exact E-scaled payloads — the compiled proof that
    collective count is flat in E — the slab bound widens to E x the
    local block (a batched payload legitimately aggregates every
    member's slabs), and ``guard_floats`` stays the PER-MEMBER float
    count: the expected psum payload scales to ``f32[E·guard_floats]``
    exactly like `guard_contract`.

    ``wire_stage`` (the `ops.wire.resolve_wire_stage` spelling family)
    derives the TOPOLOGY-STAGED program's contract: a staged axis's
    expectations prove the hierarchical pipeline byte-exactly — per-stage
    permute counts (``fold - 1`` gather + 1 striped DCN + ``fold - 1``
    scatter per cross direction, plus any intra pair), each stage's ops
    counted on the mesh axis its ROUTE attributes to, and exactly
    ``dcn_pairs`` DCN-crossing transfers per round (ONE per granule-pair
    per direction)."""
    from ..parallel.topology import check_initialized, global_grid

    check_initialized()
    gg = global_grid()
    E = 1
    if ensemble is not None:
        E = int(ensemble)
        if E < 1:
            raise InvalidArgumentError(
                f"exchange_contract: ensemble must be >= 1; got "
                f"{ensemble}.")
    rounds = rounds if rounds is not None else (tuple(range(len(fields))),)
    merged = _merged_plan(fields, rounds, dims=dims, coalesce=coalesce,
                          wire_dtype=wire_dtype, ensemble=ensemble,
                          comm_every=comm_every, wire_stage=wire_stage)
    axes = {a: {"permutes": r["permutes"], "wire_bytes": r["wire_bytes"],
                "dtypes": tuple(sorted(r["dtypes"]))}
            for a, r in merged.items() if r["permutes"]}
    from ..ops.wire import resolve_comm_every, resolve_wire_stage

    cad = resolve_comm_every(comm_every if comm_every is not None else 1)
    stg = resolve_wire_stage(wire_stage)
    # a staged DCN stripe legitimately aggregates fold x the packed
    # payload — widen the structural slab bound by the largest fold
    bound = _local_block_cells(fields) * E
    if stg is not None:
        from ..parallel.topology import staged_wire_layout

        folds = [staged_wire_layout(gg, d) for d in stg.staged_dims]
        fold = max((int(l.fold) for l in folds if l is not None), default=1)
        bound *= fold
    return CollectiveContract(
        axes=axes,
        routes=axis_routes(gg),
        allreduces=0 if guard_floats is None else 1,
        allreduce_payload=(None if guard_floats is None
                           else ("f32", E * int(guard_floats))),
        max_payload_cells=bound,
        meta=dict(meta or {}, dims=[int(d) for d in gg.dims],
                  periods=[int(p) for p in gg.periods],
                  **({"ensemble": E} if E > 1 else {}),
                  **({"comm_every": str(cad)} if cad.deep else {}),
                  **({"wire_stage": str(stg)} if stg is not None else {})))


def model_contract(model, fields, *, dims=None, coalesce=None,
                   wire_dtype=None, impl: str = "xla",
                   guard_floats: int | None = None,
                   ensemble: int | None = None,
                   comm_every=None, wire_stage=None) -> CollectiveContract:
    """The step contract of a model family: exchange rounds from
    `telemetry.STEP_WORKLOADS[model]`, priced over the model's state
    ``fields`` (canonical state order — PHYSICAL per-member shapes when
    ``ensemble`` is set). ``impl`` picks the kernel tier's rounds
    (`StepWorkload.groups_for`): both tiers ride the canonical wire
    schema, so a fused Pallas program gets the same byte-exact contract
    as the XLA path — only the round grouping may differ. A deep
    ``comm_every`` cadence selects the deep runner's rounds
    (``deep_exchange_groups`` — XLA tier) and the super-cycle merge of
    `exchange_contract`: the contract then describes ONE compiled
    super-step, with each axis's permute count amortized by its own
    cadence."""
    from ..ops.wire import resolve_comm_every
    from ..telemetry.perfmodel import STEP_WORKLOADS

    work = STEP_WORKLOADS.get(str(model))
    if work is None:
        raise InvalidArgumentError(
            f"model_contract: unknown model {model!r} "
            f"(have {sorted(STEP_WORKLOADS)}).")
    cad = resolve_comm_every(comm_every if comm_every is not None else 1)
    return exchange_contract(
        *fields, rounds=work.groups_for(impl, deep=cad.deep), dims=dims,
        coalesce=coalesce, wire_dtype=wire_dtype, guard_floats=guard_floats,
        ensemble=ensemble, comm_every=comm_every, wire_stage=wire_stage,
        meta={"model": str(model), "impl": str(impl)})


def guard_contract(n_fields: int, reducer_floats: int = 0,
                   meta=None, ensemble: int | None = None
                   ) -> CollectiveContract:
    """The resilient chunk program's structural contract when the step
    body is user code (per-axis permute counts unknowable): exactly one
    f32[2N + R] guard psum, no gathers, no all-to-alls. With
    ``ensemble=E`` the one psum carries every member's stats —
    ``f32[E·(2N + R)]`` cells, still exactly one all-reduce (the
    per-member verdicts ride one collective)."""
    E = 1
    if ensemble is not None:
        E = int(ensemble)
        if E < 1:
            raise InvalidArgumentError(
                f"guard_contract: ensemble must be >= 1; got {ensemble}.")
    return CollectiveContract(
        axes=None, routes=None, allreduces=1,
        allreduce_payload=("f32",
                           E * (2 * int(n_fields) + int(reducer_floats))),
        meta=dict(meta or {}, n_fields=int(n_fields),
                  reducer_floats=int(reducer_floats),
                  **({"ensemble": E} if E > 1 else {})))


# ---------------------------------------------------------------------------
# the checker

def check_contract(ir: ProgramIR, contract: CollectiveContract) -> list:
    """Verify a parsed program against a contract. Returns findings
    (empty list = the program honors the contract)."""
    if not isinstance(ir, ProgramIR):
        raise InvalidArgumentError(
            "check_contract expects a ProgramIR (use parse_program).")
    if contract.axes and contract.routes is None:
        # without routes no permute can be attributed to an axis, so every
        # per-axis expectation would "fail" with got=0 on a conforming
        # program — an unsatisfiable contract is a caller error, not a
        # finding (hand-written JSON contracts: include "routes", or use
        # axis_routes() on the live grid)
        raise InvalidArgumentError(
            "check_contract: a contract with per-axis expectations needs "
            "routes to attribute permutes (axis_routes(), or a 'routes' "
            "table in the contract JSON).")
    findings: list = []
    routes = contract.routes
    per_axis: dict = {a: {"permutes": 0, "wire_bytes": 0, "dtypes": set()}
                      for a in (contract.axes or {})}

    for op in ir.permutes:
        pay = ir.payload_of(op)
        pairs = op.attrs.get("source_target_pairs") or ()
        if contract.max_payload_cells is not None and pay is not None \
                and pay.cells >= contract.max_payload_cells:
            findings.append(AuditFinding(
                "permute-payload", SEV_ERROR,
                f"collective-permute payload {pay} is not slab-sized "
                f"(>= the {contract.max_payload_cells}-cell local block): "
                "XLA failed to fuse the slab slicing.",
                op=op.name, computation=op.computation,
                details={"payload": str(pay), "cells": pay.cells}))
        if routes is None:
            continue
        axis = attribute_axis(routes, pairs) if pairs else None
        if axis is None:
            findings.append(AuditFinding(
                "permute-route", SEV_ERROR,
                "collective-permute rides a route matching no mesh axis "
                "of the static plan (unplanned communication).",
                op=op.name, computation=op.computation,
                details={"source_target_pairs": [list(p) for p in pairs]}))
            continue
        if contract.axes is not None and axis not in contract.axes:
            findings.append(AuditFinding(
                "permute-count", SEV_ERROR,
                f"collective-permute on mesh axis {axis!r}, which the "
                "plan expects not to exchange.",
                op=op.name, computation=op.computation,
                details={"axis": axis}))
            continue
        if axis in per_axis:
            per_axis[axis]["permutes"] += 1
            per_axis[axis]["wire_bytes"] += ir.wire_bytes_of(op)
            if pay is not None:
                per_axis[axis]["dtypes"].add(pay.dtype)

    if contract.axes is not None:
        for axis, exp in contract.axes.items():
            got = per_axis.get(axis,
                               {"permutes": 0, "wire_bytes": 0,
                                "dtypes": set()})
            if got["permutes"] != int(exp["permutes"]):
                findings.append(AuditFinding(
                    "permute-count", SEV_ERROR,
                    f"axis {axis!r}: {got['permutes']} collective-permutes "
                    f"in the program, plan expects {exp['permutes']}.",
                    details={"axis": axis, "got": got["permutes"],
                             "expected": int(exp["permutes"])}))
                continue
            exp_bytes = exp.get("wire_bytes")
            if exp_bytes is not None and got["wire_bytes"] != int(exp_bytes):
                findings.append(AuditFinding(
                    "wire-bytes", SEV_ERROR,
                    f"axis {axis!r}: {got['wire_bytes']} bytes on wire in "
                    f"the program, plan prices {exp_bytes}.",
                    details={"axis": axis, "got": got["wire_bytes"],
                             "expected": int(exp_bytes)}))
            exp_dts = set(exp.get("dtypes") or ())
            if exp_dts and not set(got["dtypes"]) <= exp_dts:
                findings.append(AuditFinding(
                    "permute-dtype", SEV_ERROR,
                    f"axis {axis!r}: payload dtypes "
                    f"{sorted(got['dtypes'])} not within the plan's "
                    f"{sorted(exp_dts)} (wire-dtype contract).",
                    details={"axis": axis,
                             "got": sorted(got["dtypes"]),
                             "expected": sorted(exp_dts)}))

    ars = ir.all_reduces
    if len(ars) != int(contract.allreduces):
        findings.append(AuditFinding(
            "allreduce-count", SEV_ERROR,
            f"{len(ars)} all-reduces in the program, contract expects "
            f"{contract.allreduces}.",
            details={"got": len(ars), "expected": int(contract.allreduces)}))
    if contract.allreduce_payload is not None:
        dt, length = contract.allreduce_payload
        for op in ars:
            pay = ir.payload_of(op)
            if pay is None or pay.dtype != dt or pay.cells != int(length):
                findings.append(AuditFinding(
                    "allreduce-payload", SEV_ERROR,
                    f"all-reduce payload {pay} is not the guard's tiny "
                    f"{dt}[{length}] stats vector.",
                    op=op.name, computation=op.computation,
                    details={"payload": str(pay) if pay else None,
                             "expected": f"{dt}[{length}]"}))
    if ir.all_gathers and not contract.allow_all_gathers:
        findings.append(AuditFinding(
            "all-gather-forbidden", SEV_ERROR,
            f"{len(ir.all_gathers)} all-gather(s) in a program whose "
            "contract forbids them (a gather over the implicit grid "
            "materializes what must never exist).",
            details={"got": len(ir.all_gathers)}))
    if ir.all_to_alls and not contract.allow_all_to_alls:
        findings.append(AuditFinding(
            "all-to-all-forbidden", SEV_ERROR,
            f"{len(ir.all_to_alls)} all-to-all(s) in a program whose "
            "contract forbids them.",
            details={"got": len(ir.all_to_alls)}))
    return sort_findings(findings)


# ---------------------------------------------------------------------------
# perfmodel cross-check

def perfmodel_crosscheck(model, fields, ir: ProgramIR, *, profile=None,
                         dims=None, coalesce=None, wire_dtype=None,
                         impl: str = "xla",
                         ensemble: int | None = None,
                         comm_every=None, wire_stage=None) -> dict:
    """Prove `telemetry.predict_step`'s collective pricing against the
    compiled program: per mesh axis, the oracle's priced ppermute PAIRS
    and all-links wire bytes must equal what the parser measured in the
    program. Returns ``{"ok", "findings", "axes"}`` where each axis entry
    carries modeled vs parsed numbers — drift in the static model becomes
    a caught ``perfmodel-drift`` finding instead of silent mispricing.
    With ``ensemble=E`` the oracle prices the E-member batched program
    (same pairs, E x bytes) against the vmapped compile — proving the
    amortization claim byte-exactly. With a deep ``comm_every`` cadence
    the parsed program is the compiled SUPER-STEP (one cadence cycle):
    the oracle's per-exchange pairs scale by each axis's
    ``cycle / k_d`` events per cycle — proving the per-axis amortization
    (latency term ÷ k_axis) against exactly what the compiler emitted.
    With ``wire_stage`` the oracle prices the hierarchical staged
    program: a staged axis's gather/scatter hops ride the GATHER axis's
    routes in the compiled program, so the per-axis comparison runs
    against the route-attributed plan merge and the oracle-vs-plan
    self-consistency check moves to the TOTAL pair count (the oracle
    books every staged op under the staged axis; the attribution books
    it where the parser will see it — same total, different split)."""
    from ..ops.wire import resolve_comm_every
    from ..parallel.topology import check_initialized, global_grid
    from ..telemetry.perfmodel import predict_step

    check_initialized()
    gg = global_grid()
    cad = resolve_comm_every(comm_every if comm_every is not None else 1)
    pred = predict_step(model, fields, profile=profile, dims=dims,
                        coalesce=coalesce, wire_dtype=wire_dtype, impl=impl,
                        ensemble=ensemble, comm_every=cad,
                        wire_stage=wire_stage)
    plan = _merged_plan(fields,
                        _exchange_rounds(model, len(fields), impl,
                                         deep=cad.deep),
                        dims=dims, coalesce=coalesce, wire_dtype=wire_dtype,
                        ensemble=ensemble, comm_every=cad,
                        wire_stage=wire_stage)
    parsed = measure_axes(ir, axis_routes(gg))
    from ..parallel.topology import AXIS_NAMES

    axis_dim = {a: i for i, a in enumerate(AXIS_NAMES)}
    staged_axes = {a for a, c in pred["comm"].items() if "staged" in c}
    findings: list = []
    axes: dict = {}

    def _events(axis):
        # events per compiled program: 1 per step normally; under a deep
        # cadence the super-step fires this axis cycle/k_d times
        return (cad.cycle // cad.for_dim(axis_dim[axis])
                if cad.deep else 1)

    # the pairs come from predict_step (the oracle under test), the
    # all-links bytes from this module's round merge — the two price
    # the SAME rounds from the SAME plan, so a disagreement between
    # them means one merge loop was edited without the other: flag it
    # rather than crosscheck against a self-inconsistent model. With a
    # staged axis the split across axes legitimately differs (route
    # attribution vs link class), so the check runs on the TOTALS.
    oracle_total = sum(_events(a) * c["ppermute_pairs"]
                       for a, c in pred["comm"].items())
    plan_total = sum(r["permutes"] for r in plan.values()) / 2.0
    if staged_axes:
        if plan_total != oracle_total:
            findings.append(AuditFinding(
                "model-inconsistent", SEV_ERROR,
                f"predict_step prices {oracle_total} ppermute pairs "
                f"total but the plan merge counts {plan_total} — the "
                "model's two round-merge paths have diverged "
                "(fix telemetry.perfmodel / analysis.contracts before "
                "trusting the crosscheck).",
                details={"predict_step_pairs": oracle_total,
                         "plan_pairs": plan_total,
                         "staged_axes": sorted(staged_axes)}))
    for axis in sorted(set(plan) | set(k for k in parsed if k is not None)):
        modeled_pairs = _events(axis) * pred["comm"].get(axis, {}).get(
            "ppermute_pairs", 0.0)
        modeled_bytes = plan.get(axis, {}).get("wire_bytes", 0)
        plan_pairs = plan.get(axis, {}).get("permutes", 0) / 2.0
        if not staged_axes and plan_pairs != modeled_pairs:
            findings.append(AuditFinding(
                "model-inconsistent", SEV_ERROR,
                f"axis {axis!r}: predict_step prices {modeled_pairs} "
                f"ppermute pairs but the plan merge counts {plan_pairs} "
                "— the model's two round-merge paths have diverged "
                "(fix telemetry.perfmodel / analysis.contracts before "
                "trusting the crosscheck).",
                details={"axis": axis, "predict_step_pairs": modeled_pairs,
                         "plan_pairs": plan_pairs}))
        if staged_axes:
            # compare the parser against the route-attributed merge —
            # where the compiled program actually carries each stage
            modeled_pairs = plan_pairs
        got = parsed.get(axis, {"permutes": 0, "wire_bytes": 0})
        got_pairs = got["permutes"] / 2.0
        axes[axis] = {"modeled_pairs": modeled_pairs,
                      "parsed_pairs": got_pairs,
                      "modeled_wire_bytes": int(modeled_bytes),
                      "parsed_wire_bytes": int(got["wire_bytes"])}
        if got_pairs != modeled_pairs \
                or int(got["wire_bytes"]) != int(modeled_bytes):
            findings.append(AuditFinding(
                "perfmodel-drift", SEV_ERROR,
                f"axis {axis!r}: predict_step prices "
                f"{modeled_pairs} ppermute pairs / {modeled_bytes} wire "
                f"bytes per step, the compiled program carries "
                f"{got_pairs} / {got['wire_bytes']} — the static cost "
                "model has drifted from what the compiler emits.",
                details=axes[axis]))
    if None in parsed:
        findings.append(AuditFinding(
            "permute-route", SEV_ERROR,
            f"{parsed[None]['permutes']} collective-permute(s) ride "
            "routes matching no mesh axis — unpriceable by the model.",
            details=parsed[None]))
    return {"ok": not findings, "findings": findings, "axes": axes,
            "model": str(model), "impl": str(impl),
            "ensemble": int(pred.get("ensemble", 1)),
            "comm_every": str(cad),
            "wire_stage": pred.get("wire_stage"),
            "profile_source": pred["profile_source"]}


def _exchange_rounds(model, n_fields: int, impl: str = "xla",
                     deep: bool = False):
    from ..telemetry.perfmodel import STEP_WORKLOADS, StepWorkload

    if isinstance(model, StepWorkload):
        return model.groups_for(impl, deep=deep)
    work = STEP_WORKLOADS.get(str(model))
    if work is None:
        raise InvalidArgumentError(
            f"unknown model {model!r} (have {sorted(STEP_WORKLOADS)}).")
    return work.groups_for(impl, deep=deep)
