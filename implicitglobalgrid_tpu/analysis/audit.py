"""The audit entry points: program -> findings, wired for humans and runs.

- `audit_program(src, *args, contract=..., lints=...)` — parse anything
  (`parse_program` forms) and run contract + lint checks: the API the
  refactored HLO-audit tests and the ``tools audit`` CLI call.
- `audit_model(name, impl=...)` — compile one model family's step program
  on the CURRENT grid, derive its contract from the static plan
  (`model_contract` = `STEP_WORKLOADS` rounds over `halo_comm_plan`),
  check it, and cross-check `telemetry.predict_step`'s collective pricing
  against what the compiler actually emitted.
- `audit_chunk_program(runner, args, names=...)` — the resilient driver's
  compile-time audit (`run_resilient(audit=True)`): parses the LOWERED
  StableHLO (trace + lower only — no second XLA compile, the chunk
  program is untouched), checks the guard-psum contract and the lints,
  and returns the report the driver streams to the flight recorder and
  the ``igg_audit_findings_total`` metric family.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

from ..utils.exceptions import InvalidArgumentError
from .contracts import (
    CollectiveContract, SEV_ERROR, SEV_WARNING, axis_routes,
    check_contract, guard_contract, measure_axes, model_contract,
    perfmodel_crosscheck, sort_findings,
)
from .hlo import ProgramIR, parse_program
from .lints import LintConfig, default_lint_config, run_lints

__all__ = ["AuditReport", "audit_program", "audit_model",
           "audit_chunk_program"]


@dataclass(frozen=True)
class AuditReport:
    """One audited program: findings + the collective summary behind them."""

    findings: tuple
    inventory: dict
    collectives: dict
    dialect: str
    contract: CollectiveContract | None = None
    crosscheck: dict | None = None
    meta: dict = dc_field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not any(f.severity == SEV_ERROR for f in self.findings)

    @property
    def errors(self) -> int:
        return sum(1 for f in self.findings if f.severity == SEV_ERROR)

    @property
    def warnings(self) -> int:
        return sum(1 for f in self.findings if f.severity == SEV_WARNING)

    def by_rule(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def to_json(self) -> dict:
        out = {
            "ok": self.ok,
            "dialect": self.dialect,
            "errors": self.errors,
            "warnings": self.warnings,
            "findings": [f.to_json() for f in self.findings],
            "collectives": self.collectives,
            "inventory": self.inventory,
        }
        if self.crosscheck is not None:
            cc = dict(self.crosscheck)
            cc["findings"] = [f.to_json() for f in cc.get("findings", [])]
            out["crosscheck"] = cc
        if self.meta:
            out["meta"] = self.meta
        return out


def _collective_summary(ir: ProgramIR, routes=None) -> dict:
    out = {
        "permutes": len(ir.permutes),
        "all_reduces": len(ir.all_reduces),
        "all_gathers": len(ir.all_gathers),
        "all_to_alls": len(ir.all_to_alls),
        "wire_bytes": sum(ir.wire_bytes_of(p) for p in ir.permutes),
    }
    if routes:
        # None = a permute whose source_target_pairs match no mesh-axis
        # route; an explicit sentinel keeps the JSON key unambiguous
        out["by_axis"] = {("unattributed" if a is None else str(a)): r
                          for a, r in measure_axes(ir, routes).items()}
    return out


def audit_program(src, *args, contract: CollectiveContract | None = None,
                  lints=None, lint_config: LintConfig | None = None,
                  optimized: bool = True, meta=None) -> AuditReport:
    """Parse ``src`` (program text, a Lowered/Compiled object, or a jitted
    callable plus example args — see `parse_program`) and audit it.

    ``contract=None`` skips the contract check (lints still run);
    ``lints=None`` runs every rule, ``lints=()`` none, else a tuple of
    rule names from `lints.LINT_RULES`. ``lint_config`` defaults to
    `default_lint_config()` over the live grid when one is initialized
    (grid-free otherwise — the host-only golden-fixture path)."""
    ir = parse_program(src, *args, optimized=optimized)
    findings: list = []
    if contract is not None:
        findings.extend(check_contract(ir, contract))
    if lints is None or lints:
        findings.extend(run_lints(ir, config=lint_config,
                                  rules=lints))
    routes = contract.routes if contract is not None else _maybe_routes()
    return AuditReport(
        findings=tuple(sort_findings(findings)),
        inventory=ir.inventory(),
        collectives=_collective_summary(ir, routes),
        dialect=ir.dialect,
        contract=contract,
        meta=dict(meta or {}))


def _maybe_routes():
    from ..parallel.topology import grid_is_initialized

    return axis_routes() if grid_is_initialized() else None


# ---------------------------------------------------------------------------
# model programs

def _model_program(model: str, impl: str, dtype, ensemble=None,
                   comm_every=None):
    """(runner, example args, PHYSICAL state fields in canonical order).
    With ``ensemble=E`` the runner is the E-member batched chunk
    (`make_*_run(..., ensemble=E)`) and ``args`` are the member-stacked
    arrays — ``fields`` stay the per-member state the contracts price.
    With a deep ``comm_every`` cadence the runner is the deep-halo
    SUPER-STEP (`make_*_run_deep` at one super-step per call — the grid
    must carry ``depth*k_d``-wide halos per axis); XLA tier only."""
    from ..models.common import resolve_comm_every
    from .. import models as M

    cad = resolve_comm_every(comm_every if comm_every is not None else 1)
    if cad.deep and str(impl).startswith("pallas"):
        raise InvalidArgumentError(
            f"audit_model: impl={impl!r} is incompatible with "
            f"comm_every={cad} (deep-halo stepping runs only the XLA "
            "tier — the same rule the runners enforce).")
    ce = str(cad)
    if model in ("diffusion3d", "diffusion2d"):
        ndim = 3 if model.endswith("3d") else 2
        init = M.init_diffusion3d if ndim == 3 else M.init_diffusion2d
        if cad.deep:
            T, Cp, p = M.init_diffusion3d(dtype=dtype, comm_every=ce) \
                if ndim == 3 else M.init_diffusion2d(dtype=dtype)
            if ndim == 2:
                import dataclasses

                p = dataclasses.replace(p, comm_every=ce)
            run = M.make_run_deep(p, 1, ndim=ndim, ensemble=ensemble)
        else:
            T, Cp, p = init(dtype=dtype)
            run = M.make_run(p, 1, ndim=ndim, impl=impl, ensemble=ensemble)
        args = (T, Cp)
    elif model == "acoustic3d":
        if cad.deep:
            state, p = M.init_acoustic3d(dtype=dtype, comm_every=ce)
            run = M.make_acoustic_run_deep(p, 1, ensemble=ensemble)
        else:
            state, p = M.init_acoustic3d(dtype=dtype)
            run = M.make_acoustic_run(p, 1, impl=impl, ensemble=ensemble)
        args = tuple(state)
    elif model == "stokes3d":
        if cad.deep:
            state, p = M.init_stokes3d(dtype=dtype, comm_every=ce)
            run = M.make_stokes_run_deep(p, 1, ensemble=ensemble)
        else:
            state, p = M.init_stokes3d(dtype=dtype)
            run = M.make_stokes_run(p, 1, impl=impl, ensemble=ensemble)
        args = tuple(state)
    else:
        raise InvalidArgumentError(
            f"audit_model: unknown model {model!r} (have diffusion3d, "
            "diffusion2d, acoustic3d, stokes3d).")
    fields = args
    if ensemble is not None:
        from ..models.common import ensemble_state

        args = ensemble_state(args, int(ensemble))
    return run, args, fields


def _rounds_impl(model: str, impl: str, fields) -> str:
    """The impl whose exchange ROUNDS the compiled program actually uses.

    A Pallas request silently falls back to the XLA formulation when the
    fused kernel's eligibility gate rejects the current grid/state
    (`wave_exchange_modes`/`stokes_exchange_modes` — e.g. halowidth != 1
    deep-halo grids), and the contract must follow the fallback: pricing
    the fused rounds against an XLA-round program would fail a healthy
    program — the false-failure class the retired ``contract_skipped``
    exemption existed to prevent."""
    if not str(impl).startswith("pallas"):
        return impl
    from ..parallel.topology import global_grid

    gg = global_grid()
    local = [tuple(int(s) // int(gg.dims[d]) if d < 3 else int(s)
                   for d, s in enumerate(f.shape)) for f in fields]
    if model == "acoustic3d":
        from ..ops.pallas_wave import wave_exchange_modes

        if wave_exchange_modes(gg, local) is None:
            return "xla"
    elif model == "stokes3d":
        from ..ops.pallas_stokes import stokes_exchange_modes

        if stokes_exchange_modes(gg, local) is None:
            return "xla"
    # diffusion's fused rounds equal the XLA rounds, so its fallbacks
    # never change the contract
    return impl


def audit_model(model: str, *, impl: str = "xla", dtype=None,
                wire_dtype=None, wire_stage=None, lints=None,
                crosscheck: bool = True,
                optimized: bool = True,
                ensemble: int | None = None,
                comm_every=None) -> AuditReport:
    """Compile one model family's step program on the CURRENT grid and
    audit it against its plan-derived contract.

    EVERY kernel tier gets a real contract: the fused Pallas kernels ride
    the same canonical wire schema as the XLA path
    (`ops.halo.exchange_recv_slabs_multi` — one ppermute pair per mesh
    axis per round, byte-identical payload layout), so ``impl`` only
    selects which exchange ROUNDS the contract prices
    (`StepWorkload.groups_for`: e.g. the fused acoustic pass packs all
    four fields into one round where the XLA leapfrog does two). The
    pre-schema ``impl != 'xla'`` exemption (``contract_skipped``) is
    gone — ``tools audit``'s exit-1 gate covers Pallas programs.
    ``crosscheck`` additionally proves the perf oracle's priced ppermute
    pairs and wire bytes equal the parsed program's (models outside
    `STEP_WORKLOADS` skip it).

    ``wire_dtype`` is applied to BOTH sides: the compile (scoped
    ``IGG_HALO_WIRE_DTYPE`` — the runners resolve the wire format from
    the environment at trace time; restored after, never leaked into the
    process) and the expectation (contract payload dtypes, wire bytes,
    lint config, crosscheck pricing). On a backend whose optimizer
    normalizes narrow payloads back to full precision (XLA:CPU does for
    bf16) the LOWERED module is audited instead of the optimized one —
    ``meta["lowered_for_wire_audit"]`` records the switch — so the
    documented CLI gate never false-fails a healthy program.

    ``wire_stage`` audits the TOPOLOGY-STAGED program (the
    `ops.wire.resolve_wire_stage` spelling family, e.g. ``"z:staged"``):
    applied to both sides like ``wire_dtype`` — the compile (scoped
    ``IGG_HALO_WIRE_STAGE``, restored after) and the expectation (the
    staged multi-stage contract from `model_contract(wire_stage=)` plus
    the crosscheck's staged pricing).

    ``comm_every`` (a deep per-axis cadence — int / ``"z:2,x:1"`` /
    dict) audits the DEEP-HALO SUPER-STEP program instead of the plain
    step: the compiled cycle's per-axis permute counts and k_d-wide
    payload bytes must equal the super-cycle contract
    (`model_contract(comm_every=)`), and the crosscheck proves
    `predict_step`'s per-axis amortized pricing against the emitted
    collectives. The current grid must carry the cadence's halo
    geometry (``halowidths[d] = depth*k_d``); composes with
    ``ensemble`` (the vmapped deep chunk) and per-axis ``wire_dtype``.
    XLA tier only."""
    import os

    import numpy as np

    from ..models.common import resolve_comm_every
    from ..parallel.topology import check_initialized

    check_initialized()
    dtype = np.float32 if dtype is None else dtype
    meta = {"model": model, "impl": impl}
    cad = resolve_comm_every(comm_every if comm_every is not None else 1)
    if cad.deep:
        meta["comm_every"] = str(cad)
    if ensemble is not None:
        ensemble = int(ensemble)
        meta["ensemble"] = ensemble
    saved_wire = os.environ.get("IGG_HALO_WIRE_DTYPE")
    saved_stage = os.environ.get("IGG_HALO_WIRE_STAGE")
    try:
        if wire_stage is not None:
            from ..ops.wire import resolve_wire_stage

            stage_policy = resolve_wire_stage(wire_stage)
            # canonical spelling round-trip, same contract as wire_dtype:
            # the runners resolve staging from the env var at trace time
            os.environ["IGG_HALO_WIRE_STAGE"] = (
                "off" if stage_policy is None else str(stage_policy))
            meta["wire_stage"] = os.environ["IGG_HALO_WIRE_STAGE"]
        if wire_dtype is not None:
            from ..ops.precision import resolve_wire_dtype

            policy = resolve_wire_dtype(wire_dtype)
            # the CANONICAL policy string: every accepted form (dict,
            # WirePolicy, dtype-like) round-trips through the env var the
            # runners resolve at trace time — str() of a dict would not
            os.environ["IGG_HALO_WIRE_DTYPE"] = str(policy)
            if optimized:
                import jax
                # only narrow FLOAT casts are at the mercy of the CPU
                # backend's float-normalization pass; quantized int8
                # payloads survive optimized HLO on every backend, so a
                # quant-only policy keeps the deeper post-SPMD audit
                if policy is not None and policy.casts_any_below \
                        and jax.devices()[0].platform == "cpu":
                    optimized = False
                    meta["lowered_for_wire_audit"] = (
                        "XLA:CPU normalizes narrow wire payloads back to "
                        "full precision in optimized HLO; audited the "
                        "lowered module instead")
        runner, args, fields = _model_program(model, impl, dtype,
                                              ensemble=ensemble,
                                              comm_every=comm_every)
        ir = parse_program(runner, *args, optimized=optimized)
    finally:
        if saved_wire is None:
            os.environ.pop("IGG_HALO_WIRE_DTYPE", None)
        else:
            os.environ["IGG_HALO_WIRE_DTYPE"] = saved_wire
        if saved_stage is None:
            os.environ.pop("IGG_HALO_WIRE_STAGE", None)
        else:
            os.environ["IGG_HALO_WIRE_STAGE"] = saved_stage
    from ..telemetry.perfmodel import STEP_WORKLOADS

    rounds_impl = impl if cad.deep else _rounds_impl(model, impl, fields)
    if rounds_impl != impl:
        meta["rounds_impl"] = (
            f"{rounds_impl} (fused kernel ineligible on this grid/state; "
            "the step fell back to the XLA formulation and the contract "
            "follows it)")
    contract = None
    if model in STEP_WORKLOADS:
        contract = model_contract(model, fields, wire_dtype=wire_dtype,
                                  impl=rounds_impl, ensemble=ensemble,
                                  comm_every=comm_every,
                                  wire_stage=wire_stage)
    cfg = default_lint_config(
        state_dtypes={str(np.dtype(getattr(f, "dtype", "float32")))
                      for f in fields},
        wire_dtype=wire_dtype)
    rep = audit_program(ir, contract=contract, lints=lints,
                        lint_config=cfg, meta=meta)
    cc = None
    if crosscheck and model in STEP_WORKLOADS:
        cc = perfmodel_crosscheck(model, fields, ir,
                                  wire_dtype=wire_dtype, impl=rounds_impl,
                                  ensemble=ensemble,
                                  comm_every=comm_every,
                                  wire_stage=wire_stage)
    if cc is None:
        return rep
    return AuditReport(
        findings=tuple(sort_findings(list(rep.findings)
                                     + list(cc["findings"]))),
        inventory=rep.inventory, collectives=rep.collectives,
        dialect=rep.dialect, contract=rep.contract, crosscheck=cc,
        meta=rep.meta)


# ---------------------------------------------------------------------------
# the driver's compile-time audit

def audit_chunk_program(runner, args, *, names, reducer_floats: int = 0,
                        contract: CollectiveContract | None = None,
                        lints=None,
                        ensemble: int | None = None) -> AuditReport:
    """Audit a resilient chunk runner ONCE at compile time, without
    touching it: traces + lowers the jitted ``runner`` with the run's
    ``args`` and parses the StableHLO (no second backend compile — the
    XLA executable the run dispatches is built exactly as without the
    audit). The default contract is the structural guard one
    (`guard_contract`): exactly one f32[2N + R] psum, no gathers; pass an
    explicit `CollectiveContract` (e.g. from `model_contract`) to also
    pin the per-axis permute counts of a known step. ``ensemble=E``
    widens the expected guard psum to the batched ``f32[E·(2N + R)]``
    stats (still exactly one all-reduce)."""
    import numpy as np

    if contract is None:
        contract = guard_contract(len(tuple(names)), reducer_floats,
                                  ensemble=ensemble)
    state_dtypes = set()
    for a in args:
        try:
            state_dtypes.add(str(np.dtype(a.dtype)))
        except (TypeError, AttributeError):
            pass
    cfg = default_lint_config(state_dtypes=state_dtypes)
    return audit_program(runner, *args, contract=contract, lints=lints,
                         lint_config=cfg, optimized=False,
                         meta={"program": "chunk",
                               "names": list(names),
                               **({"ensemble": int(ensemble)}
                                  if ensemble else {})})
