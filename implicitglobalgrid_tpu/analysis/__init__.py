"""Static analysis of compiled programs (the ISSUE-7 tentpole).

The framework's core claim — the reference's pack/Isend/Irecv/unpack
machinery collapses into exactly one `collective-permute` pair per
exchanging mesh axis, and the guard/reducer machinery into exactly one
psum per chunk — is enforced here as a first-class subsystem instead of
per-test regexes:

- `hlo` — `parse_program`/`parse_text`: optimized HLO and StableHLO text
  -> `ProgramIR` (full op inventory, every collective with shapes,
  dtypes, bytes-on-wire, source-target/replica-group metadata, def-use
  closure queries). Stdlib+numpy only: golden fixtures parse host-only.
- `contracts` — `CollectiveContract` derived automatically from the
  static wire plan (`halo_comm_plan` + `STEP_WORKLOADS` exchange rounds +
  grid topology routes) and `check_contract` verifying a parsed program
  against it; `perfmodel_crosscheck` proves `predict_step`'s collective
  pricing equals what the compiler emitted.
- `lints` — implicit-global-grid hazard rules: global-shape
  materialization, missing wire downcasts, unaliased donations, host
  transfers in the chunk body, opaque custom-calls, f64 leakage, copies
  staging collective payloads.
- `audit` — `audit_program` / `audit_model` / `audit_chunk_program`: the
  wiring the tests, the ``tools audit`` CLI, and
  `run_resilient(audit=True)` call.
"""

from .audit import (
    AuditReport, audit_chunk_program, audit_model, audit_program,
)
from .contracts import (
    AuditFinding, CollectiveContract, axis_routes, check_contract,
    exchange_contract, guard_contract, measure_axes, model_contract,
    perfmodel_crosscheck,
)
from .hlo import HloOp, ProgramIR, Shape, parse_program, parse_text
from .lints import (
    DEFAULT_LINTS, LINT_RULES, LintConfig, default_lint_config, run_lints,
)

__all__ = [
    "Shape", "HloOp", "ProgramIR", "parse_text", "parse_program",
    "AuditFinding", "CollectiveContract", "axis_routes", "measure_axes",
    "exchange_contract", "model_contract", "guard_contract",
    "check_contract", "perfmodel_crosscheck",
    "LintConfig", "default_lint_config", "run_lints", "LINT_RULES",
    "DEFAULT_LINTS",
    "AuditReport", "audit_program", "audit_model", "audit_chunk_program",
]
