"""Environment/config handling.

TPU-native analog of the reference's env-var config block
(`/root/reference/src/init_global_grid.jl:57-75`): the reference reads
`IGG_CUDAAWARE_MPI`, `IGG_ROCMAWARE_MPI`, `IGG_USE_POLYESTER` (each with
`_DIMX/_DIMY/_DIMZ` per-dimension refinements) and rejects the legacy
`IGG_LOOPVECTORIZATION`. On TPU the GPU-aware-MPI distinction does not exist —
ICI collectives always move HBM-to-HBM — so those variables are *rejected* with
an explanatory error (mirroring the reference's legacy-var rejection at
`init_global_grid.jl:57`). The TPU-meaningful knobs are:

- ``IGG_TPU_PLATFORM``: force the JAX backend platform ("tpu", "cpu", "gpu").
- ``IGG_USE_PALLAS`` (+ ``_DIMX/_DIMY/_DIMZ``): select the hand-written
  Pallas TPU kernels where they exist (analog of the reference's
  copy-kernel toggle `IGG_USE_POLYESTER`, `init_global_grid.jl:60,71-75`).
  Unlike the reference's opt-in default, the Pallas tier is ON by default on
  TPU grids (it is ~3x faster than the broadcast form there — see bench.py);
  set ``IGG_USE_PALLAS=0`` to force the pure-XLA path. Selects the fused
  Pallas stencil step in the models (`models.diffusion._resolve_impl`); the
  per-dim refinements are recorded on the grid for the future per-dimension
  halo pack path.
- ``IGG_TPU_DCN_AXES``: comma-separated mesh axes ("x","y","z") that cross
  slice boundaries (DCN) in a multi-slice deployment.
- ``IGG_TPU_DCN_GRANULES``: per-axis DCN granule counts (``"z:2"`` /
  ``"x:2,z:2"``) — how many ICI granules (slices/hosts) the mesh spans
  along each axis. On real multi-slice pools `init_global_grid` derives
  this from the device pool's slice structure; the env var declares it
  for single-granule dev boxes (CPU meshes, contract fixtures) so the
  topology-staged wire (`IGG_HALO_WIRE_STAGE`) and its pricing/contract
  layers see the granule shape they would see on the pod.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from .exceptions import InvalidArgumentError

__all__ = ["EnvConfig", "read_env_config"]

_REJECTED_ENV_VARS = {
    "IGG_CUDAAWARE_MPI": "GPU-aware MPI does not apply on TPU: ICI collectives always move data HBM-to-HBM.",
    "IGG_ROCMAWARE_MPI": "GPU-aware MPI does not apply on TPU: ICI collectives always move data HBM-to-HBM.",
    "IGG_LOOPVECTORIZATION": "Environment variable IGG_LOOPVECTORIZATION is not supported. Use IGG_USE_PALLAS instead.",
    "IGG_USE_POLYESTER": "Environment variable IGG_USE_POLYESTER does not apply on TPU. Use IGG_USE_PALLAS instead.",
}

_DIM_SUFFIXES = ("_DIMX", "_DIMY", "_DIMZ")


def _env_flag(name: str) -> bool | None:
    if name not in os.environ:
        return None
    try:
        return int(os.environ[name]) > 0
    except ValueError as e:
        raise InvalidArgumentError(
            f"Environment variable {name}: expected an integer, got {os.environ[name]!r}."
        ) from e


@dataclass
class EnvConfig:
    platform: str | None = None            # IGG_TPU_PLATFORM
    use_pallas: list = field(default_factory=lambda: [None, None, None])
    # tri-state per dim: None = unset (resolved at init: True on TPU grids,
    # False elsewhere), True/False = explicit env setting
    dcn_axes: tuple = ()                   # IGG_TPU_DCN_AXES
    dcn_granules: tuple = (1, 1, 1)        # IGG_TPU_DCN_GRANULES


def read_env_config() -> EnvConfig:
    """Read and validate env configuration (called from ``init_global_grid``,
    mirroring reference `init_global_grid.jl:57-75`)."""
    for var, msg in _REJECTED_ENV_VARS.items():
        if var in os.environ:
            raise InvalidArgumentError(f"Environment variable {var} is not supported: {msg}")
        for sfx in _DIM_SUFFIXES:
            if var + sfx in os.environ:
                raise InvalidArgumentError(f"Environment variable {var + sfx} is not supported: {msg}")

    cfg = EnvConfig()
    cfg.platform = os.environ.get("IGG_TPU_PLATFORM") or None

    # Per-dimension refinement semantics mirror the reference: the global flag
    # sets all three; per-dim vars refine only when the global flag was not set
    # to a blanket True (reference `init_global_grid.jl:71-75` refines only
    # `if all(use_polyester)` after a global default of false — we mirror the
    # observable behavior: global var sets all dims, per-dim vars override).
    g = _env_flag("IGG_USE_PALLAS")
    if g is not None:
        cfg.use_pallas = [g, g, g]
    for d, sfx in enumerate(_DIM_SUFFIXES):
        v = _env_flag("IGG_USE_PALLAS" + sfx)
        if v is not None:
            cfg.use_pallas[d] = v

    axes = os.environ.get("IGG_TPU_DCN_AXES", "")
    if axes:
        names = tuple(a.strip() for a in axes.split(",") if a.strip())
        bad = [a for a in names if a not in ("x", "y", "z")]
        if bad:
            raise InvalidArgumentError(
                f"Environment variable IGG_TPU_DCN_AXES: invalid axis name(s) {bad}; valid names are x, y, z."
            )
        if len(set(names)) != len(names):
            raise InvalidArgumentError(
                f"Environment variable IGG_TPU_DCN_AXES: duplicate axis name(s) in {names}."
            )
        cfg.dcn_axes = names

    gran = os.environ.get("IGG_TPU_DCN_GRANULES", "")
    if gran:
        per_dim = [1, 1, 1]
        seen = set()
        for part in gran.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                raise InvalidArgumentError(
                    f"Environment variable IGG_TPU_DCN_GRANULES: entry {part!r} "
                    "must be '<axis>:<count>' (e.g. 'z:2')."
                )
            axis, cnt = part.split(":", 1)
            axis = axis.strip()
            dim = {"x": 0, "y": 1, "z": 2}.get(axis)
            if dim is None:
                raise InvalidArgumentError(
                    f"Environment variable IGG_TPU_DCN_GRANULES: invalid axis name {axis!r}; "
                    "valid names are x, y, z."
                )
            if dim in seen:
                raise InvalidArgumentError(
                    f"Environment variable IGG_TPU_DCN_GRANULES: duplicate axis name {axis!r}."
                )
            seen.add(dim)
            try:
                n = int(cnt.strip())
            except ValueError as e:
                raise InvalidArgumentError(
                    f"Environment variable IGG_TPU_DCN_GRANULES: granule count for axis "
                    f"{axis!r} must be an integer >= 1, got {cnt!r}."
                ) from e
            if n < 1:
                raise InvalidArgumentError(
                    f"Environment variable IGG_TPU_DCN_GRANULES: granule count for axis "
                    f"{axis!r} must be >= 1, got {n}."
                )
            per_dim[dim] = n
        cfg.dcn_granules = tuple(per_dim)
    return cfg
