"""Profiling/tracing — the TPU-native upgrade of the reference's timing story.

The reference offers only `tic`/`toc` (`/root/reference/src/tools.jl:230-236`)
and keeps its streams/tasks persistent partly so external profilers can see
the overlap structure (`src/update_halo.jl:207` note). On TPU the profiler IS
the external tool: `jax.profiler` captures an XLA trace (HLO ops, fusion
boundaries, collective overlap, HBM traffic) viewable in XProf/TensorBoard or
Perfetto. This module wraps it with the framework's naming conventions AND
analyzes the capture in-process (`utils/xplane.py` decodes the profile
protobuf directly), so comm/compute overlap is a NUMBER the framework can
report, not a screenshot:

    with igg.trace("/tmp/igg_trace"):
        T = igg.sync(run_diffusion(T, Cp, p, nt))  # whole hot loop captured

    stats = igg.overlap_stats("/tmp/igg_trace")
    # {'TPU:0': {'busy_us': ..., 'comm_us': ..., 'hidden_comm_us': ...,
    #            'exposed_comm_us': ..., 'overlap_frac': ...}, ...}

    igg.op_breakdown("/tmp/igg_trace")   # top ops by device time

`overlap_stats` is the quantitative analog of inspecting the reference's
max-priority-stream overlap in Nsight: collectives (`collective-permute` =
the exchange's ppermutes, plus all-reduce/all-gather) are attributed from
the device planes' "XLA Ops"/"Async XLA Ops" lines; async collective spans
that run concurrently with compute intervals count as HIDDEN communication.
"""

from __future__ import annotations

import contextlib
import re

__all__ = ["trace", "annotate", "overlap_stats", "op_breakdown"]

# The PR-2 `health_counters`/`record_health_event`/`reset_health_counters`
# shims that lived here were RETIRED after two majors of deprecation
# notice (PRs 3-9): the resilient runtime records through
# `telemetry.hooks.record_health_event` and readers consume the
# ``igg_health_events_total{kind=...}`` family via
# ``igg.metrics_registry()`` / ``igg.prometheus_snapshot()``.


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    """Capture a `jax.profiler` trace of the enclosed block into ``log_dir``.

    Pass the block's outputs through `igg.sync` before exiting so trailing
    device work lands inside the capture window. Analyze the capture with
    `overlap_stats`/`op_breakdown`, or open it in XProf/TensorBoard.
    """
    import jax

    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield


def annotate(name: str):
    """Named region in the profiler timeline (XLA `TraceAnnotation`): shows
    up around everything dispatched inside the block."""
    import jax

    return jax.profiler.TraceAnnotation(name)


# HLO ops that move data between devices. `collective-permute` is the
# exchange's wire op (one pair per axis — tests/test_hlo_audit.py); the rest
# guard against hidden collectives sneaking into a "local" program.
_COMM_RE = re.compile(
    r"collective-permute|all-reduce|all-gather|all-to-all|reduce-scatter"
    r"|ppermute|send|recv", re.IGNORECASE)

_OP_KIND_RE = re.compile(r"\s([a-z][a-z0-9._-]*)\(")


def _op_kind(name: str) -> str:
    """Short op kind from an HLO event name ('%fusion.3 = f32[…] fusion(…)'
    -> 'fusion'); module-level events ('jit_step(123…)') keep their title.

    Tuple-typed ops ('%f = (f32[…], f32[…]) fusion(…)') put spaces inside
    the type, so the kind is located as the last lowercase token before a
    '(' AFTER skipping a parenthesized tuple type when present."""
    rhs = name.split(" = ", 1)[-1]
    if rhs.startswith("("):  # tuple type: skip to its closing paren
        depth = 0
        for i, ch in enumerate(rhs):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    rhs = rhs[i + 1:]
                    break
    m = _OP_KIND_RE.search(" " + rhs)
    if m:
        return m.group(1)
    # short-form names (real captures emit e.g. 'copy.15', 'fusion.35'):
    # drop the instruction suffix so kinds aggregate
    short = name.split("(")[0].strip() or name
    return re.sub(r"\.\d+$", "", short)


_planes_cache: dict = {}


def _all_planes(log_dir: str):
    """All planes of the newest capture; memoized on the capture files'
    (path, mtime, size) so overlap_stats + op_breakdown on the same trace
    decode the (potentially large) protobuf once. Only the most recent
    trace is retained (size-1 cache): analyzing several large traces in
    one process must not accumulate all their decoded events."""
    import os

    from .xplane import find_xplane_files, parse_xspace

    files = find_xplane_files(log_dir)
    key = tuple((p, os.path.getmtime(p), os.path.getsize(p)) for p in files)
    hit = _planes_cache.get(log_dir)
    if hit is not None and hit[0] == key:
        return hit[1]
    planes = []
    for path in files:
        planes.extend(parse_xspace(path))
    _planes_cache.clear()
    _planes_cache[log_dir] = (key, planes)
    return planes


def _device_planes(log_dir: str):
    return [p for p in _all_planes(log_dir)
            if p.name.startswith("/device:")]


def _merge(intervals):
    """Union of [start, end) intervals; returns merged list and total."""
    if not intervals:
        return [], 0
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out, sum(e - s for s, e in out)


def _intersect_total(a, b):
    """Total overlap between two MERGED interval lists."""
    i = j = 0
    total = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def overlap_stats(log_dir: str):
    """Comm/compute overlap numbers per device plane of the NEWEST capture
    under ``log_dir``.

    For each `/device:*` plane: compute intervals come from the non-comm
    events of every op line; comm intervals from events matching the
    collective patterns on any line — crucially including the "Async XLA
    Ops" line, where an async collective's event SPANS start→done, so the
    span's intersection with compute intervals measures communication the
    scheduler actually hid (the XLA analog of the reference overlapping
    its pack kernels and MPI traffic with user kernels on max-priority
    streams). Returns ``{device_name: {busy_us, compute_us, comm_us,
    hidden_comm_us, exposed_comm_us, overlap_frac}}``.

    Captures with no ``/device:`` planes (the XLA:CPU backend, incl. the
    virtual multi-device mesh) fall back to `_host_overlap_stats`, which
    reads the same quantities off the runtime thread-pool lines and
    returns one aggregate ``CPU:threadpool`` entry; an empty dict means
    the capture had neither device planes nor pool events."""
    out = {}
    for plane in _device_planes(log_dir):
        comm = []
        compute = []
        for line in plane.lines:
            if line.name in ("XLA Modules", "Steps", "Framework Ops",
                             "TC Overlay"):
                continue  # containers duplicating the op lines
            # Comm events are recognized on EVERY op line (async collective
            # spans live on "Async XLA Ops"); compute intervals come ONLY
            # from the synchronous "XLA Ops" line — a non-collective async
            # span (copy-start, host offload DMA) is not core compute, and
            # counting it would inflate hidden_comm when a collective
            # merely overlaps another DMA while the core sits idle.
            for ev in line.events:
                if ev.duration_ps <= 0:
                    continue
                iv = (ev.start_ps, ev.end_ps)
                # classify by the OP KIND, not the full HLO text — a fusion
                # consuming '%collective-permute-done.2' is compute, not comm
                if _COMM_RE.search(_op_kind(ev.name)):
                    comm.append(iv)
                elif line.name == "XLA Ops":
                    compute.append(iv)
        out[plane.name.replace("/device:", "")] = _stats_from(comm, compute)
    if not out:
        out = _host_overlap_stats(log_dir)
    return out


def _stats_from(comm, compute) -> dict:
    """The shared stats record of both the device-plane and host-fallback
    paths: merged totals, busy union, and comm∩compute = hidden."""
    comm_m, comm_total = _merge(comm)
    comp_m, comp_total = _merge(compute)
    busy = _merge(comm + compute)[1]
    hidden = _intersect_total(comm_m, comp_m)
    return {
        "busy_us": busy / 1e6,
        "compute_us": comp_total / 1e6,
        "comm_us": comm_total / 1e6,
        "hidden_comm_us": hidden / 1e6,
        "exposed_comm_us": (comm_total - hidden) / 1e6,
        "overlap_frac": hidden / comm_total if comm_total else None,
    }


# Runtime-infrastructure event names on the host thread lines that must
# count as COMMUNICATION: the XLA:CPU backend implements cross-(virtual-)
# device collectives by in-process rendezvous, so a device's exchange
# appears as a `ppermute` thunk span plus nested Rendezvous waits.
_HOST_COMM_RE = re.compile(
    r"^(Rendezvous|InvokeRendezvous|Wait for rendezvous)|^psum",
)


def _host_event_class(ev):
    """Classify one host thread-pool event: ``"comm"`` (collective op
    kinds + the CPU backend's rendezvous machinery), ``"thunk"`` (HLO
    thunk spans: lowercase-named, not C++ infrastructure, not the
    ``while`` container), or ``None`` (completion markers, zero-duration,
    infrastructure). The ONE predicate shared by `_host_overlap_stats`
    and `_host_op_agg` so the two fallbacks can never desynchronize."""
    if ev.duration_ps <= 0 or ev.name.startswith("end: "):
        # completion markers are neither comm nor compute — excluded
        # BEFORE the comm match, or 'end: ppermute.3' would count
        return None
    kind = _op_kind(ev.name)
    if _COMM_RE.search(kind) or _HOST_COMM_RE.search(ev.name):
        return "comm"
    if ev.name[:1].islower() and "::" not in ev.name and kind != "while":
        return "thunk"
    return None


def _host_overlap_stats(log_dir: str):
    """Comm/compute overlap from the HOST thread-pool lines — the fallback
    when the capture has no ``/device:`` planes (the XLA:CPU backend, incl.
    the virtual ``--xla_force_host_platform_device_count`` mesh, attributes
    op execution to runtime pool threads of ``/host:CPU``, not to device
    planes).

    Classification on the pool (``tf_*``) lines: comm = collective op
    kinds (`_COMM_RE`) plus the CPU backend's rendezvous machinery
    (`_HOST_COMM_RE` — ppermute spans block in an in-process rendezvous,
    the CPU analog of an exposed wire transfer); compute = HLO thunk spans,
    recognized as lowercase-named events (``wrapped_add``, ``fusion.3``,
    ``copy.15``…) that are not C++ infrastructure (``::``), not completion
    markers (``end: …``), and not the ``while`` control-flow container
    (its span covers the whole loop body, comm included).

    All pool threads aggregate into ONE ``CPU:threadpool`` entry: virtual
    devices share the pool, so per-thread attribution is meaningless.
    ``hidden_comm_us`` is comm time during which at least one thread was
    computing — communication the runtime actually covered with useful
    work; ``exposed_comm_us`` is comm time with the whole pool idle or
    blocked, the quantity that transfers to ICI-exposed time on hardware
    (round-4 verdict: separate core contention from exposed collectives).

    Caveat: the window must not contain a compile (warm every chunk size
    first) — compiler passes run on the same pool and a CamelCase pass
    name slipping through the lowercase filter is not compute."""
    comm = []
    compute = []
    for plane in _all_planes(log_dir):
        if not plane.name.startswith("/host:CPU"):
            continue
        for line in plane.lines:
            if not line.name.startswith("tf_"):
                continue
            for ev in line.events:
                cls = _host_event_class(ev)
                if cls == "comm":
                    comm.append((ev.start_ps, ev.end_ps))
                elif cls == "thunk":
                    compute.append((ev.start_ps, ev.end_ps))
    if not comm and not compute:
        return {}
    return {"CPU:threadpool": _stats_from(comm, compute)}


def op_breakdown(log_dir: str, top: int = 12):
    """Aggregate device time by op kind over the NEWEST capture under
    ``log_dir``: ``[(kind, total_us, count), …]`` sorted by time. Fusions
    appear as 'fusion', the exchange's wire ops as 'collective-permute*',
    Pallas kernels as 'custom-call' (Mosaic kernels are custom calls).

    Captures with no ``/device:`` op events (the XLA:CPU backend, incl.
    the virtual multi-device mesh) fall back to the host thread-pool
    lines — the same fallback `overlap_stats` has — aggregating the HLO
    thunk spans (and the rendezvous comm machinery) by op kind; an empty
    list means the capture had neither."""
    agg: dict = {}
    for plane in _device_planes(log_dir):
        for line in plane.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                kind = _op_kind(ev.name)
                t, c = agg.get(kind, (0, 0))
                agg[kind] = (t + ev.duration_ps, c + 1)
    if not agg:
        agg = _host_op_agg(log_dir)
    rows = sorted(((k, t / 1e6, c) for k, (t, c) in agg.items()),
                  key=lambda r: -r[1])
    return rows[:top]


def _host_op_agg(log_dir: str) -> dict:
    """`op_breakdown`'s host thread-pool fallback: per-kind (time, count)
    from the runtime pool (``tf_*``) lines of ``/host:CPU`` planes, using
    the SAME event classification as `_host_overlap_stats`
    (`_host_event_class`): HLO thunk spans plus the collective/rendezvous
    comm spans; completion markers and C++ infrastructure excluded."""
    agg: dict = {}
    for plane in _all_planes(log_dir):
        if not plane.name.startswith("/host:CPU"):
            continue
        for line in plane.lines:
            if not line.name.startswith("tf_"):
                continue
            for ev in line.events:
                if _host_event_class(ev) is None:
                    continue
                kind = _op_kind(ev.name)
                t, c = agg.get(kind, (0, 0))
                agg[kind] = (t + ev.duration_ps, c + 1)
    return agg
