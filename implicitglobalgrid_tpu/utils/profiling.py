"""Profiling/tracing — the TPU-native upgrade of the reference's timing story.

The reference offers only `tic`/`toc` (`/root/reference/src/tools.jl:230-236`)
and keeps its streams/tasks persistent partly so external profilers can see
the overlap structure (`src/update_halo.jl:207` note). On TPU the profiler IS
the external tool: `jax.profiler` captures an XLA trace (HLO ops, fusion
boundaries, collective overlap, HBM traffic) viewable in XProf/TensorBoard or
Perfetto. This module wraps it with the framework's naming conventions:

    with igg.trace("/tmp/igg_trace"):
        T = run_diffusion(T, Cp, p, nt)          # whole hot loop captured

    with igg.annotate("halo_z"):                  # named region in the trace
        A = igg.update_halo(A)

The capture contains the per-axis `ppermute` collectives and the Pallas
kernels by name — the direct analog of inspecting the reference's
max-priority-stream overlap in Nsight.
"""

from __future__ import annotations

import contextlib

__all__ = ["trace", "annotate"]


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    """Capture a `jax.profiler` trace of the enclosed block into ``log_dir``.

    The block's dispatched work is drained (`sync`-style barrier via
    `jax.block_until_ready` on the profiler's own bookkeeping is NOT enough —
    callers should pass their outputs through `igg.sync` before exiting the
    block so trailing device work lands inside the capture window).
    """
    import jax

    with jax.profiler.trace(log_dir, create_perfetto_link=create_perfetto_link):
        yield


def annotate(name: str):
    """Named region in the profiler timeline (XLA `TraceAnnotation`): shows
    up around everything dispatched inside the block."""
    import jax

    return jax.profiler.TraceAnnotation(name)
