"""JAX version compatibility shims.

The framework targets the modern `jax.shard_map` entry point (promoted out
of `jax.experimental` in JAX 0.4.35+ nightlies / 0.5-era releases, with the
variance checker renamed ``check_rep`` -> ``check_vma``). Installed JAX
builds differ on both counts, so every `shard_map` in the package and the
test/bench harnesses routes through :func:`shard_map` here:

- resolve `jax.shard_map` vs `jax.experimental.shard_map.shard_map`;
- translate the ``check_vma=`` kwarg to legacy ``check_rep=`` when the
  resolved function predates the rename (same meaning: ``False`` disables
  the per-output mesh-axis variance/replication checker, required whenever
  Pallas kernels run under the map — see `ops.halo.halo_may_use_pallas`).

Resolution happens lazily on first use (importing `jax` at module import
would defeat the package's lazy-jax layout) and is cached.
"""

from __future__ import annotations

__all__ = ["shard_map"]

_resolved = None  # (fn, vma_kwarg_name) once resolved


def _resolve():
    global _resolved
    if _resolved is None:
        import inspect

        import jax

        fn = getattr(jax, "shard_map", None)
        if fn is None:
            from jax.experimental.shard_map import shard_map as fn
        params = inspect.signature(fn).parameters
        kw = "check_vma" if "check_vma" in params else "check_rep"
        _resolved = (fn, kw)
    return _resolved


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable `shard_map`.

    ``check_vma=None`` keeps the resolved function's own default (checker
    on); ``True``/``False`` is forwarded under whichever name the installed
    JAX accepts (``check_vma``, or legacy ``check_rep``).
    """
    fn, kw = _resolve()
    kwargs = {} if check_vma is None else {kw: bool(check_vma)}
    return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
