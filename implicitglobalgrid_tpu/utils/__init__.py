"""utils subpackage of implicitglobalgrid_tpu."""
