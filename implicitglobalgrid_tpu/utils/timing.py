"""Barrier-synchronized timing — analog of reference `tic`/`toc`
(`/root/reference/src/tools.jl:230-236`): `MPI.Barrier(comm())` + wall clock.

On TPU the barrier is: flush every device's execution queue by running a tiny
jitted psum over the full grid mesh and blocking on the result (devices
execute their queues in order, so the probe completing means all previously
enqueued work completed), plus a cross-process sync in multi-host deployments
(`multihost_utils.sync_global_devices`). The probe is compiled once at init
(analog of the reference pre-compiling tic/toc, `init_global_grid.jl:119-123`).
"""

from __future__ import annotations

import time

from ..parallel.topology import AXIS_NAMES, check_initialized, global_grid

__all__ = ["tic", "toc", "barrier", "sync", "init_timing_functions"]

_t0 = None
_probe_cache: dict = {}


def sync(tree):
    """Force completion of every computation producing ``tree``'s arrays and
    return ``tree``.

    Stronger than ``jax.block_until_ready``: fetches ONE element of every
    device shard, which cannot resolve before that device's producing program
    finishes. Needed because some PJRT transports (e.g. the axon TPU tunnel)
    let ``block_until_ready`` — and even independent barrier programs —
    return before queued work completes; a concrete value fetch is the only
    ordering guarantee that holds everywhere. Cost: one scalar D2H per shard.

    Works for multi-host arrays too: the global array cannot be eagerly
    indexed when not fully addressable, but each ``shard.data`` is a local
    single-device array and fetching from it is always legal.
    """
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            for shard in leaf.addressable_shards:
                d = shard.data
                np.asarray(d[(0,) * d.ndim] if d.ndim else d)
    return tree


def _device_barrier() -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    gg = global_grid()
    mesh = gg.mesh
    if mesh is None:
        return
    key = gg.epoch
    fn = _probe_cache.get(key)
    if fn is None:
        _probe_cache.clear()

        def probe(x):
            s = x
            for ax in AXIS_NAMES:
                s = jax.lax.psum(s, ax)
            return s

        fn = jax.jit(jax.shard_map(probe, mesh=mesh, in_specs=P(), out_specs=P()))
        _probe_cache[key] = fn
    jax.block_until_ready(fn(jnp.zeros(())))
    if jax.process_count() > 1:  # DCN barrier for multi-host
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("igg_tpu_barrier")


def barrier(sync_on=None) -> None:
    """Block until all devices (and processes) reach this point. Pass the
    arrays whose pending computations must drain as ``sync_on`` for a
    data-dependent guarantee (see `sync`)."""
    check_initialized()
    if sync_on is not None:
        sync(sync_on)
    _device_barrier()


def tic(sync_on=None) -> None:
    """Start the chronometer once all devices have reached this point
    (reference `tools.jl:234`)."""
    global _t0
    check_initialized()
    if sync_on is not None:
        sync(sync_on)
    _device_barrier()
    _t0 = time.time()


def toc(sync_on=None) -> float:
    """Elapsed seconds since `tic` once all devices have reached this point
    (reference `tools.jl:235`). Pass the arrays produced by the timed region
    as ``sync_on`` to guarantee their computations are included (data-
    dependent drain; framework runners like ``run_chunked`` already sync)."""
    check_initialized()
    if sync_on is not None:
        sync(sync_on)
    _device_barrier()
    return time.time() - _t0


def init_timing_functions() -> None:
    """Pre-compile the barrier probe so first user timing is cheap
    (reference `init_global_grid.jl:119-123`)."""
    tic()
    toc()
