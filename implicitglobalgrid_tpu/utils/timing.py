"""Barrier-synchronized timing — analog of reference `tic`/`toc`
(`/root/reference/src/tools.jl:230-236`): `MPI.Barrier(comm())` + wall clock.

On TPU the barrier is: flush every device's execution queue by running a tiny
jitted psum over the full grid mesh and blocking on the result (devices
execute their queues in order, so the probe completing means all previously
enqueued work completed), plus a cross-process sync in multi-host deployments
(`multihost_utils.sync_global_devices`). The probe is compiled once at init
(analog of the reference pre-compiling tic/toc, `init_global_grid.jl:119-123`).
"""

from __future__ import annotations

import time

from ..parallel.topology import (
    AXIS_NAMES, check_initialized, global_grid, grid_is_initialized,
)

__all__ = ["tic", "toc", "barrier", "sync", "init_timing_functions"]

_t0 = None
_probe_cache: dict = {}
_drain_cache: dict = {}


def _drain_fn(gg, sig):
    """Compiled drain for a leaf signature: local first element of every
    leaf (inside shard_map, so each SHARD contributes), psum over every
    mesh axis, ONE replicated scalar out. Fetching that scalar proves every
    device executed past all the leaves' producers — one D2H round trip
    total instead of one per shard per array (a large fixed cost on
    tunneled PJRT transports)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    specs = tuple(spec for (_, _, spec) in sig)

    def drain(*leaves):
        s = jnp.zeros((), jnp.float32)
        for x in leaves:
            v = x[(0,) * x.ndim] if x.ndim else x
            if jnp.issubdtype(v.dtype, jnp.complexfloating):
                v = v.real
            s = s + v.astype(jnp.float32)
        for ax in AXIS_NAMES:
            s = lax.psum(s, ax)
        return s

    from .compat import shard_map

    return jax.jit(shard_map(drain, mesh=gg.mesh, in_specs=specs,
                             out_specs=P()))


def _sync_strong(tree):
    """Drain ``tree`` with the single-fetch compiled program when every
    array leaf is NamedSharding'ed on the grid mesh; returns (tree, True)
    on success, (tree, False) when some leaf needs the per-shard path."""
    import jax
    import numpy as np

    if not grid_is_initialized():
        return tree, False
    gg = global_grid()
    if gg.mesh is None:
        return tree, False
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if isinstance(l, jax.Array)]
    if not leaves:
        # nothing to drain, but NOT a barrier either — callers relying on
        # the barrier semantics (tic/toc/barrier) must still run the probe
        return tree, False
    sig = []
    for l in leaves:
        sh = l.sharding
        if not (isinstance(sh, jax.sharding.NamedSharding)
                and sh.mesh == gg.mesh):
            return tree, False
        sig.append((tuple(l.shape), str(l.dtype), sh.spec))
    key = (gg.epoch, tuple(sig))
    fn = _drain_cache.get(key)
    if fn is None:
        if _drain_cache:
            # dead-epoch eviction only: scheduler-retained grids
            # (`topology.retain_epoch`) keep their drains warm across
            # context switches
            from ..parallel.topology import live_epochs

            live = live_epochs()
            for k in [k for k in _drain_cache if k[0] not in live]:
                del _drain_cache[k]
        fn = _drain_fn(gg, sig)
        _drain_cache[key] = fn
    np.asarray(fn(*leaves))  # concrete fetch = the ordering guarantee
    return tree, True


def sync(tree):
    """Force completion of every computation producing ``tree``'s arrays and
    return ``tree``.

    Stronger than ``jax.block_until_ready``: resolves a CONCRETE value that
    data-depends on every shard of every leaf. Needed because some PJRT
    transports (e.g. the axon TPU tunnel) let ``block_until_ready`` — and
    even independent barrier programs — return before queued work
    completes; a concrete value fetch is the only ordering guarantee that
    holds everywhere.

    Fast path (grid-mesh arrays): ONE compiled psum-drain program and ONE
    scalar D2H for the whole tree (cached per tree signature). Fallback
    (foreign shardings, no grid): one element per device shard —
    ``shard.data`` is locally addressable even for multi-host arrays.
    """
    tree, done = _sync_strong(tree)
    if not done:
        _sync_slow(tree)
    return tree


def _sync_slow(tree) -> None:
    """Per-shard scalar-fetch fallback drain (see `sync`)."""
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            for shard in leaf.addressable_shards:
                d = shard.data
                np.asarray(d[(0,) * d.ndim] if d.ndim else d)


def _device_barrier() -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    gg = global_grid()
    mesh = gg.mesh
    if mesh is None:
        return
    key = gg.epoch
    fn = _probe_cache.get(key)
    if fn is None:
        from ..parallel.topology import live_epochs

        live = live_epochs()
        for k in [k for k in _probe_cache if k not in live]:
            del _probe_cache[k]

        def probe(x):
            s = x
            for ax in AXIS_NAMES:
                s = jax.lax.psum(s, ax)
            return s

        from .compat import shard_map

        fn = jax.jit(shard_map(probe, mesh=mesh, in_specs=P(), out_specs=P()))
        _probe_cache[key] = fn
    # concrete fetch, not block_until_ready — the latter can return early
    # on some PJRT transports (see `sync`)
    import numpy as np

    np.asarray(fn(jnp.zeros(())))
    if jax.process_count() > 1:  # DCN barrier for multi-host
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("igg_tpu_barrier")


def _sync_then_barrier(sync_on) -> None:
    """Shared tic/toc/barrier path. When ``sync_on`` drains through the
    strong single-fetch program, that drain already psums over every mesh
    axis and resolves concretely — strictly stronger than the probe — so
    the separate device barrier (an extra D2H round trip inside timed
    windows) is skipped; multi-host still adds the DCN sync."""
    import jax

    strong = False
    if sync_on is not None:
        _, strong = _sync_strong(sync_on)
        if not strong:
            _sync_slow(sync_on)
    if strong:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("igg_tpu_barrier")
        return
    _device_barrier()


def barrier(sync_on=None) -> None:
    """Block until all devices (and processes) reach this point. Pass the
    arrays whose pending computations must drain as ``sync_on`` for a
    data-dependent guarantee (see `sync`)."""
    check_initialized()
    _sync_then_barrier(sync_on)


def tic(sync_on=None) -> None:
    """Start the chronometer once all devices have reached this point
    (reference `tools.jl:234`)."""
    global _t0
    check_initialized()
    _sync_then_barrier(sync_on)
    _t0 = time.time()


def toc(sync_on=None) -> float:
    """Elapsed seconds since `tic` once all devices have reached this point
    (reference `tools.jl:235`). Pass the arrays produced by the timed region
    as ``sync_on`` to guarantee their computations are included (data-
    dependent drain; framework runners like ``run_chunked`` already sync).

    Raises `InvalidArgumentError` when no `tic` started the chronometer
    (instead of the bare ``NoneType`` TypeError the subtraction would
    throw)."""
    check_initialized()
    if _t0 is None:
        from .exceptions import InvalidArgumentError

        raise InvalidArgumentError(
            "toc() called with no running chronometer: call tic() first "
            "(init_global_grid pre-compiles the pair, but "
            "finalize_global_grid resets it).")
    _sync_then_barrier(sync_on)
    return time.time() - _t0


def init_timing_functions() -> None:
    """Pre-compile the barrier probe so first user timing is cheap
    (reference `init_global_grid.jl:119-123`)."""
    tic()
    toc()
