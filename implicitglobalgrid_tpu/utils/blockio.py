"""Shared block-format layer: the PR-2 checkpoint container, factored out.

One on-disk format serves both durability layers of the framework — the
sharded checkpoints (`utils/checkpoint.py`) and the async snapshot pipeline
(`implicitglobalgrid_tpu/io/`): a DIRECTORY holding

- ``shards_p<process>.npz`` — each process's addressable shard blocks,
  keyed by BLOCK COORDINATES (``shard_key``: array name + stacked start
  offsets), so any reader can reassemble any sub-box without knowing the
  writer's process->shard mapping;
- ``meta.npz`` — the grid topology (``grid_meta``), array names / stacked
  shapes / dtypes, the save token that ties the file set together, and
  the step; its write is the COMMIT record of the set;
- a ``<file>.sha256`` content-checksum sidecar per file (written after the
  data file is fsync'ed — its presence marks that file complete), verified
  before any byte of the file is used.

Durability protocol (both writers follow it): stage every file into a
``<dir>.tmp-<token>`` directory, fsync each, and only after the complete
set (meta last) is on disk does ONE rename give the directory its final
name — a crash at any point leaves either a previous complete directory or
a stale ``.tmp-``, never a half-written committed one.

All helpers are host-side numpy/os code — no jax import, so the reader
side (`io.reader`, `tools.py` CLI) works on a machine with no accelerator
runtime at all.
"""

from __future__ import annotations

import os

import numpy as np

from .exceptions import IncoherentArgumentError, InvalidArgumentError

__all__ = [
    "META_PREFIX", "ARR_PREFIX", "file_sha256", "write_npz_synced",
    "verify_checksum", "fsync_dir", "starts_of", "shard_key", "grid_meta",
    "load_prefixed_meta", "block_scanner", "validate_block_keys",
    "commit_staged_dir",
]

META_PREFIX = "__igg_meta__"
ARR_PREFIX = "__igg_arr__"


def validate_block_keys(state: dict, what: str) -> None:
    """The container's key rule, shared by every writer: array names key
    npz members (`shard_key`), so they must be plain strings without the
    ``__`` separator and outside the reserved ``__igg_`` namespace."""
    if not isinstance(state, dict) or not state:
        raise InvalidArgumentError(
            f"{what} expects a non-empty dict of name -> array.")
    for k in state:
        if not isinstance(k, str) or k.startswith("__igg_") or "__" in k:
            raise InvalidArgumentError(
                f"Invalid state key {k!r}: keys must be strings without "
                "'__' and not starting with '__igg_'.")


def grid_meta(gg) -> dict:
    """The topology record every container carries (prefixed keys)."""
    return {
        f"{META_PREFIX}nxyz": np.asarray(gg.nxyz, dtype=np.int64),
        f"{META_PREFIX}dims": np.asarray(gg.dims, dtype=np.int64),
        f"{META_PREFIX}overlaps": np.asarray(gg.overlaps, dtype=np.int64),
        f"{META_PREFIX}periods": np.asarray(gg.periods, dtype=np.int64),
        f"{META_PREFIX}halowidths": np.asarray(gg.halowidths,
                                               dtype=np.int64),
    }


# ---------------------------------------------------------------------------
# File integrity: fsync'ed writes + sha256 content sidecars
# ---------------------------------------------------------------------------

def file_sha256(path) -> str:
    import hashlib

    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_npz_synced(path, payload: dict) -> None:
    """`np.savez` to ``path`` with fsync, plus a ``<path>.sha256``
    content-checksum sidecar (also fsync'ed) verified before reads. The
    sidecar lands LAST, so its presence marks the data file complete —
    the multi-process snapshot commit polls for exactly that."""
    with open(path, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    side = path + ".sha256"
    with open(side + ".tmp", "w") as f:
        f.write(file_sha256(path) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(side + ".tmp", side)


def verify_checksum(path, *, required: bool) -> None:
    """Compare ``path`` against its ``.sha256`` sidecar. ``required=False``
    tolerates a MISSING sidecar (containers from before the checksum
    format); a PRESENT sidecar is always enforced."""
    side = path + ".sha256"
    if not os.path.exists(side):
        if required:
            raise IncoherentArgumentError(
                f"Checkpoint file {path} has no .sha256 sidecar but the "
                "save recorded checksums — the directory was tampered with "
                "or partially copied; do not resume from it.")
        return
    with open(side) as f:
        expect = f.read().strip()
    got = file_sha256(path)
    if got != expect:
        raise IncoherentArgumentError(
            f"Checkpoint file {path} is corrupt: content checksum "
            f"{got[:12]}… does not match the recorded {expect[:12]}… — the "
            "file was truncated or bit-flipped after the save; restore "
            "from another checkpoint.")


def fsync_dir(path) -> None:
    """Durability for a commit rename (POSIX: the rename is only durable
    once the parent directory is fsync'ed); best-effort on platforms
    without directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# ---------------------------------------------------------------------------
# Block keys and scanning
# ---------------------------------------------------------------------------

def starts_of(index) -> tuple:
    return tuple(int(sl.start or 0) for sl in index)


def shard_key(name: str, starts) -> str:
    return f"{ARR_PREFIX}{name}__" + "_".join(str(s) for s in starts)


def load_prefixed_meta(dirpath) -> dict:
    """Open + verify + prefix-strip ``meta.npz`` — the ONE meta-loading
    path of every block container. The file is checksum-verified BEFORE
    parsing (a corrupt meta must raise the typed error, not a raw zipfile
    one); ``required=False`` tolerates pre-checksum-format saves, which
    have no sidecars at all."""
    meta_path = os.path.join(dirpath, "meta.npz")
    if not os.path.exists(meta_path):
        raise InvalidArgumentError(
            f"Sharded checkpoint meta not found: {meta_path}")
    verify_checksum(meta_path, required=False)
    import zipfile

    try:
        with np.load(meta_path) as z:
            return {k[len(META_PREFIX):]: z[k] for k in z.files
                    if k.startswith(META_PREFIX)}
    except (OSError, ValueError, zipfile.BadZipFile) as e:
        # a half-committed / truncated meta.npz without a sidecar (a
        # pre-checksum save interrupted mid-copy) surfaces as a raw
        # zipfile error — readers polling a live root need the TYPED
        # refusal instead
        raise IncoherentArgumentError(
            f"{meta_path} is unreadable ({type(e).__name__}: {e}) — the "
            "directory is half-committed or was truncated after commit; "
            "do not read from it.") from e


def commit_staged_dir(stage: str, final: str, token: str) -> None:
    """The container's one-rename commit, shared by every writer: a
    pre-existing ``final`` is moved aside first (stale files from an
    earlier save can never shadow the new set — the whole directory is
    replaced, not patched), the staging dir takes the final name in ONE
    rename, the parent is fsync'ed (POSIX: the rename is only durable
    then), and the old set is removed last."""
    import shutil

    old = None
    if os.path.exists(final):
        old = f"{final}.old-{token}"
        os.rename(final, old)
    os.rename(stage, final)
    fsync_dir(os.path.dirname(os.path.abspath(final)) or ".")
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def block_scanner(files, wanted: set, checksums_required: bool,
                  verified: set, *, pop: bool = True):
    """Lazy scan over the shard files for the keys in ``wanted``: each file
    is opened at most once (checksum-verified on first open) and each
    found block cached, so host memory stays at the CONSUMER'S working-set
    volume — the restore's per-process shard volume, the snapshot reader's
    requested box — never the global array. ``pop=True`` drops a block
    once consumed (the plain restore's one consumer per block);
    ``pop=False`` keeps it cached — the elastic restore and the box reader
    reuse one saved block for several destinations."""

    blocks: dict = {}
    unscanned = list(files)

    def find_block(key: str):
        while key not in blocks and unscanned:
            path = unscanned.pop(0)
            if path not in verified:
                verify_checksum(path, required=checksums_required)
                verified.add(path)
            with np.load(path) as z:
                for k in z.files:
                    if k in wanted:
                        blocks[k] = z[k]
        if key not in blocks:
            raise IncoherentArgumentError(
                f"Sharded checkpoint is missing block `{key}` — was the "
                "save interrupted, or written with a different topology?")
        return blocks.pop(key) if pop else blocks[key]

    return find_block
