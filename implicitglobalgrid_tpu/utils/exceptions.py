"""Typed exceptions for the TPU implicit-global-grid framework.

TPU-native re-design of the reference's exception module
(`/root/reference/src/Exceptions.jl:1-49`): the reference defines seven typed
exception structs plus throw-macros. Here they are plain Python exception
classes; unlike the reference (which mostly raises untyped `error()` despite
defining these), this framework raises the typed classes everywhere so callers
and tests can catch precisely.
"""

__all__ = [
    "GlobalGridError",
    "ModuleInternalError",
    "NotInitializedError",
    "AlreadyInitializedError",
    "InvalidArgumentError",
    "IncoherentArgumentError",
    "KeywordArgumentError",
    "NotLoadedError",
    "NotSupportedError",
    "ResilienceError",
]


class GlobalGridError(Exception):
    """Base class for all framework errors."""


class ModuleInternalError(GlobalGridError):
    """An internal invariant was violated (reference: `Exceptions.jl` ModuleInternalError)."""


class NotInitializedError(GlobalGridError):
    """API used before `init_global_grid` / after `finalize_global_grid`
    (reference: `shared.jl:90` check_initialized)."""


class AlreadyInitializedError(GlobalGridError):
    """`init_global_grid` called twice (reference: `init_global_grid.jl:42`)."""


class InvalidArgumentError(GlobalGridError):
    """An argument value is invalid on its own (reference: `Exceptions.jl` InvalidArgumentError)."""


class IncoherentArgumentError(GlobalGridError):
    """Arguments are individually valid but mutually incoherent
    (reference: `Exceptions.jl` IncoherentArgumentError)."""


class KeywordArgumentError(GlobalGridError):
    """A keyword argument is not supported in this context."""


class NotLoadedError(GlobalGridError):
    """A required backend/extension is not available
    (reference: `Exceptions.jl` NotLoadedError)."""


class NotSupportedError(GlobalGridError):
    """Feature unsupported for the given input (reference: `shared.jl:176` B>1 CellArrays)."""


class ResilienceError(GlobalGridError):
    """The resilient runtime could not recover a run: a health guard tripped
    with no usable checkpoint, every checkpoint slot failed to restore, or
    the bounded retry budget of the recovery policy was exhausted (no
    reference analog — the reference has no runtime supervision at all)."""
