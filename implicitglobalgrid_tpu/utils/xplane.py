"""Minimal pure-Python reader for the XLA profiler's XSpace/XPlane protos.

`jax.profiler.trace` writes its capture as
``<log_dir>/plugins/profile/<run>/<host>.xplane.pb`` — an ``XSpace`` protobuf
(the TensorBoard/XProf exchange format). The usual consumers are external
GUIs; this module decodes the wire format directly (no tensorflow/protobuf
dependency) so the framework can compute numbers from its own traces —
the quantitative upgrade over the reference's approach of structuring its
CUDA streams for external Nsight inspection
(`/root/reference/src/update_halo.jl:207` note).

Only the fields the analysis needs are decoded:

    XSpace.planes[]                                 (field 1)
      XPlane: name=2, lines=3, event_metadata=4     (map<id, XEventMetadata>)
        XLine: name=2, timestamp_ns=3, events=4, display_name=11
          XEvent: metadata_id=1, offset_ps=2, duration_ps=3
        XEventMetadata: id=1, name=2, display_name=4

Everything else (stats, reference events) is skipped structurally, so the
parser stays correct as the schema grows.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

__all__ = ["XEvent", "XLine", "XPlane", "parse_xspace", "find_xplane_files"]


@dataclass
class XEvent:
    name: str
    start_ps: int       # absolute within the plane (line timestamp + offset)
    duration_ps: int

    @property
    def end_ps(self) -> int:
        return self.start_ps + self.duration_ps


@dataclass
class XLine:
    name: str
    timestamp_ns: int
    events: list = field(default_factory=list)


@dataclass
class XPlane:
    name: str
    lines: list = field(default_factory=list)


def _varint(buf: bytes, i: int):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message's wire bytes."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:                       # varint
            v, i = _varint(buf, i)
        elif wt == 2:                     # length-delimited
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:                     # fixed32
            v = struct.unpack_from("<I", buf, i)[0]
            i += 4
        elif wt == 1:                     # fixed64
            v = struct.unpack_from("<Q", buf, i)[0]
            i += 8
        else:  # groups (3/4) never appear in xplane protos
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, wt, v


def _parse_event_metadata(buf: bytes):
    """XEventMetadata -> (id, best-effort name)."""
    mid = None
    name = None
    display = None
    for fn, _, v in _fields(buf):
        if fn == 1:
            mid = v
        elif fn == 2:
            name = v.decode("utf-8", "replace")
        elif fn == 4:
            display = v.decode("utf-8", "replace")
    return mid, (display or name or "")


def _parse_line(buf: bytes, names: dict):
    name = ""
    display = ""
    timestamp_ns = 0
    raw_events = []
    for fn, _, v in _fields(buf):
        if fn == 2:
            name = v.decode("utf-8", "replace")
        elif fn == 11:
            display = v.decode("utf-8", "replace")
        elif fn == 3:
            timestamp_ns = v
        elif fn == 4:
            raw_events.append(v)
    line = XLine(name=display or name, timestamp_ns=timestamp_ns)
    base_ps = timestamp_ns * 1000
    for ev in raw_events:
        mid = 0
        off_ps = 0
        dur_ps = 0
        for fn, _, v in _fields(ev):
            if fn == 1:
                mid = v
            elif fn == 2:
                off_ps = v
            elif fn == 3:
                dur_ps = v
        line.events.append(
            XEvent(name=names.get(mid, str(mid)), start_ps=base_ps + off_ps,
                   duration_ps=dur_ps))
    return line


def _parse_plane(buf: bytes):
    name = ""
    raw_lines = []
    names: dict = {}
    for fn, _, v in _fields(buf):
        if fn == 2:
            name = v.decode("utf-8", "replace")
        elif fn == 3:
            raw_lines.append(v)
        elif fn == 4:  # map<int64, XEventMetadata>: entry{key=1, value=2}
            key = None
            meta = None
            for f2, _, v2 in _fields(v):
                if f2 == 1:
                    key = v2
                elif f2 == 2:
                    meta = v2
            if meta is not None:
                mid, mname = _parse_event_metadata(meta)
                names[mid if mid is not None else key] = mname
    plane = XPlane(name=name)
    for ln in raw_lines:
        plane.lines.append(_parse_line(ln, names))
    return plane


def parse_xspace(path: str):
    """Parse one ``*.xplane.pb`` file into a list of `XPlane`s."""
    with open(path, "rb") as f:
        data = f.read()
    planes = []
    for fn, wt, v in _fields(data):
        if fn == 1 and wt == 2:
            planes.append(_parse_plane(v))
    return planes


def find_xplane_files(log_dir: str):
    """``*.xplane.pb`` files of the NEWEST run under a `jax.profiler.trace`
    log directory (captures land in ``plugins/profile/<timestamp>/``)."""
    root = os.path.join(log_dir, "plugins", "profile")
    if not os.path.isdir(root):
        return []
    runs = sorted(d for d in os.listdir(root)
                  if os.path.isdir(os.path.join(root, d)))
    if not runs:
        return []
    run_dir = os.path.join(root, runs[-1])
    return sorted(os.path.join(run_dir, f) for f in os.listdir(run_dir)
                  if f.endswith(".xplane.pb"))
