"""Checkpoint/resume for distributed grid state.

The reference has NO checkpointing (SURVEY §5.4): `gather!` is the provided
IO primitive (`/root/reference/src/gather.jl`) and users handle files. Here
checkpointing is first-class: functional state (stacked global `jax.Array`s)
plus the recorded grid topology make save/restore a pair of calls
(doctest):

>>> import os, tempfile
>>> import implicitglobalgrid_tpu as igg
>>> _ = igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
>>> T = igg.ones_g()
>>> path = os.path.join(tempfile.mkdtemp(), "ckpt.npz")
>>> igg.save_checkpoint(path, {"T": T}, step=42)
>>> state, step = igg.restore_checkpoint(path)   # arrays re-sharded
>>> step, tuple(state["T"].shape)
(42, (8, 8, 8))
>>> sdir = os.path.join(tempfile.mkdtemp(), "ckpt_dir")   # pod-scale path
>>> igg.save_checkpoint_sharded(sdir, {"T": T}, step=43)
>>> state, step = igg.restore_checkpoint_sharded(sdir)
>>> step
43
>>> igg.finalize_global_grid()

Two formats:

- **Single-file** (`save_checkpoint`/`restore_checkpoint`): one `.npz`
  (portable, numpy-readable anywhere) holding the GATHERED stacked arrays
  plus the grid topology (`nxyz`, `dims`, `overlaps`, `periods`,
  `halowidths`). The gather funnels the whole state through one process —
  right for small/medium runs and for files users open elsewhere.
- **Sharded** (`save_checkpoint_sharded`/`restore_checkpoint_sharded`):
  a DIRECTORY in which every process writes only its addressable shards
  (`shards_p<process>.npz`, one meta file from process 0) — no host ever
  materializes the global state, so the path scales to pod-size grids
  (v5p-256 at 256³/chip f32 is ~17 GB/field gathered — the single-file
  path cannot carry the north-star config; the round-3 verdict's item 7).
  Restore reassembles by BLOCK COORDINATES, so it works even when the
  process→shard mapping changed between save and restore (each process
  reads its own file first and scans the others only for blocks it is
  missing). Requires a filesystem reachable by all processes (the normal
  pod setup).

`restore_checkpoint*` validates the topology against the live grid and
re-shards onto the current mesh, so a run can resume on different hardware
with the same decomposition. In multi-host runs save/restore are collective
(every process must call them); restore is SPMD-uniform.
"""

from __future__ import annotations

import os

import numpy as np

from ..parallel.topology import check_initialized, global_grid
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError

__all__ = ["save_checkpoint", "restore_checkpoint", "load_checkpoint",
           "save_checkpoint_sharded", "restore_checkpoint_sharded"]

_META_PREFIX = "__igg_meta__"
_ARR_PREFIX = "__igg_arr__"


def _grid_meta(gg) -> dict:
    return {
        f"{_META_PREFIX}nxyz": np.asarray(gg.nxyz, dtype=np.int64),
        f"{_META_PREFIX}dims": np.asarray(gg.dims, dtype=np.int64),
        f"{_META_PREFIX}overlaps": np.asarray(gg.overlaps, dtype=np.int64),
        f"{_META_PREFIX}periods": np.asarray(gg.periods, dtype=np.int64),
        f"{_META_PREFIX}halowidths": np.asarray(gg.halowidths, dtype=np.int64),
    }


def save_checkpoint(path, state: dict, *, step: int | None = None,
                    root: int = 0) -> None:
    """Write ``state`` (a dict name -> stacked global array) and the grid
    topology to ``path`` (.npz). Collective in multi-host runs; only ``root``
    writes the file. Writes atomically (tmp file + rename) so an interrupted
    save never corrupts an existing checkpoint."""
    import jax

    from ..ops.gather import gather

    check_initialized()
    if not isinstance(state, dict) or not state:
        raise InvalidArgumentError(
            "save_checkpoint expects a non-empty dict of name -> array.")
    for k in state:
        if not isinstance(k, str) or k.startswith("__igg_"):
            raise InvalidArgumentError(
                f"Invalid state key {k!r}: keys must be strings not starting "
                "with '__igg_'.")
    gg = global_grid()
    # Gather every array on every process (collective), write on root only.
    hosts = {k: gather(v, root=root) for k, v in state.items()}
    if jax.process_index() == root:
        payload = {f"{_ARR_PREFIX}{k}": np.asarray(v) for k, v in hosts.items()}
        payload.update(_grid_meta(gg))
        if step is not None:
            payload[f"{_META_PREFIX}step"] = np.int64(step)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    # All processes synchronize on the completed write so an immediately
    # following restore_checkpoint never reads a stale/missing file on
    # non-root hosts (save/restore is documented as an SPMD-uniform pair).
    from .timing import barrier

    barrier()


def load_checkpoint(path):
    """Read a checkpoint file: returns ``(state, meta)`` with ``state`` a dict
    of numpy arrays (stacked layout) and ``meta`` the saved topology dict
    (keys: nxyz, dims, overlaps, periods, halowidths, step|None). Host-only —
    does not require an initialized grid."""
    if not os.path.exists(path):
        raise InvalidArgumentError(f"Checkpoint file not found: {path}")
    with np.load(path) as z:
        state = {k[len(_ARR_PREFIX):]: z[k] for k in z.files
                 if k.startswith(_ARR_PREFIX)}
        meta = {k[len(_META_PREFIX):]: z[k] for k in z.files
                if k.startswith(_META_PREFIX)}
    meta["step"] = int(meta["step"]) if "step" in meta else None
    return state, meta


def _validate_topology(meta: dict, gg, strict: bool,
                       required=()) -> None:
    """``required`` fields are validated even with ``strict=False`` (the
    sharded layout cannot reassemble across a different decomposition —
    its blocks are keyed by the saved block coordinates; the single-file
    path CAN reshard, hence its escape hatch)."""
    for name in ("nxyz", "dims", "overlaps", "periods", "halowidths"):
        hard = name in required
        if not strict and not hard:
            continue
        saved = meta.get(name)
        live = np.asarray(getattr(gg, name))
        if saved is None or not np.array_equal(np.asarray(saved), live):
            hint = ("Re-init the grid to match (sharded restore cannot "
                    "reshard; use the single-file restore_checkpoint for "
                    "that)." if hard else
                    "Re-init the grid to match or pass strict=False.")
            raise IncoherentArgumentError(
                f"Checkpoint topology mismatch for `{name}`: saved "
                f"{None if saved is None else list(np.asarray(saved))}, live "
                f"{list(live)}. {hint}"
            )


def _starts_of(index) -> tuple:
    return tuple(int(sl.start or 0) for sl in index)


def _shard_key(name: str, starts) -> str:
    return f"{_ARR_PREFIX}{name}__" + "_".join(str(s) for s in starts)


def save_checkpoint_sharded(dirpath, state: dict, *,
                            step: int | None = None) -> None:
    """Write ``state`` to directory ``dirpath`` with each process saving
    only its ADDRESSABLE shards (pod-scale path: no host gathers the
    global state). Collective; atomic per file (tmp + rename)."""
    import jax

    from ..ops.alloc import device_put_g

    check_initialized()
    if not isinstance(state, dict) or not state:
        raise InvalidArgumentError(
            "save_checkpoint_sharded expects a non-empty dict of "
            "name -> array.")
    for k in state:
        if not isinstance(k, str) or k.startswith("__igg_") or "__" in k:
            raise InvalidArgumentError(
                f"Invalid state key {k!r}: keys must be strings without "
                "'__' and not starting with '__igg_'.")
    gg = global_grid()
    os.makedirs(dirpath, exist_ok=True)
    pidx = jax.process_index()

    # One token per SAVE, shared by all processes: restore validates every
    # shard file against it, so a crash between one process's write and
    # another's can never silently mix blocks from two different saves
    # (per-file tmp+rename is atomic; the multi-file SET is not).
    import secrets

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        token_arr = multihost_utils.broadcast_one_to_all(
            np.frombuffer(secrets.token_bytes(16), dtype=np.uint8))
        token = bytes(np.asarray(token_arr)).hex()
    else:
        token = secrets.token_hex(16)

    payload = {f"{_META_PREFIX}save_token": np.str_(token)}
    names, shapes, dtypes = [], {}, {}
    for k, v in state.items():
        if not hasattr(v, "addressable_shards"):  # host array: shard first
            v = device_put_g(v)
        names.append(k)
        shapes[f"{_META_PREFIX}shape__{k}"] = np.asarray(v.shape,
                                                         dtype=np.int64)
        dtypes[f"{_META_PREFIX}dtype__{k}"] = np.str_(str(v.dtype))
        for s in v.addressable_shards:
            if getattr(s, "replica_id", 0) != 0:
                continue  # replicated shards: one copy is enough
            payload[_shard_key(k, _starts_of(s.index))] = np.asarray(s.data)

    shard_path = os.path.join(dirpath, f"shards_p{pidx}.npz")
    with open(shard_path + ".tmp", "wb") as f:
        np.savez(f, **payload)
    os.replace(shard_path + ".tmp", shard_path)

    # Barrier BEFORE process 0 writes meta.npz: meta carries the new save
    # token, so it must be the COMMIT record of a fully-written shard set.
    # Without this, a crash while other processes are still writing leaves
    # a meta newer than some shards — detectable only at restore, with a
    # non-uniform failure across processes.
    from .timing import barrier

    barrier()

    if pidx == 0:
        meta = _grid_meta(gg)
        meta[f"{_META_PREFIX}names"] = np.asarray(names)
        meta[f"{_META_PREFIX}save_token"] = np.str_(token)
        meta[f"{_META_PREFIX}nprocs_files"] = np.int64(jax.process_count())
        meta.update(shapes)
        meta.update(dtypes)
        if step is not None:
            meta[f"{_META_PREFIX}step"] = np.int64(step)
        meta_path = os.path.join(dirpath, "meta.npz")
        with open(meta_path + ".tmp", "wb") as f:
            np.savez(f, **meta)
        os.replace(meta_path + ".tmp", meta_path)
        # Remove stale shard files from an earlier save with MORE
        # processes (no current process writes these indices): leftovers
        # would otherwise be globbed by a later restore and could shadow
        # the new state with old-step blocks.
        import glob as _glob
        import re as _re

        for f in _glob.glob(os.path.join(dirpath, "shards_p*.npz")):
            m = _re.search(r"shards_p(\d+)\.npz$", f)
            if m and int(m.group(1)) >= jax.process_count():
                os.remove(f)

    # Final barrier: no process returns (and possibly starts the NEXT
    # save, or reports the checkpoint usable) before meta.npz exists.
    barrier()


def restore_checkpoint_sharded(dirpath, *, strict: bool = True):
    """Load a `save_checkpoint_sharded` directory and reassemble every
    array on the live mesh from block coordinates — each process reads its
    own shard file first and scans the others only for blocks it misses,
    so no process ever holds the global state. Returns ``(state, step)``."""
    import glob as _glob

    import jax

    from ..ops.alloc import sharding_of

    check_initialized()
    gg = global_grid()
    meta_path = os.path.join(dirpath, "meta.npz")
    if not os.path.exists(meta_path):
        raise InvalidArgumentError(
            f"Sharded checkpoint meta not found: {meta_path}")
    with np.load(meta_path) as z:
        meta = {k[len(_META_PREFIX):]: z[k] for k in z.files
                if k.startswith(_META_PREFIX)}
    # nxyz/dims are REQUIRED even with strict=False: blocks are keyed by
    # the saved block coordinates, so a different decomposition cannot be
    # reassembled here (the single-file path reshards; this one does not).
    _validate_topology(meta, gg, strict, required=("nxyz", "dims"))
    names = [str(n) for n in meta["names"]]
    step = int(meta["step"]) if "step" in meta else None

    pidx = jax.process_index()
    # The meta records how many shard files the save wrote; read EXACTLY
    # those (a bare glob could pick up stale files from an earlier save
    # with more processes and silently restore old-step blocks).
    n_files = int(meta.get("nprocs_files", 0)) or len(
        _glob.glob(os.path.join(dirpath, "shards_p*.npz")))
    files = [os.path.join(dirpath, f"shards_p{i}.npz")
             for i in range(n_files)]
    missing = [f for f in files if not os.path.exists(f)]
    if not files or missing:
        raise InvalidArgumentError(
            f"Sharded checkpoint in {dirpath} is incomplete: expected "
            f"{n_files} shard file(s), missing {missing or 'all'}.")
    own = os.path.join(dirpath, f"shards_p{pidx}.npz")
    if own in files:  # own file first: the no-remap fast path
        files.remove(own)
        files.insert(0, own)

    # Every block THIS process needs, across all arrays — scanning loads
    # only these keys and each is dropped once consumed, so host memory
    # stays at this process' shard volume even after a process->shard
    # remap (the pod-scale guarantee of this path).
    plans = {}
    wanted: set = set()
    for name in names:
        shape = tuple(int(s) for s in meta[f"shape__{name}"])
        dtype = np.dtype(str(meta[f"dtype__{name}"]))
        sharding = sharding_of(len(shape))
        needed = sharding.addressable_devices_indices_map(shape)
        plans[name] = (shape, dtype, sharding, needed)
        wanted |= {_shard_key(name, _starts_of(idx))
                   for idx in needed.values()}

    blocks: dict = {}       # key -> np.ndarray, only keys in `wanted`
    unscanned = list(files)
    expect_token = str(meta["save_token"]) if "save_token" in meta else None
    token_key = f"{_META_PREFIX}save_token"

    # Token-check EVERY shard file up front (cheap: npz loads members
    # lazily, so this reads one tiny array per file), not just the files
    # this process happens to scan for blocks.  A lazy per-scan check is
    # non-SPMD-uniform: after an interrupted save, a process whose blocks
    # all sit in its own (valid) shard file would restore successfully
    # while others raise — hanging the multi-host run at the next
    # collective instead of failing cleanly on every process.
    if expect_token is not None:
        for path in files:
            with np.load(path) as z:
                ftok = str(z[token_key]) if token_key in z.files else None
            if ftok != expect_token:
                raise IncoherentArgumentError(
                    f"Shard file {path} belongs to a different save than "
                    "meta.npz (save-token mismatch) — the save was "
                    "interrupted; do not resume from this checkpoint.")

    def find_block(key: str):
        while key not in blocks and unscanned:
            path = unscanned.pop(0)
            with np.load(path) as z:
                for k in z.files:
                    if k in wanted:
                        blocks[k] = z[k]
        if key not in blocks:
            raise IncoherentArgumentError(
                f"Sharded checkpoint is missing block `{key}` — was the "
                "save interrupted, or written with a different topology?")
        return blocks.pop(key)

    out = {}
    for name in names:
        shape, dtype, sharding, needed = plans[name]
        # several devices can need the SAME block (mesh axes the field is
        # not sharded over are replicated): fetch once, place on each
        by_key: dict = {}
        for dev, idx in needed.items():
            by_key.setdefault(_shard_key(name, _starts_of(idx)),
                              []).append(dev)
        arrays = []
        for key, devs in by_key.items():
            block = np.asarray(find_block(key), dtype=dtype)
            arrays.extend(jax.device_put(block, dev) for dev in devs)
        out[name] = jax.make_array_from_single_device_arrays(
            shape, sharding, arrays)
    return out, step


def restore_checkpoint(path, *, strict: bool = True):
    """Load ``path`` and re-shard every array onto the live grid's mesh.

    Returns ``(state, step)`` with ``state`` a dict of stacked global
    `jax.Array`s. With ``strict`` (default) the saved topology (``nxyz, dims,
    overlaps, periods, halowidths``) must match the live grid exactly;
    ``strict=False`` skips the check (e.g. resuming onto a different
    decomposition of the same global grid — caller's responsibility)."""
    from ..ops.alloc import device_put_g

    check_initialized()
    gg = global_grid()
    state, meta = load_checkpoint(path)
    _validate_topology(meta, gg, strict)
    out = {k: device_put_g(v) for k, v in state.items()}
    return out, meta["step"]
