"""Checkpoint/resume for distributed grid state.

The reference has NO checkpointing (SURVEY §5.4): `gather!` is the provided
IO primitive (`/root/reference/src/gather.jl`) and users handle files. Here
checkpointing is first-class: functional state (stacked global `jax.Array`s)
plus the recorded grid topology make save/restore a pair of calls
(doctest):

>>> import os, tempfile
>>> import implicitglobalgrid_tpu as igg
>>> _ = igg.init_global_grid(4, 4, 4, dimx=2, dimy=2, dimz=2, quiet=True)
>>> T = igg.ones_g()
>>> tmp = tempfile.TemporaryDirectory()   # doctests must not leak dirs
>>> path = os.path.join(tmp.name, "ckpt.npz")
>>> igg.save_checkpoint(path, {"T": T}, step=42)
>>> state, step = igg.restore_checkpoint(path)   # arrays re-sharded
>>> step, tuple(state["T"].shape)
(42, (8, 8, 8))
>>> sdir = os.path.join(tmp.name, "ckpt_dir")   # pod-scale path
>>> igg.save_checkpoint_sharded(sdir, {"T": T}, step=43)
>>> state, step = igg.restore_checkpoint_sharded(sdir)
>>> step
43
>>> igg.finalize_global_grid()
>>> tmp.cleanup()

Two formats:

- **Single-file** (`save_checkpoint`/`restore_checkpoint`): one `.npz`
  (portable, numpy-readable anywhere) holding the GATHERED stacked arrays
  plus the grid topology (`nxyz`, `dims`, `overlaps`, `periods`,
  `halowidths`). The gather funnels the whole state through one process —
  right for small/medium runs and for files users open elsewhere.
- **Sharded** (`save_checkpoint_sharded`/`restore_checkpoint_sharded`):
  a DIRECTORY in which every process writes only its addressable shards
  (`shards_p<process>.npz`, one meta file from process 0) — no host ever
  materializes the global state, so the path scales to pod-size grids
  (v5p-256 at 256³/chip f32 is ~17 GB/field gathered — the single-file
  path cannot carry the north-star config; the round-3 verdict's item 7).
  Restore reassembles by BLOCK COORDINATES, so it works even when the
  process→shard mapping changed between save and restore (each process
  reads its own file first and scans the others only for blocks it is
  missing). Requires a filesystem reachable by all processes (the normal
  pod setup).

Durability and integrity (the resilient runtime's substrate,
`runtime/driver.py`):

- The sharded save is ATOMIC at the directory level: every file is staged
  into `<dirpath>.tmp-<token>` (fsync'ed), and only after every process
  finished and `meta.npz` committed the set does the staging directory
  take the final name — a crash at any point leaves either the previous
  complete checkpoint or a stale `.tmp-`/`.old-` directory, never a
  half-written `dirpath`.
- Every file carries a `<file>.sha256` content-checksum sidecar, verified
  on restore before any block from that file is used — a truncated or
  bit-flipped file raises `IncoherentArgumentError` instead of restoring
  garbage silently.
- `restore_checkpoint_elastic` restores onto a grid whose `dims` DIFFER
  from the saved decomposition (the elastic-restart path): the same
  implicit global grid is re-blocked from the saved block coordinates,
  each process reading only the saved blocks its shards overlap.

`restore_checkpoint*` validates the topology against the live grid and
re-shards onto the current mesh, so a run can resume on different hardware
with the same decomposition. In multi-host runs save/restore are collective
(every process must call them); restore is SPMD-uniform.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..parallel.topology import check_initialized, global_grid
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError
from .blockio import (
    ARR_PREFIX as _ARR_PREFIX,
    META_PREFIX as _META_PREFIX,
    block_scanner as _block_scanner,
    commit_staged_dir as _commit_staged_dir,
    grid_meta as _grid_meta,
    load_prefixed_meta as _load_meta,
    shard_key as _shard_key,
    starts_of as _starts_of,
    validate_block_keys as _validate_block_keys,
    verify_checksum as _verify_checksum,
    write_npz_synced as _write_npz_synced,
)

__all__ = ["save_checkpoint", "restore_checkpoint", "load_checkpoint",
           "save_checkpoint_sharded", "restore_checkpoint_sharded",
           "restore_checkpoint_elastic", "saved_topology",
           "elastic_local_size", "AxisRedistribution"]

# The container format (shard_key block layout, meta/arr key prefixes,
# fsync'ed writes + sha256 sidecars, staged-directory atomic commit) is
# factored into `utils/blockio.py`, shared with the async snapshot pipeline
# (`implicitglobalgrid_tpu/io/`) — one on-disk format, two durability
# layers, and `io.open_snapshot` can read checkpoint directories too.


def save_checkpoint(path, state: dict, *, step: int | None = None,
                    root: int = 0) -> None:
    """Write ``state`` (a dict name -> stacked global array) and the grid
    topology to ``path`` (.npz). Collective in multi-host runs; only ``root``
    writes the file. Writes atomically (fsync'ed tmp file + rename) so an
    interrupted save never corrupts an existing checkpoint."""
    import jax

    from ..ops.gather import gather

    check_initialized()
    t0 = time.monotonic()
    if not isinstance(state, dict) or not state:
        raise InvalidArgumentError(
            "save_checkpoint expects a non-empty dict of name -> array.")
    for k in state:
        if not isinstance(k, str) or k.startswith("__igg_"):
            raise InvalidArgumentError(
                f"Invalid state key {k!r}: keys must be strings not starting "
                "with '__igg_'.")
    gg = global_grid()
    # Gather every array on every process (collective), write on root only.
    hosts = {k: gather(v, root=root) for k, v in state.items()}
    if jax.process_index() == root:
        payload = {f"{_ARR_PREFIX}{k}": np.asarray(v) for k, v in hosts.items()}
        payload.update(_grid_meta(gg))
        if step is not None:
            payload[f"{_META_PREFIX}step"] = np.int64(step)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    # All processes synchronize on the completed write so an immediately
    # following restore_checkpoint never reads a stale/missing file on
    # non-root hosts (save/restore is documented as an SPMD-uniform pair).
    from .timing import barrier

    barrier()
    from ..telemetry import observe_checkpoint

    observe_checkpoint("save", time.monotonic() - t0, path=path, step=step)


def load_checkpoint(path):
    """Read a checkpoint file: returns ``(state, meta)`` with ``state`` a dict
    of numpy arrays (stacked layout) and ``meta`` the saved topology dict
    (keys: nxyz, dims, overlaps, periods, halowidths, step|None). Host-only —
    does not require an initialized grid."""
    if not os.path.exists(path):
        raise InvalidArgumentError(f"Checkpoint file not found: {path}")
    with np.load(path) as z:
        state = {k[len(_ARR_PREFIX):]: z[k] for k in z.files
                 if k.startswith(_ARR_PREFIX)}
        meta = {k[len(_META_PREFIX):]: z[k] for k in z.files
                if k.startswith(_META_PREFIX)}
    meta["step"] = int(meta["step"]) if "step" in meta else None
    return state, meta


def _validate_topology(meta: dict, gg, strict: bool,
                       required=()) -> None:
    """``required`` fields are validated even with ``strict=False`` (the
    sharded layout cannot reassemble across a different decomposition —
    its blocks are keyed by the saved block coordinates; the single-file
    path CAN reshard, hence its escape hatch)."""
    for name in ("nxyz", "dims", "overlaps", "periods", "halowidths"):
        hard = name in required
        if not strict and not hard:
            continue
        saved = meta.get(name)
        live = np.asarray(getattr(gg, name))
        if saved is None or not np.array_equal(np.asarray(saved), live):
            hint = ("Re-init the grid to match (sharded restore cannot "
                    "reshard; use the single-file restore_checkpoint, or "
                    "restore_checkpoint_elastic for a dims change)."
                    if hard else
                    "Re-init the grid to match or pass strict=False.")
            raise IncoherentArgumentError(
                f"Checkpoint topology mismatch for `{name}`: saved "
                f"{None if saved is None else list(np.asarray(saved))}, live "
                f"{list(live)}. {hint}"
            )


def save_checkpoint_sharded(dirpath, state: dict, *,
                            step: int | None = None) -> None:
    """Write ``state`` to directory ``dirpath`` with each process saving
    only its ADDRESSABLE shards (pod-scale path: no host gathers the
    global state). Collective; ATOMIC at the directory level: all files
    are staged into ``<dirpath>.tmp-<token>`` (fsync + per-file sha256
    sidecars) and the staging dir takes the final name only after
    ``meta.npz`` commits the complete set. Replacing an existing
    checkpoint moves it aside first, so ``dirpath`` is briefly absent
    during the swap — the resilient driver's double-buffered slots +
    pointer file (`runtime/driver.py`) cover that window; standalone users
    should alternate two directories for the same guarantee."""
    import jax

    from ..ops.alloc import device_put_g

    check_initialized()
    t0 = time.monotonic()
    _validate_block_keys(state, "save_checkpoint_sharded")
    gg = global_grid()
    pidx = jax.process_index()

    # One token per SAVE, shared by all processes: restore validates every
    # shard file against it, so a crash between one process's write and
    # another's can never silently mix blocks from two different saves.
    # The token also names the staging directory all processes write into.
    import secrets

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        token_arr = multihost_utils.broadcast_one_to_all(
            np.frombuffer(secrets.token_bytes(16), dtype=np.uint8))
        token = bytes(np.asarray(token_arr)).hex()
    else:
        token = secrets.token_hex(16)

    stage = f"{dirpath}.tmp-{token}"
    os.makedirs(stage, exist_ok=True)

    payload = {f"{_META_PREFIX}save_token": np.str_(token)}
    names, shapes, dtypes = [], {}, {}
    for k, v in state.items():
        if not hasattr(v, "addressable_shards"):  # host array: shard first
            v = device_put_g(v)
        names.append(k)
        shapes[f"{_META_PREFIX}shape__{k}"] = np.asarray(v.shape,
                                                         dtype=np.int64)
        dtypes[f"{_META_PREFIX}dtype__{k}"] = np.str_(str(v.dtype))
        # leading REPLICATED axes (the ensemble member axis, ISSUE 12):
        # recorded per array so restore rebuilds the true sharding — a
        # rank heuristic cannot tell a 2-D ensemble (E, x, y) from a
        # solo 3-D field, and mis-sharding the member axis over gx would
        # make every wanted block key miss the saved set
        lead = _leading_replicated_axes(v)
        if lead:
            shapes[f"{_META_PREFIX}lead__{k}"] = np.int64(lead)
        for s in v.addressable_shards:
            if getattr(s, "replica_id", 0) != 0:
                continue  # replicated shards: one copy is enough
            payload[_shard_key(k, _starts_of(s.index))] = np.asarray(s.data)

    _write_npz_synced(os.path.join(stage, f"shards_p{pidx}.npz"), payload)

    # Barrier BEFORE process 0 writes meta.npz: meta carries the new save
    # token, so it must be the COMMIT record of a fully-written shard set.
    # Without this, a crash while other processes are still writing leaves
    # a meta newer than some shards — detectable only at restore, with a
    # non-uniform failure across processes.
    from .timing import barrier

    barrier()

    if pidx == 0:
        meta = _grid_meta(gg)
        meta[f"{_META_PREFIX}names"] = np.asarray(names)
        meta[f"{_META_PREFIX}save_token"] = np.str_(token)
        meta[f"{_META_PREFIX}nprocs_files"] = np.int64(jax.process_count())
        meta[f"{_META_PREFIX}checksums"] = np.str_("sha256")
        meta.update(shapes)
        meta.update(dtypes)
        if step is not None:
            meta[f"{_META_PREFIX}step"] = np.int64(step)
        _write_npz_synced(os.path.join(stage, "meta.npz"), meta)
        # Commit: the complete staging dir takes the final name in one
        # rename (`blockio.commit_staged_dir`, shared with the snapshot
        # writer) — stale shard files from an earlier save with MORE
        # processes can no longer shadow the new state.
        _commit_staged_dir(stage, dirpath, token)

    # Final barrier: no process returns (and possibly starts the NEXT
    # save, or reports the checkpoint usable) before the commit rename.
    barrier()
    from ..telemetry import observe_checkpoint

    observe_checkpoint("save_sharded", time.monotonic() - t0, path=dirpath,
                       step=step)


def _leading_replicated_axes(v) -> int:
    """Number of LEADING replicated (``None``-spec) axes of a sharded
    array — the ensemble member axes of `models.common.ensemble_state`'s
    layout (0 for every solo field). Unknown sharding kinds report 0 (the
    historical behavior)."""
    spec = getattr(getattr(v, "sharding", None), "spec", None)
    if spec is None:
        return 0
    lead = 0
    for entry in tuple(spec):
        if entry is not None:
            break
        lead += 1
    return lead


def _restore_sharding(meta, name, shape):
    """The sharding a restored array takes: the recorded leading
    replicated axes (member axes) ahead of the mesh-axis sharding of the
    remaining rank; without the record, the rank-based default
    (`sharding_of`)."""
    import jax

    from ..ops.alloc import sharding_of
    from ..parallel.topology import AXIS_NAMES, global_grid

    lead = int(meta.get(f"lead__{name}", 0))
    if not lead:
        return sharding_of(len(shape))
    from jax.sharding import PartitionSpec as P

    gg = global_grid()
    spec = P(*([None] * lead), *AXIS_NAMES[:len(shape) - lead])
    return jax.sharding.NamedSharding(gg.mesh, spec)


def _sharded_meta_and_files(dirpath):
    """Shared open/validate path of the sharded restores: load ``meta.npz``,
    enumerate exactly the shard files the save wrote (own file first),
    token-check every one, and checksum-verify meta plus this process's
    own file up front. Returns ``(meta, files, checksums_required,
    verified)``. Full-content checksums of OTHER processes' files are
    deferred to scan time (`_block_scanner`) — an up-front whole-set
    verification would read O(total bytes) on EVERY process, which the
    pod-scale path cannot afford; every byte actually used is still
    verified before use."""
    import glob as _glob

    import jax

    meta = _load_meta(dirpath)
    checksums = "checksums" in meta

    pidx = jax.process_index()
    # The meta records how many shard files the save wrote; read EXACTLY
    # those (a bare glob could pick up stale files from an earlier save
    # with more processes and silently restore old-step blocks).
    n_files = int(meta.get("nprocs_files", 0)) or len(
        _glob.glob(os.path.join(dirpath, "shards_p*.npz")))
    files = [os.path.join(dirpath, f"shards_p{i}.npz")
             for i in range(n_files)]
    missing = [f for f in files if not os.path.exists(f)]
    if not files or missing:
        raise InvalidArgumentError(
            f"Sharded checkpoint in {dirpath} is incomplete: expected "
            f"{n_files} shard file(s), missing {missing or 'all'}.")
    own = os.path.join(dirpath, f"shards_p{pidx}.npz")
    if own in files:  # own file first: the no-remap fast path
        files.remove(own)
        files.insert(0, own)

    # Token-check EVERY shard file up front (cheap: npz loads members
    # lazily, so this reads one tiny array per file), not just the files
    # this process happens to scan for blocks.  A lazy per-scan check is
    # non-SPMD-uniform: after an interrupted save, a process whose blocks
    # all sit in its own (valid) shard file would restore successfully
    # while others raise — hanging the multi-host run at the next
    # collective instead of failing cleanly on every process.
    expect_token = str(meta["save_token"]) if "save_token" in meta else None
    token_key = f"{_META_PREFIX}save_token"
    if expect_token is not None:
        for path in files:
            try:
                with np.load(path) as z:
                    ftok = str(z[token_key]) if token_key in z.files else None
            except Exception as e:
                # unreadable container: name the real cause if the content
                # checksum disagrees (truncation), else surface the error
                _verify_checksum(path, required=checksums)
                raise IncoherentArgumentError(
                    f"Unreadable shard file {path}: {e!r}") from e
            if ftok != expect_token:
                raise IncoherentArgumentError(
                    f"Shard file {path} belongs to a different save than "
                    "meta.npz (save-token mismatch) — the save was "
                    "interrupted; do not resume from this checkpoint.")

    verified = set()
    if own in files:
        _verify_checksum(own, required=checksums)
        verified.add(own)
    return meta, files, checksums, verified


def restore_checkpoint_sharded(dirpath, *, strict: bool = True,
                               _preloaded=None):
    """Load a `save_checkpoint_sharded` directory and reassemble every
    array on the live mesh from block coordinates — each process reads its
    own shard file first and scans the others only for blocks it misses,
    so no process ever holds the global state. Every file read is verified
    against its content checksum first. Returns ``(state, step)``.

    ``_preloaded`` carries an already-built `_sharded_meta_and_files`
    result (the elastic restore's dims-match delegation — re-running the
    open/token/checksum pass would double the restore I/O)."""
    import jax

    check_initialized()
    t0 = time.monotonic()
    gg = global_grid()
    meta, files, checksums, verified = (
        _preloaded if _preloaded is not None
        else _sharded_meta_and_files(dirpath))
    # nxyz/dims are REQUIRED even with strict=False: blocks are keyed by
    # the saved block coordinates, so a different decomposition cannot be
    # reassembled here (use restore_checkpoint_elastic for that).
    _validate_topology(meta, gg, strict, required=("nxyz", "dims"))
    names = [str(n) for n in meta["names"]]
    step = int(meta["step"]) if "step" in meta else None

    # Every block THIS process needs, across all arrays — scanning loads
    # only these keys and each is dropped once consumed.
    plans = {}
    wanted: set = set()
    for name in names:
        shape = tuple(int(s) for s in meta[f"shape__{name}"])
        dtype = np.dtype(str(meta[f"dtype__{name}"]))
        sharding = _restore_sharding(meta, name, shape)
        needed = sharding.addressable_devices_indices_map(shape)
        plans[name] = (shape, dtype, sharding, needed)
        wanted |= {_shard_key(name, _starts_of(idx))
                   for idx in needed.values()}

    find_block = _block_scanner(files, wanted, checksums, verified)

    out = {}
    for name in names:
        shape, dtype, sharding, needed = plans[name]
        # several devices can need the SAME block (mesh axes the field is
        # not sharded over are replicated): fetch once, place on each
        by_key: dict = {}
        for dev, idx in needed.items():
            by_key.setdefault(_shard_key(name, _starts_of(idx)),
                              []).append(dev)
        arrays = []
        for key, devs in by_key.items():
            block = np.asarray(find_block(key), dtype=dtype)
            arrays.extend(jax.device_put(block, dev) for dev in devs)
        out[name] = jax.make_array_from_single_device_arrays(
            shape, sharding, arrays)
    from ..telemetry import observe_checkpoint

    observe_checkpoint("restore_sharded", time.monotonic() - t0,
                       path=dirpath, step=step)
    return out, step


# ---------------------------------------------------------------------------
# Elastic restore: same implicit global grid, different decomposition
# ---------------------------------------------------------------------------

def saved_topology(dirpath) -> dict:
    """Host-only read of a sharded checkpoint's saved grid topology:
    ``{nxyz, dims, overlaps, periods, halowidths, step}`` — no initialized
    grid required (the elastic-restart path reads it to size and re-init
    the grid BEFORE restoring, `runtime/recovery.py`)."""
    meta = _load_meta(dirpath)
    out = {name: np.asarray(meta[name], dtype=np.int64)
           for name in ("nxyz", "dims", "overlaps", "periods", "halowidths")}
    out["step"] = int(meta["step"]) if "step" in meta else None
    return out


def elastic_local_size(topo: dict, new_dims) -> tuple:
    """LOCAL block size ``(nx, ny, nz)`` that decomposes the SAME implicit
    global grid as ``topo`` (a `saved_topology` record) over ``new_dims``:
    inverts ``nxyz_g = dims*(nxyz-overlaps) + overlaps*(periods==0)`` per
    dimension. Raises when the interior does not divide evenly over the
    requested decomposition."""
    nxyz = np.asarray(topo["nxyz"], dtype=np.int64)
    dims = np.asarray(topo["dims"], dtype=np.int64)
    ol = np.asarray(topo["overlaps"], dtype=np.int64)
    per = np.asarray(topo["periods"], dtype=np.int64)
    new_dims = np.asarray(new_dims, dtype=np.int64)
    nxyz_g = dims * (nxyz - ol) + ol * (per == 0)
    out = []
    for d in range(3):
        interior = int(nxyz_g[d]) - (int(ol[d]) if not per[d] else 0)
        nd = int(new_dims[d])
        if nd < 1 or interior % nd:
            raise IncoherentArgumentError(
                f"Cannot redistribute dimension {d}: global interior "
                f"{interior} does not divide evenly over new dims[{d}]="
                f"{nd}.")
        out.append(interior // nd + int(ol[d]))
    return tuple(out)


class AxisRedistribution:
    """Per-dimension owner/coverage maps of the elastic re-blocking.

    Physical index space: the `gather_interior` convention — a periodic
    dim has ``NG = dims*(n-ol)`` cells and every block OWNS ``n-ol`` of
    them (its ghost cells wrap); a non-periodic dim has
    ``NG = dims*(n-ol)+ol`` with the LAST block owning its full extent.
    ``c_of[p]``/``i_of[p]`` give the saved block and block-local index
    owning physical cell ``p``; `new_phys(c)` gives the physical index of
    every local cell of live block ``c`` — so the reassembly is
    ``new_block[i] = saved_block[c_of[g[i]]][i_of[g[i]]]`` with
    ``g = new_phys(c)``. Because overlap cells are fetched from their
    interior OWNER, a checkpoint with exchange-fresh halos (what the
    framework runners produce) restores bit-identical to laying the
    gathered global field out over the new decomposition."""

    def __init__(self, n_old: int, n_new: int, dd_old: int, dd_new: int,
                 ol_f: int, per: bool):
        s_o, s_n = n_old - ol_f, n_new - ol_f
        if per:
            ng_o, ng_n = dd_old * s_o, dd_new * s_n
        else:
            ng_o, ng_n = dd_old * s_o + ol_f, dd_new * s_n + ol_f
        if ng_o != ng_n:
            raise IncoherentArgumentError(
                f"Elastic restore: saved axis covers {ng_o} global cells, "
                f"the live one {ng_n} — the decompositions describe "
                "different global grids (staggering changed?).")
        self.ng = ng_o
        p = np.arange(self.ng)
        if per:
            c = p // s_o
            i = p - c * s_o + 1
        else:
            c = np.minimum(p // s_o, dd_old - 1)
            i = p - c * s_o
        self.c_of, self.i_of = c, i
        self._s_n, self._n_new, self._per = s_n, n_new, per

    def new_phys(self, c: int) -> np.ndarray:
        i = np.arange(self._n_new)
        if self._per:
            return (c * self._s_n + i - 1) % self.ng
        return c * self._s_n + i


class _IdentityAxis:
    """Degenerate axis map for LEADING MEMBER axes (the ensemble axis):
    replicated, never decomposed — every cell owned by 'block' 0 at its
    own index, so the redistribution passes the axis through untouched
    (ROADMAP ensemble rung c: elastic restart for batched runs)."""

    def __init__(self, n: int):
        self.ng = int(n)
        self.c_of = np.zeros(self.ng, dtype=np.int64)
        self.i_of = np.arange(self.ng)

    def new_phys(self, c: int) -> np.ndarray:
        return np.arange(self.ng)


def restore_checkpoint_elastic(dirpath):
    """Restore a `save_checkpoint_sharded` directory onto a grid whose
    ``dims`` DIFFER from the saved decomposition — the elastic-restart
    path. The same implicit global grid is re-blocked: every live block is
    assembled from the saved blocks overlapping its physical cells (block
    coordinates, per-axis owner maps — the array-redistribution problem of
    arXiv:2112.01075 restricted to Cartesian re-blocking), and each
    process reads ONLY the saved blocks its own addressable shards need,
    keeping the pod-scale guarantee.

    Requires equal ``overlaps``/``periods``/``halowidths`` and the same
    implicit global size (`elastic_local_size` computes the local block
    size to re-init with); a live grid equal to the saved one delegates to
    the plain block-keyed restore. Member-stacked (ensemble) state
    re-blocks too: the recorded leading member axes are passed through
    untouched (each member's cells redistribute exactly like a solo
    field's — per-member bit-identity asserted in tests), so
    `ProcessLoss` recovery and `ResilientRun.resize` work under
    ``ensemble=E``. Returns ``(state, step)``."""
    import itertools

    import jax

    check_initialized()
    t0 = time.monotonic()
    gg = global_grid()
    meta, files, checksums, verified = _sharded_meta_and_files(dirpath)
    names = [str(n) for n in meta["names"]]
    step = int(meta["step"]) if "step" in meta else None

    dims_o = np.asarray(meta["dims"], dtype=np.int64)
    nxyz_o = np.asarray(meta["nxyz"], dtype=np.int64)
    if np.array_equal(dims_o, np.asarray(gg.dims)) and \
            np.array_equal(nxyz_o, np.asarray(gg.nxyz)):
        return restore_checkpoint_sharded(
            dirpath, _preloaded=(meta, files, checksums, verified))
    for field in ("overlaps", "periods", "halowidths"):
        if not np.array_equal(np.asarray(meta[field]),
                              np.asarray(getattr(gg, field))):
            raise IncoherentArgumentError(
                f"Elastic restore requires equal `{field}` (saved "
                f"{list(np.asarray(meta[field]))}, live "
                f"{list(np.asarray(getattr(gg, field)))}): only the "
                "decomposition may change.")
    ol = np.asarray(gg.overlaps, dtype=np.int64)
    per = np.asarray(gg.periods, dtype=np.int64)
    saved_g = dims_o * (nxyz_o - ol) + ol * (per == 0)
    if not np.array_equal(saved_g, np.asarray(gg.nxyz_g)):
        raise IncoherentArgumentError(
            f"Elastic restore: saved implicit global grid {list(saved_g)} "
            f"differs from the live one {list(np.asarray(gg.nxyz_g))}; "
            "re-init with elastic_local_size(saved_topology(dir), dims).")

    # Per-field plans: for each addressable live block, the saved blocks
    # covering its physical cells and the index maps placing them.
    plans = {}
    wanted: set = set()
    for name in names:
        shape_o = tuple(int(s) for s in meta[f"shape__{name}"])
        dtype = np.dtype(str(meta[f"dtype__{name}"]))
        nd = len(shape_o)
        # leading member axes (ensemble state): replicated, re-blocking
        # skips them — the per-axis maps below reason over the SPATIAL
        # axes only and every member's cells travel with its slice
        lead = int(meta.get(f"lead__{name}", 0))
        loc_o, loc_n, axes = [], [], []
        for d in range(nd):
            if d < lead:
                axes.append(_IdentityAxis(shape_o[d]))
                loc_o.append(shape_o[d])
                loc_n.append(shape_o[d])
                continue
            sd = d - lead
            dd_o = int(dims_o[sd])
            if shape_o[d] % dd_o:
                raise IncoherentArgumentError(
                    f"Saved stacked size {shape_o[d]} of `{name}` along "
                    f"dimension {sd} is not divisible by the saved "
                    f"dims[{sd}]={dd_o}.")
            lo = shape_o[d] // dd_o
            stag = lo - int(nxyz_o[sd])     # staggered fields carry their
            ln = int(gg.nxyz[sd]) + stag    # extra cells to the new blocks
            axes.append(AxisRedistribution(
                lo, ln, dd_o, int(gg.dims[sd]),
                int(ol[sd]) + stag, bool(per[sd])))
            loc_o.append(lo)
            loc_n.append(ln)
        shape_n = tuple(loc_n[d] if d < lead
                        else int(gg.dims[d - lead]) * loc_n[d]
                        for d in range(nd))
        sharding = _restore_sharding(meta, name, shape_n)
        needed = sharding.addressable_devices_indices_map(shape_n)
        by_start: dict = {}
        for dev, idx in needed.items():
            by_start.setdefault(_starts_of(idx), []).append(dev)
        blockplans = []
        for starts, devs in by_start.items():
            per_axis = []
            for d in range(nd):
                g = axes[d].new_phys(starts[d] // loc_n[d])
                per_axis.append((axes[d].c_of[g], axes[d].i_of[g]))
            pieces = []
            for co in itertools.product(
                    *[np.unique(pa[0]) for pa in per_axis]):
                sel_new, sel_old = [], []
                for d in range(nd):
                    c_of, i_of = per_axis[d]
                    jj = np.nonzero(c_of == co[d])[0]
                    sel_new.append(jj)
                    sel_old.append(i_of[jj])
                key = _shard_key(
                    name, tuple(int(co[d]) * loc_o[d] for d in range(nd)))
                pieces.append((key, sel_new, sel_old))
                wanted.add(key)
            blockplans.append((devs, pieces))
        plans[name] = (shape_n, dtype, sharding, tuple(loc_n), blockplans)

    # pop=False: one saved block can source several live blocks (and the
    # same live block via replicated mesh axes); cached blocks stay
    # bounded by this process' shard volume plus its overlap neighbors.
    find_block = _block_scanner(files, wanted, checksums, verified,
                                pop=False)

    out = {}
    for name in names:
        shape_n, dtype, sharding, loc_n, blockplans = plans[name]
        arrays = []
        for devs, pieces in blockplans:
            block = np.empty(loc_n, dtype=dtype)
            for key, sel_new, sel_old in pieces:
                src = np.asarray(find_block(key), dtype=dtype)
                block[np.ix_(*sel_new)] = src[np.ix_(*sel_old)]
            arrays.extend(jax.device_put(block, dev) for dev in devs)
        out[name] = jax.make_array_from_single_device_arrays(
            shape_n, sharding, arrays)
    from ..telemetry import observe_checkpoint

    observe_checkpoint("restore_elastic", time.monotonic() - t0,
                       path=dirpath, step=step,
                       saved_dims=[int(d) for d in dims_o],
                       live_dims=[int(d) for d in np.asarray(gg.dims)])
    return out, step


def restore_checkpoint(path, *, strict: bool = True):
    """Load ``path`` and re-shard every array onto the live grid's mesh.

    Returns ``(state, step)`` with ``state`` a dict of stacked global
    `jax.Array`s. With ``strict`` (default) the saved topology (``nxyz, dims,
    overlaps, periods, halowidths``) must match the live grid exactly;
    ``strict=False`` skips the check (e.g. resuming onto a different
    decomposition of the same global grid — caller's responsibility)."""
    from ..ops.alloc import device_put_g

    check_initialized()
    t0 = time.monotonic()
    gg = global_grid()
    state, meta = load_checkpoint(path)
    _validate_topology(meta, gg, strict)
    out = {k: device_put_g(v) for k, v in state.items()}
    from ..telemetry import observe_checkpoint

    observe_checkpoint("restore", time.monotonic() - t0, path=path,
                       step=meta["step"])
    return out, meta["step"]
