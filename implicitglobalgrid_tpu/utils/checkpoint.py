"""Checkpoint/resume for distributed grid state.

The reference has NO checkpointing (SURVEY §5.4): `gather!` is the provided
IO primitive (`/root/reference/src/gather.jl`) and users handle files. Here
checkpointing is first-class: functional state (stacked global `jax.Array`s)
plus the recorded grid topology make save/restore a pair of calls::

    igg.save_checkpoint("ckpt.npz", {"T": T, "Cp": Cp}, step=it)
    state, step = igg.restore_checkpoint("ckpt.npz")     # arrays re-sharded
    T, Cp = state["T"], state["Cp"]

Format: one `.npz` (portable, numpy-readable anywhere) holding the gathered
stacked arrays plus the grid topology (`nxyz`, `dims`, `overlaps`, `periods`,
`halowidths`). `restore_checkpoint` validates the topology against the live
grid and re-shards each array onto the current mesh (`device_put_g`), so a
run can resume on different hardware with the same decomposition. In
multi-host runs the gather is collective (every process must call save) and
only the ``root`` process writes; restore is SPMD-uniform.
"""

from __future__ import annotations

import os

import numpy as np

from ..parallel.topology import check_initialized, global_grid
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError

__all__ = ["save_checkpoint", "restore_checkpoint", "load_checkpoint"]

_META_PREFIX = "__igg_meta__"
_ARR_PREFIX = "__igg_arr__"


def _grid_meta(gg) -> dict:
    return {
        f"{_META_PREFIX}nxyz": np.asarray(gg.nxyz, dtype=np.int64),
        f"{_META_PREFIX}dims": np.asarray(gg.dims, dtype=np.int64),
        f"{_META_PREFIX}overlaps": np.asarray(gg.overlaps, dtype=np.int64),
        f"{_META_PREFIX}periods": np.asarray(gg.periods, dtype=np.int64),
        f"{_META_PREFIX}halowidths": np.asarray(gg.halowidths, dtype=np.int64),
    }


def save_checkpoint(path, state: dict, *, step: int | None = None,
                    root: int = 0) -> None:
    """Write ``state`` (a dict name -> stacked global array) and the grid
    topology to ``path`` (.npz). Collective in multi-host runs; only ``root``
    writes the file. Writes atomically (tmp file + rename) so an interrupted
    save never corrupts an existing checkpoint."""
    import jax

    from ..ops.gather import gather

    check_initialized()
    if not isinstance(state, dict) or not state:
        raise InvalidArgumentError(
            "save_checkpoint expects a non-empty dict of name -> array.")
    for k in state:
        if not isinstance(k, str) or k.startswith("__igg_"):
            raise InvalidArgumentError(
                f"Invalid state key {k!r}: keys must be strings not starting "
                "with '__igg_'.")
    gg = global_grid()
    # Gather every array on every process (collective), write on root only.
    hosts = {k: gather(v, root=root) for k, v in state.items()}
    if jax.process_index() == root:
        payload = {f"{_ARR_PREFIX}{k}": np.asarray(v) for k, v in hosts.items()}
        payload.update(_grid_meta(gg))
        if step is not None:
            payload[f"{_META_PREFIX}step"] = np.int64(step)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    # All processes synchronize on the completed write so an immediately
    # following restore_checkpoint never reads a stale/missing file on
    # non-root hosts (save/restore is documented as an SPMD-uniform pair).
    from .timing import barrier

    barrier()


def load_checkpoint(path):
    """Read a checkpoint file: returns ``(state, meta)`` with ``state`` a dict
    of numpy arrays (stacked layout) and ``meta`` the saved topology dict
    (keys: nxyz, dims, overlaps, periods, halowidths, step|None). Host-only —
    does not require an initialized grid."""
    if not os.path.exists(path):
        raise InvalidArgumentError(f"Checkpoint file not found: {path}")
    with np.load(path) as z:
        state = {k[len(_ARR_PREFIX):]: z[k] for k in z.files
                 if k.startswith(_ARR_PREFIX)}
        meta = {k[len(_META_PREFIX):]: z[k] for k in z.files
                if k.startswith(_META_PREFIX)}
    meta["step"] = int(meta["step"]) if "step" in meta else None
    return state, meta


def restore_checkpoint(path, *, strict: bool = True):
    """Load ``path`` and re-shard every array onto the live grid's mesh.

    Returns ``(state, step)`` with ``state`` a dict of stacked global
    `jax.Array`s. With ``strict`` (default) the saved topology (``nxyz, dims,
    overlaps, periods, halowidths``) must match the live grid exactly;
    ``strict=False`` skips the check (e.g. resuming onto a different
    decomposition of the same global grid — caller's responsibility)."""
    from ..ops.alloc import device_put_g

    check_initialized()
    gg = global_grid()
    state, meta = load_checkpoint(path)
    if strict:
        for name in ("nxyz", "dims", "overlaps", "periods", "halowidths"):
            saved = meta.get(name)
            live = np.asarray(getattr(gg, name))
            if saved is None or not np.array_equal(np.asarray(saved), live):
                raise IncoherentArgumentError(
                    f"Checkpoint topology mismatch for `{name}`: saved "
                    f"{None if saved is None else list(np.asarray(saved))}, live "
                    f"{list(live)}. Re-init the grid to match or pass strict=False."
                )
    out = {k: device_put_g(v) for k, v in state.items()}
    return out, meta["step"]
