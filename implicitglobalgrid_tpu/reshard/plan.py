"""The redistribution plan: Cartesian re-blocking as scheduled slice rounds.

An elastic ``dims`` change re-blocks the SAME implicit global grid over a
different Cartesian decomposition. The checkpoint-based elastic restore
(`utils.checkpoint.restore_checkpoint_elastic`) already derives exactly
which saved block sources every cell of every new block — per-axis
owner/coverage maps built from the implicit-global-grid formula (the
``x_g``-style block-coordinate arithmetic). This module reuses THAT math
(`utils.checkpoint.AxisRedistribution` — one copy, so the two paths can
never diverge) but compiles the answer into a **transfer plan** instead of
host file reads: the exact set of (source block, destination block,
sub-box) pieces, scheduled into **rounds** where every device sends at
most one slab and receives at most one slab — each round is a partial
permutation, i.e. ONE ``lax.ppermute`` over a flat mesh axis
(`reshard.program` compiles it), and peak HBM per device stays bounded by
one padded send slab + one receive slab + the destination block
regardless of how skewed the re-blocking is (the round-scheduling idea of
memory-efficient array redistribution, arXiv:2112.01075).

Everything here is host-side numpy — a plan (and its
`reshard_contract`) can be built, priced (`telemetry.predict_reshard`)
and golden-fixture-audited on a machine with no accelerator runtime.

Conventions:

- Block ranks are row-major over the FULL 3-D ``dims`` (the linearized
  mesh positions JAX emits in ``source_target_pairs``); fields of lower
  spatial rank pad their missing coordinates with 0 (their primary
  replica's position).
- Flat-program slots are IDENTITY-mapped to ranks: destination rank ``q``
  lives at flat slot ``q``, source rank ``r`` at slot ``r``
  (``n_flat = max(prod(src_dims), prod(dst_dims))``). The program layer
  places blocks accordingly, so the plan alone determines the legal
  ``source_target_pairs`` of every round — what makes the collective
  contract derivable host-only.
- Pieces with ``src_rank == dst_rank`` never touch the wire: they are
  scheduled as LOCAL rounds (in-HBM copies on the owning device).
- Leading member axes (the ensemble axis, ISSUE 12) are passed through
  untouched: the per-axis maps reason over the spatial axes only and
  every payload simply carries all ``E`` members' slabs (same rounds,
  E x bytes — exactly the ensemble wire amortization).
"""

from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..utils.checkpoint import AxisRedistribution, elastic_local_size
from ..utils.exceptions import IncoherentArgumentError, InvalidArgumentError

__all__ = ["Piece", "Round", "SigPlan", "ReshardPlan",
           "build_reshard_plan", "live_topology", "fields_of_state",
           "apply_plan_host", "reshard_contract",
           "device_pool", "init_from_topology", "restore_topology"]

_NDIMS = 3


@dataclass(frozen=True)
class Piece:
    """One (source block -> destination block) sub-box transfer.

    ``src_start``/``dst_start``/``size`` are per-SPATIAL-axis tuples in
    each block's local coordinates (leading member axes excluded — they
    ride whole)."""

    src_rank: int
    dst_rank: int
    src_start: tuple
    dst_start: tuple
    size: tuple

    @property
    def cells(self) -> int:
        n = 1
        for s in self.size:
            n *= int(s)
        return n


@dataclass(frozen=True)
class Round:
    """One scheduled slice round = one ``ppermute``: a partial permutation
    (every source and every destination appears at most once), payload
    padded to ``pad`` (the elementwise max piece size of the round)."""

    pairs: tuple            # ((src_slot, dst_slot), ...) sorted by src
    pieces: tuple           # the Piece set this round carries
    pad: tuple              # per-spatial-axis padded payload extent


@dataclass(frozen=True)
class SigPlan:
    """All fields sharing one block signature (same spatial local shape,
    dtype, and leading member axes) move through the SAME rounds with
    their slabs stacked into one payload — fields in a round coalesce,
    exactly like the halo wire."""

    names: tuple            # field names, canonical order
    dtype: str              # numpy dtype name
    lead: tuple             # leading (member) axis sizes, () for solo
    src_block: tuple        # spatial local block on the source dims
    dst_block: tuple        # spatial local block on the destination dims
    rounds: tuple           # wire rounds (Round)
    local: tuple            # Piece list with src_rank == dst_rank

    @property
    def lead_cells(self) -> int:
        n = 1
        for s in self.lead:
            n *= int(s)
        return n

    def _mult(self) -> int:
        return self.lead_cells * len(self.names) * np.dtype(self.dtype).itemsize

    @property
    def wire_bytes(self) -> int:
        """Exact all-links bytes on wire: padded payload x directed pairs
        per round (what `ProgramIR.wire_bytes_of` measures in the
        compiled program)."""
        m = self._mult()
        return sum(int(np.prod(r.pad, dtype=np.int64)) * len(r.pairs) * m
                   for r in self.rounds)

    @property
    def payload_bytes(self) -> int:
        """Useful (unpadded) bytes the wire pieces carry."""
        m = self._mult()
        return sum(p.cells * m for r in self.rounds for p in r.pieces)

    @property
    def local_bytes(self) -> int:
        m = self._mult()
        return sum(p.cells * m for p in self.local)

    @property
    def round_payload_bytes(self) -> list:
        """Per-round padded payload bytes PER DEVICE (the peak-HBM and
        link-time unit `telemetry.predict_reshard` prices)."""
        m = self._mult()
        return [int(np.prod(r.pad, dtype=np.int64)) * m for r in self.rounds]


@dataclass(frozen=True)
class ReshardPlan:
    """The complete (src_dims -> dst_dims) transfer program description."""

    src_dims: tuple
    dst_dims: tuple
    nxyz_src: tuple         # base local block on the source decomposition
    nxyz_dst: tuple
    overlaps: tuple
    periods: tuple
    halowidths: tuple
    n_flat: int             # flat-mesh extent = max(prod(src), prod(dst))
    sigs: tuple = dc_field(default_factory=tuple)

    @property
    def rounds(self) -> int:
        return sum(len(s.rounds) for s in self.sigs)

    @property
    def wire_bytes(self) -> int:
        return sum(s.wire_bytes for s in self.sigs)

    @property
    def payload_bytes(self) -> int:
        return sum(s.payload_bytes for s in self.sigs)

    @property
    def local_bytes(self) -> int:
        return sum(s.local_bytes for s in self.sigs)

    @property
    def peak_payload_bytes(self) -> int:
        """Largest single-round per-device payload — with the destination
        block, the plan's peak-HBM bound per device."""
        per_round = [b for s in self.sigs for b in s.round_payload_bytes]
        return max(per_round) if per_round else 0

    def stats(self) -> dict:
        return {"rounds": self.rounds, "wire_bytes": self.wire_bytes,
                "payload_bytes": self.payload_bytes,
                "local_bytes": self.local_bytes,
                "peak_payload_bytes": self.peak_payload_bytes,
                "n_flat": self.n_flat,
                "src_dims": list(self.src_dims),
                "dst_dims": list(self.dst_dims)}

    def to_json(self) -> dict:
        return {
            **self.stats(),
            "nxyz_src": list(self.nxyz_src), "nxyz_dst": list(self.nxyz_dst),
            "overlaps": list(self.overlaps), "periods": list(self.periods),
            "halowidths": list(self.halowidths),
            "sigs": [{
                "names": list(s.names), "dtype": s.dtype,
                "lead": list(s.lead),
                "src_block": list(s.src_block),
                "dst_block": list(s.dst_block),
                "wire_bytes": s.wire_bytes,
                "local_pieces": len(s.local),
                "rounds": [{
                    "pairs": [list(p) for p in r.pairs],
                    "pad": list(r.pad),
                    "pieces": len(r.pieces),
                } for r in s.rounds],
            } for s in self.sigs],
        }

    def fingerprint(self) -> str:
        """Stable identity for program caching."""
        import hashlib

        return hashlib.sha1(
            json.dumps(self.to_json(), sort_keys=True).encode()).hexdigest()


# ---------------------------------------------------------------------------
# plan construction
# ---------------------------------------------------------------------------

def live_topology(gg=None) -> dict:
    """The `saved_topology`-shaped record of the LIVE grid — what
    `build_reshard_plan` takes as its source side (the on-device analog
    of reading a checkpoint's meta)."""
    from ..parallel.topology import global_grid

    gg = gg if gg is not None else global_grid()
    return {name: np.asarray(getattr(gg, name), dtype=np.int64).copy()
            for name in ("nxyz", "dims", "overlaps", "periods",
                         "halowidths")}


def device_pool(gg):
    """The flat device pool a re-block may target — the grid's own
    device type when that backend is still answering, every visible
    device otherwise. One resolver for `reshard_state` and the driver's
    `resize` pre-check, so the two can never size the pool differently."""
    import jax

    try:
        return jax.devices(gg.device_type) \
            if gg.device_type not in ("none",) else jax.devices()
    except RuntimeError:
        return jax.devices()


def init_from_topology(topo: dict, *, nxyz=None, dims=None,
                       quiet: bool = True):
    """(Re-)init the global grid described by a `live_topology` /
    `saved_topology` dict, optionally onto different ``dims`` with the
    matching LOCAL ``nxyz`` (`init_global_grid` takes per-process
    sizes). The ONE grid rebuild used by the reshard forward path and
    every source-grid-restore recovery handler, so recovery re-inits
    cannot drift from the forward one."""
    from ..parallel.grid import init_global_grid

    nx = [int(n) for n in (topo["nxyz"] if nxyz is None else nxyz)]
    d = [int(x) for x in (topo["dims"] if dims is None else dims)]
    p = [int(x) for x in topo["periods"]]
    init_global_grid(
        nx[0], nx[1], nx[2], dimx=d[0], dimy=d[1], dimz=d[2],
        periodx=p[0], periody=p[1], periodz=p[2],
        overlaps=tuple(int(o) for o in topo["overlaps"]),
        halowidths=tuple(int(h) for h in topo["halowidths"]),
        quiet=quiet)


def restore_topology(topo: dict, *, quiet: bool = True) -> None:
    """Best effort: tear down whatever grid is live (if any) and put the
    ``topo`` grid back. For recovery handlers only — swallows its own
    failures so the original exception stays the one the caller sees."""
    from ..parallel.grid import finalize_global_grid
    from ..parallel.topology import grid_is_initialized

    try:
        if grid_is_initialized():
            finalize_global_grid()
        init_from_topology(topo, quiet=quiet)
    except Exception:
        pass


def fields_of_state(state: dict) -> dict:
    """``name -> (stacked shape, dtype name, leading member axes)`` of a
    driver state dict — the field description `build_reshard_plan`
    consumes. Leading replicated axes are read from each array's
    sharding (`utils.checkpoint._leading_replicated_axes` — the ensemble
    member axes)."""
    from ..utils.checkpoint import _leading_replicated_axes

    out = {}
    for k, v in state.items():
        out[k] = (tuple(int(s) for s in v.shape), str(np.dtype(v.dtype)),
                  int(_leading_replicated_axes(v)))
    return out


def _ravel(coords, dims) -> int:
    c = list(coords) + [0] * (_NDIMS - len(coords))
    return int(np.ravel_multi_index(c, dims))


def _axis_runs(ax, c_new: int) -> list:
    """Contiguous (src_block, dst_start, src_start, length) runs covering
    destination block ``c_new`` along one axis: segments of `new_phys`
    where the owner block is constant AND the owner-local index advances
    by 1 (a periodic wrap or an owner change starts a new run, so every
    run is a plain contiguous slice on both sides)."""
    g = ax.new_phys(c_new)
    c_of, i_of = ax.c_of[g], ax.i_of[g]
    runs = []
    j0 = 0
    for j in range(1, len(g) + 1):
        if j == len(g) or c_of[j] != c_of[j0] or i_of[j] != i_of[j - 1] + 1:
            runs.append((int(c_of[j0]), j0, int(i_of[j0]), j - j0))
            j0 = j
    return runs


def _schedule_rounds(pieces) -> tuple:
    """Greedy edge coloring of the transfer multigraph: each round is a
    partial permutation (every src and dst at most once). Deterministic
    (pieces arrive sorted), and within a factor of the max degree of
    optimal — the degree bound is what bounds peak HBM and round count."""
    rounds: list = []        # [(used_src, used_dst, [pieces])]
    for p in pieces:
        for used_src, used_dst, members in rounds:
            if p.src_rank not in used_src and p.dst_rank not in used_dst:
                used_src.add(p.src_rank)
                used_dst.add(p.dst_rank)
                members.append(p)
                break
        else:
            rounds.append(({p.src_rank}, {p.dst_rank}, [p]))
    out = []
    for _, _, members in rounds:
        members.sort(key=lambda p: p.src_rank)
        nd = len(members[0].size)
        pad = tuple(max(int(p.size[d]) for p in members) for d in range(nd))
        pairs = tuple((p.src_rank, p.dst_rank) for p in members)
        out.append(Round(pairs=pairs, pieces=tuple(members), pad=pad))
    return tuple(out)


def build_reshard_plan(topo: dict, new_dims, fields: dict) -> ReshardPlan:
    """Derive the HBM-to-HBM transfer plan re-blocking ``fields`` from the
    decomposition in ``topo`` (a `live_topology`/`saved_topology` record)
    onto ``new_dims`` — same implicit global grid, same overlaps/periods.

    ``fields`` maps names to ``(stacked shape, dtype, lead)`` (see
    `fields_of_state`): shapes are the SOURCE-decomposition stacked
    layouts, staggered fields carrying their extra cells exactly as in
    the elastic restore. Raises `IncoherentArgumentError` when
    ``new_dims`` cannot decompose the global grid evenly."""
    src_dims = tuple(int(d) for d in np.asarray(topo["dims"]))
    new_dims = tuple(int(d) for d in new_dims)
    if len(new_dims) != _NDIMS or any(d < 1 for d in new_dims):
        raise InvalidArgumentError(
            f"build_reshard_plan: new_dims must be 3 positive ints; got "
            f"{new_dims}.")
    nxyz_src = tuple(int(n) for n in np.asarray(topo["nxyz"]))
    ol = tuple(int(o) for o in np.asarray(topo["overlaps"]))
    per = tuple(int(p) for p in np.asarray(topo["periods"]))
    hw = tuple(int(h) for h in np.asarray(topo["halowidths"]))
    nxyz_dst = tuple(int(n) for n in elastic_local_size(topo, new_dims))
    if src_dims == new_dims:
        raise InvalidArgumentError(
            f"build_reshard_plan: source and destination dims are both "
            f"{src_dims} — nothing to re-block.")
    n_src = int(np.prod(src_dims))
    n_dst = int(np.prod(new_dims))
    n_flat = max(n_src, n_dst)

    if not isinstance(fields, dict) or not fields:
        raise InvalidArgumentError(
            "build_reshard_plan expects a non-empty dict of name -> "
            "(shape, dtype, lead).")

    # group fields by block signature; keep first-seen name order
    groups: dict = {}
    for name, (shape, dtype, lead) in fields.items():
        shape = tuple(int(s) for s in shape)
        lead_sh = shape[:int(lead)]
        sp = shape[int(lead):]
        if not 1 <= len(sp) <= _NDIMS:
            raise InvalidArgumentError(
                f"field {name!r}: spatial rank {len(sp)} is outside 1..3 "
                f"(shape {shape}, lead {lead}).")
        loc_src = []
        for d, s in enumerate(sp):
            if s % src_dims[d]:
                raise IncoherentArgumentError(
                    f"field {name!r}: stacked size {s} along dimension "
                    f"{d} is not divisible by the source dims[{d}]="
                    f"{src_dims[d]}.")
            loc_src.append(s // src_dims[d])
        key = (lead_sh, tuple(loc_src), str(np.dtype(dtype)))
        groups.setdefault(key, []).append(name)

    sigs = []
    for (lead_sh, loc_src, dtype), names in groups.items():
        nd_s = len(loc_src)
        axes, loc_dst = [], []
        for d in range(nd_s):
            stag = loc_src[d] - nxyz_src[d]   # staggered fields carry
            ln = nxyz_dst[d] + stag           # their extra cells along
            if ol[d] + stag < 0 or ln < 1:
                raise IncoherentArgumentError(
                    f"field(s) {names}: local block {loc_src[d]} along "
                    f"dimension {d} is inconsistent with the grid's "
                    f"nxyz[{d}]={nxyz_src[d]} / overlaps[{d}]={ol[d]} "
                    "(stacked shape not from this decomposition?).")
            axes.append(AxisRedistribution(
                loc_src[d], ln, src_dims[d], new_dims[d],
                ol[d] + stag, bool(per[d])))
            loc_dst.append(ln)
        pieces = []
        for c in itertools.product(*[range(new_dims[d])
                                     for d in range(nd_s)]):
            runs_per_axis = [_axis_runs(axes[d], c[d]) for d in range(nd_s)]
            dst_rank = _ravel(c, new_dims)
            for combo in itertools.product(*runs_per_axis):
                src_rank = _ravel([r[0] for r in combo], src_dims)
                pieces.append(Piece(
                    src_rank=src_rank, dst_rank=dst_rank,
                    src_start=tuple(r[2] for r in combo),
                    dst_start=tuple(r[1] for r in combo),
                    size=tuple(r[3] for r in combo)))
        pieces.sort(key=lambda p: (p.dst_rank, p.src_rank, p.dst_start))
        wire = [p for p in pieces if p.src_rank != p.dst_rank]
        local = tuple(p for p in pieces if p.src_rank == p.dst_rank)
        sigs.append(SigPlan(
            names=tuple(names), dtype=str(np.dtype(dtype)), lead=lead_sh,
            src_block=tuple(loc_src), dst_block=tuple(loc_dst),
            rounds=_schedule_rounds(wire), local=local))

    return ReshardPlan(
        src_dims=src_dims, dst_dims=new_dims, nxyz_src=nxyz_src,
        nxyz_dst=nxyz_dst, overlaps=ol, periods=per, halowidths=hw,
        n_flat=n_flat, sigs=tuple(sigs))


# ---------------------------------------------------------------------------
# host oracle
# ---------------------------------------------------------------------------

def apply_plan_host(plan: ReshardPlan, state: dict) -> dict:
    """Execute the plan on HOST numpy arrays (source-stacked layout) —
    the pure-python oracle the compiled collective program is tested
    bit-identical against, and the engine behind ``tools reshard run``'s
    verification. Moves raw bytes only (no arithmetic), exactly like the
    device program."""
    out: dict = {}
    for sig in plan.sigs:
        nd_s = len(sig.src_block)
        lead = len(sig.lead)
        for name in sig.names:
            src = np.asarray(state[name])
            expect = tuple(sig.lead) + tuple(
                plan.src_dims[d] * sig.src_block[d] for d in range(nd_s))
            if tuple(src.shape) != expect:
                raise InvalidArgumentError(
                    f"apply_plan_host: field {name!r} has shape "
                    f"{tuple(src.shape)}, the plan expects {expect}.")
            dst = np.zeros(tuple(sig.lead) + tuple(
                plan.dst_dims[d] * sig.dst_block[d] for d in range(nd_s)),
                dtype=src.dtype)
            all_pieces = [p for r in sig.rounds for p in r.pieces]
            all_pieces += list(sig.local)
            for p in all_pieces:
                sc = _coords(p.src_rank, plan.src_dims)[:nd_s]
                dcr = _coords(p.dst_rank, plan.dst_dims)[:nd_s]
                src_sel = tuple(slice(None) for _ in range(lead)) + tuple(
                    slice(sc[d] * sig.src_block[d] + p.src_start[d],
                          sc[d] * sig.src_block[d] + p.src_start[d]
                          + p.size[d])
                    for d in range(nd_s))
                dst_sel = tuple(slice(None) for _ in range(lead)) + tuple(
                    slice(dcr[d] * sig.dst_block[d] + p.dst_start[d],
                          dcr[d] * sig.dst_block[d] + p.dst_start[d]
                          + p.size[d])
                    for d in range(nd_s))
                dst[dst_sel] = src[src_sel]
            out[name] = dst
    return out


def _coords(rank: int, dims) -> tuple:
    return tuple(int(c) for c in np.unravel_index(rank, dims))


# ---------------------------------------------------------------------------
# the collective contract
# ---------------------------------------------------------------------------

def reshard_contract(plan: ReshardPlan, meta=None):
    """The plan's `analysis.CollectiveContract`: exactly one
    collective-permute per scheduled round on the flat ``rs`` axis, with
    byte-exact padded payloads (all-links total) and each permute's
    ``source_target_pairs`` matching one round's pair set verbatim — an
    unplanned route, a merged/split round, or a payload a byte off the
    schedule is an error finding. No all-reduces, no gathers, no
    all-to-alls: the program is pure permute rounds plus local copies.
    Host-derivable (routes come from the plan, not a live grid)."""
    from ..analysis.contracts import CollectiveContract, hlo_dtype

    dtypes = sorted({hlo_dtype(s.dtype) for s in plan.sigs if s.rounds})
    routes = tuple(frozenset(r.pairs) for s in plan.sigs for r in s.rounds)
    axes = None
    if plan.rounds:
        axes = {"rs": {"permutes": plan.rounds,
                       "wire_bytes": plan.wire_bytes,
                       "dtypes": tuple(dtypes)}}
    return CollectiveContract(
        axes=axes,
        routes={"rs": routes} if routes else None,
        allreduces=0,
        allreduce_payload=None,
        meta=dict(meta or {}, program="reshard",
                  src_dims=list(plan.src_dims),
                  dst_dims=list(plan.dst_dims),
                  rounds=plan.rounds))
