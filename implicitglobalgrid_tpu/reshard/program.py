"""The compiled redistribution program: plan rounds -> ppermute slices.

`reshard_state` is the HBM-to-HBM fast path of an elastic ``dims``
change: it re-blocks the LIVE state onto a new decomposition with no
disk round-trip. Mechanics, in order:

1. The plan (`reshard.plan.build_reshard_plan`) is derived from the live
   grid's topology and the state's shapes — host arithmetic only.
2. The grid is re-initialized onto the destination dims (same implicit
   global grid; `elastic_local_size` math), exactly like
   `runtime.recovery.elastic_restart` — but the state never leaves HBM.
3. A FLAT one-axis mesh (axis ``rs``) spans the union of the two
   decompositions' device pools (``n_flat = max(N_src, N_dst)`` slots;
   destination rank ``q`` at slot ``q``, source rank ``r`` at slot
   ``r``). Each field-signature group's source blocks are stacked into
   one ``(n_flat, F, *lead, *block)`` array (device-local reshapes plus
   at most a device-to-device placement copy — never through the host).
4. ONE jitted `shard_map` program executes the plan: per scheduled
   round, every participating device gathers its padded send slab from
   its source block (per-device offsets via tiny host-built index
   tables keyed by ``lax.axis_index``), ONE ``lax.ppermute`` moves all
   slabs (a partial permutation — the round-scheduling guarantee), and
   the receivers mask-write their valid sub-box into the destination
   block. Same-device pieces run as local rounds with no collective.
   Peak HBM per device: destination block + one padded slab + the
   gather temporary — bounded by the schedule, not by the re-blocking
   skew (arXiv:2112.01075's memory-bounded redistribution shape).
5. The destination blocks are reassembled into stacked global arrays on
   the NEW grid's mesh (replicated mesh axes rebuilt by placement).

The program is a first-class collective citizen: `reshard_contract`
declares its exact permute rounds/routes/bytes, `audit_reshard_program`
proves the compiled module against them (``tools reshard run`` gates on
it, tests pin a golden HLO fixture), and `telemetry.predict_reshard`
prices it statically.

Single-controller only for now: the flat mesh assembles per-device
buffers addressable from one process. Multi-controller runs keep the
checkpoint-based elastic restore (`restore_checkpoint_elastic`), which
remains the verified fallback and the bit-identity oracle everywhere.
"""

from __future__ import annotations

import numpy as np

from ..utils.exceptions import InvalidArgumentError
from .plan import (
    ReshardPlan, build_reshard_plan, fields_of_state, live_topology,
    reshard_contract,
)

__all__ = ["reshard_state", "compile_reshard_program",
           "audit_reshard_program", "clear_program_cache"]

# compiled programs keyed by (plan fingerprint, flat device ids): a
# resize bounced back and forth (autoscaling under variable traffic)
# pays each direction's XLA compile once. LRU-bounded — a long-lived
# scheduler resizing heterogeneous tenants must not accumulate one
# executable per geometry it ever visited
_PROGRAM_CACHE_MAX = 8
_program_cache: dict = {}


def clear_program_cache() -> None:
    _program_cache.clear()


# ---------------------------------------------------------------------------
# index tables (host-built, baked into the program as constants)
# ---------------------------------------------------------------------------

def _round_tables(pieces, pad, sig, n_flat):
    """Per-device gather/write tables of one round.

    ``send[d][dev]`` indexes the padded send window into the source
    block (positions past the piece are edge-clamped garbage the
    receiver never reads); ``wsel[d][dev]`` maps each destination-block
    position to its payload position, -1 where this round contributes
    nothing (the write mask)."""
    nd = len(sig.src_block)
    send = [np.zeros((n_flat, int(pad[d])), np.int32) for d in range(nd)]
    wsel = [np.full((n_flat, int(sig.dst_block[d])), -1, np.int32)
            for d in range(nd)]
    for p in pieces:
        for d in range(nd):
            idx = p.src_start[d] + np.arange(int(pad[d]))
            send[d][p.src_rank] = np.clip(idx, 0, sig.src_block[d] - 1)
            wsel[d][p.dst_rank,
                    p.dst_start[d]:p.dst_start[d] + p.size[d]] = \
                np.arange(p.size[d])
    return send, wsel


def _local_rounds(local_pieces):
    """Schedule same-device pieces so each device copies at most one
    sub-box per local round (one gather/mask-write pass each)."""
    rounds: list = []
    for p in local_pieces:
        for used, members in rounds:
            if p.src_rank not in used:
                used.add(p.src_rank)
                members.append(p)
                break
        else:
            rounds.append(({p.src_rank}, [p]))
    out = []
    for _, members in rounds:
        nd = len(members[0].size)
        pad = tuple(max(int(p.size[d]) for p in members) for d in range(nd))
        out.append((tuple(members), pad))
    return out


# ---------------------------------------------------------------------------
# the collective program
# ---------------------------------------------------------------------------

def compile_reshard_program(plan: ReshardPlan, mesh):
    """Jit the plan's collective program over ``mesh`` (one flat ``rs``
    axis of ``plan.n_flat`` devices). Takes one
    ``(n_flat, F, *lead, *src_block)`` array per field signature and
    returns the matching ``(n_flat, F, *lead, *dst_block)`` arrays."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..utils.compat import shard_map

    n_flat = plan.n_flat
    sig_progs = []
    for sig in plan.sigs:
        wire = [( _round_tables(r.pieces, r.pad, sig, n_flat),
                  [tuple(pr) for pr in r.pairs]) for r in sig.rounds]
        local = [(_round_tables(pieces, pad, sig, n_flat), None)
                 for pieces, pad in _local_rounds(sig.local)]
        sig_progs.append((sig, wire + local))

    def _write(out, payload, wsel, me, off, nd):
        g = payload
        mask = None
        for d in range(nd):
            w = jnp.asarray(wsel[d])[me]
            g = jnp.take(g, jnp.clip(w, 0, payload.shape[off + d] - 1),
                         axis=off + d)
            mshape = [1] * out.ndim
            mshape[off + d] = int(w.shape[0])
            md = (w >= 0).reshape(mshape)
            mask = md if mask is None else (mask & md)
        return jnp.where(mask, g, out)

    def body(*blocks):
        me = lax.axis_index("rs")
        outs = []
        for (sig, rounds), src in zip(sig_progs, blocks):
            nd = len(sig.src_block)
            off = 2 + len(sig.lead)      # (slot, F, *lead, *spatial)
            out = jnp.zeros((1, len(sig.names)) + tuple(sig.lead)
                            + tuple(sig.dst_block), src.dtype)
            for (send, wsel), pairs in rounds:
                payload = src
                for d in range(nd):
                    payload = jnp.take(payload,
                                       jnp.asarray(send[d])[me],
                                       axis=off + d)
                if pairs is not None:
                    payload = lax.ppermute(payload, "rs", perm=pairs)
                out = _write(out, payload, wsel, me, off, nd)
            outs.append(out)
        return tuple(outs)

    specs = tuple(P("rs") for _ in plan.sigs)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=specs,
                             out_specs=specs, check_vma=False))


# ---------------------------------------------------------------------------
# pack / unpack (device-local reshapes + placement, never through host)
# ---------------------------------------------------------------------------

def _flat_devices(plan: ReshardPlan, src_devices, dst_devices):
    """Flat-slot device list: destination ranks first (slot q = dst rank
    q), extended with source-pool devices for send-only slots when the
    source decomposition is the larger one."""
    devices = list(dst_devices)
    have = {d.id for d in devices}
    for d in src_devices:
        if len(devices) >= plan.n_flat:
            break
        if d.id not in have:
            devices.append(d)
            have.add(d.id)
    if len(devices) < plan.n_flat:
        raise InvalidArgumentError(
            f"reshard: the device pool holds {len(devices)} device(s) but "
            f"the flat transfer mesh needs {plan.n_flat}.")
    return devices[:plan.n_flat]


def _shard_on(arr, device):
    for s in arr.addressable_shards:
        if s.device.id == device.id:
            return s.data
    raise InvalidArgumentError(
        f"reshard: no addressable shard of the source array on device "
        f"{device.id} (multi-controller runs use the checkpoint path).")


def _pack_inputs(plan: ReshardPlan, state: dict, src_devices,
                 flat_devices, mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_src = int(np.prod(plan.src_dims))
    sharding = NamedSharding(mesh, P("rs"))
    flats = []
    for sig in plan.sigs:
        shape = ((plan.n_flat, len(sig.names)) + tuple(sig.lead)
                 + tuple(sig.src_block))
        dtype = np.dtype(sig.dtype)
        arrs = []
        for slot in range(plan.n_flat):
            dev = flat_devices[slot]
            if slot < n_src:
                parts = [jnp.asarray(_shard_on(state[name],
                                               src_devices[slot]))
                         for name in sig.names]
                blk = jnp.stack(parts)[None]
            else:
                blk = jnp.zeros(shape[1:], dtype)[None]
            arrs.append(jax.device_put(blk, dev))
        flats.append(jax.make_array_from_single_device_arrays(
            shape, sharding, arrs))
    return flats


def _unpack_outputs(plan: ReshardPlan, outs, dst_gg):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.topology import AXIS_NAMES
    from ..utils.blockio import starts_of

    state: dict = {}
    for sig, flat in zip(plan.sigs, outs):
        by_slot = {}
        for s in flat.addressable_shards:
            by_slot[int(s.index[0].start or 0)] = s.data
        nd_s = len(sig.dst_block)
        lead = len(sig.lead)
        spec = P(*([None] * lead), *AXIS_NAMES[:nd_s])
        sharding = NamedSharding(dst_gg.mesh, spec)
        shape = tuple(sig.lead) + tuple(
            plan.dst_dims[d] * sig.dst_block[d] for d in range(nd_s))
        needed = sharding.addressable_devices_indices_map(shape)
        for fi, name in enumerate(sig.names):
            arrs = []
            for dev, idx in needed.items():
                starts = starts_of(idx)
                coords = [starts[lead + d] // sig.dst_block[d]
                          for d in range(nd_s)]
                slot = int(np.ravel_multi_index(
                    coords + [0] * (3 - nd_s), plan.dst_dims))
                arrs.append(jax.device_put(by_slot[slot][0, fi], dev))
            state[name] = jax.make_array_from_single_device_arrays(
                shape, sharding, arrs)
    return state


# ---------------------------------------------------------------------------
# audit + the top-level move
# ---------------------------------------------------------------------------

def audit_reshard_program(plan: ReshardPlan, program, flats, *,
                          optimized: bool = False, lints=None):
    """Prove a compiled redistribution program against its plan-derived
    contract (`reshard_contract`): per-round permute counts, byte-exact
    padded payloads, route attribution, no reductions/gathers — plus the
    standard implicit-grid lints. ``optimized=False`` parses the lowered
    StableHLO (trace + lower only, no second backend compile — the form
    the driver's in-run audit uses); ``tools reshard run`` and the golden
    fixture audit the optimized HLO."""
    from ..analysis import audit_program

    return audit_program(program, *flats,
                         contract=reshard_contract(plan),
                         lints=lints, optimized=optimized,
                         meta={"program": "reshard",
                               "src_dims": list(plan.src_dims),
                               "dst_dims": list(plan.dst_dims)})


def reshard_state(state: dict, new_dims, *, quiet: bool = True,
                  audit: bool = False, lints=None):
    """Re-block the live ``state`` onto ``new_dims`` entirely HBM-to-HBM
    and re-initialize the global grid to match. Returns
    ``(new_state, info)`` where ``info`` carries the plan stats
    (``rounds``, ``wire_bytes``, ``local_bytes``, ``peak_payload_bytes``)
    plus ``audit_report`` (an `analysis.AuditReport`, or None).

    The result is bit-identical to saving a sharded checkpoint and
    `restore_checkpoint_elastic`-ing it onto the new decomposition — the
    plan reuses that path's owner-map arithmetic verbatim and the
    program moves raw bytes only (asserted in tests/test_reshard.py).
    Raises (`IncoherentArgumentError` /`InvalidArgumentError`) without
    touching the grid when the move is impossible — callers
    (`runtime.ResilientRun.resize`) fall back to the checkpoint path."""
    import jax

    from ..parallel.grid import finalize_global_grid
    from ..parallel.topology import check_initialized, global_grid
    from .plan import device_pool, init_from_topology, restore_topology

    check_initialized()
    if jax.process_count() > 1:
        raise InvalidArgumentError(
            "On-device resharding runs single-controller for now "
            "(the flat transfer mesh assembles per-device buffers from "
            "one process); multi-controller runs keep the checkpoint-"
            "based elastic restore.")
    gg = global_grid()
    topo = live_topology(gg)
    plan = build_reshard_plan(topo, new_dims, fields_of_state(state))
    src_devices = list(np.asarray(gg.mesh.devices).reshape(-1))
    # the destination pool must exist BEFORE the source grid is torn
    # down: failing here leaves the caller's grid (and its fallback
    # options) fully intact
    pool = device_pool(gg)
    n_dst = int(np.prod(plan.dst_dims))
    if n_dst > len(pool):
        raise InvalidArgumentError(
            f"reshard: destination dims {plan.dst_dims} need {n_dst} "
            f"device(s); {len(pool)} available.")

    # same grid swap as `runtime.recovery.elastic_restart` — but the
    # state stays in HBM across it (arrays outlive the grid epoch)
    finalize_global_grid()
    try:
        init_from_topology(topo, nxyz=plan.nxyz_dst, dims=plan.dst_dims,
                           quiet=quiet)
        dst_gg = global_grid()
        dst_devices = list(np.asarray(dst_gg.mesh.devices).reshape(-1))
        flat_devices = _flat_devices(plan, src_devices, dst_devices)
        mesh = jax.sharding.Mesh(np.array(flat_devices), ("rs",))

        key = (plan.fingerprint(), tuple(d.id for d in flat_devices))
        program, reports = _program_cache.pop(key, (None, None))
        if program is None:
            program = compile_reshard_program(plan, mesh)
            reports = {}
        _program_cache[key] = (program, reports)   # re-insert = recent
        while len(_program_cache) > _PROGRAM_CACHE_MAX:
            _program_cache.pop(next(iter(_program_cache)))
        flats = _pack_inputs(plan, state, src_devices, flat_devices, mesh)
        report = None
        audit_error = None
        if audit:
            # the verdict is deterministic per (key, lints): a bounced
            # autoscale must not re-trace/re-parse the program inside
            # every resize's downtime window
            lkey = None if lints is None else tuple(lints)
            report = reports.get(lkey)
            if report is None:
                try:
                    report = audit_reshard_program(plan, program, flats,
                                                   lints=lints)
                    reports[lkey] = report
                except Exception as e:
                    # the audit OBSERVES — a parser failure must not
                    # push a healthy transfer onto the disk fallback
                    audit_error = f"{type(e).__name__}: {e}"
        outs = program(*flats)
        new_state = _unpack_outputs(plan, outs, dst_gg)
    except BaseException:
        # best effort: put the SOURCE grid back so the caller (the
        # driver's via="auto") can still run its checkpoint fallback
        # against a live grid — the original state arrays are untouched
        restore_topology(topo, quiet=quiet)
        raise
    info = dict(plan.stats(), audit_report=report)
    if audit_error is not None:
        info["audit_error"] = audit_error
    return new_state, info
