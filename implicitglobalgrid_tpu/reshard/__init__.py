"""On-device elastic resharding (ISSUE 14 tentpole).

An elastic ``dims`` change as a COLLECTIVE PROGRAM instead of a disk
round-trip: `build_reshard_plan` derives the exact block-overlap
transfer plan from the same implicit-global-grid block-coordinate
arithmetic the checkpoint-based elastic restore uses
(`utils.checkpoint.AxisRedistribution` — one copy of the math), schedules
it into partial-permutation rounds with bounded peak HBM, and
`reshard_state` compiles and runs it as a sequence of ``lax.ppermute``
slice rounds over a flat mesh spanning both decompositions — the live
state re-blocks HBM-to-HBM, bit-identical to `restore_checkpoint_elastic`
(which stays the verified fallback and the oracle).

The program is contract-audited (`reshard_contract` /
`audit_reshard_program`; golden fixture in tests/data/hlo/), priced
statically (`telemetry.predict_reshard`), surfaced in the driver as
`runtime.ResilientRun.resize(dims)` and in the scheduler as
`service.MeshScheduler.resize` / ``tools jobs resize`` — the
autoscaling primitive (ROADMAP "On-device elastic resharding").
"""

from .plan import (
    Piece, ReshardPlan, Round, SigPlan, apply_plan_host,
    build_reshard_plan, fields_of_state, live_topology, reshard_contract,
)
from .program import (
    audit_reshard_program, clear_program_cache, compile_reshard_program,
    reshard_state,
)

__all__ = [
    "ReshardPlan", "SigPlan", "Round", "Piece",
    "build_reshard_plan", "live_topology", "fields_of_state",
    "apply_plan_host", "reshard_contract",
    "reshard_state", "compile_reshard_program", "audit_reshard_program",
    "clear_program_cache",
]
